// Native wire-ingest encoder: JSON-lines sequenced messages -> op tensors.
//
// The device fleet (models/doc_batch_engine.py) applies merge-tree ops from
// int32 row tensors; producing those rows from the wire is pure host work
// and the measured ingest bottleneck when done per-op in Python.  This is
// the C++ data-plane equivalent of the reference's server-side codecs
// (routerlicious consumes Kafka JSON through native librdkafka + JS codecs;
// here the whole decode+encode runs native).
//
// One encoder per document: it owns the quorum table (clientId -> short id,
// built from sequenced joins), the property-slot interning table, and the
// MSN watermark — the same per-doc host state DocBatchEngine keeps.
//
// The parser is a STREAMING recursive-descent JSON reader specialized for
// the SequencedMessage schema (protocol/messages.py to_json): no DOM, no
// per-line allocation (string scratch buffers are reused), tolerant of key
// order, handles escapes incl. \uXXXX surrogate pairs, and decodes UTF-8
// to codepoints so payload rows match Python's ord() exactly.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o libtpuingest.so ingest.cpp

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Op row layout mirrors ops/mergetree_kernel.py:
//   0 kind | 1 key | 2 client | 3 ref_seq | 4 pos1 | 5 pos2 | 6 a | 7 b
enum OpKind { NOOP = 0, INSERT = 1, REMOVE = 2, ANNOTATE = 3, ACK = 4, OBLITERATE = 5 };
constexpr int OP_FIELDS = 8;
constexpr int SIDE_BEFORE = 0, SIDE_AFTER = 1;

struct Scanner {
  const char* p;
  const char* end;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }
  bool consume(char c) {
    skip_ws();
    if (p < end && *p == c) { p++; return true; }
    return false;
  }
  char peek() {
    skip_ws();
    return p < end ? *p : '\0';
  }
};

// Decode a JSON string starting AT the opening quote.  Appends codepoints
// to *cps (when non-null) and raw bytes to *bytes (when non-null).
bool parse_string(Scanner& s, std::vector<uint32_t>* cps, std::string* bytes) {
  if (!s.consume('"')) return false;
  while (s.p < s.end) {
    unsigned char c = (unsigned char)*s.p;
    if (c == '"') { s.p++; return true; }
    uint32_t cp;
    if (c == '\\') {
      s.p++;
      if (s.p >= s.end) return false;
      char e = *s.p++;
      switch (e) {
        case '"': cp = '"'; break;
        case '\\': cp = '\\'; break;
        case '/': cp = '/'; break;
        case 'b': cp = '\b'; break;
        case 'f': cp = '\f'; break;
        case 'n': cp = '\n'; break;
        case 'r': cp = '\r'; break;
        case 't': cp = '\t'; break;
        case 'u': {
          if (s.end - s.p < 4) return false;
          cp = 0;
          for (int i = 0; i < 4; i++) {
            char h = *s.p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else return false;
          }
          if (cp >= 0xD800 && cp <= 0xDBFF && s.end - s.p >= 6 &&
              s.p[0] == '\\' && s.p[1] == 'u') {
            uint32_t lo = 0;
            bool ok = true;
            for (int i = 0; i < 4; i++) {
              char h = s.p[2 + i];
              lo <<= 4;
              if (h >= '0' && h <= '9') lo |= h - '0';
              else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
              else { ok = false; break; }
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              s.p += 6;
            }
          }
          break;
        }
        default: return false;
      }
    } else {
      int extra;
      if (c < 0x80) { cp = c; extra = 0; }
      else if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
      else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
      else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
      else return false;
      s.p++;
      for (int i = 0; i < extra; i++) {
        if (s.p >= s.end || ((unsigned char)*s.p >> 6) != 0x2) return false;
        cp = (cp << 6) | ((unsigned char)*s.p & 0x3F);
        s.p++;
      }
    }
    if (cps) cps->push_back(cp);
    if (bytes) {
      // Re-encode codepoint as UTF-8 (ids/keys are normally ASCII).
      if (cp < 0x80) bytes->push_back((char)cp);
      else if (cp < 0x800) {
        bytes->push_back((char)(0xC0 | (cp >> 6)));
        bytes->push_back((char)(0x80 | (cp & 0x3F)));
      } else if (cp < 0x10000) {
        bytes->push_back((char)(0xE0 | (cp >> 12)));
        bytes->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        bytes->push_back((char)(0x80 | (cp & 0x3F)));
      } else {
        bytes->push_back((char)(0xF0 | (cp >> 18)));
        bytes->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
        bytes->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        bytes->push_back((char)(0x80 | (cp & 0x3F)));
      }
    }
  }
  return false;
}

// Fast path for OBJECT KEYS: our schema's keys are plain ASCII without
// escapes, so scan straight to the closing quote (fall back to the full
// string parser if a backslash shows up).
bool parse_key(Scanner& s, std::string* out) {
  if (!s.consume('"')) return false;
  const char* q = (const char*)memchr(s.p, '"', s.end - s.p);
  if (!q) return false;
  if (memchr(s.p, '\\', q - s.p)) {  // escaped key: rare, take the slow path
    s.p--;  // back onto the opening quote
    out->clear();
    return parse_string(s, nullptr, out);
  }
  out->assign(s.p, q - s.p);
  s.p = q + 1;
  return true;
}

bool parse_number(Scanner& s, double* out) {
  s.skip_ws();
  char* endp = nullptr;
  *out = strtod(s.p, &endp);
  if (endp == s.p) return false;
  s.p = endp;
  return true;
}

bool skip_value(Scanner& s);

bool skip_container(Scanner& s, char open, char close) {
  if (!s.consume(open)) return false;
  if (s.consume(close)) return true;
  while (true) {
    if (open == '{') {
      if (!parse_string(s, nullptr, nullptr)) return false;
      if (!s.consume(':')) return false;
    }
    if (!skip_value(s)) return false;
    if (s.consume(',')) continue;
    return s.consume(close);
  }
}

bool skip_value(Scanner& s) {
  char c = s.peek();
  if (c == '{') return skip_container(s, '{', '}');
  if (c == '[') return skip_container(s, '[', ']');
  if (c == '"') return parse_string(s, nullptr, nullptr);
  if (c == 't') { s.p += 4; return s.p <= s.end; }
  if (c == 'f') { s.p += 5; return s.p <= s.end; }
  if (c == 'n') { s.p += 4; return s.p <= s.end; }
  double d;
  return parse_number(s, &d);
}

struct Encoder {
  int max_insert_len;
  int prop_slots;
  int64_t min_seq = 0;
  std::unordered_map<std::string, int32_t> quorum;
  std::unordered_map<int64_t, int32_t> prop_slot;
  std::string error;
  // Reused per-line scratch (the no-allocation-per-line contract).
  std::string key, str_a, str_b;
  std::vector<uint32_t> seg;

  int prop_for(int64_t prop) {
    auto it = prop_slot.find(prop);
    if (it != prop_slot.end()) return it->second;
    if ((int)prop_slot.size() >= prop_slots) return -1;
    int slot = (int)prop_slot.size();
    prop_slot.emplace(prop, slot);
    return slot;
  }
};

struct Out {
  int32_t* ops;
  int32_t* payloads;
  int32_t max_rows;
  int L;
  int32_t n = 0;
  bool overflow = false;

  int32_t* next_row() {
    if (n >= max_rows) { overflow = true; return nullptr; }
    int32_t* row = ops + (int64_t)n * OP_FIELDS;
    memset(payloads + (int64_t)n * L, 0, sizeof(int32_t) * L);
    n++;
    return row;
  }
};

// Parsed fields of one contents object (wire op forms, shared_string.py).
struct Contents {
  int64_t type = -1;
  int64_t pos1 = 0, pos2 = 0;         // plain positions
  int64_t p1pos = 0, p2pos = 0;       // sided places
  bool p1before = true, p2before = true;
  bool sided1 = false, sided2 = false;
  bool has_seg = false;
  // join form
  bool has_client = false;
  int64_t short_id = -1;
  // annotate: (prop id, value) pairs
  std::vector<std::pair<int64_t, int64_t>> props;
};

// Parse a place object {"pos": N, "before": B}.
bool parse_place(Scanner& s, Encoder& e, int64_t* pos, bool* before) {
  if (!s.consume('{')) return false;
  if (s.consume('}')) return true;
  while (true) {
    if (!parse_key(s, &e.key)) return false;
    if (!s.consume(':')) return false;
    if (e.key == "pos") {
      double d;
      if (!parse_number(s, &d)) return false;
      *pos = (int64_t)d;
    } else if (e.key == "before") {
      char c = s.peek();
      if (c == 't') { *before = true; s.p += 4; }
      else if (c == 'f') { *before = false; s.p += 5; }
      else return false;
    } else if (!skip_value(s)) {
      return false;
    }
    if (s.consume(',')) continue;
    return s.consume('}');
  }
}

bool parse_contents(Scanner& s, Encoder& e, Contents* c) {
  if (s.peek() == 'n') { s.p += 4; return true; }  // null contents
  if (!s.consume('{')) return false;
  if (s.consume('}')) return true;
  while (true) {
    if (!parse_key(s, &e.key)) return false;
    if (!s.consume(':')) return false;
    if (e.key == "type") {
      double d;
      if (!parse_number(s, &d)) return false;
      c->type = (int64_t)d;
    } else if (e.key == "pos1") {
      if (s.peek() == '{') {
        c->sided1 = true;
        if (!parse_place(s, e, &c->p1pos, &c->p1before)) return false;
      } else {
        double d;
        if (!parse_number(s, &d)) return false;
        c->pos1 = (int64_t)d;
      }
    } else if (e.key == "pos2") {
      if (s.peek() == '{') {
        c->sided2 = true;
        if (!parse_place(s, e, &c->p2pos, &c->p2before)) return false;
      } else {
        double d;
        if (!parse_number(s, &d)) return false;
        c->pos2 = (int64_t)d;
      }
    } else if (e.key == "seg") {
      e.seg.clear();
      if (!parse_string(s, &e.seg, nullptr)) return false;
      c->has_seg = true;
    } else if (e.key == "props") {
      if (!s.consume('{')) return false;
      if (!s.consume('}')) {
        while (true) {
          e.str_b.clear();
          if (!parse_string(s, nullptr, &e.str_b)) return false;
          if (!s.consume(':')) return false;
          double d;
          if (!parse_number(s, &d)) return false;
          // Match the Python path's int(prop): a non-numeric key must error
          // loudly, never collapse to id 0.
          char* kend = nullptr;
          int64_t pid = strtoll(e.str_b.c_str(), &kend, 10);
          if (kend == e.str_b.c_str() || *kend != '\0') return false;
          c->props.emplace_back(pid, (int64_t)d);
          if (s.consume(',')) continue;
          if (!s.consume('}')) return false;
          break;
        }
      }
    } else if (e.key == "clientId") {
      e.str_a.clear();
      if (!parse_string(s, nullptr, &e.str_a)) return false;
      c->has_client = true;
    } else if (e.key == "short") {
      double d;
      if (!parse_number(s, &d)) return false;
      c->short_id = (int64_t)d;
    } else if (!skip_value(s)) {
      return false;
    }
    if (s.consume(',')) continue;
    return s.consume('}');
  }
}

bool emit_line(Encoder& e, Scanner& s, Out& out) {
  // Top-level message fields.
  int64_t seq = 0, ref = 0, mseq = 0;
  char mtype = '\0';  // 'o' op, 'j' join, other
  bool have_contents = false;
  Contents c;
  e.str_a.clear();  // join contents clientId
  std::string client_id;

  if (!s.consume('{')) { e.error = "json parse error"; return false; }
  if (!s.consume('}')) {
    while (true) {
      if (!parse_key(s, &e.key)) { e.error = "bad key"; return false; }
      if (!s.consume(':')) { e.error = "missing colon"; return false; }
      if (e.key == "sequenceNumber") {
        double d; if (!parse_number(s, &d)) return false; seq = (int64_t)d;
      } else if (e.key == "referenceSequenceNumber") {
        double d; if (!parse_number(s, &d)) return false; ref = (int64_t)d;
      } else if (e.key == "minimumSequenceNumber") {
        double d; if (!parse_number(s, &d)) return false; mseq = (int64_t)d;
      } else if (e.key == "type") {
        e.str_b.clear();
        if (!parse_string(s, nullptr, &e.str_b)) return false;
        mtype = e.str_b == "op" ? 'o' : (e.str_b == "join" ? 'j' : 'x');
      } else if (e.key == "clientId") {
        client_id.clear();
        if (!parse_string(s, nullptr, &client_id)) return false;
      } else if (e.key == "contents") {
        if (!parse_contents(s, e, &c)) { e.error = "bad contents"; return false; }
        have_contents = true;
      } else if (!skip_value(s)) {
        e.error = "bad value";
        return false;
      }
      if (s.consume(',')) continue;
      if (s.consume('}')) break;
      e.error = "unterminated object";
      return false;
    }
  }

  if (mseq > e.min_seq) e.min_seq = mseq;
  if (mtype == 'j') {
    if (!have_contents || !c.has_client || c.short_id < 0) {
      e.error = "bad join";
      return false;
    }
    e.quorum[e.str_a] = (int32_t)c.short_id;
    return true;
  }
  if (mtype != 'o') return true;  // leave/noop/summarize...: MSN only
  auto q = e.quorum.find(client_id);
  if (q == e.quorum.end()) { e.error = "op from unjoined client"; return false; }
  int32_t client = q->second;

  if (c.type == 0) {  // INSERT: chunk back-to-front (mk.encode_insert)
    if (!c.has_seg) { e.error = "insert without seg"; return false; }
    int n = (int)e.seg.size();
    int L = e.max_insert_len;
    int nchunks = (n + L - 1) / L;
    for (int ch = nchunks - 1; ch >= 0; ch--) {
      int start = ch * L;
      int len = std::min(L, n - start);
      int32_t* row = out.next_row();
      if (!row) return true;
      row[0] = INSERT; row[1] = (int32_t)seq; row[2] = client;
      row[3] = (int32_t)ref; row[4] = (int32_t)c.pos1; row[5] = 0;
      row[6] = len; row[7] = 0;
      int32_t* pay = out.payloads + (int64_t)(out.n - 1) * out.L;
      for (int i = 0; i < len; i++) pay[i] = (int32_t)e.seg[start + i];
    }
  } else if (c.type == 1) {  // REMOVE
    int32_t* row = out.next_row();
    if (!row) return true;
    row[0] = REMOVE; row[1] = (int32_t)seq; row[2] = client;
    row[3] = (int32_t)ref; row[4] = (int32_t)c.pos1; row[5] = (int32_t)c.pos2;
    row[6] = row[7] = 0;
  } else if (c.type == 2) {  // ANNOTATE: one row per property
    for (auto& pv : c.props) {
      int slot = e.prop_for(pv.first);
      if (slot < 0) { e.error = "out of prop slots"; return false; }
      int32_t* row = out.next_row();
      if (!row) return true;
      row[0] = ANNOTATE; row[1] = (int32_t)seq; row[2] = client;
      row[3] = (int32_t)ref; row[4] = (int32_t)c.pos1; row[5] = (int32_t)c.pos2;
      row[6] = slot; row[7] = (int32_t)pv.second;
    }
  } else if (c.type == 4) {  // OBLITERATE plain: (pos1,Before)..(pos2-1,After)
    int32_t* row = out.next_row();
    if (!row) return true;
    row[0] = OBLITERATE; row[1] = (int32_t)seq; row[2] = client;
    row[3] = (int32_t)ref; row[4] = (int32_t)c.pos1;
    row[5] = (int32_t)c.pos2 - 1; row[6] = SIDE_BEFORE; row[7] = SIDE_AFTER;
  } else if (c.type == 5) {  // OBLITERATE_SIDED
    if (!c.sided1 || !c.sided2) { e.error = "bad sided places"; return false; }
    int32_t* row = out.next_row();
    if (!row) return true;
    row[0] = OBLITERATE; row[1] = (int32_t)seq; row[2] = client;
    row[3] = (int32_t)ref; row[4] = (int32_t)c.p1pos; row[5] = (int32_t)c.p2pos;
    row[6] = c.p1before ? SIDE_BEFORE : SIDE_AFTER;
    row[7] = c.p2before ? SIDE_BEFORE : SIDE_AFTER;
  } else {
    e.error = "unsupported op type";
    return false;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Tree wire decode: sequenced tree-edit batches -> mark-pool columns.
//
// The tree family's host fold pools sequence-field mark lists as columnar
// spans (fluidframework_tpu/dds/tree/mark_pool.py).  This decoder parses
// the NUMERIC PLANE of a tree op batch — message envelopes, edit framing,
// and every mark's kind/count/id/offset — straight into flat columns, and
// hands payloads that are genuinely object-shaped (insert content, removed
// subtrees, nested changes, non-sequence field kinds) back to Python as
// RAW JSON byte spans, so only those spans pay a json.loads.
//
// Stateless by design (no quorum/prop tables: tree identity is the
// client-id string plus the edit's (sid, rev) revision, all returned as
// spans), so one call decodes a whole feed chunk idempotently.  Anything
// the columnar grammar cannot express — grouped batches, address
// envelopes, dict-form commits with constraints, escaped key strings —
// degrades per MESSAGE to an opaque contents span the Python side parses
// exactly like the no-native path, and a malformed line fails the whole
// call so the Python oracle owns error semantics.
// ---------------------------------------------------------------------------

namespace tree {

// Mark kind codes — MUST match dds/tree/mark_pool.py (K_SKIP..K_MOVEIN).
enum MarkKind { MK_SKIP = 0, MK_INSERT = 1, MK_REMOVE = 2, MK_MODIFY = 3,
                MK_MOVEOUT = 4, MK_MOVEIN = 5 };

constexpr int MSG_FIELDS = 14;   // see ing_tree_decode docstring
constexpr int CHG_FIELDS = 3;
constexpr int FLD_FIELDS = 4;
constexpr int MARK_FIELDS = 5;

// Message status codes.
enum MsgStatus { ST_EDITS = 0, ST_SKIP = 1, ST_OPAQUE = 2 };

struct TreeOut {
  const char* base;
  int64_t* msgs; int32_t max_msgs;
  int32_t* chgs; int32_t max_chgs;
  int32_t* flds; int32_t max_flds;
  int32_t* marks; int32_t max_marks;
  int64_t* spans; int32_t max_spans;
  int32_t n_msgs = 0, n_chgs = 0, n_flds = 0, n_marks = 0, n_spans = 0;
  bool overflow = false;

  int32_t span(const char* s, const char* e) {
    if (n_spans >= max_spans) { overflow = true; return -1; }
    spans[2 * (int64_t)n_spans] = s - base;
    spans[2 * (int64_t)n_spans + 1] = e - s;
    return n_spans++;
  }
  int32_t* mark_row() {
    if (n_marks >= max_marks) { overflow = true; return nullptr; }
    int32_t* r = marks + (int64_t)n_marks++ * MARK_FIELDS;
    r[0] = r[1] = r[2] = r[3] = 0; r[4] = -1;
    return r;
  }
  int32_t* fld_row() {
    if (n_flds >= max_flds) { overflow = true; return nullptr; }
    int32_t* r = flds + (int64_t)n_flds++ * FLD_FIELDS;
    r[0] = -1; r[1] = r[2] = 0; r[3] = -1;
    return r;
  }
  int32_t* chg_row() {
    if (n_chgs >= max_chgs) { overflow = true; return nullptr; }
    int32_t* r = chgs + (int64_t)n_chgs++ * CHG_FIELDS;
    r[0] = r[1] = 0; r[2] = -1;
    return r;
  }
};

// Raw escape-free string span (keys / ids / tags).  Any backslash fails —
// the caller degrades to the opaque route, never mis-slices.
bool span_string(Scanner& s, const char** b, const char** e) {
  s.skip_ws();
  if (s.p >= s.end || *s.p != '"') return false;
  const char* q = (const char*)memchr(s.p + 1, '"', s.end - s.p - 1);
  if (!q) return false;
  if (memchr(s.p + 1, '\\', q - s.p - 1)) return false;
  *b = s.p + 1; *e = q; s.p = q + 1;
  return true;
}

// Record the extent of one JSON value as a span (payload handoff).
int32_t value_span(Scanner& s, TreeOut& out) {
  s.skip_ws();
  const char* start = s.p;
  if (!skip_value(s)) return -2;  // malformed
  return out.span(start, s.p);
}

bool parse_i64(Scanner& s, int64_t* v) {
  double d;
  if (!parse_number(s, &d)) return false;
  *v = (int64_t)d;
  return true;
}

// One mark array element; emits one mark row.  Returns false on malformed
// input (whole-line error: Python owns the failure semantics).
bool parse_mark(Scanner& s, TreeOut& out) {
  if (!s.consume('[')) return false;
  const char* tb; const char* te;
  if (!span_string(s, &tb, &te)) return false;
  size_t tl = te - tb;
  int32_t* row = out.mark_row();
  if (row == nullptr) return false;  // overflow: caller retries the call
  int64_t v = 0;
  if (tl == 1 && *tb == 's') {
    row[0] = MK_SKIP;
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[1] = (int32_t)v;
  } else if (tl == 1 && *tb == 'i') {
    row[0] = MK_INSERT;
    if (!s.consume(',')) return false;
    row[4] = value_span(s, out);
    if (row[4] == -2) return false;
  } else if (tl == 1 && *tb == 'r') {
    row[0] = MK_REMOVE;
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[1] = (int32_t)v;
    if (s.peek() == ',') {
      s.consume(',');
      row[4] = value_span(s, out);
      if (row[4] == -2) return false;
    }
  } else if (tl == 1 && *tb == 'm') {
    row[0] = MK_MODIFY;
    row[1] = 1;
    if (!s.consume(',')) return false;
    row[4] = value_span(s, out);
    if (row[4] == -2) return false;
  } else if (tl == 2 && tb[0] == 'm' && tb[1] == 'o') {
    row[0] = MK_MOVEOUT;
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[1] = (int32_t)v;
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[2] = (int32_t)v;
    if (s.peek() == ',') {
      s.consume(',');
      if (!parse_i64(s, &v)) return false;
      row[3] = (int32_t)v;
    }
  } else if (tl == 2 && tb[0] == 'm' && tb[1] == 'i') {
    row[0] = MK_MOVEIN;
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[2] = (int32_t)v;  // id
    if (!s.consume(',') || !parse_i64(s, &v)) return false;
    row[1] = (int32_t)v;  // count
    row[3] = -1;          // offset None sentinel (mark_pool._NONE_OFF)
    if (s.peek() == ',') {
      s.consume(',');
      if (s.peek() == 'n') { s.p += 4; }
      else if (parse_i64(s, &v)) row[3] = (int32_t)v;
      else return false;
    }
  } else {
    return false;  // unknown tag: Python raises on it, so do we
  }
  return s.consume(']');
}

// One NodeChange object {"v": [...], "f": {key: fieldchange}}.
bool parse_change(Scanner& s, TreeOut& out) {
  int32_t* chg = out.chg_row();
  int32_t fld_start = out.n_flds;
  int32_t v_span = -1;
  if (!s.consume('{')) return false;
  if (!s.consume('}')) {
    while (true) {
      const char* kb; const char* ke;
      if (!span_string(s, &kb, &ke)) return false;
      if (!s.consume(':')) return false;
      size_t kl = ke - kb;
      if (kl == 1 && *kb == 'v') {
        v_span = value_span(s, out);
        if (v_span == -2) return false;
      } else if (kl == 1 && *kb == 'f') {
        if (!s.consume('{')) return false;
        if (!s.consume('}')) {
          while (true) {
            const char* fb; const char* fe;
            if (!span_string(s, &fb, &fe)) return false;
            if (!s.consume(':')) return false;
            int32_t* fld = out.fld_row();
            int32_t key_span = out.span(fb, fe);
            int32_t mark_start = out.n_marks;
            if (s.peek() == '[') {
              s.consume('[');
              if (!s.consume(']')) {
                while (true) {
                  if (!parse_mark(s, out)) return false;
                  if (s.consume(',')) continue;
                  if (!s.consume(']')) return false;
                  break;
                }
              }
              if (fld != nullptr) {
                fld[0] = key_span;
                fld[1] = mark_start;
                fld[2] = out.n_marks - mark_start;
              }
            } else {
              // Non-sequence field kind: raw span, Python's registry
              // decodes it (same as the no-native path).
              int32_t os = value_span(s, out);
              if (os == -2) return false;
              if (fld != nullptr) {
                fld[0] = key_span;
                fld[3] = os;
              }
            }
            if (s.consume(',')) continue;
            if (!s.consume('}')) return false;
            break;
          }
        }
      } else if (!skip_value(s)) {
        return false;
      }
      if (s.consume(',')) continue;
      if (!s.consume('}')) return false;
      break;
    }
  }
  if (chg != nullptr) {
    chg[0] = fld_start;
    chg[1] = out.n_flds - fld_start;
    chg[2] = v_span;
  }
  return true;
}

enum ContentsResult { CT_EDIT, CT_OPAQUE, CT_ERROR };

// Parse contents as a direct {"type":"edit", "sid", "rev", "changes":[..]}
// object.  Emits chg/fld/mark/span rows as it goes; a shape the grammar
// cannot express rolls those rows back and reports CT_OPAQUE (the caller
// records the raw span instead).
ContentsResult parse_edit_contents(
    Scanner& s, TreeOut& out, int64_t* sid_off, int64_t* sid_len,
    int64_t* rev, int32_t* chg_start, int32_t* chg_count) {
  int32_t m0 = out.n_msgs, c0 = out.n_chgs, f0 = out.n_flds;
  int32_t k0 = out.n_marks, s0 = out.n_spans;
  (void)m0;
  bool is_edit = false, saw_changes = false;
  *chg_start = out.n_chgs;
  if (!s.consume('{')) return CT_OPAQUE;
  if (!s.consume('}')) {
    while (true) {
      const char* kb; const char* ke;
      if (!span_string(s, &kb, &ke)) goto opaque;
      if (!s.consume(':')) return CT_ERROR;
      {
        size_t kl = ke - kb;
        if (kl == 4 && memcmp(kb, "type", 4) == 0) {
          const char* vb; const char* ve;
          if (!span_string(s, &vb, &ve)) goto opaque;
          if (ve - vb != 4 || memcmp(vb, "edit", 4) != 0) goto opaque;
          is_edit = true;
        } else if (kl == 3 && memcmp(kb, "sid", 3) == 0) {
          const char* vb; const char* ve;
          if (!span_string(s, &vb, &ve)) goto opaque;
          *sid_off = vb - out.base;
          *sid_len = ve - vb;
        } else if (kl == 3 && memcmp(kb, "rev", 3) == 0) {
          if (!parse_i64(s, rev)) goto opaque;
        } else if (kl == 7 && memcmp(kb, "changes", 7) == 0) {
          if (s.peek() != '[') goto opaque;  // dict form (constraints)
          s.consume('[');
          saw_changes = true;
          if (!s.consume(']')) {
            while (true) {
              if (!parse_change(s, out)) return CT_ERROR;
              if (s.consume(',')) continue;
              if (!s.consume(']')) return CT_ERROR;
              break;
            }
          }
        } else if (!skip_value(s)) {
          return CT_ERROR;
        }
      }
      if (s.consume(',')) continue;
      if (!s.consume('}')) return CT_ERROR;
      break;
    }
  }
  if (!is_edit || !saw_changes) goto opaque;
  *chg_count = out.n_chgs - *chg_start;
  return CT_EDIT;
opaque:
  out.n_chgs = c0; out.n_flds = f0; out.n_marks = k0; out.n_spans = s0;
  return CT_OPAQUE;
}

}  // namespace tree

}  // namespace

extern "C" {

void* ing_create(int32_t max_insert_len, int32_t prop_slots) {
  auto* e = new Encoder();
  e->max_insert_len = max_insert_len;
  e->prop_slots = prop_slots;
  return e;
}

void ing_destroy(void* h) { delete (Encoder*)h; }

int64_t ing_min_seq(void* h) { return ((Encoder*)h)->min_seq; }

const char* ing_last_error(void* h) { return ((Encoder*)h)->error.c_str(); }

// Encode newline-separated JSON messages.  Returns rows written, or
// -1 on parse/semantic error (see ing_last_error), or -(2+rows) when
// out_ops capacity was exhausted mid-stream (caller grows and retries; all
// encoder state updates are idempotent so a re-run is safe).
int32_t ing_encode(void* h, const char* data, int64_t len,
                   int32_t* out_ops, int32_t* out_payloads, int32_t max_rows) {
  Encoder& e = *(Encoder*)h;
  e.error.clear();
  Out out{out_ops, out_payloads, max_rows, e.max_insert_len};
  const char* p = data;
  const char* end = data + len;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    const char* line_end = nl ? nl : end;
    if (line_end > p) {
      Scanner s{p, line_end};
      if (!emit_line(e, s, out)) return -1;
      if (out.overflow) return -(2 + out.n);
    }
    p = nl ? nl + 1 : end;
  }
  return out.n;
}

// Tree wire decode (see the tree:: namespace header comment).
//
// Layouts (row-major):
//   out_msgs  int64[max_msgs, 14]: seq, ref, min_seq, rev, client_off,
//             client_len, sid_off, sid_len, chg_start, chg_count, status
//             (0 edits, 1 skip, 2 opaque), opq_off, opq_len, client_seq
//   out_chgs  int32[max_chgs, 3]: fld_start, fld_count, v_span
//   out_flds  int32[max_flds, 4]: key_span, mark_start, mark_count,
//             opaque_span (>=0: non-sequence field change JSON)
//   out_marks int32[max_marks, 5]: kind, a, b, c, payload_span
//   out_spans int64[max_spans, 2]: byte offset, byte length (into data)
//
// Returns the message count (counts for all five tables in out_counts),
// -1 on a malformed line (*err_line = its index; the caller falls back to
// the Python decode, which owns error semantics), or -2 when any output
// table filled (caller doubles capacities and re-runs; the decode is
// stateless so a re-run is safe).
int32_t ing_tree_decode(const char* data, int64_t len,
                        int64_t* out_msgs, int32_t max_msgs,
                        int32_t* out_chgs, int32_t max_chgs,
                        int32_t* out_flds, int32_t max_flds,
                        int32_t* out_marks, int32_t max_marks,
                        int64_t* out_spans, int32_t max_spans,
                        int32_t* out_counts, int32_t* err_line) {
  using namespace tree;
  TreeOut out{data, out_msgs, max_msgs, out_chgs, max_chgs,
              out_flds, max_flds, out_marks, max_marks,
              out_spans, max_spans};
  *err_line = -1;
  const char* p = data;
  const char* end = data + len;
  int32_t line_idx = -1;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', end - p);
    const char* line_end = nl ? nl : end;
    if (line_end > p) {
      line_idx++;
      if (out.n_msgs >= max_msgs) return -2;
      int64_t* m = out_msgs + (int64_t)out.n_msgs * MSG_FIELDS;
      for (int i = 0; i < MSG_FIELDS; i++) m[i] = 0;
      m[10] = ST_SKIP;
      Scanner s{p, line_end};
      bool is_op = false;
      const char* cstart = nullptr;
      const char* cend = nullptr;
      if (!s.consume('{')) { *err_line = line_idx; return -1; }
      if (!s.consume('}')) {
        while (true) {
          const char* kb; const char* ke;
          if (!span_string(s, &kb, &ke)) { *err_line = line_idx; return -1; }
          if (!s.consume(':')) { *err_line = line_idx; return -1; }
          size_t kl = ke - kb;
          bool ok = true;
          if (kl == 14 && memcmp(kb, "sequenceNumber", 14) == 0) {
            ok = parse_i64(s, &m[0]);
          } else if (kl == 23 &&
                     memcmp(kb, "referenceSequenceNumber", 23) == 0) {
            ok = parse_i64(s, &m[1]);
          } else if (kl == 21 &&
                     memcmp(kb, "minimumSequenceNumber", 21) == 0) {
            ok = parse_i64(s, &m[2]);
          } else if (kl == 4 && memcmp(kb, "type", 4) == 0) {
            const char* vb; const char* ve;
            ok = span_string(s, &vb, &ve);
            is_op = ok && (ve - vb == 2) && memcmp(vb, "op", 2) == 0;
          } else if (kl == 20 &&
                     memcmp(kb, "clientSequenceNumber", 20) == 0) {
            ok = parse_i64(s, &m[13]);
          } else if (kl == 8 && memcmp(kb, "clientId", 8) == 0) {
            const char* vb; const char* ve;
            ok = span_string(s, &vb, &ve);
            if (ok) { m[4] = vb - data; m[5] = ve - vb; }
          } else if (kl == 8 && memcmp(kb, "contents", 8) == 0) {
            s.skip_ws();
            cstart = s.p;
            ok = skip_value(s);
            cend = s.p;
          } else {
            ok = skip_value(s);
          }
          if (!ok) { *err_line = line_idx; return -1; }
          if (s.consume(',')) continue;
          if (s.consume('}')) break;
          *err_line = line_idx;
          return -1;
        }
      }
      if (is_op && cstart != nullptr) {
        Scanner cs{cstart, cend};
        int32_t chg_start = 0, chg_count = 0;
        ContentsResult r = parse_edit_contents(
            cs, out, &m[6], &m[7], &m[3], &chg_start, &chg_count);
        if (r == CT_ERROR) {
          if (out.overflow) return -2;  // table filled mid-parse: retry
          *err_line = line_idx;
          return -1;
        }
        if (r == CT_EDIT) {
          m[8] = chg_start;
          m[9] = chg_count;
          m[10] = ST_EDITS;
        } else {
          m[10] = ST_OPAQUE;
          m[11] = cstart - data;
          m[12] = cend - cstart;
        }
      }
      if (out.overflow) return -2;
      out.n_msgs++;
    }
    p = nl ? nl + 1 : end;
  }
  out_counts[0] = out.n_msgs;
  out_counts[1] = out.n_chgs;
  out_counts[2] = out.n_flds;
  out_counts[3] = out.n_marks;
  out_counts[4] = out.n_spans;
  return out.n_msgs;
}

// Export the property interning table: writes up to max_entries
// (prop_id, slot) pairs into out_props/out_slots and returns the entry
// count.  This is the checkpoint-fidelity seam — the host folds these
// REAL property ids into its own table before cutting a checkpoint of a
// native-mode document, so restored annotations round-trip prop ids
// instead of this encoder's private slot numbers.
int32_t ing_prop_table(void* h, int64_t* out_props, int32_t* out_slots,
                       int32_t max_entries) {
  Encoder& e = *(Encoder*)h;
  int32_t n = 0;
  for (const auto& kv : e.prop_slot) {
    if (n >= max_entries) break;
    out_props[n] = kv.first;
    out_slots[n] = kv.second;
    ++n;
  }
  return n;
}

}  // extern "C"
