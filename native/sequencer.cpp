// Native deli sequencer: the ordering-service hot loop as a C library.
//
// Reference parity: routerlicious deli's ticket() state machine
// (server/routerlicious/packages/lambdas/src/deli/lambda.ts:851 semantics,
// re-implemented): monotone sequence assignment, per-client clientSeq
// exactly-once validation, refSeq tracking, and MSN (minimum sequence
// number) computation over joined clients (clientSeqManager.ts) — the pure
// integer kernel the Python Sequencer wraps for tests and the pipeline
// runs in production form.
//
// C ABI for ctypes (no pybind11 in the image). All strings are
// NUL-terminated UTF-8. Thread-compatible (one state = one partition; the
// partition manager shards documents across states, so no locking here —
// same as deli's per-partition single-threaded consumption).

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

struct ClientEntry {
    int32_t short_id;
    int64_t client_seq;  // last accepted clientSeq (exactly-once)
    int64_t ref_seq;     // latest refSeq observed from this client
};

struct SequencerState {
    int64_t seq;
    int64_t min_seq;     // last computed MSN (monotone)
    int32_t next_short;
    std::map<std::string, ClientEntry> clients;

    int64_t compute_msn() const {
        // MSN = min over clients' refSeq; with no clients the window floor
        // rides the head (deli: msn tracks seq when the quorum is empty).
        if (clients.empty()) return seq;
        int64_t m = INT64_MAX;
        for (const auto& kv : clients)
            m = kv.second.ref_seq < m ? kv.second.ref_seq : m;
        return m;
    }

    void advance_msn() {
        int64_t m = compute_msn();
        if (m > min_seq) min_seq = m;
    }
};

}  // namespace

extern "C" {

// Nack codes mirror server/sequencer.py ticket() rules.
enum TicketStatus {
    TICKET_OK = 0,
    NACK_NOT_JOINED = 1,
    NACK_REFSEQ_BELOW_MSN = 2,
    NACK_REFSEQ_FUTURE = 3,
    NACK_CLIENTSEQ_ORDER = 4,
};

void* seq_create(int64_t starting_seq) {
    auto* s = new SequencerState();
    s->seq = starting_seq;
    s->min_seq = 0;
    s->next_short = 0;
    return s;
}

void seq_destroy(void* h) { delete static_cast<SequencerState*>(h); }

int64_t seq_current(void* h) { return static_cast<SequencerState*>(h)->seq; }
int64_t seq_min(void* h) {
    auto* s = static_cast<SequencerState*>(h);
    s->advance_msn();
    return s->min_seq;
}
int32_t seq_client_count(void* h) {
    return (int32_t) static_cast<SequencerState*>(h)->clients.size();
}

// Join: assigns the next short id, seq-stamps the join. Returns short id,
// with *out_seq = the join's sequence number, *out_min = MSN after join.
int32_t seq_join(void* h, const char* client_id, int64_t* out_seq, int64_t* out_min) {
    auto* s = static_cast<SequencerState*>(h);
    if (s->clients.count(client_id)) return -1;  // duplicate join
    ClientEntry e;
    e.short_id = s->next_short++;
    e.client_seq = 0;
    // The join message is stamped with the joiner's floor at the PRE-join
    // head; only after stamping does the joiner's window start at its own
    // join seq (matches server/sequencer.py join()).
    e.ref_seq = s->seq;
    s->clients[client_id] = e;
    s->seq += 1;
    s->advance_msn();
    *out_seq = s->seq;
    *out_min = s->min_seq;
    s->clients[client_id].ref_seq = s->seq;
    return e.short_id;
}

// Leave: seq-stamps the leave, drops the client from MSN computation.
// Returns the leaver's short id on success, -1 if unknown. The leave
// message is stamped exactly like the Python oracle's: clientSeq is the
// client's next clientSeq (last accepted + 1) and refSeq is the client's
// last observed refSeq — both reported via out params so the wrapper can
// persist a bit-identical op log.
int32_t seq_leave(void* h, const char* client_id, int64_t* out_seq, int64_t* out_min,
                  int64_t* out_client_seq, int64_t* out_ref_seq) {
    auto* s = static_cast<SequencerState*>(h);
    auto it = s->clients.find(client_id);
    if (it == s->clients.end()) return -1;
    int32_t short_id = it->second.short_id;
    *out_client_seq = it->second.client_seq + 1;
    *out_ref_seq = it->second.ref_seq;
    s->clients.erase(it);
    s->seq += 1;
    s->advance_msn();
    *out_seq = s->seq;
    *out_min = s->min_seq;
    return short_id;
}

// The hot loop: validate + stamp one op.
int32_t seq_ticket(void* h, const char* client_id, int64_t client_seq,
                   int64_t ref_seq, int64_t* out_seq, int64_t* out_min,
                   int32_t* out_short) {
    auto* s = static_cast<SequencerState*>(h);
    auto it = s->clients.find(client_id);
    if (it == s->clients.end()) return NACK_NOT_JOINED;
    if (ref_seq < s->min_seq) return NACK_REFSEQ_BELOW_MSN;
    if (ref_seq > s->seq) return NACK_REFSEQ_FUTURE;
    if (client_seq != it->second.client_seq + 1) return NACK_CLIENTSEQ_ORDER;
    it->second.client_seq = client_seq;
    if (ref_seq > it->second.ref_seq) it->second.ref_seq = ref_seq;
    s->seq += 1;
    s->advance_msn();
    *out_seq = s->seq;
    *out_min = s->min_seq;
    *out_short = it->second.short_id;
    return TICKET_OK;
}

// Service-minted message (summary acks): stamp without a client.
int64_t seq_mint_service(void* h, int64_t* out_min) {
    auto* s = static_cast<SequencerState*>(h);
    s->seq += 1;
    s->advance_msn();  // empty quorum: the floor rides the head
    *out_min = s->min_seq;
    return s->seq;
}

// ---------------------------------------------------------------- checkpoint
// Flat binary checkpoint (deli checkpointManager analog): the full integer
// state keyed by the caller's log offset. Layout:
//   int64 seq, int64 min_seq, int32 next_short, int32 n_clients,
//   then per client: int32 short, int64 client_seq, int64 ref_seq,
//                    int32 name_len, bytes name.
int64_t seq_checkpoint(void* h, uint8_t* buf, int64_t cap) {
    auto* s = static_cast<SequencerState*>(h);
    std::vector<uint8_t> out;
    auto put = [&out](const void* p, size_t n) {
        const uint8_t* b = static_cast<const uint8_t*>(p);
        out.insert(out.end(), b, b + n);
    };
    int32_t n = (int32_t)s->clients.size();
    put(&s->seq, 8); put(&s->min_seq, 8); put(&s->next_short, 4); put(&n, 4);
    for (const auto& kv : s->clients) {
        put(&kv.second.short_id, 4);
        put(&kv.second.client_seq, 8);
        put(&kv.second.ref_seq, 8);
        int32_t len = (int32_t)kv.first.size();
        put(&len, 4);
        put(kv.first.data(), len);
    }
    if ((int64_t)out.size() <= cap && buf) std::memcpy(buf, out.data(), out.size());
    return (int64_t)out.size();
}

void* seq_restore(const uint8_t* buf, int64_t len) {
    auto* s = new SequencerState();
    int64_t off = 0;
    bool bad = false;
    // Every read is validated against len so a truncated or corrupt
    // checkpoint yields nullptr instead of out-of-bounds reads.
    auto get = [&](void* p, size_t n) {
        if (bad || off + (int64_t)n > len) { bad = true; return; }
        std::memcpy(p, buf + off, n);
        off += (int64_t)n;
    };
    int32_t n = 0;
    get(&s->seq, 8); get(&s->min_seq, 8); get(&s->next_short, 4); get(&n, 4);
    if (bad || n < 0) { delete s; return nullptr; }
    for (int32_t i = 0; i < n; i++) {
        ClientEntry e; int32_t slen = 0;
        get(&e.short_id, 4); get(&e.client_seq, 8); get(&e.ref_seq, 8); get(&slen, 4);
        if (bad || slen < 0 || off + (int64_t)slen > len) { delete s; return nullptr; }
        std::string name(reinterpret_cast<const char*>(buf + off), (size_t)slen);
        off += slen;
        s->clients[name] = e;
    }
    return s;
}

}  // extern "C"
