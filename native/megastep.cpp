// Native CPU dispatch plane: the merge-tree megastep as tight row loops.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -o libtpumegastep.so megastep.cpp
//
// This is a transliteration of ops/mergetree_kernel.py's single-lane op
// branches (_do_insert/_do_remove/_do_annotate/_do_ack/_do_obliterate +
// compact/set_min_seq) over the SAME int32 state columns, applied as the
// [K, D, B] op ring apply_megastep dispatches.  The contract is byte
// identity with the lax oracle over the FULL arrays — including the
// shift remnants _open_slot leaves in padding slots and the _SEG_FILL
// values compaction writes there — so the conformance fuzz
// (tests/test_dispatch_backends.py) can compare raw columns, not just
// the canonical_doc live prefix.
//
// Two deliberate semantic notes, both proven no-ops for identity:
//  * The lax kernel gates the insert-time swallow analysis on a fleet
//    -global per-slice scalar (any doc's ob table nonempty | any op in
//    the slice is an OBLITERATE).  The full analysis on an EMPTY table
//    yields exactly the no-swallow result, so these loops always run it.
//  * Padding slots only ever hold shift remnants of previously-live
//    values or _SEG_FILL; a per-doc high-water mark (``hw``) bounds the
//    suffix that can differ from fill, so shifts memmove [k, hw) instead
//    of [k, S) — bitwise identical, not an approximation.
//
// Column pointer table (all int32, row-major, doc axis leading):
//   idx  field        shape
//    0   text         [D, T]
//    1   text_end     [D]
//    2   nseg         [D]
//    3   seg_start    [D, S]
//    4   seg_len      [D, S]
//    5   ins_key      [D, S]
//    6   ins_client   [D, S]
//    7   seg_uid      [D, S]
//    8   seg_obpre    [D, S]
//    9   rem_keys     [R, D, S]   (tuple fields stacked on a leading axis)
//   10   rem_clients  [R, D, S]
//   11   prop_keys    [P, D, S]
//   12   prop_vals    [P, D, S]
//   13   uid_next     [D]
//   14   ob_key       [D, OB]
//   15   ob_client    [D, OB]
//   16   ob_start_uid [D, OB]
//   17   ob_end_uid   [D, OB]
//   18   ob_start_side[D, OB]
//   19   ob_end_side  [D, OB]
//   20   ob_ref_seq   [D, OB]
//   21   min_seq      [D]
//   22   error        [D]
//
// dims: [D, T, S, R, P, OB, K, B, L]
// ops:  int32[K, D, B, 8]; payloads: int32[K, D, B, L].

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t LOCAL_BASE = INT32_C(1) << 30;
constexpr int32_t NO_REMOVE = INT32_MAX;  // (1 << 31) - 1

constexpr int32_t ERR_SEG_OVERFLOW = 1;
constexpr int32_t ERR_TEXT_OVERFLOW = 2;
constexpr int32_t ERR_REM_OVERFLOW = 4;
constexpr int32_t ERR_POS_RANGE = 8;
constexpr int32_t ERR_OB_OVERFLOW = 16;

enum OpKind : int32_t {
  NOOP = 0,
  INSERT = 1,
  REMOVE = 2,
  ANNOTATE = 3,
  ACK = 4,
  OBLITERATE = 5,
};

constexpr int32_t SIDE_BEFORE = 0;
constexpr int32_t SIDE_AFTER = 1;

constexpr int MAX_TUPLE = 16;   // R / P slots supported
constexpr int MAX_OB = 64;      // obliterate window slots supported

// int32 wraparound arithmetic (jnp semantics; signed overflow is UB in
// C++, so route through uint32).
inline int32_t add32(int32_t a, int32_t b) {
  return static_cast<int32_t>(static_cast<uint32_t>(a) +
                              static_cast<uint32_t>(b));
}
inline int32_t sub32(int32_t a, int32_t b) {
  return static_cast<int32_t>(static_cast<uint32_t>(a) -
                              static_cast<uint32_t>(b));
}

// _SEG_FILL (mergetree_kernel._SEG_FILL): the padding-slot conventions.
struct SegFill {
  int32_t seg_start = 0, seg_len = 0, ins_key = 0, ins_client = -1;
  int32_t seg_uid = -1, seg_obpre = -1;
  int32_t rem_keys = NO_REMOVE, rem_clients = -1;
  int32_t prop_keys = -1, prop_vals = 0;
};
constexpr SegFill FILL{};

// One document's state columns (raw pointers into the fleet arrays).
struct Doc {
  int32_t* text;
  int32_t* text_end;
  int32_t* nseg;
  int32_t* seg_start;
  int32_t* seg_len;
  int32_t* ins_key;
  int32_t* ins_client;
  int32_t* seg_uid;
  int32_t* seg_obpre;
  int32_t* rem_keys[MAX_TUPLE];
  int32_t* rem_clients[MAX_TUPLE];
  int32_t* prop_keys[MAX_TUPLE];
  int32_t* prop_vals[MAX_TUPLE];
  int32_t* uid_next;
  int32_t* ob_key;
  int32_t* ob_client;
  int32_t* ob_start_uid;
  int32_t* ob_end_uid;
  int32_t* ob_start_side;
  int32_t* ob_end_side;
  int32_t* ob_ref_seq;
  int32_t* min_seq;
  int32_t* error;
  int T, S, R, P, OB;
  int hw;  // high-water: slots >= hw hold exact _SEG_FILL values
};

// Scratch reused across ops (sized once per call).
struct Scratch {
  std::vector<uint8_t> vis;
  std::vector<int32_t> vlen;
  std::vector<int32_t> excl;
  std::vector<uint8_t> mark;
  void size(int S) {
    vis.resize(S);
    vlen.resize(S);
    excl.resize(S);
    mark.resize(S);
  }
};

// _visible + _vis_lengths: perspective mask / visible prefix, live slots
// only (every lax consumer of these masks ANDs with _alive).  Returns
// the visible total.
int32_t compute_vis(const Doc& d, Scratch& sc, int32_t ref_seq,
                    int32_t client) {
  const int n = *d.nseg;
  int32_t run = 0;
  for (int i = 0; i < n; ++i) {
    bool ins_occ = d.ins_key[i] <= ref_seq || d.ins_client[i] == client;
    bool rem_occ = false;
    for (int r = 0; r < d.R; ++r) {
      if (d.rem_keys[r][i] <= ref_seq || d.rem_clients[r][i] == client) {
        rem_occ = true;
        break;
      }
    }
    bool v = ins_occ && !rem_occ;
    sc.vis[i] = v;
    int32_t vl = v ? d.seg_len[i] : 0;
    sc.vlen[i] = vl;
    sc.excl[i] = run;
    run = add32(run, vl);
  }
  return run;
}

// _tiebreak: >= keys win (grouped batches / back-to-front insert chunks).
inline bool tiebreak(const Doc& d, int i, int32_t op_key) {
  int32_t rem0 = NO_REMOVE;
  for (int r = 0; r < d.R; ++r)
    if (d.rem_keys[r][i] < rem0) rem0 = d.rem_keys[r][i];
  return op_key >= d.ins_key[i] || (rem0 < LOCAL_BASE && rem0 > op_key);
}

struct NewSeg {
  int32_t seg_start, seg_len, ins_key, ins_client, seg_uid, seg_obpre;
  int32_t rem_keys[MAX_TUPLE], rem_clients[MAX_TUPLE];
  int32_t prop_keys[MAX_TUPLE], prop_vals[MAX_TUPLE];
};

// One column's slot-open: shift [k, hw) right one, write newval at k.
// Slots >= hw are fill, and shifting fill over fill is the identity, so
// the bounded memmove reproduces lax _shift_right over the full array.
inline void shift_col(int32_t* a, int k, int hw, int S, int32_t newval) {
  int top = hw < S - 1 ? hw : S - 1;
  if (top > k) std::memmove(a + k + 1, a + k, (top - k) * sizeof(int32_t));
  a[k] = newval;
}

// _open_slot: conditionally shift every per-segment array right at k and
// write the new segment.  Returns whether the slot actually opened
// (capacity overflow latches ERR_SEG_OVERFLOW and cancels the shift).
bool open_slot(Doc& d, int k, bool doit, const NewSeg& ns) {
  if (!doit) return false;
  if (*d.nseg >= d.S) {
    *d.error |= ERR_SEG_OVERFLOW;
    return false;
  }
  const int hw = d.hw, S = d.S;
  shift_col(d.seg_start, k, hw, S, ns.seg_start);
  shift_col(d.seg_len, k, hw, S, ns.seg_len);
  shift_col(d.ins_key, k, hw, S, ns.ins_key);
  shift_col(d.ins_client, k, hw, S, ns.ins_client);
  shift_col(d.seg_uid, k, hw, S, ns.seg_uid);
  shift_col(d.seg_obpre, k, hw, S, ns.seg_obpre);
  for (int r = 0; r < d.R; ++r) {
    shift_col(d.rem_keys[r], k, hw, S, ns.rem_keys[r]);
    shift_col(d.rem_clients[r], k, hw, S, ns.rem_clients[r]);
  }
  for (int p = 0; p < d.P; ++p) {
    shift_col(d.prop_keys[p], k, hw, S, ns.prop_keys[p]);
    shift_col(d.prop_vals[p], k, hw, S, ns.prop_vals[p]);
  }
  *d.nseg += 1;
  int nhw = hw + 1;
  if (k + 1 > nhw) nhw = k + 1;
  d.hw = nhw < S ? nhw : S;
  return true;
}

// _ensure_boundary: split the segment strictly containing pos; After-side
// obliterate anchors on the split segment follow the right half's uid.
void ensure_boundary(Doc& d, Scratch& sc, int32_t pos, int32_t ref_seq,
                     int32_t client) {
  compute_vis(d, sc, ref_seq, client);
  const int n = *d.nseg;
  int k = -1;
  for (int i = 0; i < n; ++i) {
    if (sc.vis[i] && sc.excl[i] < pos &&
        pos < add32(sc.excl[i], sc.vlen[i])) {
      k = i;
      break;
    }
  }
  if (k < 0) return;
  const int32_t off = sub32(pos, sc.excl[k]);
  const int32_t old_uid = d.seg_uid[k];
  const int32_t right_uid = *d.uid_next;
  NewSeg right{};
  right.seg_start = add32(d.seg_start[k], off);
  right.seg_len = sub32(d.seg_len[k], off);
  right.ins_key = d.ins_key[k];
  right.ins_client = d.ins_client[k];
  right.seg_uid = right_uid;
  right.seg_obpre = d.seg_obpre[k];
  for (int r = 0; r < d.R; ++r) {
    right.rem_keys[r] = d.rem_keys[r][k];
    right.rem_clients[r] = d.rem_clients[r][k];
  }
  for (int p = 0; p < d.P; ++p) {
    right.prop_keys[p] = d.prop_keys[p][k];
    right.prop_vals[p] = d.prop_vals[p][k];
  }
  open_slot(d, k + 1, true, right);
  // The lax kernel trims the left half, bumps uid_next and moves anchors
  // whenever the split was REQUESTED — even when _open_slot's capacity
  // check cancelled the right half (error latched above).
  d.seg_len[k] = off;
  *d.uid_next = add32(*d.uid_next, 1);
  for (int j = 0; j < d.OB; ++j) {
    if (d.ob_start_uid[j] == old_uid && d.ob_start_side[j] == SIDE_AFTER)
      d.ob_start_uid[j] = right_uid;
    if (d.ob_end_uid[j] == old_uid && d.ob_end_side[j] == SIDE_AFTER)
      d.ob_end_uid[j] = right_uid;
  }
}

// _obliterate_swallow (via _ob_anchor_indices): the insert-time rule.
// Writes the new segment's R remove slots (sorted ascending, NO_REMOVE
// padded) + obpre; returns candidate-overflow.
bool obliterate_swallow(const Doc& d, int k, int32_t key, int32_t client,
                        int32_t ref_seq, NewSeg& ns) {
  const int OB = d.OB, n = *d.nseg;
  bool concurrent[MAX_OB], others[MAX_OB], acked_conc[MAX_OB],
      unacked_conc[MAX_OB];
  bool any_conc = false, any_others = false, any_acked = false;
  // argmax/argmin with lax first-occurrence tie-breaking, defaults over
  // the masked fills exactly as jnp.where produces them.
  int newest_i = 0, na_i = 0, ou_i = 0;
  int32_t newest_val = INT32_MIN, na_val = INT32_MIN, ou_val = INT32_MAX;
  for (int j = 0; j < OB; ++j) {
    bool used = d.ob_key[j] >= 0;
    int s_idx = 0, e_idx = 0;
    bool s_found = false, e_found = false;
    if (used) {
      for (int i = 0; i < n; ++i)
        if (d.seg_uid[i] == d.ob_start_uid[j]) {
          s_idx = i;
          s_found = true;
          break;
        }
      for (int i = 0; i < n; ++i)
        if (d.seg_uid[i] == d.ob_end_uid[j]) {
          e_idx = i;
          e_found = true;
          break;
        }
    }
    bool inside = used && s_found && e_found && s_idx < k && e_idx >= k;
    concurrent[j] = inside && d.ob_key[j] > ref_seq;
    others[j] = concurrent[j] && d.ob_client[j] != client;
    acked_conc[j] = concurrent[j] && d.ob_key[j] < LOCAL_BASE;
    unacked_conc[j] = concurrent[j] && d.ob_key[j] >= LOCAL_BASE;
    any_conc = any_conc || concurrent[j];
    any_others = any_others || others[j];
    any_acked = any_acked || acked_conc[j];
    int32_t ck = concurrent[j] ? d.ob_key[j] : -1;
    if (ck > newest_val) {
      newest_val = ck;
      newest_i = j;
    }
    int32_t ak = acked_conc[j] ? d.ob_key[j] : -1;
    if (ak > na_val) {
      na_val = ak;
      na_i = j;
    }
    int32_t uk = unacked_conc[j] ? d.ob_key[j] : NO_REMOVE;
    if (uk < ou_val) {
      ou_val = uk;
      ou_i = j;
    }
  }
  int32_t newest_key = concurrent[newest_i] ? d.ob_key[newest_i] : -1;
  int32_t newest_client = d.ob_client[newest_i];
  int32_t na_key = acked_conc[na_i] ? d.ob_key[na_i] : -1;
  int32_t na_client = d.ob_client[na_i];
  bool mark = any_others && any_conc && newest_client != client;
  bool include_acked =
      !any_acked || na_key == newest_key || na_client != client;
  int32_t ckeys[MAX_OB];
  for (int j = 0; j < OB; ++j) {
    bool cand = mark && ((others[j] && acked_conc[j] && include_acked) ||
                         (unacked_conc[j] && j == ou_i));
    ckeys[j] = cand ? d.ob_key[j] : NO_REMOVE;
  }
  // Extract the R smallest candidate stamps ascending (first-min ties).
  for (int r = 0; r < d.R; ++r) {
    int mi = 0;
    for (int j = 1; j < OB; ++j)
      if (ckeys[j] < ckeys[mi]) mi = j;
    int32_t kk = OB > 0 ? ckeys[mi] : NO_REMOVE;
    ns.rem_keys[r] = kk;
    ns.rem_clients[r] = kk < NO_REMOVE ? d.ob_client[mi] : -1;
    if (OB > 0) ckeys[mi] = NO_REMOVE;
  }
  bool overflow = false;
  for (int j = 0; j < OB; ++j)
    if (ckeys[j] < NO_REMOVE) overflow = true;
  ns.seg_obpre = any_conc ? newest_key : -1;
  return overflow;
}

// _do_insert.
void do_insert(Doc& d, Scratch& sc, const int32_t* op,
               const int32_t* payload, int L) {
  const int32_t key = op[1], client = op[2], ref_seq = op[3], pos = op[4];
  const int32_t text_len = op[6];
  ensure_boundary(d, sc, pos, ref_seq, client);
  const int32_t total = compute_vis(d, sc, ref_seq, client);
  const int n = *d.nseg;
  int k = n;
  for (int i = 0; i < n; ++i) {
    if (sc.excl[i] >= pos && (sc.vlen[i] > 0 || tiebreak(d, i, key))) {
      k = i;
      break;
    }
  }
  const bool text_over = add32(*d.text_end, text_len) > d.T;
  if (!text_over) {
    // Masked scatter with mode="drop": at most L payload entries land,
    // text_end still advances by text_len below (lax parity).
    int32_t lim = text_len < L ? text_len : L;
    for (int32_t t = 0; t < lim; ++t) {
      int32_t dst = add32(*d.text_end, t);
      if (dst >= 0 && dst < d.T) d.text[dst] = payload[t];
    }
  }
  NewSeg ns{};
  ns.seg_start = *d.text_end;
  ns.seg_len = text_len;
  ns.ins_key = key;
  ns.ins_client = client;
  ns.seg_uid = *d.uid_next;
  // Always the full analysis: on an empty ob table it reduces exactly to
  // _no_obliterate_swallow, which is how the lax per-slice gate stays a
  // pure optimization (see module comment).
  bool rem_over = obliterate_swallow(d, k, key, client, ref_seq, ns);
  for (int p = 0; p < d.P; ++p) {
    ns.prop_keys[p] = -1;
    ns.prop_vals[p] = 0;
  }
  const bool ok = !text_over && pos <= total;
  open_slot(d, k, ok, ns);  // seg overflow latches inside, uid/text still move
  if (ok) {
    *d.text_end = add32(*d.text_end, text_len);
    *d.uid_next = add32(*d.uid_next, 1);
  }
  if (text_over) *d.error |= ERR_TEXT_OVERFLOW;
  if (pos > total) *d.error |= ERR_POS_RANGE;
  if (ok && rem_over) *d.error |= ERR_REM_OVERFLOW;
}

// _mark_range: split both boundaries, mark visible fully-inside segments.
void mark_range(Doc& d, Scratch& sc, const int32_t* op) {
  const int32_t client = op[2], ref_seq = op[3], pos1 = op[4], pos2 = op[5];
  ensure_boundary(d, sc, pos1, ref_seq, client);
  ensure_boundary(d, sc, pos2, ref_seq, client);
  const int32_t total = compute_vis(d, sc, ref_seq, client);
  const int n = *d.nseg;
  for (int i = 0; i < n; ++i) {
    sc.mark[i] = sc.vis[i] && sc.excl[i] >= pos1 &&
                 add32(sc.excl[i], sc.vlen[i]) <= pos2 && sc.vlen[i] > 0;
  }
  if (pos2 > total) *d.error |= ERR_POS_RANGE;
}

// _splice_remove_stamp over sc.mark[0, nseg).
bool splice_remove_stamp(Doc& d, const Scratch& sc, int32_t key,
                         int32_t client) {
  bool overflow = false;
  const int n = *d.nseg;
  for (int i = 0; i < n; ++i) {
    if (!sc.mark[i]) continue;
    bool placed = false;
    for (int r = 0; r < d.R; ++r) {
      if (d.rem_keys[r][i] == NO_REMOVE) {
        d.rem_keys[r][i] = key;
        d.rem_clients[r][i] = client;
        placed = true;
        break;
      }
    }
    if (!placed) overflow = true;
  }
  return overflow;
}

void do_remove(Doc& d, Scratch& sc, const int32_t* op) {
  mark_range(d, sc, op);
  if (splice_remove_stamp(d, sc, op[1], op[2])) *d.error |= ERR_REM_OVERFLOW;
}

// _do_annotate: LWW by stamp key, >= ties to the later-applied op.
void do_annotate(Doc& d, Scratch& sc, const int32_t* op) {
  mark_range(d, sc, op);
  const int32_t key = op[1], prop_slot = op[6], value = op[7];
  if (prop_slot < 0 || prop_slot >= d.P) return;
  const int n = *d.nseg;
  int32_t* pk = d.prop_keys[prop_slot];
  int32_t* pv = d.prop_vals[prop_slot];
  for (int i = 0; i < n; ++i) {
    if (sc.mark[i] && key >= pk[i]) {
      pk[i] = key;
      pv[i] = value;
    }
  }
}

// _do_obliterate: sided mark + window-table record.
void do_obliterate(Doc& d, Scratch& sc, const int32_t* op) {
  const int32_t key = op[1], client = op[2], ref_seq = op[3];
  const int32_t pos1 = op[4], pos2 = op[5], side1 = op[6], side2 = op[7];
  const int32_t start_pos = add32(pos1, side1);
  const int32_t end_pos = add32(pos2, side2);
  int32_t total = compute_vis(d, sc, ref_seq, client);
  const bool valid =
      0 <= pos1 && pos1 <= pos2 && pos2 < total && start_pos <= end_pos;
  // Invalid ops split at 0 in the lax kernel — a strict-interior test
  // can never hit pos 0, so only the valid path splits.
  if (valid) {
    ensure_boundary(d, sc, start_pos, ref_seq, client);
    ensure_boundary(d, sc, end_pos, ref_seq, client);
  }
  compute_vis(d, sc, ref_seq, client);
  const int n = *d.nseg;
  int s_idx = n, e_idx = n;
  for (int i = 0; i < n; ++i)
    if (sc.vis[i] && sc.excl[i] <= pos1 &&
        pos1 < add32(sc.excl[i], sc.vlen[i])) {
      s_idx = i;
      break;
    }
  for (int i = 0; i < n; ++i)
    if (sc.vis[i] && sc.excl[i] <= pos2 &&
        pos2 < add32(sc.excl[i], sc.vlen[i])) {
      e_idx = i;
      break;
    }
  const int32_t lo = s_idx + (side1 == SIDE_AFTER ? 1 : 0);
  const int32_t hi = e_idx - (side2 == SIDE_BEFORE ? 1 : 0);
  const bool local_op = key >= LOCAL_BASE;
  for (int i = 0; i < n; ++i) {
    // _obliterate_visit, element-wise.
    int32_t rem_min = NO_REMOVE;
    bool same_client_stamp = false;
    for (int r = 0; r < d.R; ++r) {
      int32_t rk = d.rem_keys[r][i];
      if (rk < rem_min) rem_min = rk;
      if (d.rem_clients[r][i] == client && rk > d.ins_key[i] && rk <= key)
        same_client_stamp = true;
    }
    bool has_acked_rem = rem_min < LOCAL_BASE;
    bool is_local_ins = d.ins_key[i] >= LOCAL_BASE;
    bool ins_conc =
        !(d.ins_key[i] <= ref_seq || d.ins_client[i] == client);
    bool visit = local_op
                     ? static_cast<bool>(sc.vis[i])
                     : (!has_acked_rem || sc.vis[i] || is_local_ins ||
                        (ins_conc && !same_client_stamp));
    bool skip = is_local_ins && d.seg_obpre[i] >= LOCAL_BASE && !local_op;
    sc.mark[i] = valid && i >= lo && i <= hi && visit && !skip;
  }
  bool rem_over = splice_remove_stamp(d, sc, key, client);
  int slot = 0;
  bool has_free = false;
  for (int j = 0; j < d.OB; ++j)
    if (d.ob_key[j] < 0) {
      slot = j;
      has_free = true;
      break;
    }
  if (valid && has_free) {
    // Anchor reads clamp like jnp out-of-bounds gathers (s_idx/e_idx
    // default to nseg, which can equal S on a full doc).
    int si = s_idx < d.S ? s_idx : d.S - 1;
    int ei = e_idx < d.S ? e_idx : d.S - 1;
    d.ob_key[slot] = key;
    d.ob_client[slot] = client;
    d.ob_start_uid[slot] = d.seg_uid[si];
    d.ob_end_uid[slot] = d.seg_uid[ei];
    d.ob_start_side[slot] = side1;
    d.ob_end_side[slot] = side2;
    d.ob_ref_seq[slot] = ref_seq;
  }
  if (!valid) *d.error |= ERR_POS_RANGE;
  if (valid && !has_free) *d.error |= ERR_OB_OVERFLOW;
  if (rem_over) *d.error |= ERR_REM_OVERFLOW;
}

// _do_ack: pending localSeq stamps -> acked seq.  Scans [0, hw): the lax
// where() covers the full arrays, but slots >= hw hold exact fill values
// (0 / NO_REMOVE / -1), none of which can equal a local key (>= 2^30,
// < NO_REMOVE), so the bounded scan is identical.
void do_ack(Doc& d, const int32_t* op) {
  const int32_t new_client = op[2], new_ref = op[3];
  const int32_t local_seq = op[6], seq = op[7];
  const int32_t local_key = add32(LOCAL_BASE, local_seq);
  const bool rw_c = new_client >= 0;
  const int hw = d.hw;
  for (int i = 0; i < hw; ++i) {
    if (d.ins_key[i] == local_key) {
      d.ins_key[i] = seq;
      if (rw_c) d.ins_client[i] = new_client;
    }
    for (int r = 0; r < d.R; ++r) {
      if (d.rem_keys[r][i] == local_key) {
        d.rem_keys[r][i] = seq;
        if (rw_c) d.rem_clients[r][i] = new_client;
      }
    }
    for (int p = 0; p < d.P; ++p)
      if (d.prop_keys[p][i] == local_key) d.prop_keys[p][i] = seq;
    if (d.seg_obpre[i] == local_key) d.seg_obpre[i] = seq;
  }
  for (int j = 0; j < d.OB; ++j) {
    if (d.ob_key[j] == local_key) {
      d.ob_key[j] = seq;
      if (rw_c) d.ob_client[j] = new_client;
      if (new_ref >= 0) d.ob_ref_seq[j] = new_ref;
    }
  }
}

void apply_op(Doc& d, Scratch& sc, const int32_t* op, const int32_t* payload,
              int L) {
  int32_t kind = op[0];
  if (kind < 0) kind = 0;          // lax.switch clamps
  if (kind > OBLITERATE) kind = OBLITERATE;
  switch (kind) {
    case NOOP:
      break;
    case INSERT:
      do_insert(d, sc, op, payload, L);
      break;
    case REMOVE:
      do_remove(d, sc, op);
      break;
    case ANNOTATE:
      do_annotate(d, sc, op);
      break;
    case ACK:
      do_ack(d, op);
      break;
    case OBLITERATE:
      do_obliterate(d, sc, op);
      break;
  }
}

// set_min_seq + compact (zamboni), per doc: evict segments whose winning
// remove is acked at or below min_seq, keep obliterate anchors, write
// _SEG_FILL into every vacated slot (the lax gather fills [n_keep, S)).
void compact_doc(Doc& d, int32_t new_min_arg) {
  int32_t new_min = *d.min_seq > new_min_arg ? *d.min_seq : new_min_arg;
  *d.min_seq = new_min;
  for (int j = 0; j < d.OB; ++j) {
    int32_t k = d.ob_key[j];
    if (k >= 0 && k < LOCAL_BASE && k <= new_min) d.ob_key[j] = -1;
  }
  const int n = *d.nseg;
  int w = 0;
  for (int i = 0; i < n; ++i) {
    int32_t rem0 = NO_REMOVE;
    for (int r = 0; r < d.R; ++r)
      if (d.rem_keys[r][i] < rem0) rem0 = d.rem_keys[r][i];
    bool dead = rem0 < LOCAL_BASE && rem0 <= new_min;
    bool anchored = false;
    if (dead) {
      for (int j = 0; j < d.OB; ++j) {
        if (d.ob_key[j] >= 0 && (d.seg_uid[i] == d.ob_start_uid[j] ||
                                 d.seg_uid[i] == d.ob_end_uid[j])) {
          anchored = true;
          break;
        }
      }
    }
    if (dead && !anchored) continue;
    if (w != i) {
      d.seg_start[w] = d.seg_start[i];
      d.seg_len[w] = d.seg_len[i];
      d.ins_key[w] = d.ins_key[i];
      d.ins_client[w] = d.ins_client[i];
      d.seg_uid[w] = d.seg_uid[i];
      d.seg_obpre[w] = d.seg_obpre[i];
      for (int r = 0; r < d.R; ++r) {
        d.rem_keys[r][w] = d.rem_keys[r][i];
        d.rem_clients[r][w] = d.rem_clients[r][i];
      }
      for (int p = 0; p < d.P; ++p) {
        d.prop_keys[p][w] = d.prop_keys[p][i];
        d.prop_vals[p][w] = d.prop_vals[p][i];
      }
    }
    ++w;
  }
  for (int i = w; i < d.hw; ++i) {
    d.seg_start[i] = FILL.seg_start;
    d.seg_len[i] = FILL.seg_len;
    d.ins_key[i] = FILL.ins_key;
    d.ins_client[i] = FILL.ins_client;
    d.seg_uid[i] = FILL.seg_uid;
    d.seg_obpre[i] = FILL.seg_obpre;
    for (int r = 0; r < d.R; ++r) {
      d.rem_keys[r][i] = FILL.rem_keys;
      d.rem_clients[r][i] = FILL.rem_clients;
    }
    for (int p = 0; p < d.P; ++p) {
      d.prop_keys[p][i] = FILL.prop_keys;
      d.prop_vals[p][i] = FILL.prop_vals;
    }
  }
  *d.nseg = w;
  d.hw = w;
}

// Bind one doc's column pointers from the table + compute its high-water
// mark (first index from the top whose slot differs from _SEG_FILL).
bool bind_doc(Doc& d, const int64_t* cols, const int32_t* dims, int didx) {
  const int D = dims[0], T = dims[1], S = dims[2], R = dims[3], P = dims[4],
            OB = dims[5];
  if (R > MAX_TUPLE || P > MAX_TUPLE || OB > MAX_OB) return false;
  (void)D;
  auto p32 = [&](int c) { return reinterpret_cast<int32_t*>(cols[c]); };
  d.T = T;
  d.S = S;
  d.R = R;
  d.P = P;
  d.OB = OB;
  d.text = p32(0) + static_cast<int64_t>(didx) * T;
  d.text_end = p32(1) + didx;
  d.nseg = p32(2) + didx;
  d.seg_start = p32(3) + static_cast<int64_t>(didx) * S;
  d.seg_len = p32(4) + static_cast<int64_t>(didx) * S;
  d.ins_key = p32(5) + static_cast<int64_t>(didx) * S;
  d.ins_client = p32(6) + static_cast<int64_t>(didx) * S;
  d.seg_uid = p32(7) + static_cast<int64_t>(didx) * S;
  d.seg_obpre = p32(8) + static_cast<int64_t>(didx) * S;
  for (int r = 0; r < R; ++r) {
    d.rem_keys[r] = p32(9) + (static_cast<int64_t>(r) * dims[0] + didx) * S;
    d.rem_clients[r] =
        p32(10) + (static_cast<int64_t>(r) * dims[0] + didx) * S;
  }
  for (int p = 0; p < P; ++p) {
    d.prop_keys[p] = p32(11) + (static_cast<int64_t>(p) * dims[0] + didx) * S;
    d.prop_vals[p] = p32(12) + (static_cast<int64_t>(p) * dims[0] + didx) * S;
  }
  d.uid_next = p32(13) + didx;
  d.ob_key = p32(14) + static_cast<int64_t>(didx) * OB;
  d.ob_client = p32(15) + static_cast<int64_t>(didx) * OB;
  d.ob_start_uid = p32(16) + static_cast<int64_t>(didx) * OB;
  d.ob_end_uid = p32(17) + static_cast<int64_t>(didx) * OB;
  d.ob_start_side = p32(18) + static_cast<int64_t>(didx) * OB;
  d.ob_end_side = p32(19) + static_cast<int64_t>(didx) * OB;
  d.ob_ref_seq = p32(20) + static_cast<int64_t>(didx) * OB;
  d.min_seq = p32(21) + didx;
  d.error = p32(22) + didx;
  int hw = S;
  while (hw > 0) {
    const int i = hw - 1;
    bool fill = d.seg_start[i] == FILL.seg_start &&
                d.seg_len[i] == FILL.seg_len &&
                d.ins_key[i] == FILL.ins_key &&
                d.ins_client[i] == FILL.ins_client &&
                d.seg_uid[i] == FILL.seg_uid &&
                d.seg_obpre[i] == FILL.seg_obpre;
    for (int r = 0; fill && r < R; ++r)
      fill = d.rem_keys[r][i] == FILL.rem_keys &&
             d.rem_clients[r][i] == FILL.rem_clients;
    for (int p = 0; fill && p < P; ++p)
      fill = d.prop_keys[p][i] == FILL.prop_keys &&
             d.prop_vals[p][i] == FILL.prop_vals;
    if (!fill) break;
    --hw;
  }
  if (hw < *d.nseg) hw = *d.nseg;
  d.hw = hw;
  return true;
}

}  // namespace

extern "C" {

int32_t ms_abi_version() { return 1; }

// Apply a [K, D, B] op ring in place.  dims = [D,T,S,R,P,OB,K,B,L].
// Returns 0 on success, -1 on unsupported dims.
int32_t ms_megastep(const int64_t* cols, const int32_t* dims,
                    const int32_t* ops, const int32_t* payloads) {
  const int D = dims[0], K = dims[6], B = dims[7], L = dims[8];
  Scratch sc;
  sc.size(dims[2]);
  for (int dd = 0; dd < D; ++dd) {
    Doc d;
    if (!bind_doc(d, cols, dims, dd)) return -1;
    for (int k = 0; k < K; ++k) {
      const int64_t slice = (static_cast<int64_t>(k) * D + dd) * B;
      for (int b = 0; b < B; ++b) {
        apply_op(d, sc, ops + (slice + b) * 8, payloads + (slice + b) * L, L);
      }
    }
  }
  return 0;
}

// set_min_seq + compact every doc in place.  dims = [D,T,S,R,P,OB].
int32_t ms_compact(const int64_t* cols, const int32_t* dims,
                   const int32_t* min_seqs) {
  const int D = dims[0];
  for (int dd = 0; dd < D; ++dd) {
    Doc d;
    if (!bind_doc(d, cols, dims, dd)) return -1;
    compact_doc(d, min_seqs[dd]);
  }
  return 0;
}

}  // extern "C"
