"""Cross-cutting utilities: distributed ID compression, telemetry, config.

Reference parity: packages/runtime/id-compressor, packages/utils/
telemetry-utils, packages/common/core-interfaces config contracts.
"""

from .config import CachedConfigProvider, ConfigTypes, MonitoringContext
from .id_compressor import IdCompressor, IdCreationRange
from .telemetry import (
    Histogram,
    Logger,
    PerformanceEvent,
    SampledTelemetryHelper,
    create_child_logger,
)

__all__ = [
    "CachedConfigProvider",
    "ConfigTypes",
    "Histogram",
    "IdCompressor",
    "IdCreationRange",
    "Logger",
    "MonitoringContext",
    "PerformanceEvent",
    "SampledTelemetryHelper",
    "create_child_logger",
]
