"""Feature-gate / config provider with typed cached reads.

Reference parity: ``IConfigProviderBase.getRawConfig(name)`` (packages/common/
core-interfaces/src/config.ts) consumed through ``CachedConfigProvider`` with
typed parsing (telemetry-utils/src/config.ts:193,240) and surfaced together
with a logger as ``MonitoringContext`` (config.ts:276). Feature gates are
dotted string keys, e.g. ``"FluidTpu.Runtime.CompressionEnabled"``, checked at
use sites; unset keys fall through to the caller's default.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Union

from .telemetry import Logger

ConfigTypes = Union[str, int, float, bool, list, None]


class CachedConfigProvider:
    """Layered typed config reads with per-key caching.

    ``providers`` are consulted in order; the first non-None raw value wins
    (ref CachedConfigProvider wraps an ordered provider chain). Raw values may
    be strings (parsed) or already-typed.
    """

    def __init__(
        self, *providers: Callable[[str], ConfigTypes] | Mapping[str, ConfigTypes]
    ) -> None:
        self._providers = [
            p if callable(p) else (lambda key, _m=p: _m.get(key)) for p in providers
        ]
        self._cache: dict[str, ConfigTypes] = {}

    def _raw(self, key: str) -> ConfigTypes:
        if key in self._cache:
            return self._cache[key]
        value: ConfigTypes = None
        for provider in self._providers:
            value = provider(key)
            if value is not None:
                break
        self._cache[key] = value
        return value

    def get_bool(self, key: str, default: bool | None = None) -> bool | None:
        v = self._raw(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            if v.lower() in ("true", "1"):
                return True
            if v.lower() in ("false", "0"):
                return False
        return default

    def get_number(self, key: str, default: float | None = None) -> float | None:
        v = self._raw(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return default
        if isinstance(v, (int, float)):
            return v
        if isinstance(v, str):
            try:
                return float(v)
            except ValueError:
                return default
        return default

    def get_string(self, key: str, default: str | None = None) -> str | None:
        v = self._raw(key)
        return v if isinstance(v, str) else default


class MonitoringContext:
    """Logger + config pair threaded through subsystems (ref config.ts:276)."""

    def __init__(
        self, logger: Logger | None = None, config: CachedConfigProvider | None = None
    ) -> None:
        self.logger = logger if logger is not None else Logger()
        self.config = config if config is not None else CachedConfigProvider()

    def child(self, namespace: str, **properties: Any) -> "MonitoringContext":
        from .telemetry import create_child_logger

        return MonitoringContext(
            create_child_logger(self.logger, namespace, properties), self.config
        )
