"""Structured telemetry: child loggers, performance spans, sampled helpers.

Reference parity: packages/utils/telemetry-utils/src/logger.ts —
``createChildLogger`` with inherited properties (:161,432), ``PerformanceEvent``
spans (:690), and ``SampledTelemetryHelper`` (sampledTelemetryHelper.ts) which
aggregates hot-path measurements and emits one event every N calls (wired into
every DDS op apply in the reference, sharedObject.ts:100-104).

Host-side only: nothing here touches the device path. Events are plain dicts
delivered to a sink callable, so tests can assert on them (ref mockLogger.ts).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

Sink = Callable[[dict[str, Any]], None]


class Logger:
    """A namespace-prefixed structured logger with inherited properties."""

    def __init__(
        self,
        namespace: str = "",
        sink: Sink | None = None,
        properties: dict[str, Any] | None = None,
    ) -> None:
        self.namespace = namespace
        self._sink = sink
        self.properties = dict(properties or {})
        self.events: list[dict[str, Any]] = []  # retained when no sink (mock mode)

    def send(self, event: dict[str, Any]) -> None:
        out = dict(self.properties)
        out.update(event)
        if self.namespace and "eventName" in out:
            out["eventName"] = f"{self.namespace}:{out['eventName']}"
        if self._sink is not None:
            self._sink(out)
        else:
            self.events.append(out)

    # Category helpers (ref ITelemetryLoggerExt send{Telemetry,Error,Perf}Event)
    def generic(self, event_name: str, **props: Any) -> None:
        self.send({"eventName": event_name, "category": "generic", **props})

    def error(self, event_name: str, error: BaseException | str = "", **props: Any) -> None:
        self.send(
            {
                "eventName": event_name,
                "category": "error",
                "error": str(error),
                **props,
            }
        )

    def performance(self, event_name: str, duration_s: float, **props: Any) -> None:
        self.send(
            {
                "eventName": event_name,
                "category": "performance",
                "duration": duration_s,
                **props,
            }
        )

    def matching(self, **filters: Any) -> list[dict[str, Any]]:
        """Mock-mode assertion helper (ref mockLogger matchEvents)."""
        return [
            e
            for e in self.events
            if all(e.get(k) == v for k, v in filters.items())
        ]


def create_child_logger(
    parent: Logger, namespace: str = "", properties: dict[str, Any] | None = None
) -> Logger:
    """Child logger: prefixes the namespace, inherits + overrides properties,
    shares the parent's sink/event buffer (ref logger.ts:161)."""
    # Route through parent.send: the parent applies its own namespace prefix
    # and properties, so the child carries only its own segment/overrides.
    return Logger(namespace=namespace, sink=parent.send, properties=properties)


class PerformanceEvent:
    """A span: start/end/cancel with duration, used around phases like
    container load and summarize (ref logger.ts:690). Context-manager form
    reports success on clean exit, error on exception."""

    def __init__(self, logger: Logger, event_name: str, **props: Any) -> None:
        self.logger = logger
        self.event_name = event_name
        self.props = props
        self._start = time.perf_counter()
        self._done = False

    def end(self, **props: Any) -> None:
        if self._done:
            return
        self._done = True
        self.logger.performance(
            f"{self.event_name}_end",
            time.perf_counter() - self._start,
            **{**self.props, **props},
        )

    def cancel(self, error: BaseException | str = "", **props: Any) -> None:
        if self._done:
            return
        self._done = True
        self.logger.error(
            f"{self.event_name}_cancel", error, **{**self.props, **props}
        )

    def __enter__(self) -> "PerformanceEvent":
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        if exc is None:
            self.end()
        else:
            self.cancel(exc)


class HealthCounters:
    """Named monotonic counters + gauges for degraded-mode health surfaces
    (engine quarantine/checkpoint/watchdog state).  Counters accumulate
    (``bump``), gauges overwrite (``gauge``); ``snapshot`` returns a plain
    dict for status lines and bench artifacts, ``emit`` sends the same dict
    as one structured telemetry event so fleets report health through the
    ordinary logger pipeline."""

    def __init__(self, logger: Logger | None = None, **initial: int) -> None:
        self.logger = logger
        self._values: dict[str, Any] = dict(initial)

    def bump(self, name: str, by: int = 1) -> int:
        self._values[name] = self._values.get(name, 0) + by
        return self._values[name]

    def gauge(self, name: str, value: Any) -> None:
        self._values[name] = value

    def ratio(self, name: str, numerator: str, denominator: str) -> None:
        """Derived gauge: ``numerator``/``denominator`` counter ratio at
        snapshot time (0.0 while the denominator is empty).  Used for
        amortization surfaces like ``steps_per_dispatch`` where the two
        raw counters accumulate independently."""
        den = self._values.get(denominator, 0)
        self._values[name] = (
            round(self._values.get(numerator, 0) / den, 2) if den else 0.0
        )

    def get(self, name: str, default: Any = 0) -> Any:
        return self._values.get(name, default)

    def snapshot(self) -> dict[str, Any]:
        return dict(self._values)

    def emit(self, event_name: str = "engine_health", **props: Any) -> None:
        if self.logger is not None:
            self.logger.generic(event_name, **self._values, **props)


@dataclass
class _SampleBucket:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0


class SampledTelemetryHelper:
    """Aggregate hot-path timings, emit one event per ``sample_every`` calls
    per bucket key (ref sampledTelemetryHelper.ts). Cheap enough to wrap every
    op-apply: one perf_counter pair + dict update per call."""

    def __init__(
        self, logger: Logger, event_name: str, sample_every: int = 100
    ) -> None:
        self.logger = logger
        self.event_name = event_name
        self.sample_every = sample_every
        self._buckets: dict[str, _SampleBucket] = {}

    def measure(self, fn: Callable[[], Any], bucket: str = "") -> Any:
        start = time.perf_counter()
        out = fn()
        self.record(time.perf_counter() - start, bucket)
        return out

    def record(self, duration_s: float, bucket: str = "") -> None:
        b = self._buckets.setdefault(bucket, _SampleBucket())
        b.count += 1
        b.total_s += duration_s
        b.min_s = min(b.min_s, duration_s)
        b.max_s = max(b.max_s, duration_s)
        if b.count >= self.sample_every:
            self.flush(bucket)

    def flush(self, bucket: str = "") -> None:
        b = self._buckets.pop(bucket, None)
        if b is None or b.count == 0:
            return
        self.logger.performance(
            self.event_name,
            b.total_s,
            bucket=bucket,
            count=b.count,
            avg=b.total_s / b.count,
            min=b.min_s,
            max=b.max_s,
        )
