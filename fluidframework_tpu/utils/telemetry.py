"""Structured telemetry: child loggers, performance spans, sampled helpers.

Reference parity: packages/utils/telemetry-utils/src/logger.ts —
``createChildLogger`` with inherited properties (:161,432), ``PerformanceEvent``
spans (:690), and ``SampledTelemetryHelper`` (sampledTelemetryHelper.ts) which
aggregates hot-path measurements and emits one event every N calls (wired into
every DDS op apply in the reference, sharedObject.ts:100-104).

Host-side only: nothing here touches the device path. Events are plain dicts
delivered to a sink callable, so tests can assert on them (ref mockLogger.ts).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable

Sink = Callable[[dict[str, Any]], None]


class Logger:
    """A namespace-prefixed structured logger with inherited properties."""

    def __init__(
        self,
        namespace: str = "",
        sink: Sink | None = None,
        properties: dict[str, Any] | None = None,
    ) -> None:
        self.namespace = namespace
        self._sink = sink
        self.properties = dict(properties or {})
        self.events: list[dict[str, Any]] = []  # retained when no sink (mock mode)

    def send(self, event: dict[str, Any]) -> None:
        out = dict(self.properties)
        out.update(event)
        if self.namespace and "eventName" in out:
            out["eventName"] = f"{self.namespace}:{out['eventName']}"
        if self._sink is not None:
            self._sink(out)
        else:
            self.events.append(out)

    # Category helpers (ref ITelemetryLoggerExt send{Telemetry,Error,Perf}Event)
    def generic(self, event_name: str, **props: Any) -> None:
        self.send({"eventName": event_name, "category": "generic", **props})

    def error(self, event_name: str, error: BaseException | str = "", **props: Any) -> None:
        self.send(
            {
                "eventName": event_name,
                "category": "error",
                "error": str(error),
                **props,
            }
        )

    def performance(self, event_name: str, duration_s: float, **props: Any) -> None:
        self.send(
            {
                "eventName": event_name,
                "category": "performance",
                "duration": duration_s,
                **props,
            }
        )

    def matching(self, **filters: Any) -> list[dict[str, Any]]:
        """Mock-mode assertion helper (ref mockLogger matchEvents)."""
        return [
            e
            for e in self.events
            if all(e.get(k) == v for k, v in filters.items())
        ]


def create_child_logger(
    parent: Logger, namespace: str = "", properties: dict[str, Any] | None = None
) -> Logger:
    """Child logger: prefixes the namespace, inherits + overrides properties,
    shares the parent's sink/event buffer (ref logger.ts:161)."""
    # Route through parent.send: the parent applies its own namespace prefix
    # and properties, so the child carries only its own segment/overrides.
    return Logger(namespace=namespace, sink=parent.send, properties=properties)


class PerformanceEvent:
    """A span: start/end/cancel with duration, used around phases like
    container load and summarize (ref logger.ts:690). Context-manager form
    reports success on clean exit, error on exception.

    The end event carries ``startTime`` (wall-clock seconds at span start)
    alongside the existing ``duration``, so spans can be PLACED on a
    timeline, not just sized.  Additive only: every pre-existing field
    keeps its name and meaning."""

    def __init__(self, logger: Logger, event_name: str, **props: Any) -> None:
        self.logger = logger
        self.event_name = event_name
        self.props = props
        self._start = time.perf_counter()
        self.start_time = time.time()  # wall clock: timeline placement
        self._done = False

    def end(self, **props: Any) -> None:
        if self._done:
            return
        self._done = True
        self.logger.performance(
            f"{self.event_name}_end",
            time.perf_counter() - self._start,
            startTime=self.start_time,
            **{**self.props, **props},
        )

    def cancel(self, error: BaseException | str = "", **props: Any) -> None:
        if self._done:
            return
        self._done = True
        self.logger.error(
            f"{self.event_name}_cancel", error,
            startTime=self.start_time,
            **{**self.props, **props},
        )

    def __enter__(self) -> "PerformanceEvent":
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        if exc is None:
            self.end()
        else:
            self.cancel(exc)


class HealthCounters:
    """Named monotonic counters + gauges for degraded-mode health surfaces
    (engine quarantine/checkpoint/watchdog state).  Counters accumulate
    (``bump``), gauges overwrite (``gauge``); ``snapshot`` returns a plain
    dict for status lines and bench artifacts, ``emit`` sends the same dict
    as one structured telemetry event so fleets report health through the
    ordinary logger pipeline."""

    def __init__(self, logger: Logger | None = None, **initial: int) -> None:
        self.logger = logger
        self._values: dict[str, Any] = dict(initial)

    def bump(self, name: str, by: int = 1) -> int:
        self._values[name] = self._values.get(name, 0) + by
        return self._values[name]

    def gauge(self, name: str, value: Any) -> None:
        self._values[name] = value

    def ratio(self, name: str, numerator: str, denominator: str) -> None:
        """Derived gauge: ``numerator``/``denominator`` counter ratio at
        snapshot time (0.0 while the denominator is empty).  Used for
        amortization surfaces like ``steps_per_dispatch`` where the two
        raw counters accumulate independently."""
        den = self._values.get(denominator, 0)
        self._values[name] = (
            round(self._values.get(numerator, 0) / den, 2) if den else 0.0
        )

    def get(self, name: str, default: Any = 0) -> Any:
        return self._values.get(name, default)

    def snapshot(self) -> dict[str, Any]:
        return dict(self._values)

    def emit(self, event_name: str = "engine_health", **props: Any) -> None:
        if self.logger is not None:
            self.logger.generic(event_name, **self._values, **props)


@dataclass
class _SampleBucket:
    count: int = 0
    total_s: float = 0.0
    min_s: float = float("inf")
    max_s: float = 0.0


class SampledTelemetryHelper:
    """Aggregate hot-path timings, emit one event per ``sample_every`` calls
    per bucket key (ref sampledTelemetryHelper.ts). Cheap enough to wrap every
    op-apply: one perf_counter pair + dict update per call."""

    def __init__(
        self, logger: Logger, event_name: str, sample_every: int = 100
    ) -> None:
        self.logger = logger
        self.event_name = event_name
        self.sample_every = sample_every
        self._buckets: dict[str, _SampleBucket] = {}

    def measure(self, fn: Callable[[], Any], bucket: str = "") -> Any:
        start = time.perf_counter()
        out = fn()
        self.record(time.perf_counter() - start, bucket)
        return out

    def record(self, duration_s: float, bucket: str = "") -> None:
        b = self._buckets.setdefault(bucket, _SampleBucket())
        b.count += 1
        b.total_s += duration_s
        b.min_s = min(b.min_s, duration_s)
        b.max_s = max(b.max_s, duration_s)
        if b.count >= self.sample_every:
            self.flush(bucket)

    def flush(self, bucket: str = "") -> None:
        b = self._buckets.pop(bucket, None)
        if b is None or b.count == 0:
            return
        self.logger.performance(
            self.event_name,
            b.total_s,
            bucket=bucket,
            count=b.count,
            avg=b.total_s / b.count,
            min=b.min_s,
            max=b.max_s,
        )

    def flush_all(self) -> int:
        """Flush every residual bucket (shutdown / status-snapshot hook):
        tail samples below ``sample_every`` must never be silently dropped
        when the process drains.  Returns the buckets flushed."""
        pending = [k for k, b in self._buckets.items() if b.count > 0]
        for key in pending:
            self.flush(key)
        return len(pending)


class Histogram:
    """Log-bucketed, mergeable latency histogram with percentile queries.

    Values bucket at geometric boundaries ``base * growth**i`` (sparse
    dict of counts, so an idle histogram is a few machine words); exact
    ``count``/``sum``/``min``/``max`` ride alongside, and ``percentile``
    answers from the bucket cumulative clamped to the observed [min, max]
    — the result is within one bucket (a factor of ``growth``) of the
    exact order statistic, single-sample case exact.  Two histograms with
    the same (base, growth) layout merge by bucket-count addition, so
    per-doc / per-shard histograms roll up into fleet aggregates without
    re-touching samples.  Recording costs one ``math.log`` + one dict
    update: cheap enough for sampled per-op latency, kept OFF per-message
    paths regardless.
    """

    __slots__ = ("base", "growth", "_lg", "count", "sum", "min", "max",
                 "_buckets")

    def __init__(self, base: float = 1e-6, growth: float = 2 ** 0.25) -> None:
        if base <= 0 or growth <= 1:
            raise ValueError("base must be > 0 and growth > 1")
        self.base = base
        self.growth = growth
        self._lg = math.log(growth)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._buckets: dict[int, int] = {}

    def record(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        # Bucket i covers (base*growth**(i-1), base*growth**i]; everything
        # at or below base lands in bucket 0.
        i = 0 if v <= self.base else math.ceil(
            math.log(v / self.base) / self._lg - 1e-12
        )
        self._buckets[i] = self._buckets.get(i, 0) + 1

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into this histogram (same layout required)."""
        if (self.base, self.growth) != (other.base, other.growth):
            raise ValueError("histogram layouts differ; cannot merge")
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        for i, c in other._buckets.items():
            self._buckets[i] = self._buckets.get(i, 0) + c
        return self

    def percentile(self, q: float) -> float | None:
        """The q-quantile (q in [0, 1]); None while empty."""
        if self.count == 0:
            return None
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"quantile {q} outside [0, 1]")
        target = max(1, math.ceil(q * self.count))
        cum = 0
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if cum >= target:
                upper = self.base * self.growth ** i
                return min(max(upper, self.min), self.max)
        return self.max  # unreachable; defensive

    def percentiles(self, qs=(0.5, 0.9, 0.99)) -> dict[float, float | None]:
        return {q: self.percentile(q) for q in qs}

    def snapshot(self) -> dict[str, Any]:
        """Plain-dict view (status lines, JSON artifacts)."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(0.5),
            "p90": self.percentile(0.9),
            "p99": self.percentile(0.99),
        }

    def to_wire(self) -> dict[str, Any]:
        """Lossless JSON-serializable form: full bucket counts ride along
        (unlike ``snapshot``), so a histogram shipped across a process
        boundary merges on the far side exactly as if the samples had been
        recorded there.  Bucket keys stringify for JSON object keys."""
        return {
            "base": self.base,
            "growth": self.growth,
            "count": self.count,
            "sum": self.sum,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "buckets": {str(i): c for i, c in self._buckets.items()},
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "Histogram":
        """Rebuild a histogram from ``to_wire`` output (JSON round-trip)."""
        h = cls(base=wire["base"], growth=wire["growth"])
        h.count = int(wire["count"])
        h.sum = float(wire["sum"])
        if h.count > 0:
            h.min = float(wire["min"])
            h.max = float(wire["max"])
        h._buckets = {int(i): int(c) for i, c in wire["buckets"].items()}
        return h
