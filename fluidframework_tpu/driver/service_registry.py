"""Local-service provider seam: the driver/framework -> server inversion.

The in-process local driver and the local service client are, by design,
bindings TO the local server (tinylicious shape) — which left the driver
and framework layers importing ``server.local_service`` upward, edges the
fftpu-check baseline carried with rationales since the layer gate landed.
This module inverts them the same way ``models.dispatch`` inverted the
engines' mesh edge: the lower layers depend on an abstract provider slot,
and the concrete service registers itself here when its module loads.

Resolution order:

1. whatever called :func:`register_local_service` first (in-process
   composition: importing ``fluidframework_tpu.server.local_service``
   anywhere — which every caller constructing a service already does —
   registers it);
2. otherwise the provider named by ``FFTPU_LOCAL_SERVICE`` (a dotted
   module path) is loaded and must self-register — an alternative
   in-process service (a fake for tests, a future sharded local server)
   binds here without drivers or clients changing;
3. the default provider is ``fluidframework_tpu.server.local_service``.

The provider surface is the service CLASS: calling it with no arguments
yields a service whose ``document(doc_id)`` returns the per-document
backend the local driver wraps.
"""

from __future__ import annotations

import importlib
import os

_SERVICE_CLS = None

DEFAULT_PROVIDER = "fluidframework_tpu.server.local_service"


def register_local_service(service_cls):
    """Install the concrete local-service class (called by the provider
    module at import time).  Last registration wins — tests swap fakes."""
    global _SERVICE_CLS
    _SERVICE_CLS = service_cls
    return service_cls


def local_service_class():
    """The active local-service class, loading the configured provider on
    first use (the composition-root binding; see module docstring)."""
    if _SERVICE_CLS is None:
        provider = os.environ.get("FFTPU_LOCAL_SERVICE", DEFAULT_PROVIDER)
        importlib.import_module(provider)
        if _SERVICE_CLS is None:
            raise RuntimeError(
                f"local-service provider {provider!r} imported but did not "
                "call register_local_service()"
            )
    return _SERVICE_CLS
