"""Fault-injection driver decorator: wrap any driver, inject failures.

Reference parity: test-service-load's FaultInjectionDocumentServiceFactory
(packages/test/test-service-load/src/faultInjectionDriver.ts:40) — a
decorator over a REAL driver whose connections expose ``injectNack``
(:294), ``injectError`` (:309), and ``injectDisconnect`` (:327), so stress
runs exercise the host's recovery machinery (reconnect, backoff, pending
replay) against deterministic failures instead of waiting for real ones.
"""

from __future__ import annotations

from typing import Any, Callable

from ..protocol.messages import Nack, SequencedMessage, SignalMessage
from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)


class FaultInjectionConnection(DeltaConnection):
    """Delegating connection with injectable failures."""

    def __init__(
        self,
        inner: DeltaConnection,
        nack_listener: Callable[[Nack], None] | None,
    ) -> None:
        self._inner = inner
        self._nack_listener = nack_listener
        self._error_armed: bool | None = None  # None = disarmed, else can_retry
        self.client_id = inner.client_id
        self.mode = inner.mode
        self.join_msg = inner.join_msg
        self.checkpoint_seq = inner.checkpoint_seq

    # ------------------------------------------------------------- injection
    def inject_nack(self, reason: str = "injected nack") -> None:
        """Synthesize a server nack: tears the connection down, then fires
        the nack listener — exactly the real nack path (:294)."""
        nack = Nack(client_id=self.client_id, client_seq=0, reason=reason)
        self._inner.disconnect()
        if self._nack_listener is not None:
            self._nack_listener(nack)

    def inject_error(self, can_retry: bool = True) -> None:
        """Arm a one-shot submit failure with the given retryability (:309)."""
        self._error_armed = can_retry

    def inject_disconnect(self) -> None:
        """Synthetic socket drop (:327): the connection dies without a
        leave handshake; the host discovers on its next use."""
        self._inner.disconnect()

    # ------------------------------------------------------------- delegate
    def submit(self, message: Any) -> None:
        if self._error_armed is not None:
            can_retry, self._error_armed = self._error_armed, None
            raise DriverError("injected submit error", can_retry=can_retry)
        self._inner.submit(message)

    def submit_signal(self, content: Any) -> None:
        self._inner.submit_signal(content)

    def disconnect(self) -> None:
        self._inner.disconnect()

    @property
    def connected(self) -> bool:
        return self._inner.connected


class FaultInjectionDocumentService(DocumentService):
    def __init__(self, factory: "FaultInjectionDocumentServiceFactory", inner: DocumentService) -> None:
        self._factory = factory
        self._inner = inner

    def connect_to_delta_stream(
        self,
        client_id: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
    ) -> DeltaConnection:
        inner = self._inner.connect_to_delta_stream(
            client_id, listener, nack_listener, signal_listener, mode=mode
        )
        conn = FaultInjectionConnection(inner, nack_listener)
        self._factory.connections.append(conn)
        return conn

    def connect_to_delta_storage(self) -> DeltaStorageService:
        return self._inner.connect_to_delta_storage()

    def connect_to_storage(self) -> StorageService:
        return self._inner.connect_to_storage()


class FaultInjectionDocumentServiceFactory(DocumentServiceFactory):
    """Decorator factory (:40): every connection it hands out is
    injectable; ``connections`` lists them newest-last for the stress
    harness to pick victims from."""

    def __init__(self, inner: DocumentServiceFactory) -> None:
        self._inner = inner
        self.connections: list[FaultInjectionConnection] = []

    def create_document_service(self, doc_id: str) -> DocumentService:
        return FaultInjectionDocumentService(
            self, self._inner.create_document_service(doc_id)
        )

    def live(self) -> list[FaultInjectionConnection]:
        self.connections = [c for c in self.connections if c.connected]
        return self.connections
