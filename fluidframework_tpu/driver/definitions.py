"""Service abstraction contracts — re-export shim.

The contract classes moved to ``protocol.driver_contracts`` (the
contracts tier), so the runtime can name ``DriverError`` without an
upward edge into this layer — the same treatment the channel contracts
got with ``protocol.channel``.  Drivers and the loader keep importing
from here; the definitions ARE the driver-definitions package's surface
(ref packages/common/driver-definitions).
"""

from __future__ import annotations

from ..protocol.driver_contracts import (
    AuthRejection,
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)

__all__ = [
    "AuthRejection",
    "DeltaConnection",
    "DeltaStorageService",
    "DocumentService",
    "DocumentServiceFactory",
    "DriverError",
    "StorageService",
]
