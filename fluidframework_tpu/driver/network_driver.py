"""Network driver: the driver contracts over TCP + HTTP fronts.

Reference parity: routerlicious-driver — ``DocumentService`` backed by a
real service: the delta stream over the nexus socket protocol
(driver-base/src/documentDeltaConnection.ts socket.io analog, here JSON
lines over TCP), delta ranges and snapshots over the alfred/historian REST
front (documentStorageService/deltaStorageService).

Threading model: a reader thread drains the socket into a queue; message
DISPATCH happens on the host's thread via ``pump()`` (or the blocking
``sync()``, which drains until the server echoes a marker — deterministic
quiescence without sleeps).  This mirrors the reference's inbound
DeltaQueue: the wire is asynchronous, processing is single-threaded.
"""

from __future__ import annotations

import http.client
import json
import queue
import socket
import threading
from typing import Any, Callable

from ..protocol.messages import Nack, SequencedMessage, SignalMessage, UnsequencedMessage
from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)


def _seq_from_dict(d: dict) -> SequencedMessage:
    return SequencedMessage.from_json(json.dumps(d))


class NetworkDeltaConnection(DeltaConnection):
    """One TCP delta-stream connection (ref DocumentDeltaConnection)."""

    def __init__(
        self,
        host: str,
        port: int,
        doc_id: str,
        client_id: str,
        mode: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None,
        signal_listener: Callable[[SignalMessage], None] | None,
        token: str | None = None,
        boot_listener: Callable[[], None] | None = None,
        interests: list | None = None,
    ) -> None:
        self.client_id = client_id
        self.mode = mode
        self._listener = listener
        self._nack_listener = nack_listener
        self._signal_listener = signal_listener
        self._boot_listener = boot_listener
        self.boot_resyncs = 0
        self._inbound: queue.Queue = queue.Queue()
        self._connected = False
        self._sync_counter = 0

        self._sock = socket.create_connection((host, port), timeout=30)
        self._rfile = self._sock.makefile("r", encoding="utf-8")
        self._wlock = threading.Lock()
        try:
            connect_req = {
                "t": "connect",
                "doc": doc_id,
                "client": client_id,
                "mode": mode,
                "token": token,
                "signals": signal_listener is not None,
            }
            if interests is not None:
                # Scoped presence workspace: only signals whose scope key
                # is in this list (plus unscoped signals) are delivered.
                connect_req["interests"] = list(interests)
            self._send(connect_req)
            # Handshake: block for the joined ack.  Broadcasts for this
            # socket can land BEFORE it (e.g. our own audience clientJoin
            # signal fans out during connect) — buffer them for dispatch
            # after the handshake, the reference driver-base
            # earlyOpHandler pattern (documentDeltaConnection.ts:54).
            while True:
                line = self._rfile.readline()
                if not line:
                    raise DriverError("connection closed during handshake")
                ack = json.loads(line)
                if ack.get("t") == "error":
                    raise DriverError(
                        f"connection rejected: {ack.get('reason')}",
                        can_retry=bool(ack.get("canRetry", False)),
                    )
                if ack.get("t") == "joined":
                    break
                self._inbound.put(ack)  # early broadcast: deliver post-join
            self.join_msg = _seq_from_dict(ack["join"]) if ack.get("join") else None
            self.checkpoint_seq = ack["deliveredSeq"]
        except BaseException:
            # A failed handshake must not leak the socket (reconnect loops
            # would exhaust fds).
            self._rfile.close()
            self._sock.close()
            raise
        self._connected = True
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # ----------------------------------------------------------------- wire
    def _send(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        with self._wlock:
            self._sock.sendall(data)

    def _read_loop(self) -> None:
        try:
            for raw in self._rfile:
                line = raw.strip()
                if line:
                    self._inbound.put(json.loads(line))
        except (OSError, ValueError):
            pass
        self._inbound.put({"t": "__eof__"})

    # ------------------------------------------------------------- dispatch
    def pump(self, block_s: float | None = None) -> int:
        """Dispatch buffered inbound messages on the CALLER's thread;
        returns the number dispatched."""
        n = 0
        while True:
            try:
                item = self._inbound.get(timeout=block_s) if block_s else self._inbound.get_nowait()
            except queue.Empty:
                return n
            block_s = None  # only the first get blocks
            if self._dispatch(item):
                n += 1

    def _dispatch(self, item: dict) -> bool:
        kind = item.get("t")
        if kind == "op":
            self._listener(_seq_from_dict(item["msg"]))
            return True
        if kind == "nack":
            # A protocol nack invalidates the connection (ref: server
            # closes the socket; client reconnects).  An ADMISSION nack
            # (canRetry, retryAfter set) sheds the op BEFORE the sequencer
            # saw it: the connection and the client's clientSeq stream are
            # both still valid — keep the socket, hand the nack up, and
            # let the sender back off retryAfter and resubmit in place.
            if not item.get("canRetry", False):
                self.disconnect()
            if self._nack_listener is not None:
                self._nack_listener(
                    Nack(
                        client_id=item["clientId"],
                        client_seq=item["clientSeq"],
                        reason=item["reason"],
                        retry_after=item.get("retryAfter", 0.0),
                    )
                )
            return True
        if kind == "signal":
            if self._signal_listener is not None:
                self._signal_listener(
                    SignalMessage(client_id=item["clientId"], contents=item["contents"])
                )
            return True
        if kind == "resync":
            # Fan-out plane drop-to-catch-up: ``boot: true`` means this
            # connection's missed range left the retained log — the host
            # must re-seed from the historian snapshot tier and reconnect
            # (the FleetConsumer implements the full fetch-adopt-resume
            # loop; container hosts register ``boot_listener`` to reload
            # through their storage service).
            if item.get("boot"):
                self.boot_resyncs += 1
                if self._boot_listener is not None:
                    self._boot_listener()
            return False
        if kind == "sync":
            self._sync_seen = item.get("n")
            return False
        if kind == "__eof__":
            self._connected = False
            return False
        return False

    def sync(self, timeout_s: float = 10.0) -> int:
        """Round-trip a marker through the server: every message the server
        broadcast to this socket BEFORE the echo is dispatched when this
        returns.  The deterministic quiescence primitive for tests and
        batch-mode hosts."""
        if not self._connected:
            return self.pump()
        self._sync_counter += 1
        want = self._sync_counter
        self._sync_seen = None
        self._send({"t": "sync", "n": want})
        dispatched = 0
        while self._sync_seen != want:
            try:
                item = self._inbound.get(timeout=timeout_s)
            except queue.Empty:
                raise DriverError(f"sync {want} timed out after {timeout_s}s")
            if self._dispatch(item):
                dispatched += 1
            if not self._connected:
                break
        return dispatched

    # ---------------------------------------------------------------- sends
    def submit(self, message: Any) -> None:
        if not self._connected:
            raise DriverError("submit on disconnected connection")
        if self.mode != "write":
            raise DriverError("read connection cannot submit ops", can_retry=False)
        assert isinstance(message, UnsequencedMessage)
        self._send({"t": "submit", "msg": json.loads(message.to_json())})

    def submit_signal(self, content: Any) -> None:
        if not self._connected:
            raise DriverError("signal on disconnected connection")
        self._send({"t": "signal", "content": content})

    def disconnect(self) -> None:
        if self._connected:
            self._connected = False
            try:
                self._send({"t": "disconnect"})
            except OSError:
                pass
            # Wait for the server-side EOF: the handler ticket-and-broadcasts
            # our leave BEFORE closing the socket, so once the reader thread
            # exits, the leave is ordered ahead of any later sync marker.
            self._reader.join(timeout=5.0)
            try:
                self._sock.close()
            except OSError:
                pass

    @property
    def connected(self) -> bool:
        return self._connected


class _Http:
    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port

    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        token: str | None = None,
    ) -> tuple[int, dict]:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            payload = json.dumps(body) if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            if token:
                headers["Authorization"] = f"Bearer {token}"
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()


# Storage reads authenticate under this pseudo-client identity (the token
# provider signs for it; the server validates the same scope).
STORAGE_CLIENT = "__storage__"


class HttpDeltaStorageService(DeltaStorageService):
    def __init__(self, http: _Http, doc_id: str, token: str | None = None) -> None:
        self._http = http
        self._doc = doc_id
        self._token = token

    def get_deltas(self, from_seq: int, to_seq: int) -> list[SequencedMessage]:
        status, body = self._http.request(
            "GET", f"/doc/{self._doc}/deltas?from={from_seq}&to={to_seq}",
            token=self._token,
        )
        if status != 200:
            raise DriverError(f"delta read failed: {body}")
        return [_seq_from_dict(d) for d in body["ops"]]


class HttpStorageService(StorageService):
    def __init__(self, http: _Http, doc_id: str, token: str | None = None) -> None:
        self._http = http
        self._doc = doc_id
        self._token = token

    def get_latest_snapshot(self) -> tuple[int, dict] | None:
        status, body = self._http.request(
            "GET", f"/doc/{self._doc}/snapshot", token=self._token
        )
        if status == 404:
            return None
        if status != 200:
            raise DriverError(f"snapshot read failed: {body}")
        return body["seq"], body["summary"]

    def write_snapshot(self, seq: int, summary: dict) -> None:
        status, body = self._http.request(
            "PUT", f"/doc/{self._doc}/snapshot", {"seq": seq, "summary": summary},
            token=self._token,
        )
        if status != 200:
            raise DriverError(f"snapshot write failed: {body}")

    def upload_summary(self, summary_tree: dict) -> str:
        status, body = self._http.request(
            "POST", f"/doc/{self._doc}/summary", {"tree": summary_tree},
            token=self._token,
        )
        if status != 200:
            raise DriverError(f"summary upload failed: {body}")
        return body["handle"]

    def upload_blob_content(self, content: str) -> str:
        status, body = self._http.request(
            "POST", f"/doc/{self._doc}/blob", {"content": content},
            token=self._token,
        )
        if status != 200:
            raise DriverError(f"blob upload failed: {body}")
        return body["id"]

    def read_blob_content(self, blob_id: str) -> str:
        status, body = self._http.request(
            "GET", f"/doc/{self._doc}/blob/{blob_id}", token=self._token
        )
        if status != 200:
            # 404 = definitively absent (not retryable); other statuses may
            # be transient — callers distinguishing "missing" from "broken"
            # rely on can_retry.
            raise DriverError(f"blob read failed: {body}", can_retry=status != 404)
        return body["content"]

    def get_versions(self, max_count: int = 5) -> list[dict]:
        status, body = self._http.request(
            "GET", f"/doc/{self._doc}/versions?max={max_count}", token=self._token
        )
        if status != 200:
            raise DriverError(f"version list failed: {body}")
        return body["versions"]

    def get_snapshot_version(self, version_id: str) -> tuple[int, dict] | None:
        status, body = self._http.request(
            "GET", f"/doc/{self._doc}/snapshot?version={version_id}",
            token=self._token,
        )
        if status == 404:
            return None
        if status != 200:
            raise DriverError(f"versioned snapshot read failed: {body}")
        return body["seq"], body["summary"]


class NetworkDocumentService(DocumentService):
    def __init__(self, factory: "NetworkDocumentServiceFactory", doc_id: str) -> None:
        self._f = factory
        self._doc = doc_id

    def connect_to_delta_stream(
        self,
        client_id: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
        boot_listener: Callable[[], None] | None = None,
    ) -> DeltaConnection:
        token = None
        if self._f.token_provider is not None:
            token = self._f.token_provider(self._doc, client_id)
        conn = NetworkDeltaConnection(
            self._f.host, self._f.port, self._doc, client_id, mode,
            listener, nack_listener, signal_listener, token=token,
            boot_listener=boot_listener,
        )
        self._f.live_connections.append(conn)
        return conn

    def _storage_token(self) -> str | None:
        if self._f.token_provider is None:
            return None
        return self._f.token_provider(self._doc, STORAGE_CLIENT)

    def connect_to_delta_storage(self) -> DeltaStorageService:
        return HttpDeltaStorageService(self._f.http, self._doc, self._storage_token())

    def connect_to_storage(self) -> StorageService:
        return HttpStorageService(self._f.http, self._doc, self._storage_token())


class NetworkDocumentServiceFactory(DocumentServiceFactory):
    """Driver factory bound to one service plane (host, tcp port, http
    port).  Tracks every delta connection it opens so hosts/tests can pump
    them deterministically (``sync_all``)."""

    def __init__(
        self, host: str, port: int, http_port: int, token_provider=None
    ) -> None:
        self.host = host
        self.port = port
        self.http = _Http(host, http_port)
        self.token_provider = token_provider
        self.live_connections: list[NetworkDeltaConnection] = []

    def create_document_service(self, doc_id: str) -> DocumentService:
        return NetworkDocumentService(self, doc_id)

    def sync_all(self, rounds: int = 16) -> int:
        """Dispatch until every live connection is quiescent: repeated sync
        rounds, stopping after a full round that dispatched nothing (an op
        dispatched on one connection may trigger submits that feed
        another)."""
        total = 0
        for _ in range(rounds):
            n = 0
            for conn in list(self.live_connections):
                if conn.connected:
                    n += conn.sync()
                else:
                    # Final drain, then drop: dead connections must not
                    # accumulate across reconnect churn.
                    n += conn.pump()
                    self.live_connections.remove(conn)
            total += n
            if n == 0:
                return total
        raise DriverError(f"sync_all did not quiesce after {rounds} rounds")
