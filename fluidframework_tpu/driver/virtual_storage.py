"""Snapshot virtualization, persistent caching, and retry/prefetch utils.

Reference parity:
- **odsp-driver snapshot virtualization** (packages/drivers/odsp-driver/src/
  odspDocumentStorageService.ts + the compact snapshot format): a snapshot
  is stored as a small SKELETON whose large subtrees are content-addressed
  blobs fetched on demand, so boot transfers the spine plus only the blobs
  this client doesn't already hold.
- **driver-web-cache** (persistent snapshot/blob cache keyed by content id;
  here an in-memory dict with optional directory persistence).
- **driver-utils** (packages/loader/driver-utils/src/): ``run_with_retry``
  with the driver error taxonomy (DriverError.can_retry, throttling
  retry-after), and ``PrefetchStorageService``
  (prefetchDocumentStorageService.ts — warm the cache ahead of reads).

Virtualization is transparent to the loader: ``get_latest_snapshot``
returns a ``LazySnapshot`` mapping that hydrates a subtree the first time
its key is read, counting fetches vs cache hits (the odsp telemetry
measure). Content addressing makes re-uploads of unchanged subtrees free
and makes warm-cache reboots fetch only what changed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable

from .definitions import DriverError, StorageService

VBLOB_KEY = "__vblob__"
VBLOB_ESCAPE = "__vblob_escaped__"


class ThrottlingError(DriverError):
    """ref odsp throttling / 429: retryable after a delay."""

    def __init__(self, message: str, retry_after: float = 0.0) -> None:
        super().__init__(message, can_retry=True)
        self.retry_after = retry_after


# ---------------------------------------------------------------------------
# runWithRetry (ref driver-utils/src/runWithRetry.ts)
# ---------------------------------------------------------------------------

def run_with_retry(
    fn: Callable[[], Any],
    *,
    max_attempts: int = 5,
    base_delay: float = 0.01,
    max_delay: float = 2.0,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Callable[[int, Exception], None] | None = None,
):
    """Run ``fn``, retrying retryable DriverErrors with exponential backoff
    (throttling errors wait their retry_after). Non-retryable errors and
    non-driver exceptions propagate immediately."""
    attempt = 0
    while True:
        try:
            return fn()
        except DriverError as e:
            attempt += 1
            if not e.can_retry or attempt >= max_attempts:
                raise
            delay = min(base_delay * (2 ** (attempt - 1)), max_delay)
            if isinstance(e, ThrottlingError):
                delay = max(delay, e.retry_after)
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


# ---------------------------------------------------------------------------
# Persistent blob cache (ref driver-web-cache)
# ---------------------------------------------------------------------------

class SnapshotCache:
    """Content-addressed blob cache; optionally persisted to a directory
    (one file per blob id — survives process restarts like the reference's
    IndexedDB cache survives page loads)."""

    def __init__(self, directory: str | None = None) -> None:
        self._mem: dict[str, str] = {}
        self._dir = directory
        if directory is not None:
            os.makedirs(directory, exist_ok=True)

    def get(self, blob_id: str) -> str | None:
        if blob_id in self._mem:
            return self._mem[blob_id]
        if self._dir is not None:
            path = os.path.join(self._dir, blob_id)
            if os.path.exists(path):
                with open(path) as f:
                    content = f.read()
                self._mem[blob_id] = content
                return content
        return None

    def put(self, blob_id: str, content: str) -> None:
        self._mem[blob_id] = content
        if self._dir is not None:
            with open(os.path.join(self._dir, blob_id), "w") as f:
                f.write(content)


# ---------------------------------------------------------------------------
# Shredding: summary dict -> skeleton + content-addressed subtree blobs
# ---------------------------------------------------------------------------

def _canonical(value: Any) -> str:
    return json.dumps(value, sort_keys=True, separators=(",", ":"))


def shred_summary(
    summary: dict,
    upload: Callable[[str], str],
    threshold: int = 256,
    known_chunk: Callable[[str], bool] | None = None,
) -> dict:
    """Replace large subtrees (bottom-up) with ``{VBLOB_KEY: id}`` markers.
    Children shred first, so a huge tree becomes a spine of small nodes
    pointing at content-addressed chunks — unchanged chunks keep their ids
    across snapshots (the virtualization dedup).

    ``known_chunk`` validates pass-through markers (re-shredding an
    unhydrated skeleton): an id it rejects raises at WRITE time instead of
    silently storing a dangling marker that fails far away at hydration."""

    def walk(value: Any, depth: int) -> Any:
        if isinstance(value, dict):
            keys = set(value.keys())
            if keys == {VBLOB_KEY} and isinstance(value[VBLOB_KEY], str):
                # An existing chunk marker (re-shredding a skeleton that was
                # not fully hydrated, e.g. dict(lazy_snapshot)): pass it
                # through — the id still resolves in the blob store.
                # VBLOB_KEY is a reserved key; genuine user data shaped
                # exactly {VBLOB_KEY: <str>} is not representable.
                if known_chunk is not None and not known_chunk(value[VBLOB_KEY]):
                    raise ValueError(
                        f"marker-shaped value {value!r} does not name a known "
                        f"chunk ({VBLOB_KEY!r} is a reserved key)"
                    )
                return dict(value)
            if keys == {VBLOB_KEY} or keys == {VBLOB_ESCAPE}:
                # Marker- or escape-shaped user data (non-string payload):
                # escape it, recording which key it had.
                (k,) = keys
                return {VBLOB_ESCAPE: {"k": k, "v": walk(value[k], depth + 1)}}
            out: Any = {k: walk(v, depth + 1) for k, v in value.items()}
        elif isinstance(value, list):
            out = [walk(v, depth + 1) for v in value]
        else:
            return value
        if depth > 0:
            encoded = _canonical(out)
            if len(encoded) > threshold:
                return {VBLOB_KEY: upload(encoded)}
        return out

    return walk(summary, 0)


def hydrate_summary(node: Any, fetch: Callable[[str], str]) -> Any:
    """Fully resolve a shredded skeleton (eager)."""
    if isinstance(node, dict):
        if set(node.keys()) == {VBLOB_KEY}:
            return hydrate_summary(json.loads(fetch(node[VBLOB_KEY])), fetch)
        if set(node.keys()) == {VBLOB_ESCAPE}:
            esc = node[VBLOB_ESCAPE]
            return {esc["k"]: hydrate_summary(esc["v"], fetch)}
        return {k: hydrate_summary(v, fetch) for k, v in node.items()}
    if isinstance(node, list):
        return [hydrate_summary(v, fetch) for v in node]
    return node


def iter_vblob_ids(node: Any):
    """All marker ids in a skeleton (transitively only those visible — the
    nested ones surface as their parents hydrate)."""
    if isinstance(node, dict):
        if set(node.keys()) == {VBLOB_KEY}:
            yield node[VBLOB_KEY]
            return
        for v in node.values():
            yield from iter_vblob_ids(v)
    elif isinstance(node, list):
        for v in node:
            yield from iter_vblob_ids(v)


class LazySnapshot(dict):
    """A snapshot skeleton that hydrates per-key on first read — reading
    only ``summary["protocol"]`` never fetches the runtime subtree's blobs
    (the odsp partial-snapshot access pattern)."""

    def __init__(self, skeleton: dict, fetch: Callable[[str], str]) -> None:
        super().__init__(skeleton)
        self._fetch = fetch
        self._hydrated: set = set()

    def __getitem__(self, key):
        value = super().__getitem__(key)
        if key not in self._hydrated:
            value = hydrate_summary(value, self._fetch)
            super().__setitem__(key, value)
            self._hydrated.add(key)
        return value

    def get(self, key, default=None):
        return self[key] if key in self else default

    def items(self):
        return [(k, self[k]) for k in super().keys()]

    def values(self):
        return [self[k] for k in super().keys()]


# ---------------------------------------------------------------------------
# The virtualized storage service
# ---------------------------------------------------------------------------

class VirtualizedStorageService(StorageService):
    """Wrap any driver StorageService with odsp-style virtualization.

    Writes shred the summary into content-addressed chunks (cache-seeded,
    so this client never re-fetches what it wrote); reads return a
    LazySnapshot resolving chunks through the cache first. ``stats``
    counts wire fetches vs cache hits."""

    def __init__(
        self,
        inner: StorageService,
        cache: SnapshotCache | None = None,
        threshold: int = 256,
    ) -> None:
        self._inner = inner
        self._cache = cache if cache is not None else SnapshotCache()
        self._threshold = threshold
        self.stats = {"uploads": 0, "wire_fetches": 0, "cache_hits": 0}

    # ------------------------------------------------------------- internals
    def _upload_chunk(self, content: str) -> str:
        # Always upload: the cache is strictly a READ cache (a warm cache
        # says nothing about what the server holds — it may have restarted).
        # Write-side dedup is the server's job (content-addressed blob
        # stores make re-uploads of unchanged chunks idempotent).
        blob_id = self._inner.upload_blob_content(content)
        self.stats["uploads"] += 1
        self._cache.put(blob_id, content)
        return blob_id

    def _fetch_chunk(self, blob_id: str) -> str:
        cached = self._cache.get(blob_id)
        if cached is not None:
            self.stats["cache_hits"] += 1
            return cached
        content = self._inner.read_blob_content(blob_id)
        self.stats["wire_fetches"] += 1
        self._cache.put(blob_id, content)
        return content

    # -------------------------------------------------------------- contract
    def get_latest_snapshot(self) -> tuple[int, dict] | None:
        snap = self._inner.get_latest_snapshot()
        if snap is None:
            return None
        seq, skeleton = snap
        return seq, LazySnapshot(skeleton, self._fetch_chunk)

    def _known_chunk(self, blob_id: str) -> bool:
        """Existence probe for pass-through markers. Only a DEFINITIVE
        absence (missing blob) reports unknown; a transient storage failure
        surfaces as itself, never as a reserved-key complaint. A successful
        probe warms the cache (the content was fetched anyway)."""
        if self._cache.get(blob_id) is not None:
            return True
        try:
            content = self._inner.read_blob_content(blob_id)
        except KeyError:
            return False
        except DriverError as e:
            if e.can_retry:
                raise  # transient: report the real failure
            return False
        self._cache.put(blob_id, content)
        return True

    def write_snapshot(self, seq: int, summary: dict) -> None:
        if isinstance(summary, LazySnapshot):
            # Force per-key hydration so we shred content, not markers
            # (markers that do sneak in pass through shred_summary intact).
            summary = {k: summary[k] for k in summary.keys()}
        skeleton = shred_summary(
            dict(summary), self._upload_chunk, self._threshold, self._known_chunk
        )
        self._inner.write_snapshot(seq, skeleton)

    def upload_blob_content(self, content: str) -> str:
        return self._inner.upload_blob_content(content)

    def read_blob_content(self, blob_id: str) -> str:
        return self._inner.read_blob_content(blob_id)

    def upload_summary(self, summary_tree: dict) -> str:
        return self._inner.upload_summary(summary_tree)

    def get_versions(self, max_count: int = 5) -> list[dict]:
        return self._inner.get_versions(max_count)

    def get_snapshot_version(self, version_id: str) -> tuple[int, dict] | None:
        snap = self._inner.get_snapshot_version(version_id)
        if snap is None:
            return None
        seq, skeleton = snap
        return seq, LazySnapshot(skeleton, self._fetch_chunk)


class VirtualizedDocumentServiceFactory:
    """Wrap any DocumentServiceFactory so storage connections come back
    virtualized (the odsp driver's storage path composed over an arbitrary
    transport). One cache per document id — shared across services of the
    same doc, like the web cache."""

    def __init__(
        self,
        inner,
        cache_dir: str | None = None,
        threshold: int = 256,
        prefetch: bool = False,
    ) -> None:
        self._inner = inner
        self._cache_dir = cache_dir
        self._threshold = threshold
        self._prefetch = prefetch
        self._caches: dict[str, SnapshotCache] = {}

    def cache_for(self, doc_id: str) -> SnapshotCache:
        if doc_id not in self._caches:
            sub = (
                os.path.join(self._cache_dir, doc_id)
                if self._cache_dir is not None
                else None
            )
            self._caches[doc_id] = SnapshotCache(sub)
        return self._caches[doc_id]

    def create_document_service(self, doc_id: str):
        inner_service = self._inner.create_document_service(doc_id)
        outer = self

        class _Service:
            def connect_to_delta_stream(self, *a, **kw):
                return inner_service.connect_to_delta_stream(*a, **kw)

            def connect_to_delta_storage(self):
                return inner_service.connect_to_delta_storage()

            def connect_to_storage(self):
                storage = VirtualizedStorageService(
                    inner_service.connect_to_storage(),
                    cache=outer.cache_for(doc_id),
                    threshold=outer._threshold,
                )
                return (
                    PrefetchStorageService(storage) if outer._prefetch else storage
                )

        return _Service()


class PrefetchStorageService(StorageService):
    """ref driver-utils PrefetchDocumentStorageService: wraps a (typically
    virtualized) storage service and warms every chunk reachable from the
    latest snapshot skeleton, so subsequent hydration is all cache hits."""

    def __init__(self, inner: VirtualizedStorageService) -> None:
        self._inner = inner

    def get_latest_snapshot(self) -> tuple[int, dict] | None:
        snap = self._inner.get_latest_snapshot()
        if snap is None:
            return None
        seq, lazy = snap
        # Breadth-first chunk warm-up: fetch every marker, then any markers
        # that surfaced inside fetched chunks.
        frontier = list(iter_vblob_ids(dict.copy(lazy)))
        seen = set()
        while frontier:
            blob_id = frontier.pop()
            if blob_id in seen:
                continue
            seen.add(blob_id)
            content = self._inner._fetch_chunk(blob_id)
            frontier.extend(iter_vblob_ids(json.loads(content)))
        return seq, lazy

    def write_snapshot(self, seq: int, summary: dict) -> None:
        self._inner.write_snapshot(seq, summary)

    def upload_blob_content(self, content: str) -> str:
        return self._inner.upload_blob_content(content)

    def read_blob_content(self, blob_id: str) -> str:
        return self._inner.read_blob_content(blob_id)

    def upload_summary(self, summary_tree: dict) -> str:
        return self._inner.upload_summary(summary_tree)
