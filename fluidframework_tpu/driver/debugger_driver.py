"""Debugger driver: an interposing document service for op-by-op replay.

Reference parity: packages/drivers/debugger (FluidDebugger.createFromService
/ createFromServiceFactory + DebugReplayController): wraps any document
service so inbound sequenced ops are HELD by a controller and released
under debugger control — step N ops, play to a sequence number, or resume
live pass-through. The controller here is programmatic (the reference pops
a debugger window; same control surface, no UI). Optionally starts from
no snapshot so the whole history replays through the debugger.
"""

from __future__ import annotations

from typing import Any, Callable

from ..protocol.messages import Nack, SequencedMessage, SignalMessage
from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    StorageService,
)


class DebugController:
    """Holds inbound ops; releases them on command (DebugReplayController).

    Modes: paused (default) buffers everything; live passes through.
    ``step``/``play_to_seq`` release from the buffer in order."""

    def __init__(self, start_paused: bool = True) -> None:
        self.paused = start_paused
        # Each held entry is (listener, msg): an op arriving on one
        # connection's stream is delivered only to THAT connection's
        # listener — sharing a controller across connections must not
        # fan each op out to every sink.
        self._buffer: list[tuple[Callable[[SequencedMessage], None], SequencedMessage]] = []
        self.released = 0

    # ----------------------------------------------------------- wiring
    def _on_op(
        self, sink: Callable[[SequencedMessage], None], msg: SequencedMessage
    ) -> None:
        if self.paused:
            self._buffer.append((sink, msg))
        else:
            self._deliver(sink, msg)

    def _deliver(
        self, sink: Callable[[SequencedMessage], None], msg: SequencedMessage
    ) -> None:
        self.released += 1
        sink(msg)

    # ---------------------------------------------------------- control
    @property
    def pending(self) -> int:
        return len(self._buffer)

    def next_seq(self) -> int | None:
        return self._buffer[0][1].seq if self._buffer else None

    def step(self, n: int = 1) -> int:
        """Release up to n buffered ops; returns how many were released."""
        released = 0
        while self._buffer and released < n:
            self._deliver(*self._buffer.pop(0))
            released += 1
        return released

    def play_to_seq(self, seq: int) -> int:
        """Release every buffered op with seq <= the target."""
        released = 0
        while self._buffer and self._buffer[0][1].seq <= seq:
            self._deliver(*self._buffer.pop(0))
            released += 1
        return released

    def resume(self) -> None:
        """Drain the buffer and go live (pass-through)."""
        self.paused = False
        while self._buffer:
            self._deliver(*self._buffer.pop(0))

    def pause(self) -> None:
        self.paused = True


class _DebugConnection(DeltaConnection):
    def __init__(self, inner: DeltaConnection, controller: DebugController) -> None:
        self._inner = inner
        self._controller = controller
        self.client_id = inner.client_id
        self.mode = inner.mode
        self.join_msg = inner.join_msg
        self.checkpoint_seq = inner.checkpoint_seq

    def submit(self, message: Any) -> None:
        self._inner.submit(message)

    def submit_signal(self, content: Any) -> None:
        self._inner.submit_signal(content)

    def disconnect(self) -> None:
        self._inner.disconnect()

    @property
    def connected(self) -> bool:
        return self._inner.connected


class _EmptyStorage(StorageService):
    """No-snapshot start: the debugger replays history from seq 0
    (the reference's 'start with any snapshot' choice)."""

    def __init__(self, inner: StorageService) -> None:
        self._inner = inner

    def get_latest_snapshot(self):
        return None

    def write_snapshot(self, seq: int, summary: dict) -> None:
        self._inner.write_snapshot(seq, summary)

    def upload_blob_content(self, content: str) -> str:
        return self._inner.upload_blob_content(content)

    def read_blob_content(self, blob_id: str) -> str:
        return self._inner.read_blob_content(blob_id)

    def upload_summary(self, summary_tree: dict) -> str:
        return self._inner.upload_summary(summary_tree)

    def get_versions(self, max_count: int = 5) -> list[dict]:
        return self._inner.get_versions(max_count)

    def get_snapshot_version(self, version_id: str) -> tuple[int, dict] | None:
        return self._inner.get_snapshot_version(version_id)


class DebuggerDocumentService(DocumentService):
    def __init__(
        self,
        inner: DocumentService,
        controller: DebugController,
        from_snapshot: bool = True,
    ) -> None:
        self._inner = inner
        self.controller = controller
        self._from_snapshot = from_snapshot

    def connect_to_delta_stream(
        self,
        client_id: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
    ) -> DeltaConnection:
        controller = self.controller
        conn = self._inner.connect_to_delta_stream(
            client_id,
            lambda msg: controller._on_op(listener, msg),
            nack_listener,
            signal_listener,
            mode=mode,
        )
        return _DebugConnection(conn, self.controller)

    def connect_to_delta_storage(self) -> DeltaStorageService:
        return self._inner.connect_to_delta_storage()

    def connect_to_storage(self) -> StorageService:
        storage = self._inner.connect_to_storage()
        return storage if self._from_snapshot else _EmptyStorage(storage)


class DebuggerDocumentServiceFactory(DocumentServiceFactory):
    """Wrap a factory so every created service is debugger-interposed; the
    per-document controllers are exposed for the host to drive."""

    def __init__(self, inner: DocumentServiceFactory, from_snapshot: bool = True) -> None:
        self._inner = inner
        self._from_snapshot = from_snapshot
        self.controllers: dict[str, DebugController] = {}

    def controller_for(self, doc_id: str) -> DebugController:
        if doc_id not in self.controllers:
            self.controllers[doc_id] = DebugController()
        return self.controllers[doc_id]

    def create_document_service(self, doc_id: str) -> DocumentService:
        return DebuggerDocumentService(
            self._inner.create_document_service(doc_id),
            self.controller_for(doc_id),
            from_snapshot=self._from_snapshot,
        )
