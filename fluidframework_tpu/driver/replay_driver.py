"""Replay and file drivers: boot containers from recorded histories.

Reference parity:
- ``replay-driver`` (packages/drivers/replay-driver): a read-only document
  service that replays the stored op log through the normal inbound path up
  to a target sequence number — the backbone of the replay tool and
  time-travel debugging.
- ``file-driver`` (packages/drivers/file-driver): snapshot + ops serialized
  to a plain file; load offline, no service.
- debugger-style interposition is covered by the storage/connection
  adapters accepting any underlying service.
"""

from __future__ import annotations

import json
from typing import Any, Callable

from ..protocol.messages import SequencedMessage
from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)


class _StaticDeltaStorage(DeltaStorageService):
    def __init__(self, ops: list[SequencedMessage]) -> None:
        self._ops = sorted(ops, key=lambda m: m.seq)

    def get_deltas(self, from_seq: int, to_seq: int) -> list[SequencedMessage]:
        return [m for m in self._ops if from_seq <= m.seq <= to_seq]


class _StaticStorage(StorageService):
    def __init__(self, snapshot: tuple[int, dict] | None) -> None:
        self._snapshot = snapshot

    def get_latest_snapshot(self) -> tuple[int, dict] | None:
        return self._snapshot

    def write_snapshot(self, seq: int, summary: dict) -> None:
        raise DriverError("replay storage is read-only", can_retry=False)

    def upload_summary(self, summary_tree: dict) -> str:
        raise DriverError("replay storage is read-only", can_retry=False)


class _ReplayConnection(DeltaConnection):
    """Read-only 'connection': pushes the recorded ops through the listener
    up to the replay target; never joins the quorum, never submits."""

    def __init__(self, ops: list[SequencedMessage], listener, to_seq: int | None):
        self.client_id = "__replay__"
        self.mode = "read"
        self.join_msg = None
        self.checkpoint_seq = 0
        self._connected = True
        self._listener = listener
        self._ops = [m for m in ops if to_seq is None or m.seq <= to_seq]
        self._cursor = 0

    def replay_to(self, seq: int | None = None) -> int:
        """Deliver recorded ops up to ``seq`` (all if None); returns count."""
        n = 0
        while self._cursor < len(self._ops):
            m = self._ops[self._cursor]
            if seq is not None and m.seq > seq:
                break
            self._listener(m)
            self._cursor += 1
            n += 1
        return n

    def submit(self, message: Any) -> None:
        raise DriverError("replay connection cannot submit ops", can_retry=False)

    def submit_signal(self, content: Any) -> None:
        raise DriverError("replay connection cannot signal", can_retry=False)

    def disconnect(self) -> None:
        self._connected = False

    @property
    def connected(self) -> bool:
        return self._connected


class ReplayDocumentService(DocumentService):
    """Serves one recorded document history (ref ReplayDocumentService)."""

    def __init__(
        self,
        ops: list[SequencedMessage],
        snapshot: tuple[int, dict] | None = None,
        to_seq: int | None = None,
    ) -> None:
        self._ops = sorted(ops, key=lambda m: m.seq)
        self._snapshot = snapshot
        self._to_seq = to_seq
        self.connections: list[_ReplayConnection] = []

    def connect_to_delta_stream(
        self, client_id, listener, nack_listener=None, signal_listener=None,
        mode: str = "read",
    ) -> DeltaConnection:
        if mode != "read":
            raise DriverError("replay documents are read-only", can_retry=False)
        conn = _ReplayConnection(self._ops, listener, self._to_seq)
        self.connections.append(conn)
        return conn

    def connect_to_delta_storage(self) -> DeltaStorageService:
        return _StaticDeltaStorage(self._ops)

    def connect_to_storage(self) -> StorageService:
        return _StaticStorage(self._snapshot)


class ReplayDocumentServiceFactory(DocumentServiceFactory):
    """Replays any live service's recorded history (ref replay-driver
    wrapping a real driver's delta storage)."""

    def __init__(
        self,
        history_fn: Callable[[str], tuple[list[SequencedMessage], tuple[int, dict] | None]],
        to_seq: int | None = None,
    ) -> None:
        self._history = history_fn
        self._to_seq = to_seq

    @staticmethod
    def from_local_service(service, to_seq: int | None = None) -> "ReplayDocumentServiceFactory":
        def history(doc_id: str):
            doc = service.document(doc_id)
            return list(doc.sequencer.log), doc.latest_snapshot()

        return ReplayDocumentServiceFactory(history, to_seq)

    def create_document_service(self, doc_id: str) -> ReplayDocumentService:
        ops, snapshot = self._history(doc_id)
        return ReplayDocumentService(ops, snapshot, self._to_seq)


# ---------------------------------------------------------------------------
# file driver
# ---------------------------------------------------------------------------


def save_document_file(path: str, ops: list[SequencedMessage], snapshot: tuple[int, dict] | None) -> None:
    """Serialize a document history to one JSON file (ref file-driver)."""
    data = {
        "snapshot": None if snapshot is None else [snapshot[0], snapshot[1]],
        "ops": [
            {
                "clientId": m.client_id,
                "clientSeq": m.client_seq,
                "refSeq": m.ref_seq,
                "seq": m.seq,
                "minSeq": m.min_seq,
                "type": m.type,
                "contents": m.contents,
                "metadata": m.metadata,
                "short": m.short_client,
            }
            for m in ops
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f)


def load_document_file(path: str) -> tuple[list[SequencedMessage], tuple[int, dict] | None]:
    with open(path) as f:
        data = json.load(f)
    ops = [
        SequencedMessage(
            client_id=e["clientId"],
            client_seq=e["clientSeq"],
            ref_seq=e["refSeq"],
            seq=e["seq"],
            min_seq=e["minSeq"],
            type=e["type"],
            contents=e["contents"],
            metadata=e["metadata"],
            timestamp=0.0,
            short_client=e["short"],
        )
        for e in data["ops"]
    ]
    snap = data["snapshot"]
    return ops, None if snap is None else (snap[0], snap[1])


class FileDocumentServiceFactory(DocumentServiceFactory):
    """Read-only boot from a saved document file (ref file-driver)."""

    def __init__(self, path: str, to_seq: int | None = None) -> None:
        self._path = path
        self._to_seq = to_seq

    def create_document_service(self, doc_id: str) -> ReplayDocumentService:
        ops, snapshot = load_document_file(self._path)
        return ReplayDocumentService(ops, snapshot, self._to_seq)
