"""Driver layer: the service abstraction between loader and ordering service.

Reference parity: packages/common/driver-definitions (IDocumentServiceFactory
/ IDocumentService / IDocumentDeltaConnection / IDocumentStorageService /
IDocumentDeltaStorageService) + packages/drivers/local-driver.
"""

from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)
from .local_driver import LocalDocumentServiceFactory
from .virtual_storage import (
    PrefetchStorageService,
    SnapshotCache,
    ThrottlingError,
    VirtualizedDocumentServiceFactory,
    VirtualizedStorageService,
    run_with_retry,
)

__all__ = [
    "DeltaConnection",
    "DeltaStorageService",
    "DocumentService",
    "DocumentServiceFactory",
    "DriverError",
    "LocalDocumentServiceFactory",
    "PrefetchStorageService",
    "SnapshotCache",
    "StorageService",
    "ThrottlingError",
    "VirtualizedDocumentServiceFactory",
    "VirtualizedStorageService",
    "run_with_retry",
]
