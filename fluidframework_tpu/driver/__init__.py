"""Driver layer: the service abstraction between loader and ordering service.

Reference parity: packages/common/driver-definitions (IDocumentServiceFactory
/ IDocumentService / IDocumentDeltaConnection / IDocumentStorageService /
IDocumentDeltaStorageService) + packages/drivers/local-driver.
"""

from .definitions import (
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)
from .local_driver import LocalDocumentServiceFactory

__all__ = [
    "DeltaConnection",
    "DeltaStorageService",
    "DocumentService",
    "DocumentServiceFactory",
    "DriverError",
    "LocalDocumentServiceFactory",
    "StorageService",
]
