"""In-memory driver binding the service abstraction to LocalService.

Reference parity: packages/drivers/local-driver — LocalDocumentServiceFactory
/ LocalDocumentService / LocalDeltaStorageService wrapping
LocalDeltaConnectionServer. The test backbone: full loader+runtime stacks
drive the in-process deli pipeline through exactly the interfaces a
networked driver would implement.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable

from ..protocol.messages import Nack, SequencedMessage, SignalMessage, UnsequencedMessage
from .definitions import (
    AuthRejection,
    DeltaConnection,
    DeltaStorageService,
    DocumentService,
    DocumentServiceFactory,
    DriverError,
    StorageService,
)

if TYPE_CHECKING:
    # Annotation-only: the driver binds to whatever service the registry
    # (driver.service_registry) resolved; the per-document backend surface
    # it wraps is LocalDocument's.  No runtime edge into the server tier.
    from ..server.local_service import LocalDocument, LocalService


class LocalDeltaConnection(DeltaConnection):
    def __init__(
        self,
        doc: LocalDocument,
        client_id: str,
        mode: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None,
        signal_listener: Callable[[SignalMessage], None] | None,
        token: str | None = None,
    ) -> None:
        self._doc = doc
        self.client_id = client_id
        self.mode = mode
        self._connected = True

        def on_nack(nack: Nack) -> None:
            # A nack invalidates the connection (ref: server closes the
            # socket after a nack; client must reconnect).
            self.disconnect()
            if nack_listener is not None:
                nack_listener(nack)

        try:
            self.join_msg, self.checkpoint_seq = doc.connect_stream(
                client_id, listener, on_nack, mode=mode, token=token
            )
        except AuthRejection as e:
            raise DriverError(f"connection rejected: {e}", can_retry=False) from e
        if signal_listener is not None:
            doc.subscribe_signals(client_id, signal_listener)

    def submit(self, message: Any) -> None:
        if not self._connected:
            raise DriverError("submit on disconnected connection")
        if self.mode != "write":
            raise DriverError("read connection cannot submit ops", can_retry=False)
        assert isinstance(message, UnsequencedMessage)
        self._doc.submit(message)

    def submit_signal(self, content: Any) -> None:
        if not self._connected:
            raise DriverError("signal on disconnected connection")
        self._doc.submit_signal(self.client_id, content)

    def disconnect(self) -> None:
        if self._connected:
            self._connected = False
            self._doc.disconnect(self.client_id)

    @property
    def connected(self) -> bool:
        return self._connected


class LocalDeltaStorageService(DeltaStorageService):
    def __init__(self, doc: LocalDocument) -> None:
        self._doc = doc

    def get_deltas(self, from_seq: int, to_seq: int) -> list[SequencedMessage]:
        return self._doc.ops_range(from_seq, to_seq)


class LocalStorageService(StorageService):
    def __init__(self, doc: LocalDocument) -> None:
        self._doc = doc

    def get_latest_snapshot(self) -> tuple[int, dict] | None:
        return self._doc.latest_snapshot()

    def write_snapshot(self, seq: int, summary: dict) -> None:
        self._doc.save_snapshot(seq, summary)

    def upload_blob_content(self, content: str) -> str:
        return self._doc.upload_blob(content)

    def read_blob_content(self, blob_id: str) -> str:
        return self._doc.read_blob(blob_id)

    def upload_summary(self, summary_tree: dict) -> str:
        return self._doc.upload_summary(summary_tree)

    def get_versions(self, max_count: int = 5) -> list[dict]:
        return self._doc.snapshot_versions(max_count)

    def get_snapshot_version(self, version_id: str) -> tuple[int, dict] | None:
        return self._doc.snapshot_at(version_id)


class LocalDocumentService(DocumentService):
    def __init__(self, doc: LocalDocument, token_provider=None) -> None:
        self._doc = doc
        self._token_provider = token_provider

    def connect_to_delta_stream(
        self,
        client_id: str,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
    ) -> DeltaConnection:
        token = None
        if self._token_provider is not None:
            token = self._token_provider(self._doc.doc_id, client_id)
        return LocalDeltaConnection(
            self._doc, client_id, mode, listener, nack_listener, signal_listener,
            token=token,
        )

    def connect_to_delta_storage(self) -> DeltaStorageService:
        return LocalDeltaStorageService(self._doc)

    def connect_to_storage(self) -> StorageService:
        return LocalStorageService(self._doc)


class LocalDocumentServiceFactory(DocumentServiceFactory):
    def __init__(self, service: LocalService, token_provider=None) -> None:
        """``token_provider(doc_id, client_id) -> token`` supplies tenant
        credentials when the service enforces auth (riddler analog)."""
        self._service = service
        self._token_provider = token_provider

    def create_document_service(self, doc_id: str) -> DocumentService:
        return LocalDocumentService(
            self._service.document(doc_id), self._token_provider
        )
