"""Tooling (SURVEY §2 layer 10): replay tool over the replay driver."""

from .replay_tool import ReplayTool

__all__ = ["ReplayTool"]
