"""Tooling (SURVEY §2 layer 10): replay tool over the replay driver +
summary-inspect CLI over the scribe's acked commits."""

from .replay_tool import ReplayTool

__all__ = ["ReplayTool"]
