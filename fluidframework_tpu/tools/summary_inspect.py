"""Summary-inspect CLI: browse and diff the scribe's acked summary commits.

Operator tooling over a scribe service directory (server/scribe.py):

    python -m fluidframework_tpu.tools.summary_inspect list DIR [--doc ID]
    python -m fluidframework_tpu.tools.summary_inspect show DIR --doc ID [--commit SHA]
    python -m fluidframework_tpu.tools.summary_inspect diff DIR --doc ID [SHA_A SHA_B]

``list`` prints one JSON line per acked commit (doc, seq, sha, family) —
the whole version chain when the object log holds the parents.  ``diff``
walks two materialized summaries and reports added/removed/changed paths
(defaults to the latest commit against its parent).  Read-only: safe
against a live scribe.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any


def _open(directory: str):
    from ..server.scribe import SummaryRecordStore

    return SummaryRecordStore.open(directory)


def _chain(store, doc_id: str) -> list[dict]:
    """Latest-first (seq, commit) chain for a doc, following parents."""
    ref = store.refs.get(doc_id)
    out = []
    sha = None if ref is None else ref["commit"]
    while sha is not None and sha in store.store:
        kind, payload = store.store.get(sha)
        if kind != "commit":
            break
        out.append({"commit": sha, "seq": payload["seq"]})
        sha = payload.get("parent")
    return out


def _materialize(store, doc_id: str, sha: str | None) -> tuple[int, dict]:
    ref = store.refs.get(doc_id)
    if sha is None:
        if ref is None:
            raise SystemExit(f"no acked summary for doc {doc_id!r}")
        sha = ref["commit"]
    kind, payload = store.store.get(sha)
    if kind != "commit":
        raise SystemExit(f"{sha[:12]} is a {kind}, not a commit")
    return payload["seq"], store.store.read_snapshot(payload["tree"])


def _diff(a: Any, b: Any, path: str = "") -> list[dict]:
    """Structural diff of two materialized summaries (path, kind, values
    elided past a size cap — operators diff shape first, bytes second)."""
    def clip(v: Any) -> Any:
        s = json.dumps(v)
        return v if len(s) <= 120 else s[:117] + "..."

    if isinstance(a, dict) and isinstance(b, dict):
        out: list[dict] = []
        for k in sorted(set(a) | set(b)):
            p = f"{path}/{k}" if path else k
            if k not in a:
                out.append({"path": p, "kind": "added", "to": clip(b[k])})
            elif k not in b:
                out.append({"path": p, "kind": "removed", "from": clip(a[k])})
            else:
                out.extend(_diff(a[k], b[k], p))
        return out
    if a != b:
        return [{"path": path, "kind": "changed", "from": clip(a), "to": clip(b)}]
    return []


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="summary-inspect", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="list acked summary commits")
    p_list.add_argument("directory")
    p_list.add_argument("--doc", default=None)

    p_show = sub.add_parser("show", help="materialize one summary record")
    p_show.add_argument("directory")
    p_show.add_argument("--doc", required=True)
    p_show.add_argument("--commit", default=None)

    p_diff = sub.add_parser("diff", help="diff two summaries of one doc")
    p_diff.add_argument("directory")
    p_diff.add_argument("--doc", required=True)
    p_diff.add_argument("shas", nargs="*",
                        help="two commit shas (default: latest vs parent)")

    args = p.parse_args(argv)
    store = _open(args.directory)

    if args.cmd == "list":
        docs = [args.doc] if args.doc else store.docs()
        for doc in docs:
            ref = store.refs.get(doc)
            for entry in _chain(store, doc):
                print(json.dumps({
                    "doc": doc, **entry,
                    "family": (ref or {}).get("family"),
                    "latest": entry["commit"] == (ref or {}).get("commit"),
                }))
        return 0

    if args.cmd == "show":
        seq, record = _materialize(store, args.doc, args.commit)
        print(json.dumps({"doc": args.doc, "seq": seq, "record": record}))
        return 0

    # diff
    if len(args.shas) == 2:
        sha_a, sha_b = args.shas
    elif not args.shas:
        chain = _chain(store, args.doc)
        if len(chain) < 2:
            print(json.dumps({"error": "need two commits to diff",
                              "available": chain}))
            return 1
        sha_b, sha_a = chain[0]["commit"], chain[1]["commit"]
    else:
        p.error("diff takes exactly 0 or 2 commit shas")
    seq_a, rec_a = _materialize(store, args.doc, sha_a)
    seq_b, rec_b = _materialize(store, args.doc, sha_b)
    print(json.dumps({
        "doc": args.doc,
        "from": {"commit": sha_a, "seq": seq_a},
        "to": {"commit": sha_b, "seq": seq_b},
        "changes": _diff(rec_a, rec_b),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
