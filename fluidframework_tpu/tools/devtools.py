"""Devtools: live container/DDS inspection + telemetry capture + metrics.

Reference parity: packages/tools/devtools/devtools-core — FluidDevtools
(container registry, initializeDevtools/registerContainerDevtools),
ContainerDevtools (container + audience metadata, DDS data visualization
via visualizeChildData), and DevtoolsLogger (telemetry event capture the
devtools view consumes). The reference talks to a browser extension over
window messaging; here the same state surfaces as JSON — consumable
programmatically or over the optional HTTP endpoint (``DevtoolsServer``),
the analog of the extension's message channel.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from ..utils.telemetry import Logger


# ---------------------------------------------------------------------------
# DDS visualization (devtools-core/src/data-visualization)
# ---------------------------------------------------------------------------

def visualize_channel(channel) -> dict[str, Any]:
    """Type-aware visual tree for one DDS (visualizeChildData analog):
    every known channel type renders its user-level state; unknown types
    fall back to their summary."""
    ctype = getattr(channel, "channel_type", type(channel).__name__)
    out: dict[str, Any] = {"type": ctype}
    try:
        if ctype == "sharedString":
            out["text"] = channel.text
            out["intervals"] = {
                label: [iv.to_json() for iv in coll]
                for label, coll in getattr(channel, "_collections", {}).items()
            }
        elif ctype == "sharedMap":
            out["entries"] = {k: channel.get(k) for k in channel.keys()}
        elif ctype == "sharedMatrix":
            out["rows"] = channel.row_count
            out["cols"] = channel.col_count
        elif ctype == "sharedTree":
            out["forest"] = channel.forest.to_json()
        elif ctype == "sharedCell":
            out["value"] = channel.get()
        elif ctype == "sharedJsonOT":
            out["doc"] = channel.get()
            out["pendingOps"] = len(channel._pending)
        elif ctype == "sharedDirectory":
            def walk(path: str) -> dict:
                node: dict[str, Any] = {
                    "keys": {k: channel.get(path, k) for k in sorted(channel.keys(path))},
                }
                subs = {
                    name: walk(f"{path}/{name}" if path else name)
                    for name in sorted(channel.subdirectories(path))
                }
                if subs:
                    node["subdirectories"] = subs
                return node

            out["tree"] = walk("")
        elif ctype == "taskManager":
            out["queues"] = {k: list(v) for k, v in channel.queues.items()}
        elif hasattr(channel, "value"):
            out["value"] = channel.value
        elif hasattr(channel, "summarize"):
            out["summary"] = channel.summarize()
    except Exception as e:  # visualization must never take the host down
        out["error"] = f"{type(e).__name__}: {e}"
    return out


class ContainerDevtools:
    """Inspection surface for one registered container runtime
    (devtools-core ContainerDevtools: metadata + audience + DDS data)."""

    def __init__(self, container_key: str, runtime) -> None:
        self.container_key = container_key
        self.runtime = runtime

    def metadata(self) -> dict[str, Any]:
        r = self.runtime
        return {
            "containerKey": self.container_key,
            "containerId": getattr(r, "id", None),
            "connected": bool(getattr(r, "has_document", False)),
            "refSeq": getattr(r, "ref_seq", None),
            "pendingOps": getattr(r, "pending_op_count", None),
        }

    def audience(self) -> list[dict[str, Any]]:
        quorum = getattr(self.runtime, "quorum_table", None)
        if quorum is None:
            return []
        return [
            {"clientId": cid, "shortId": short}
            for cid, short in sorted(quorum.items())
        ]

    def container_data(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for ds_id, ds in self.runtime.datastores.items():
            out[ds_id] = {
                ch_id: visualize_channel(ds.get_channel(ch_id))
                for ch_id in ds.channels
            }
        return out

    def to_json(self) -> dict[str, Any]:
        return {
            "metadata": self.metadata(),
            "audience": self.audience(),
            "data": self.container_data(),
        }


class DevtoolsLogger(Logger):
    """A telemetry logger the devtools surface (DevtoolsLogger analog):
    forwards to an optional base logger and keeps the event history."""

    def __init__(self, base: Logger | None = None, namespace: str = "") -> None:
        super().__init__(namespace=namespace)
        self._base = base

    def send(self, event: dict[str, Any]) -> None:
        super().send(event)
        if self._base is not None:
            self._base.send(dict(event))


class FluidDevtools:
    """The devtools root (devtools-core FluidDevtools.initialize):
    registered containers + captured telemetry + aggregate metrics."""

    def __init__(self, logger: DevtoolsLogger | None = None) -> None:
        self.containers: dict[str, ContainerDevtools] = {}
        self.logger = logger if logger is not None else DevtoolsLogger()
        self.disposed = False

    def register_container(self, container_key: str, runtime) -> ContainerDevtools:
        if container_key in self.containers:
            raise ValueError(f"container key {container_key!r} already registered")
        dt = ContainerDevtools(container_key, runtime)
        self.containers[container_key] = dt
        return dt

    def close_container(self, container_key: str) -> None:
        self.containers.pop(container_key, None)

    def metrics(self) -> dict[str, Any]:
        """Aggregate counters over captured telemetry (category/event)."""
        counts: dict[str, int] = {}
        durations: dict[str, float] = {}
        for e in self.logger.events:
            key = f"{e.get('category', '?')}:{e.get('eventName', '?')}"
            counts[key] = counts.get(key, 0) + 1
            if "duration" in e:
                durations[key] = durations.get(key, 0.0) + e["duration"]
        return {"eventCounts": counts, "eventDurations": durations}

    def to_json(self) -> dict[str, Any]:
        return {
            "containers": {k: c.to_json() for k, c in self.containers.items()},
            "metrics": self.metrics(),
            "events": list(self.logger.events)[-200:],
        }

    def dispose(self) -> None:
        self.containers.clear()
        self.disposed = True


# ---------------------------------------------------------------------------
# Optional HTTP surface (the extension-messaging analog)
# ---------------------------------------------------------------------------

class _DevtoolsHandler(BaseHTTPRequestHandler):
    def log_message(self, *a) -> None:  # quiet
        pass

    def do_GET(self) -> None:  # noqa: N802
        devtools: FluidDevtools = self.server.devtools  # type: ignore[attr-defined]
        if self.path == "/devtools":
            body = devtools.to_json()
        elif self.path == "/devtools/metrics":
            body = devtools.metrics()
        elif self.path.startswith("/devtools/container/"):
            key = self.path.rsplit("/", 1)[1]
            c = devtools.containers.get(key)
            if c is None:
                self.send_response(404)
                self.end_headers()
                return
            body = c.to_json()
        else:
            self.send_response(404)
            self.end_headers()
            return
        payload = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class DevtoolsServer:
    """Serve the devtools JSON over HTTP (GET /devtools[...])."""

    def __init__(self, devtools: FluidDevtools, port: int = 0) -> None:
        self._http = ThreadingHTTPServer(("127.0.0.1", port), _DevtoolsHandler)
        self._http.devtools = devtools  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    def start(self) -> "DevtoolsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
