"""fftpu-trace: summarize a flight-recorder trace without Perfetto.

Reads the Chrome trace-event JSON the flight recorder exports
(``FlightRecorder.export_chrome_trace``, ``bench.py --trace``,
``fleet_main --trace``) and prints:

- per-phase wall-time share (complete "X" spans grouped by name),
- the slowest individual spans (name, duration, labels),
- recompile instants (the recompile watchdog's de-specialization events),
- other instant events (migrations, rebalances) by name.

    fftpu-trace /tmp/t.json
    fftpu-trace /tmp/t.json --top 20
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter


def load_trace(path: str) -> list[dict]:
    """The traceEvents list of a Chrome trace JSON file (dict or bare
    array forms are both legal Chrome trace inputs)."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace: traceEvents is not a list")
    return events


def phase_table(events: list[dict]) -> list[tuple[str, float, int, float]]:
    """[(name, total_ms, count, share)] for "X" spans, biggest first.
    Nested spans each count their own full duration (attribution view)."""
    totals: dict[str, float] = {}
    counts: Counter = Counter()
    for ev in events:
        if ev.get("ph") == "X":
            name = ev.get("name", "?")
            totals[name] = totals.get(name, 0.0) + float(ev.get("dur", 0.0))
            counts[name] += 1
    grand = sum(totals.values()) or 1.0
    return [
        (name, t / 1e3, counts[name], t / grand)
        for name, t in sorted(totals.items(), key=lambda kv: -kv[1])
    ]


def slowest_spans(events: list[dict], top: int = 10) -> list[dict]:
    spans = [ev for ev in events if ev.get("ph") == "X"]
    return sorted(spans, key=lambda ev: -float(ev.get("dur", 0.0)))[:top]


def instants(events: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for ev in events:
        if ev.get("ph") == "i":
            out.setdefault(ev.get("name", "?"), []).append(ev)
    return out


def summarize(events: list[dict], top: int = 10) -> str:
    lines: list[str] = []
    n_spans = sum(1 for e in events if e.get("ph") == "X")
    tids = {e.get("tid") for e in events}
    lines.append(
        f"{len(events)} events ({n_spans} spans) across {len(tids)} threads"
    )
    table = phase_table(events)
    if table:
        lines.append("")
        lines.append("phase shares (span time attribution):")
        for name, ms, count, share in table:
            lines.append(
                f"  {name:<24} {share * 100:6.2f}%  {ms:10.3f} ms"
                f"  x{count}"
            )
    slow = slowest_spans(events, top)
    if slow:
        lines.append("")
        lines.append(f"slowest {len(slow)} spans:")
        for ev in slow:
            args = ev.get("args") or {}
            label = " ".join(f"{k}={v}" for k, v in args.items())
            lines.append(
                f"  {float(ev.get('dur', 0)) / 1e3:10.3f} ms"
                f"  {ev.get('name', '?'):<16} {label}"
            )
    inst = instants(events)
    recompiles = inst.pop("recompile", [])
    lines.append("")
    lines.append(f"recompile events: {len(recompiles)}")
    for ev in recompiles:
        args = ev.get("args") or {}
        lines.append(
            f"  @{float(ev.get('ts', 0)) / 1e3:.3f} ms"
            f"  program={args.get('program', '?')}"
            f" cache_size={args.get('cache_size', '?')}"
        )
    for name, evs in sorted(inst.items()):
        lines.append(f"instant {name}: x{len(evs)}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="fftpu-trace",
        description="summarize a flight-recorder Chrome trace",
    )
    p.add_argument("trace", help="Chrome trace-event JSON file")
    p.add_argument("--top", type=int, default=10,
                   help="slowest spans to list (default 10)")
    args = p.parse_args(argv)
    try:
        events = load_trace(args.trace)
    except (OSError, ValueError, KeyError) as e:
        print(f"fftpu-trace: {e}", file=sys.stderr)
        return 1
    print(summarize(events, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
