"""Replay tool: time-travel a recorded document through the real stack.

Reference parity: packages/tools/replay-tool — load a container read-only
over the replay driver, step it to arbitrary sequence numbers, and dump
state snapshots along the way (regression-compare runs)."""

from __future__ import annotations

from typing import Any

from ..dds.channels import default_registry
from ..driver.replay_driver import ReplayDocumentServiceFactory
from ..loader.container import Container


class ReplayTool:
    def __init__(self, factory: ReplayDocumentServiceFactory, doc_id: str,
                 registry: dict | None = None) -> None:
        self.container = Container.load(
            doc_id, factory, registry or default_registry(), "__replay__",
            mode="read",
        )
        self._conn = self.container.delta_manager.connection_manager.connection

    @classmethod
    def from_local_service(cls, service, doc_id: str, to_seq: int | None = None) -> "ReplayTool":
        return cls(
            ReplayDocumentServiceFactory.from_local_service(service, to_seq), doc_id
        )

    def step_to(self, seq: int | None = None) -> int:
        """Replay recorded ops up to ``seq`` (all when None)."""
        return self._conn.replay_to(seq)

    @property
    def current_seq(self) -> int:
        return self.container.runtime.ref_seq

    def state_dump(self) -> dict[str, Any]:
        """Full runtime state at the current replay point."""
        return self.container.runtime.summarize()
