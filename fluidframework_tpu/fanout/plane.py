"""The read fan-out hub: a shared frame ring with per-subscriber cursors.

Publishing a pump's delta batch is O(1) in the subscriber count: the frame
(encoded once, ``frames.DeltaFrame``) is appended to a bounded per-document
ring and every subscriber holds only a CURSOR into that ring — no per-
subscriber queue copies, no per-subscriber encode, no per-message walk.
The per-subscriber cost moves entirely to the drain side (the writer tier's
vectored socket sends, or a virtual drain in bench), where it is inherent.

Slow subscribers never stall the other N−1:

- the ring is bounded (frames + bytes); eviction drops the oldest frames;
- a subscriber whose cursor fell off the ring is BEHIND: at its next drain
  it gets a RESYNC — the missed range rebuilt from the ordered log (same
  cached per-message encodes, so the observed stream stays byte-identical
  to the firehose oracle) — and its cursor jumps to the ring head;
- per-peer direct queues (control messages, catch-up, signals) are bounded
  too; droppable entries (presence/signals: at-most-once by contract) are
  shed past the bound, control entries are session-bounded and never shed.

Locking: ONE plane lock covers ring/cursor/queue state; every operation
under it is O(1)-ish (append, pop, counter).  Socket sends happen on the
writer thread with the lock RELEASED.  Callers that publish under a
service lock (netserver) always take service-lock → plane-lock; the resync
callback is invoked with NO plane lock held so it can re-enter that order.

The presence plane rides the same peers and the same writer: signals are
encoded once per signal, scattered as droppable directs, and never touch
the sequencer — unsequenced, at-most-once, off the ordering path.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Callable, Iterable

from ..observability import instant, span
from ..protocol.messages import SequencedMessage
from .frames import (
    FLAVOR_ENVELOPE,
    FLAVOR_WIRE,
    KIND_RESYNC,
    DeltaFrame,
    build_frame,
)

# A resync request larger than the retained log window gets this marker
# instead of ops: the client must boot from a snapshot (historian tier).
# Client contract (FleetConsumer._boot_resync, both engine families via
# models/placement.adopt_boot_snapshot): a snapshot AHEAD of the doc's
# applied floor is adopted and consumption resumes from its seq; one
# at/below the floor is REFUSED (AdoptResult.adopted=False) and the doc
# falls to the supervisor restart path — re-subscribing from the
# engine's own floor would just draw this marker again, an infinite
# resync loop that looks healthy.
RESYNC_BOOT_MARKER = b'{"t":"resync","boot":true}\n'


class _DeltaSub:
    """One peer's delta subscription: a cursor into a doc's frame ring."""

    __slots__ = ("doc_id", "flavor", "cursor", "last_seq")

    def __init__(self, doc_id: str, flavor: str, cursor: int, last_seq: int):
        self.doc_id = doc_id
        self.flavor = flavor
        self.cursor = cursor      # next ring frame index to deliver
        self.last_seq = last_seq  # highest seq delivered/claimed (resync floor)


class FanoutPeer:
    """One outbound endpoint: a real socket (drained by the writer tier)
    or a virtual sink (drained explicitly — bench/tests)."""

    __slots__ = ("peer_id", "sock", "sink", "sub", "directs", "outbuf",
                 "dead", "sent_bytes", "sent_frames", "signal_drops",
                 "resyncs", "signal_docs", "signal_interests")

    def __init__(self, peer_id: int, sock=None, sink=None) -> None:
        self.peer_id = peer_id
        self.sock = sock
        self.sink = sink
        self.sub: _DeltaSub | None = None
        # (watermark_frame_idx, bytes): send once the delta cursor passed
        # the watermark — orders control messages relative to op frames.
        self.directs: deque[tuple[int, bytes]] = deque()
        # Claimed-but-unsent buffers (writer partial-send remainder).
        self.outbuf: list[memoryview] = []
        self.dead = False
        self.sent_bytes = 0
        self.sent_frames = 0
        self.signal_drops = 0
        self.resyncs = 0
        self.signal_docs: set[str] = set()
        # Per-doc scoped-presence interest sets: doc_id -> frozenset of
        # scope keys (None = the unscoped firehose, every signal).
        self.signal_interests: dict[str, frozenset | None] = {}

    @property
    def is_socket(self) -> bool:
        return self.sock is not None


class _DocRing:
    """Per-document frame ring + pending (un-flushed) pump batch."""

    __slots__ = ("doc_id", "frames", "base", "nbytes", "last_seq", "pending",
                 "subs", "socket_subs", "signal_peers")

    def __init__(self, doc_id: str, last_seq: int = 0) -> None:
        self.doc_id = doc_id
        self.frames: deque[DeltaFrame] = deque()
        self.base = 0          # ring index of frames[0]
        self.nbytes = 0
        self.last_seq = last_seq  # seq_hi of the newest published frame
        self.pending: list[SequencedMessage] = []
        self.subs: list[FanoutPeer] = []
        # Socket-backed subscribers only: what a flush must wake.  Kept
        # separately so publishing stays O(1) however many virtual/cursor
        # subscribers ride the ring (the 100k-subscriber bench shape).
        self.socket_subs: list[FanoutPeer] = []
        self.signal_peers: list[FanoutPeer] = []

    @property
    def head(self) -> int:
        return self.base + len(self.frames)


# resync source: (doc_id, from_seq_exclusive) -> ordered SequencedMessages
# with seq > from_seq, or None when the range is no longer retained (the
# subscriber must snapshot-boot).  Called with NO plane lock held; the
# provider takes its own (service) lock.
ResyncSource = Callable[[str, int], "list[SequencedMessage] | None"]


class FanoutPlane:
    """Delta frame ring + presence scatter over a shared peer set."""

    def __init__(
        self,
        resync_source: ResyncSource | None = None,
        ring_frames: int = 512,
        ring_bytes: int = 8 << 20,
        max_directs: int = 4096,
        claim_bytes: int = 1 << 20,
    ) -> None:
        self._lock = threading.RLock()
        self._resync_source = resync_source
        self.ring_frames = ring_frames
        self.ring_bytes = ring_bytes
        self.max_directs = max_directs
        self.claim_bytes = claim_bytes
        self._docs: dict[str, _DocRing] = {}
        self._peer_seq = 0
        self._peers: set[FanoutPeer] = set()
        # Writer tier (writer.FanoutWriter); optional — virtual-only planes
        # (bench at 100k subscribers) never start a thread.
        self._writer = None
        # -------- counters (all mutated under the plane lock) --------
        self.frames_published = 0
        self.frame_bytes = 0
        self.frames_evicted = 0
        self.flushes = 0
        self.resyncs = 0
        self.boot_resyncs = 0
        self.signals_published = 0
        self.signal_deliveries = 0
        self.signal_drops = 0
        self.presence_scope_drops = 0
        self.directs_enqueued = 0

    # ------------------------------------------------------------------ wiring
    def set_writer(self, writer) -> None:
        self._writer = writer

    def new_peer(self, sock=None, sink=None) -> FanoutPeer:
        with self._lock:
            self._peer_seq += 1
            peer = FanoutPeer(self._peer_seq, sock=sock, sink=sink)
            self._peers.add(peer)
            return peer

    def remove_peer(self, peer: FanoutPeer) -> None:
        with self._lock:
            peer.dead = True
            self._peers.discard(peer)
            sub = peer.sub
            if sub is not None:
                ring = self._docs.get(sub.doc_id)
                if ring is not None and peer in ring.subs:
                    ring.subs.remove(peer)
                if ring is not None and peer in ring.socket_subs:
                    ring.socket_subs.remove(peer)
            for doc_id in peer.signal_docs:
                ring = self._docs.get(doc_id)
                if ring is not None and peer in ring.signal_peers:
                    ring.signal_peers.remove(peer)
            peer.signal_docs.clear()
            peer.signal_interests.clear()
            peer.directs.clear()
            peer.outbuf = []
        if self._writer is not None:
            self._writer.forget(peer)

    def _ring(self, doc_id: str) -> _DocRing:
        ring = self._docs.get(doc_id)
        if ring is None:
            ring = self._docs[doc_id] = _DocRing(doc_id)
        return ring

    def ensure_doc(self, doc_id: str, last_seq: int = 0) -> None:
        """Register a document with its current broadcast floor (the seq
        already delivered before the plane tapped the stream): resyncs and
        empty-ring attaches anchor on it."""
        with self._lock:
            ring = self._docs.get(doc_id)
            if ring is None:
                self._docs[doc_id] = _DocRing(doc_id, last_seq=last_seq)

    # ---------------------------------------------------------------- publish
    def tap(self, doc_id: str, msg: SequencedMessage) -> None:
        """Per-message accumulation seam (ONE subscriber per document on the
        ordering core, whatever the subscriber count): O(1) append."""
        with self._lock:
            self._ring(doc_id).pending.append(msg)

    def flush(self, doc_id: str) -> DeltaFrame | None:
        """Frame the pending batch and publish it to the ring: the pump
        boundary.  O(1) in the subscriber count."""
        with self._lock:
            ring = self._docs.get(doc_id)
            if ring is None or not ring.pending:
                return None
            with span("fanout_flush", doc=doc_id, n=len(ring.pending)):
                frame = build_frame(doc_id, ring.pending)
                ring.pending = []
                self._publish(ring, frame)
            socket_peers = list(ring.socket_subs)
        if socket_peers and self._writer is not None:
            # O(socket peers of the doc): the unavoidable per-subscriber
            # half lives on the writer thread, not under the service lock.
            self._writer.wake(socket_peers)
        return frame

    def publish(self, doc_id: str, msgs: Iterable[SequencedMessage]):
        """Tap + flush in one call (lambda pipeline / bench seam)."""
        with self._lock:
            ring = self._ring(doc_id)
            ring.pending.extend(msgs)
        return self.flush(doc_id)

    def _publish(self, ring: _DocRing, frame: DeltaFrame) -> None:
        ring.frames.append(frame)
        ring.nbytes += frame.nbytes
        ring.last_seq = frame.seq_hi
        self.frames_published += 1
        self.frame_bytes += frame.nbytes
        # Bounded ring: evict oldest (keep >=1 so head-1 stays readable).
        while len(ring.frames) > 1 and (
            len(ring.frames) > self.ring_frames or ring.nbytes > self.ring_bytes
        ):
            old = ring.frames.popleft()
            ring.base += 1
            ring.nbytes -= old.nbytes
            self.frames_evicted += 1
        self.flushes += 1

    # ----------------------------------------------------------------- attach
    def attach(
        self, doc_id: str, peer: FanoutPeer, flavor: str = FLAVOR_WIRE,
        last_seq: int | None = None,
    ) -> None:
        """Subscribe a peer at the CURRENT ring head: everything published
        after this call arrives through the cursor; the already-delivered
        prefix is the caller's catch-up problem (direct bytes or snapshot
        boot)."""
        if flavor not in (FLAVOR_WIRE, FLAVOR_ENVELOPE):
            raise ValueError(f"unknown flavor {flavor!r}")
        with self._lock:
            old = peer.sub
            if old is not None:
                # Re-attach replaces the subscription: leave the previous
                # ring's lists or the stale entry outlives the peer there
                # (remove_peer only cleans the CURRENT sub's doc).
                old_ring = self._docs.get(old.doc_id)
                if old_ring is not None:
                    if peer in old_ring.subs:
                        old_ring.subs.remove(peer)
                    if peer in old_ring.socket_subs:
                        old_ring.socket_subs.remove(peer)
            ring = self._ring(doc_id)
            floor = ring.last_seq if last_seq is None else last_seq
            peer.sub = _DeltaSub(doc_id, flavor, ring.head, floor)
            ring.subs.append(peer)
            if peer.is_socket:
                ring.socket_subs.append(peer)

    def add_signal_peer(
        self, doc_id: str, peer: FanoutPeer,
        interests: Iterable[str] | None = None,
    ) -> None:
        """Subscribe a peer to a document's signal scatter.  ``interests``
        narrows it to a scoped presence workspace: only signals published
        with a scope key in the set reach this peer (unscoped signals —
        joins/leaves/broadcast presence — always deliver).  ``None`` is the
        legacy firehose.  Re-calling replaces the interest set in place."""
        with self._lock:
            ring = self._ring(doc_id)
            if peer not in ring.signal_peers:
                ring.signal_peers.append(peer)
                peer.signal_docs.add(doc_id)
            peer.signal_interests[doc_id] = (
                None if interests is None else frozenset(interests)
            )

    # ---------------------------------------------------------------- directs
    def enqueue_direct(
        self, peer: FanoutPeer, data: bytes, droppable: bool = False,
        wake: bool = True,
    ) -> bool:
        """Queue per-peer bytes ordered AFTER every op frame already
        published for the peer's document.  Control messages (joined/nack/
        sync/catch-up) are never shed — they are small and session-bounded;
        droppable entries (signals) shed past the bound (at-most-once).
        ``wake=False`` lets a batch caller issue ONE writer wake for the
        whole scatter instead of one per peer."""
        with self._lock:
            if peer.dead:
                return False
            if droppable and len(peer.directs) >= self.max_directs:
                peer.signal_drops += 1
                self.signal_drops += 1
                instant("fanout_signal_drop", peer=peer.peer_id)
                return False
            sub = peer.sub
            wm = 0
            if sub is not None:
                ring = self._docs.get(sub.doc_id)
                wm = ring.head if ring is not None else 0
            peer.directs.append((wm, data))
            self.directs_enqueued += 1
        if wake and self._writer is not None and peer.is_socket:
            self._writer.wake([peer])
        return True

    # ---------------------------------------------------------------- signals
    def publish_signal(
        self, doc_id: str, client_id: str, contents: Any,
        scope: str | None = None,
    ) -> int:
        """Presence/signal scatter: ONE encode, N droppable enqueues, zero
        sequencer interaction, zero blocking sends under any caller lock.
        A ``scope`` key skips peers whose interest set for the doc excludes
        it (scoped presence workspaces); unscoped signals reach everyone."""
        with self._lock:
            ring = self._docs.get(doc_id)
            peers = list(ring.signal_peers) if ring is not None else []
            self.signals_published += 1
            if scope is not None and peers:
                kept = []
                for p in peers:
                    interests = p.signal_interests.get(doc_id)
                    if interests is None or scope in interests:
                        kept.append(p)
                    else:
                        self.presence_scope_drops += 1
                peers = kept
        if not peers:
            return 0
        data = (json.dumps(
            {"t": "signal", "clientId": client_id, "contents": contents},
            separators=(",", ":"),
        ) + "\n").encode()
        delivered = 0
        woken = []
        for peer in peers:
            if self.enqueue_direct(peer, data, droppable=True, wake=False):
                delivered += 1
                if peer.is_socket:
                    woken.append(peer)
        if woken and self._writer is not None:
            # ONE wake for the whole scatter: per-peer wakes would re-add
            # the very per-subscriber syscall cost this plane removes.
            self._writer.wake(woken)
        with self._lock:
            self.signal_deliveries += delivered
        return delivered

    # ------------------------------------------------------------------ drain
    def claim(self, peer: FanoutPeer, max_bytes: int | None = None):
        """Pop the next run of sendable buffers for a peer (writer tier or
        virtual drain).  Returns ``(buffers, needs_resync)``; when
        ``needs_resync`` the caller must invoke :meth:`resync` (with no
        plane lock held) and claim again.  Cursor/last_seq advance at claim
        time — the caller owns delivering what it claimed."""
        limit = self.claim_bytes if max_bytes is None else max_bytes
        bufs: list[bytes] = []
        total = 0
        with self._lock:
            sub = peer.sub
            ring = self._docs.get(sub.doc_id) if sub is not None else None
            # Behind: the ring evicted frames this cursor never saw.  No
            # partial progress — resync first so ordering (directs included)
            # rebuilds against the post-resync cursor.
            if sub is not None and ring is not None and sub.cursor < ring.base:
                return [], True
            while total < limit:
                if peer.directs and (
                    sub is None or peer.directs[0][0] <= sub.cursor
                ):
                    _wm, data = peer.directs.popleft()
                elif sub is not None and ring is not None and sub.cursor < ring.head:
                    frame = ring.frames[sub.cursor - ring.base]
                    data = frame.payload(sub.flavor)
                    sub.cursor += 1
                    sub.last_seq = frame.seq_hi
                    peer.sent_frames += 1
                else:
                    break
                bufs.append(data)
                total += len(data)
        return bufs, False

    def backlog_of(self, peer: FanoutPeer, head_cap: int | None = None) -> int:
        """Frames-behind + queued directs + claimed-unsent buffers: the
        consumer-pressure signal admission control reads.  Monotone under a
        stall even after ring eviction (the cursor keeps falling behind).
        ``head_cap`` counts ring frames only up to a snapshot head — a
        graceful-disconnect flush waits on what was queued at goodbye
        time, not on frames the doc keeps publishing after it."""
        with self._lock:
            n = len(peer.directs) + len(peer.outbuf)
            sub = peer.sub
            if sub is not None:
                ring = self._docs.get(sub.doc_id)
                if ring is not None:
                    head = ring.head if head_cap is None else min(
                        head_cap, ring.head
                    )
                    n += max(0, head - sub.cursor)
            return n

    def head_of(self, peer: FanoutPeer) -> int:
        """Current ring head for the peer's subscription (0 when none):
        the goodbye-time snapshot ``backlog_of(head_cap=...)`` consumes."""
        with self._lock:
            sub = peer.sub
            if sub is None:
                return 0
            ring = self._docs.get(sub.doc_id)
            return ring.head if ring is not None else 0

    def backlog(self, doc_id: str, wire_only: bool = True) -> int:
        """Deepest subscriber backlog for a document (socket peers; the
        firehose-consumer signal unless ``wire_only=False``)."""
        with self._lock:
            ring = self._docs.get(doc_id)
            if ring is None:
                return 0
            peers = [
                p for p in ring.socket_subs
                if not wire_only
                or (p.sub is not None and p.sub.flavor == FLAVOR_WIRE)
            ]
        return max((self.backlog_of(p) for p in peers), default=0)

    # ----------------------------------------------------------------- resync
    def resync(self, peer: FanoutPeer) -> None:
        """Rebuild a behind peer's missed range from the ordered log and
        jump its cursor to the head.  MUST be called with no plane lock
        held: the resync source takes the service lock (service → plane is
        the plane-wide lock order)."""
        sub = peer.sub
        if sub is None:
            return
        source = self._resync_source
        msgs = source(sub.doc_id, sub.last_seq) if source is not None else None
        with self._lock:
            if peer.dead or peer.sub is not sub:
                return
            ring = self._ring(sub.doc_id)
            if msgs:
                # Cap at the PUBLISHED head: the ordered log also holds
                # ticketed-but-undelivered ops — resyncing past the last
                # published frame would deliver them early AND again when
                # their own frame flushes (engines carry no seq dedupe
                # above the checkpoint floor, so that double-applies).
                msgs = [m for m in msgs if m.seq <= ring.last_seq]
            # The source read its log under the service lock; publishes are
            # serialized by that same lock, so frames that landed before
            # this point are covered by msgs IF their seq <= the read head.
            # Jump the cursor only past frames the rebuilt range covers.
            if msgs:
                frame = DeltaFrame(sub.doc_id, msgs, kind=KIND_RESYNC)
                data = frame.payload(sub.flavor)
                cursor = ring.base
                while (
                    cursor < ring.head
                    and ring.frames[cursor - ring.base].seq_hi <= frame.seq_hi
                ):
                    cursor += 1
                sub.cursor = cursor
                sub.last_seq = max(sub.last_seq, frame.seq_hi)
                peer.directs.appendleft((-1, data))
                peer.resyncs += 1
                self.resyncs += 1
                instant("fanout_resync", doc=sub.doc_id, peer=peer.peer_id,
                        n=frame.n_msgs)
            else:
                # Range no longer retained (or no source): direct the
                # subscriber to snapshot-boot from the historian tier.
                sub.cursor = ring.head
                sub.last_seq = ring.last_seq
                peer.directs.appendleft((-1, RESYNC_BOOT_MARKER))
                peer.resyncs += 1
                self.resyncs += 1
                self.boot_resyncs += 1
                instant("fanout_resync_boot", doc=sub.doc_id,
                        peer=peer.peer_id)

    # ---------------------------------------------------------- virtual drain
    def drain_virtual(self, peer: FanoutPeer, max_rounds: int = 1 << 20) -> int:
        """Drain a sink-backed peer to quiescence (bench/tests): feeds every
        claimed buffer to ``peer.sink`` in order.  Returns bytes drained."""
        drained = 0
        for _ in range(max_rounds):
            bufs, needs_resync = self.claim(peer)
            if needs_resync:
                self.resync(peer)
                continue
            if not bufs:
                break
            for b in bufs:
                if peer.sink is not None:
                    peer.sink(b)
                drained += len(b)
                peer.sent_bytes += len(b)
        return drained

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "peers": len(self._peers),
                "docs": len(self._docs),
                "subscribers": sum(len(r.subs) for r in self._docs.values()),
                "signal_peers": sum(
                    len(r.signal_peers) for r in self._docs.values()
                ),
                "frames_published": self.frames_published,
                "frame_bytes": self.frame_bytes,
                "frames_evicted": self.frames_evicted,
                "flushes": self.flushes,
                "resyncs": self.resyncs,
                "boot_resyncs": self.boot_resyncs,
                "signals_published": self.signals_published,
                "signal_deliveries": self.signal_deliveries,
                "signal_drops": self.signal_drops,
                "presence_scope_drops": self.presence_scope_drops,
                "directs_enqueued": self.directs_enqueued,
            }
