"""Columnar delta wire frames: encode once per (doc, pump), scatter bytes.

The write path (PR 5) already encodes each ``SequencedMessage`` exactly once
(``wire_line``/``op_envelope`` cache the bytes on the message).  What the
read path still paid per subscriber was the PYTHON WALK: one callback, one
queue append, one socket write *per message per subscriber*.  A
``DeltaFrame`` collapses a whole pump's sequenced batch for one document
into ONE immutable bytes payload per wire flavor, built from the cached
per-message encodes — so fan-out to N subscribers is N buffer references to
the same object, not N x B encodes or N x B callbacks.

Two flavors of the same frame, both composed from the single cached encode:

- ``wire``     — bare ``SequencedMessage`` JSON lines (the firehose /
  deltas-topic consumer seam; exactly what ``native/ingest.cpp`` parses);
- ``envelope`` — the nexus client broadcast form, each line wrapped as
  ``{"t":"op","msg":<line>}`` (textual wrap around the SAME cached encode;
  no re-``json.dumps``).

``protocol.messages.wire_encode_count()`` counts actual ``json.dumps``
calls, so tests and the fanout bench can assert the ≤1-encode-per-
(doc, pump) contract regardless of subscriber count.
"""

from __future__ import annotations

from typing import Sequence

from ..protocol.messages import SequencedMessage

# Wire flavors a subscriber may attach with.
FLAVOR_WIRE = "wire"
FLAVOR_ENVELOPE = "envelope"

# Frame kinds: a live pump batch vs. a catch-up rebuild from the ordered
# log after a drop (byte-identical content, flagged for observability).
KIND_DELTA = "delta"
KIND_RESYNC = "resync"


class DeltaFrame:
    """One document's sequenced batch for one pump, encoded once."""

    __slots__ = ("doc_id", "seq_lo", "seq_hi", "n_msgs", "wire", "kind",
                 "_msgs", "_envelope")

    def __init__(
        self,
        doc_id: str,
        msgs: Sequence[SequencedMessage],
        kind: str = KIND_DELTA,
    ) -> None:
        if not msgs:
            raise ValueError("empty delta frame")
        self.doc_id = doc_id
        self._msgs = tuple(msgs)
        self.n_msgs = len(self._msgs)
        self.seq_lo = self._msgs[0].seq
        self.seq_hi = self._msgs[-1].seq
        self.kind = kind
        # The bare firehose payload is built eagerly (every deployment has
        # at least one wire-flavor consumer: the device fleet); the client
        # envelope lazily on first envelope subscriber.
        self.wire = b"".join(m.wire_line() for m in self._msgs)
        self._envelope: bytes | None = None

    @property
    def envelope(self) -> bytes:
        b = self._envelope
        if b is None:
            b = b"".join(m.op_envelope() for m in self._msgs)
            self._envelope = b
        return b

    def payload(self, flavor: str) -> bytes:
        return self.wire if flavor == FLAVOR_WIRE else self.envelope

    @property
    def nbytes(self) -> int:
        return len(self.wire)

    def __repr__(self) -> str:  # debugging/trace labels
        return (f"DeltaFrame({self.doc_id!r}, seq {self.seq_lo}-{self.seq_hi},"
                f" n={self.n_msgs}, kind={self.kind})")


def build_frame(
    doc_id: str, msgs: Sequence[SequencedMessage], kind: str = KIND_DELTA
) -> DeltaFrame:
    """Frame one pump's batch (the ``BroadcasterLambda.subscribe_frames``
    seam and the hub's flush both land here)."""
    return DeltaFrame(doc_id, msgs, kind=kind)
