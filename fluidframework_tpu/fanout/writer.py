"""Selector-driven writer tier: vectored socket sends off every lock.

One thread drains EVERY socket-backed fan-out peer: nonblocking sockets, a
``selectors`` readiness loop, and ``sendmsg`` vectored sends so one syscall
ships a whole run of queued frames/directs.  A peer whose kernel buffer is
full simply stays registered for writability — it never blocks the thread,
so a stalled subscriber costs the other N−1 nothing (the plane's ring
eviction + resync bounds its memory).

Claim protocol (see ``plane.FanoutPlane.claim``): the writer claims a run
of buffers under the plane lock, RELEASES the lock, and sends.  Partial
sends keep the remainder in ``peer.outbuf`` (memoryviews over the claimed
bytes) and are always finished before the next claim — a resync can
therefore never split a claimed frame.  When a claim reports the peer is
behind, the writer invokes the plane's resync (which takes the service
lock; the writer holds no plane lock at that point — lock order preserved).
"""

from __future__ import annotations

import contextlib
import selectors
import socket
import threading

from ..observability import instant

# Buffers per sendmsg call: well under every platform's IOV_MAX (1024 on
# Linux) while still amortizing syscalls over a deep backlog.
_IOV_BATCH = 64


class FanoutWriter:
    """The one writer thread over all socket peers of a FanoutPlane."""

    def __init__(self, plane, on_dead=None) -> None:
        self._plane = plane
        self._on_dead = on_dead  # callback(peer): session-layer cleanup
        self._sel = selectors.DefaultSelector()
        # Wake channel: publishers signal new work without touching the
        # selector from their thread (only the writer mutates it).
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._lock = threading.Lock()  # guards _pending/_forgotten/_stopped
        self._pending: set = set()     # peers with possibly-new work
        self._registered: set = set()  # peers currently in the selector
        self._forgotten: set = set()   # dropped peers awaiting deregistration
        self._stopped = False
        self.sends = 0
        self.send_bytes = 0
        self.partial_sends = 0
        self.dead_peers = 0
        self._thread = threading.Thread(
            target=self._run, name="fanout-writer", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ wakes
    def wake(self, peers) -> None:
        """Mark peers as having pending outbound work (any thread)."""
        with self._lock:
            if self._stopped:
                return
            before = len(self._pending)
            self._pending.update(p for p in peers if p.is_socket and not p.dead)
            changed = len(self._pending) != before
        if changed:
            with contextlib.suppress(BlockingIOError, OSError):
                # A byte already in flight wakes the loop just the same.
                self._wake_w.send(b"x")

    def forget(self, peer) -> None:
        """Drop a peer (session teardown).  The selector entry is removed
        by the writer thread on its next pass (only it touches the
        selector — and a parked entry MUST be removed, or the stale fd
        blocks a future peer reusing it from ever registering); the
        socket itself is closed by the session layer."""
        with self._lock:
            self._pending.discard(peer)
            self._forgotten.add(peer)
        with contextlib.suppress(BlockingIOError, OSError):
            self._wake_w.send(b"x")

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        with contextlib.suppress(OSError):
            self._wake_w.send(b"x")
        self._thread.join(timeout=5)
        with contextlib.suppress(OSError):
            self._wake_r.close()
        with contextlib.suppress(OSError):
            self._wake_w.close()
        with contextlib.suppress(OSError, RuntimeError):
            self._sel.close()

    # ------------------------------------------------------------------- loop
    def _run(self) -> None:
        while True:
            ready = self._sel.select(timeout=1.0)
            with self._lock:
                if self._stopped:
                    return
                fresh = self._pending
                self._pending = set()
                forgotten = self._forgotten
                self._forgotten = set()
            for peer in forgotten:
                # selectors' unregister falls back to a map scan when the
                # fd is already closed, so parked dead peers always leave.
                self._deregister(peer)
                fresh.discard(peer)
            for key, _ev in ready:
                if key.data is None:  # wake channel
                    with contextlib.suppress(BlockingIOError, OSError):
                        while self._wake_r.recv(4096):
                            pass
                else:
                    fresh.add(key.data)
            for peer in fresh:
                self._service_peer(peer)

    def _service_peer(self, peer) -> None:
        if peer.dead:
            self._deregister(peer)
            return
        progressed = True
        while progressed:
            if not peer.outbuf:
                bufs, needs_resync = self._plane.claim(peer)
                if needs_resync:
                    # No plane lock held here: resync re-enters the
                    # service-lock -> plane-lock order safely.
                    self._plane.resync(peer)
                    bufs, _ = self._plane.claim(peer)
                peer.outbuf = [memoryview(b) for b in bufs if b]
            if not peer.outbuf:
                self._deregister(peer)
                return
            progressed = self._send_some(peer)
            if peer.dead:
                self._deregister(peer)
                self._plane.remove_peer(peer)
                if self._on_dead is not None:
                    self._on_dead(peer)
                return
        # Kernel buffer full: park on writability.
        self._register(peer)

    def _send_some(self, peer) -> bool:
        """One vectored send attempt; True when bytes moved."""
        batch = peer.outbuf[:_IOV_BATCH]
        try:
            if hasattr(peer.sock, "sendmsg"):
                n = peer.sock.sendmsg(batch)
            else:  # non-socket transports in tests
                n = peer.sock.send(b"".join(batch))
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            with self._lock:
                peer.dead = True
                self.dead_peers += 1
            instant("fanout_peer_dead", peer=peer.peer_id)
            return False
        with self._lock:
            self.sends += 1
            self.send_bytes += n
            peer.sent_bytes += n
        # Trim fully-sent buffers, slice the partial one.
        i = 0
        while i < len(batch) and n >= len(batch[i]):
            n -= len(batch[i])
            i += 1
        if i < len(batch) and n:
            batch[i] = batch[i][n:]
            with self._lock:
                self.partial_sends += 1
        del peer.outbuf[:i]
        if peer.outbuf and n:
            peer.outbuf[0] = batch[i]
        return True

    # -------------------------------------------------------------- selector
    def _register(self, peer) -> None:
        if peer in self._registered:
            return
        try:
            self._sel.register(peer.sock, selectors.EVENT_WRITE, peer)
        except (KeyError, ValueError, OSError):
            return
        self._registered.add(peer)

    def _deregister(self, peer) -> None:
        if peer not in self._registered:
            return
        self._registered.discard(peer)
        with contextlib.suppress(KeyError, ValueError, OSError):
            self._sel.unregister(peer.sock)

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "sends": self.sends,
                "send_bytes": self.send_bytes,
                "partial_sends": self.partial_sends,
                "dead_peers": self.dead_peers,
            }
