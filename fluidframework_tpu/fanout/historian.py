"""Snapshot-boot tier: HTTP reads straight out of the git snapshot store.

Booting readers are the vast majority of a hot document's traffic and need
NOTHING from the ordering path: the latest acked summary commit (plus the
trailing ops from delta storage) fully seeds a client.  This tier serves
exactly that — summary commits out of ``GitSnapshotStore`` — behind real
HTTP caching semantics, so a CDN/proxy (or the client's own cache) absorbs
the fleet-sized read load (the reference's historian layer, SURVEY §1:
historian → gitrest serve snapshots behind caching; ``git_sharing_ratio``
~0.65 says the content-addressed store already dedupes the bytes).

Caching contract:

- **ETag is the commit sha** — the version identity.  Content-addressed
  storage makes this exact: same sha ⇒ byte-identical snapshot.
- ``/doc/<id>/snapshot`` (latest) answers with ``Cache-Control: no-cache``
  (always revalidate: "latest" moves) but honors ``If-None-Match`` with a
  **304** — a booting reader that raced a summary pays one header
  round-trip, not a snapshot download.
- ``/doc/<id>/snapshot/<sha>`` and ``/doc/<id>/path/<sha>?path=a/b/c`` are
  **immutable** (``max-age=31536000, immutable``): a sha-addressed read can
  be cached forever by anything between us and the reader.
- ``path`` reads resolve one subtree via ``GitStore.read_path`` — the
  virtualized partial boot (fetch a single channel without the snapshot).

The tier holds NO service lock and never touches a sequencer: reads walk
immutable content-addressed objects (the version list is append-only, and
dict reads are GIL-atomic), so a boot storm cannot stall op ticketing.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse

from ..observability import span

# source: doc_id -> GitSnapshotStore-like (``versions``/``store``/
# ``read_commit``/``latest``/``version_ids``) or None for unknown docs.
SnapshotSource = Callable[[str], object]

_IMMUTABLE = "public, max-age=31536000, immutable"
_REVALIDATE = "no-cache"


def _etag_matches(header: str | None, sha: str) -> bool:
    if not header:
        return False
    if header.strip() == "*":
        return True
    tags = [t.strip().strip('"') for t in header.split(",")]
    return sha in [t.removeprefix("W/").strip('"') for t in tags]


class _HistorianHandler(BaseHTTPRequestHandler):
    def log_message(self, *a) -> None:  # quiet
        pass

    def _json(self, code: int, obj, headers: dict | None = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        self.server.owner._count("bytes_served", len(body))  # type: ignore[attr-defined]

    def _not_modified(self, sha: str, cache_control: str) -> None:
        self.send_response(304)
        self.send_header("ETag", f'"{sha}"')
        self.send_header("Cache-Control", cache_control)
        self.end_headers()

    def do_GET(self) -> None:  # noqa: N802, C901 - route dispatch
        tier: HistorianTier = self.server.owner  # type: ignore[attr-defined]
        u = urlparse(self.path)
        parts = [p for p in u.path.split("/") if p]
        q = parse_qs(u.query)
        tier._count("requests")
        if parts == ["status"]:
            self._json(200, tier.stats())
            return
        if parts == ["metrics"]:
            from ..observability.metrics_plane import render_prometheus

            body = render_prometheus({"historian": tier.stats()}).encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if len(parts) < 3 or parts[0] != "doc":
            tier._count("bad_routes")
            self._json(404, {"error": "bad route"})
            return
        store = tier.source(parts[1])
        if store is None:
            tier._count("unknown_docs")
            self._json(404, {"error": "no such document"})
            return
        inm = self.headers.get("If-None-Match")
        with span("historian_read", doc=parts[1], route=parts[2]):
            if parts[2] == "versions" and len(parts) == 3:
                try:
                    max_count = int(q.get("max", ["5"])[0])
                except ValueError:
                    self._json(400, {"error": "non-numeric max"})
                    return
                self._json(200, {"versions": store.version_ids(max_count)})
            elif parts[2] == "snapshot" and len(parts) == 3:
                # Latest: revalidate-always, but a matching ETag costs one
                # header round-trip (the boot-storm fast path).
                if not store.versions:
                    tier._count("missing_snapshots")
                    self._json(404, {"error": "no snapshot"})
                    return
                seq, sha = store.versions[-1]
                if _etag_matches(inm, sha):
                    tier._count("not_modified_304")
                    self._not_modified(sha, _REVALIDATE)
                    return
                tier._count("cold_serves")
                _seq, summary = store.read_commit(sha)
                self._json(
                    200,
                    {"seq": seq, "commit": sha, "summary": summary},
                    headers={"ETag": f'"{sha}"',
                             "Cache-Control": _REVALIDATE},
                )
            elif parts[2] == "snapshot" and len(parts) == 4:
                sha = parts[3]
                if _etag_matches(inm, sha):
                    # Immutable: a sha-addressed conditional GET never even
                    # touches the object store.
                    tier._count("not_modified_304")
                    self._not_modified(sha, _IMMUTABLE)
                    return
                try:
                    seq, summary = store.read_commit(sha)
                except KeyError:
                    tier._count("unknown_commits")
                    self._json(404, {"error": "no such commit"})
                    return
                tier._count("cold_serves")
                self._json(
                    200,
                    {"seq": seq, "commit": sha, "summary": summary},
                    headers={"ETag": f'"{sha}"', "Cache-Control": _IMMUTABLE},
                )
            elif parts[2] == "path" and len(parts) == 4:
                sha = parts[3]
                path = q.get("path", [""])[0]
                if _etag_matches(inm, sha):
                    tier._count("not_modified_304")
                    self._not_modified(sha, _IMMUTABLE)
                    return
                try:
                    kind, payload = store.store.get(sha)
                    if kind != "commit":
                        raise KeyError(sha)
                    value = store.store.read_path(payload["tree"], path)
                except KeyError:
                    tier._count("unknown_commits")
                    self._json(404, {"error": "no such commit or path"})
                    return
                tier._count("path_reads")
                self._json(
                    200,
                    {"commit": sha, "path": path, "value": value},
                    headers={"ETag": f'"{sha}"', "Cache-Control": _IMMUTABLE},
                )
            else:
                tier._count("bad_routes")
                self._json(404, {"error": "bad route"})


class HistorianTier:
    """The standalone snapshot-boot HTTP server over a snapshot source."""

    def __init__(self, source: SnapshotSource, port: int = 0) -> None:
        self.source = source
        self._started = time.monotonic()
        self._stats_lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._http = ThreadingHTTPServer(("127.0.0.1", port), _HistorianHandler)
        self._http.owner = self  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(
            target=self._http.serve_forever, name="historian", daemon=True
        )

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._counters)
        out["uptime_s"] = round(time.monotonic() - self._started, 3)
        return out

    def start(self) -> "HistorianTier":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()


def service_snapshot_source(service) -> SnapshotSource:
    """Adapt a ``LocalService`` into a snapshot source: non-creating doc
    lookup → the document's git version chain.  Reads are lock-free by
    design (immutable content-addressed objects; append-only refs)."""
    def source(doc_id: str):
        doc = service.peek_document(doc_id)
        if doc is None:
            return None
        return doc.snapshot_store()

    return source
