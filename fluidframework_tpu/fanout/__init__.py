"""Read fan-out plane: encode-once delta broadcast, snapshot-boot tier,
sequencer-free presence (ISSUE 13).

Three planes over one peer set:

- **Delta fan-out** (``plane``/``frames``/``writer``): each document's
  sequenced pump batch is encoded ONCE into a ``DeltaFrame`` (built from
  the PR 5 cached per-message wire encodes) and published to a bounded
  per-doc frame ring; subscribers hold cursors, the selector-driven writer
  tier drains sockets with vectored ``sendmsg`` sends, and slow
  subscribers drop-to-catch-up via a byte-identical resync from the
  ordered log — never stalling the other N−1.
- **Snapshot boot** (``historian``): summary commits served straight out
  of ``GitSnapshotStore`` behind ETag/304/immutable HTTP caching and
  ``read_path`` partial subtree reads — booting readers never touch the
  sequencer or the fleet.
- **Presence** (``plane.publish_signal``): signals encoded once and
  scattered through the same writer tier as bounded droppable directs —
  unsequenced, at-most-once, off the ordering path and off the service
  lock.
"""

from .frames import (
    FLAVOR_ENVELOPE,
    FLAVOR_WIRE,
    KIND_DELTA,
    KIND_RESYNC,
    DeltaFrame,
    build_frame,
)
from .historian import HistorianTier, service_snapshot_source
from .plane import RESYNC_BOOT_MARKER, FanoutPeer, FanoutPlane
from .writer import FanoutWriter

__all__ = [
    "DeltaFrame",
    "FLAVOR_ENVELOPE",
    "FLAVOR_WIRE",
    "FanoutPeer",
    "FanoutPlane",
    "FanoutWriter",
    "HistorianTier",
    "KIND_DELTA",
    "KIND_RESYNC",
    "RESYNC_BOOT_MARKER",
    "build_frame",
    "service_snapshot_source",
]
