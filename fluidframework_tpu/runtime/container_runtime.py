"""ContainerRuntime: per-container orchestration of the full op lifecycle.

Reference parity: container-runtime/src/containerRuntime.ts — inbound
``process`` (:3181) → ungroup/decompress/unchunk → duplicate-batch drop →
pending zip (:3280) → bunching (:3428) → datastore dispatch; outbound submit
→ Outbox → flush-at-turn-end; PendingStateManager replay on reconnect;
getPendingLocalState/rehydrate for offline resume (container.ts:1152 +
pendingStateManager.ts); quorum short-id table from sequenced joins.

Connection identity semantics (the subtle part, mirrored from the
reference's connection state machine): on reconnect the container keeps
matching in-flight ops from its PREVIOUS identity during catch-up (pending
messages record the identity they were flushed under), and only after its
own new join is sequenced — i.e. provably after every old in-flight op —
does it resubmit what's still pending, under the new identity but with the
ORIGINAL batch ids (fork detection).
"""

from __future__ import annotations

from typing import Any

from ..protocol.driver_contracts import DriverError
from ..protocol.messages import MessageType, Nack, SequencedMessage
from ..protocol.channel import MessageEnvelope, bunch_contiguous
from .datastore import DataStoreRuntime
from .op_lifecycle import (
    DuplicateBatchDetector,
    InboundRuntimeMessage,
    Outbox,
    RemoteMessageProcessor,
)
from .pending_state import PendingStateManager


from .errors import ContainerForkError, DataProcessingError  # noqa: F401 (re-export)

# Address reserved for runtime-level ops (datastore/channel attach — the
# reference's attach messages, channelCollection.ts "attach" type): they ride
# the normal outbox/batch machinery but dispatch to the runtime itself.
RUNTIME_ADDRESS = "__runtime__"


class ContainerRuntime:
    """One collaborative container: datastores + op lifecycle + connection."""

    def __init__(
        self,
        registry: dict[str, Any],
        container_id: str = "container",
        track_attribution: bool = False,
    ) -> None:
        self.id = container_id
        self._registry = registry
        self._datastores: dict[str, DataStoreRuntime] = {}
        self._psm = PendingStateManager()
        self._rmp = RemoteMessageProcessor()
        self._detector = DuplicateBatchDetector()
        self._quorum: dict[str, int] = {}
        self._document = None
        self._outbox: Outbox | None = None
        self.client_id: str | None = None
        self.joined = False
        self.ref_seq = 0
        self.min_seq = 0
        self.closed = False
        self.close_error: Exception | None = None
        self._expected_join_seq = -1
        self._detached_counter = 0
        self._stash: dict[str, Any] | None = None
        self._processing_inbound = False
        # Quorum proposals in flight on the current connection; a dropped
        # connection rejects them (the reference rejects the propose promise
        # on disconnect so callers can retry — quorum.ts propose).
        self._inflight_proposals: list[dict] = []
        # (client_id) per sequenced LEAVE — audience-departure consumers
        # (presence attendee tracking) that aren't channels.
        self.member_left_listeners: list = []
        # listener(touched: set[(datastore_id, channel_id)]) after each
        # processed inbound batch — the view-binding invalidation feed.
        self.op_processed_listeners: list = []
        # Runtime attributor (ref framework/attributor mixinAttributor):
        # seq -> {client, timestamp} recorded from the sequenced stream,
        # summarized interned+delta-encoded, restored on load.
        if track_attribution:
            from ..framework.attributor import OpStreamAttributor

            self.attributor = OpStreamAttributor()
        else:
            self.attributor = None
        self.rejected_proposals: list[dict] = []
        # Summarization state (runtime/summary.py): ops since the last acked
        # summary drive the RunningSummarizer heuristics; last_summary_ref_seq
        # is the baseline for incremental handle reuse (refreshLatestSummary).
        self.ops_since_summary_ack = 0
        self.last_summary_ref_seq: int | None = None
        self.on_summary_ack = None
        self.on_summary_nack = None
        # Attachment blobs + GC (runtime/blob_manager.py, runtime/gc.py).
        from .blob_manager import BlobManager
        from .gc import GCState

        self.blobs = BlobManager(
            upload=self._upload_blob_to_storage,
            read=self._read_blob_from_storage,
            submit_attach=lambda blob_id: self._submit_datastore_op(
                RUNTIME_ADDRESS, {"runtimeOp": "attachBlob", "id": blob_id}, None
            ),
        )
        self.gc_state = GCState()
        # Sweep distance in sequence numbers: a node must stay unreferenced
        # this long before a gcDelete op removes it everywhere (the
        # reference ages by wall clock; seq distance is deterministic).
        self.gc_sweep_after_ops = 64

    # ------------------------------------------------------------------- blobs
    def _upload_blob_to_storage(self, content: str) -> str:
        if self._document is None:
            raise RuntimeError("blob upload requires a connected container")
        return self._document.upload_blob(content)

    def _read_blob_from_storage(self, blob_id: str) -> str:
        if self._document is None:
            raise RuntimeError("blob read requires a connected container")
        return self._document.read_blob(blob_id)

    def upload_blob(self, content: str) -> str:
        """Upload an attachment blob; returns its ``blob:<id>`` handle
        (store it in any DDS value to keep the blob referenced)."""
        return self.blobs.create_blob(content)

    def get_blob(self, handle: str) -> str:
        return self.blobs.get_blob(handle)

    # -------------------------------------------------------------- datastores
    def create_datastore(self, ds_id: str, root: bool = True) -> DataStoreRuntime:
        if ds_id in self._datastores:
            raise ValueError(f"datastore {ds_id!r} already exists")
        if ds_id in self.gc_state.tombstoned:
            raise ValueError(f"datastore {ds_id!r} was deleted by GC")

        def submit(
            contents: dict, metadata: Any, internal: bool = False, _ds_id: str = ds_id
        ) -> None:
            self._submit_datastore_op(_ds_id, contents, metadata, internal)

        ds = DataStoreRuntime(
            ds_id,
            self._registry,
            submit,
            lambda cid: self._quorum[cid],
            lambda: self.client_id,
            lambda: list(self._quorum),
            lambda: self.ref_seq,
            root=root,
        )
        self._datastores[ds_id] = ds
        return ds

    def datastore(self, ds_id: str) -> DataStoreRuntime:
        return self._datastores[ds_id]

    def submit_datastore_attach(self, ds_id: str) -> None:
        """Sequence a new datastore's existence + layout so every remote
        replica instantiates it before its ops arrive (ref data store attach
        ops, dataStoreContext.ts). Safe to call for snapshot-baked stores:
        replicas that already have it ignore the op."""
        ds = self._datastores[ds_id]
        self._submit_datastore_op(
            RUNTIME_ADDRESS,
            {"runtimeOp": "attachDataStore", "id": ds_id, "structure": ds.structure_summary()},
            None,
        )

    def submit_channel_attach(self, ds_id: str, channel_id: str) -> None:
        """Sequence a dynamically-created channel on an existing datastore
        (ref channelCollection "attach" message)."""
        ch = self._datastores[ds_id].get_channel(channel_id)
        self._submit_datastore_op(
            RUNTIME_ADDRESS,
            {
                "runtimeOp": "attachChannel",
                "ds": ds_id,
                "id": channel_id,
                "channelType": ch.channel_type,
            },
            None,
        )

    def _apply_runtime_op(self, inner: dict, seq: int) -> None:
        """Apply one attach op (shared by inbound dispatch and stash
        rehydrate). Marks the attached channels dirty at the attach seq so
        summaries don't emit handles into snapshots predating them."""
        op = inner["runtimeOp"]
        if op == "attachDataStore":
            if inner["id"] in self.gc_state.tombstoned:
                # A stale client (pre-sweep snapshot) re-attaching a swept
                # datastore must not poison every replica: drop the op
                # (tombstones win; ref GC tombstone enforcement).
                return
            if inner["id"] not in self._datastores:
                self.create_datastore(
                    inner["id"], root=inner["structure"].get("root", True)
                ).load(inner["structure"])
            ds = self._datastores[inner["id"]]
            for cid in ds.channels:
                ds.changed_seqs[cid] = max(ds.changed_seqs.get(cid, 0), seq)
        elif op == "attachChannel":
            ds = self._datastores[inner["ds"]]
            if inner["id"] not in ds.channels:
                ds.create_channel(inner["channelType"], inner["id"])
            ds.changed_seqs[inner["id"]] = max(
                ds.changed_seqs.get(inner["id"], 0), seq
            )
        elif op == "attachBlob":
            self.blobs.on_attach(inner["id"])
        elif op == "gcDelete":
            # Sequenced sweep (ref GC sweep-ready op): every replica deletes
            # the same nodes at the same point in the total order.
            self._apply_gc_delete(inner["ids"])
        else:
            raise DataProcessingError(f"unknown runtime op {op!r}")

    def _apply_gc_delete(self, node_keys: list[str]) -> None:
        for key in node_keys:
            kind, _, node_id = key.partition("/")
            if kind == "ds":
                self._datastores.pop(node_id, None)
                self.gc_state.tombstoned.add(node_id)
            elif kind == "blob":
                self.blobs.delete(node_id)
            self.gc_state.unreferenced_since.pop(key, None)

    def _handle_runtime_messages(self, env, run) -> None:
        for inner, _local, _md in run:
            self._apply_runtime_op(inner, env.seq)

    @property
    def datastores(self) -> dict[str, DataStoreRuntime]:
        return dict(self._datastores)

    @property
    def has_document(self) -> bool:
        """Whether a document link is live (loader checks before disconnect)."""
        return self._document is not None

    def process_sequenced(self, msg: SequencedMessage) -> None:
        """Public inbound entry for loader-driven read connections."""
        self._on_sequenced(msg)

    # ----------------------------------------------------------------- outbound
    def _submit_datastore_op(
        self, ds_id: str, contents: dict, metadata: Any, internal: bool = False
    ) -> None:
        if self._processing_inbound and not internal:
            # Reentrancy guard (ref ensureNoDataModelChanges,
            # containerRuntime.ts:1500): minting local ops from inside
            # inbound op application breaks ref-seq consistency.
            raise RuntimeError("local edit during inbound op processing")
        if self._outbox is None:
            # Disconnected/detached: stage into a connectionless outbox whose
            # flushes park in the pending list until a connection exists.
            self._outbox = Outbox(client_id="")
        self._outbox.submit({"address": ds_id, "contents": contents}, metadata)

    def flush(self) -> None:
        """End-of-turn flush (ref Outbox.flush at JS microtask end)."""
        if self._outbox is None:
            return
        if self._outbox.client_id == "" or not self.joined:
            # Not connected — or connected but our join hasn't sequenced yet
            # (the reference holds outbound until connected): park staged
            # messages as unsent pending state; they replay on join.
            self._park_outbox(keep_outbox=True)
            return
        batch = self._outbox.flush(self.ref_seq)
        if batch is None:
            return
        self._psm.on_flush_batch(batch.messages, batch.batch_id, self._outbox.client_id)
        for wire in batch.wire_messages:
            if self._document is None:
                break  # a nack mid-batch dropped the connection
            try:
                self._document.submit(wire)
            except DriverError:
                # A failed send invalidates the connection (the reference
                # treats socket submit errors as disconnects).  The batch is
                # already pending under this identity, so reconnect replay
                # re-sends whatever never arrived; sending the REST of the
                # batch now would tear the batch's atomicity.
                self._drop_connection()
                break

    def rollback_staged(self) -> None:
        """Undo every staged-but-unflushed local op, newest first (ref
        Outbox rollback used by transaction abort paths)."""
        if self._outbox is None:
            return
        while True:
            m = self._outbox.peek_staged()
            if m is None:
                break
            # Channel rollback first: if a DDS does not support rollback the
            # op must STAY staged (its effect is still applied locally).
            self._datastores[m.contents["address"]].rollback(
                m.contents["contents"], m.local_metadata
            )
            self._outbox.pop_staged()

    @property
    def pending_op_count(self) -> int:
        return self._psm.pending_count

    # --------------------------------------------------------------- connection
    def connect(self, document, client_id: str, stash: str | None = None) -> None:
        """Join a document. Catch-up is synchronous (the local service replays
        the delivered prefix through our subscriber before ticketing the
        join). A stash (from get_pending_local_state) is applied at the exact
        sequence point it was taken (ref applyStashedOpsAt)."""
        if self._document is not None:
            raise RuntimeError("already connected; disconnect first")
        if stash is not None:
            self._stash = PendingStateManager.parse_local_state(stash)
        self._document = document
        self.client_id = client_id
        self.joined = False
        self._outbox = self._adopt_outbox(client_id)
        self._expected_join_seq = -1  # catch-up must not match any join
        join_msg = document.connect(client_id, self._on_sequenced, self._on_nack)
        if self.closed:
            # Catch-up closed us (e.g. fork detection) but the join was
            # still ticketed: leave cleanly so we don't pin the MSN forever.
            document.disconnect(client_id)
            return
        self._expected_join_seq = join_msg.seq
        self._maybe_apply_stash(catch_up_done=True)

    def _adopt_outbox(self, client_id: str) -> Outbox:
        """A fresh outbox for this connection; anything staged while
        disconnected is parked as pending first (it replays on join)."""
        if self._outbox is not None and not self._outbox.is_empty:
            assert self._outbox.client_id == ""
        self._park_outbox()
        return Outbox(client_id=client_id)

    def disconnect(self) -> None:
        if self._document is None:
            return
        try:
            self.flush()  # anything staged rides out before the leave
        except DriverError:
            # The connection may already be dead (unclean drop — network
            # fault, injected disconnect): staged ops stay in the outbox and
            # park as pending on the next connect instead of crashing the
            # teardown.
            pass
        if self._document is None:
            return  # the flush was nacked; _on_nack already dropped the link
        self._document.disconnect(self.client_id)
        self._document = None
        self._park_outbox()
        self.joined = False
        self._reject_inflight_proposals()

    def _park_outbox(self, keep_outbox: bool = False) -> None:
        """Staged-but-unflushed ops must survive losing the connection: park
        them as pending (client_id "") so the next connect replays them —
        dropping the outbox would orphan the channels' optimistic state
        (their pending bookkeeping has no ack coming).  ``keep_outbox``
        retains the (drained) outbox for continued staging — the
        disconnected-flush path, where the connection identity persists."""
        if self._outbox is not None and not self._outbox.is_empty:
            self._detached_counter += 1
            batch = self._outbox.park(f"unsent_{self.id}_{self._detached_counter}")
            if batch is not None:
                self._psm.on_flush_batch(batch.messages, batch.batch_id, client_id="")
        if not keep_outbox:
            self._outbox = None

    def close(self, error: Exception | None = None) -> None:
        """Terminal: detach from the document and refuse further work (ref
        Container.close on DataProcessingError)."""
        if self._document is not None:
            self._document.disconnect(self.client_id)
            self._document = None
        self._park_outbox()  # keeps the stash (get_pending_local_state) whole
        self.joined = False
        self.closed = True
        self.close_error = error
        self._reject_inflight_proposals()

    def _drop_connection(self) -> None:
        """Sever the document link after a connection-fatal failure: staged
        ops park as pending, in-flight proposals reject, the host reconnects."""
        if self._document is not None:
            self._document.disconnect(self.client_id)
            self._document = None
        self._park_outbox()
        self.joined = False
        self._reject_inflight_proposals()

    def _on_nack(self, nack: Nack) -> None:
        """A nack invalidates the connection: drop it and let the host
        reconnect (ref ConnectionManager reconnect-on-nack)."""
        if self._document is not None:
            self._drop_connection()

    def _reject_inflight_proposals(self) -> None:
        """A dropped connection cannot sequence what it had in flight:
        surface unacked proposals so the host can retry (ref quorum.ts
        rejects the propose promise on disconnect)."""
        inflight, self._inflight_proposals = self._inflight_proposals, []
        for entry in inflight:
            if entry["type"] == MessageType.SUMMARIZE:
                # A dropped summarize surfaces as a nack so the summary
                # manager's heuristics retry on the next connection.
                if self.on_summary_nack is not None:
                    self.on_summary_nack(
                        {
                            "handle": entry["contents"].get("handle"),
                            "error": "connection dropped",
                        }
                    )
            else:
                self.rejected_proposals.append(entry)

    # ----------------------------------------------------------------- inbound
    def _on_sequenced(self, msg: SequencedMessage) -> None:
        if self.closed:
            return
        if msg.seq <= self.ref_seq:
            # Already processed (reconnect catch-up replays the full log;
            # ref DeltaManager drops ops at/below lastProcessedSequenceNumber).
            return
        if self._outbox is not None and not self._outbox.is_empty:
            # Ref-seq consistency (ref containerRuntime.ts:3188): staged
            # local ops must go out stamped with their authoring context
            # before any inbound op advances this container's state.
            self.flush()
        if self._stash is not None and msg.seq > self._stash["refSeq"]:
            self._maybe_apply_stash(catch_up_done=False)
        if self.attributor is not None and msg.type == MessageType.OP:
            # Runtime attribution (ref mixinAttributor/runtimeAttributor):
            # every sequenced op records {client, timestamp}; DDS-level
            # attribution keys (seqs) resolve through this table.
            self.attributor.observe(msg)
        self.ref_seq = msg.seq
        new_min = msg.min_seq > self.min_seq
        self.min_seq = max(self.min_seq, msg.min_seq)

        if msg.type == MessageType.JOIN:
            self._quorum[msg.contents["clientId"]] = msg.contents["short"]
            # Only THIS connection's join (matched by exact seq) flips us to
            # joined — a stale join of the same client id replayed during
            # catch-up must not trigger a premature pending replay.
            if msg.seq == self._expected_join_seq and not self.joined:
                self.joined = True
                self._replay_pending()
        elif msg.type == MessageType.LEAVE:
            self._quorum.pop(msg.contents["clientId"], None)
            for ds in self._datastores.values():
                ds.on_client_leave(msg.contents["clientId"], msg.seq)
            for fn in list(self.member_left_listeners):
                fn(msg.contents["clientId"])
        elif msg.type in (MessageType.PROPOSE, MessageType.SUMMARIZE):
            if (
                msg.client_id == self.client_id
                and self._inflight_proposals
                and self._inflight_proposals[0]["type"] == msg.type
                and self._inflight_proposals[0]["contents"] == msg.contents
            ):
                self._inflight_proposals.pop(0)  # sequenced: no longer at risk
        elif msg.type == MessageType.SUMMARY_ACK:
            # A summary is durable: advance the incremental baseline and
            # reset the heuristics counter (ref refreshLatestSummary).
            self.last_summary_ref_seq = msg.contents["refSeq"]
            self.ops_since_summary_ack = 0
            if self.on_summary_ack is not None:
                self.on_summary_ack(msg.contents)
        elif msg.type == MessageType.SUMMARY_NACK:
            if self.on_summary_nack is not None:
                self.on_summary_nack(msg.contents)
        elif msg.type == MessageType.OP:
            try:
                self._process_op(msg)
            except DataProcessingError as e:
                # Close THIS container only; other replicas keep receiving
                # the broadcast (the reference closes the faulted container,
                # not the service).
                self.close(e)
                return

        if new_min:
            for ds in self._datastores.values():
                ds.on_min_seq(self.min_seq)

    def _process_op(self, msg: SequencedMessage) -> None:
        inbound = self._rmp.process(msg)
        if not inbound:
            return  # partial chunk
        batch_id = inbound[0].batch_id
        # "Our own op" matching is by submitting identity: stashed entries
        # carry the identity they were flushed under, so a batch sequenced
        # under the PREVIOUS identity before the stash was taken acks the
        # stashed ops on rehydrate (ref pendingStateManager.ts matches
        # savedOps by clientId/clientSequenceNumber), while the same batch
        # id arriving under a DIFFERENT identity is a rehydrated twin's
        # replay — a fork.
        local = (
            self._psm.has_pending and self._psm.head_client_id == msg.client_id
        )
        if not local:
            if batch_id is not None and batch_id in self._psm.pending_batch_ids():
                raise ContainerForkError(
                    f"remote batch {batch_id!r} matches a pending local batch: "
                    "container fork detected"
                )
            if self._detector.observe(batch_id, msg.seq, msg.min_seq):
                return  # duplicate resubmission of an already-sequenced batch
        else:
            self._detector.observe(batch_id, msg.seq, msg.min_seq)

        # Summary heuristics count runtime ops, not wire messages: a grouped
        # batch contributes its full op count (ref opsSinceLastSummary) —
        # counted only after duplicate-batch drops, so resubmitted ops that
        # never mutate state don't inflate the summarizer's trigger.
        self.ops_since_summary_ack += len(inbound)

        # Outbound-reference detection (ref addedGCOutboundReference): any
        # sequenced op carrying a handle string resets that node's
        # unreferenced age — without this, a node re-referenced and
        # re-unreferenced BETWEEN two GC runs would keep its stale age and
        # sweep early.
        if self.gc_state.unreferenced_since:
            from .gc import scan_handles

            ds_refs: set[str] = set()
            blob_refs: set[str] = set()
            for m in inbound:
                scan_handles(m.contents, ds_refs, blob_refs)
            for ref in ds_refs:
                self.gc_state.unreferenced_since.pop(f"ds/{ref}", None)
            for ref in blob_refs:
                self.gc_state.unreferenced_since.pop(f"blob/{ref}", None)
        zipped: list[tuple[InboundRuntimeMessage, Any]] = []
        for m in inbound:
            md = self._psm.match_inbound(m.contents) if local else None
            zipped.append((m, md))

        # Bunch contiguous same-datastore messages (containerRuntime.ts:3428).
        self._processing_inbound = True
        touched: set[tuple[str, str]] = set()
        try:
            env = MessageEnvelope(
                client_id=msg.client_id,
                seq=msg.seq,
                min_seq=msg.min_seq,
                ref_seq=msg.ref_seq,
            )

            def dispatch(addr, run):
                if addr == RUNTIME_ADDRESS:
                    self._handle_runtime_messages(env, run)
                    return
                if addr in self.gc_state.tombstoned:
                    # Tombstone drop (ref GC tombstone routing): ops from a
                    # stale client to a swept datastore are discarded.
                    return
                for contents, _local, _md in run:
                    touched.add((addr, contents.get("address", "")))
                self._datastores[addr].process_messages(env, run)

            bunch_contiguous(
                (
                    (m.contents["address"], (m.contents["contents"], local, md))
                    for m, md in zipped
                ),
                dispatch,
            )
        finally:
            self._processing_inbound = False
        if touched:
            # View-binding invalidation (framework/bindings.py): which
            # (datastore, channel) addresses this batch changed.
            for fn in list(self.op_processed_listeners):
                fn(touched)

    # --------------------------------------------------------------- reconnect
    def _replay_pending(self) -> None:
        """Resubmit everything still pending, under the current identity but
        with original batch ids (ref replayPendingStates).  A send failure
        mid-replay drops the connection; groups not yet re-staged go back
        into the pending set untouched so the NEXT reconnect replays them
        (take_pending_for_replay removed them up front)."""
        groups = self._psm.take_pending_for_replay()
        for gi, group in enumerate(groups):
            if self._document is None:
                # Connection died mid-replay: restore the untouched tail
                # verbatim for the next reconnect's replay.
                self._psm.restore([p for later in groups[gi:] for p in later])
                return
            for p in group:
                if p.contents["address"] == RUNTIME_ADDRESS:
                    # Attach ops resubmit verbatim (position-free).
                    self._submit_datastore_op(
                        RUNTIME_ADDRESS, p.contents["contents"], p.local_metadata
                    )
                    continue
                self._datastores[p.contents["address"]].resubmit(
                    p.contents["contents"], p.local_metadata
                )
            batch = self._outbox.flush(self.ref_seq, batch_id=group[0].batch_id)
            if batch is None:
                continue  # squashed/cancelled out entirely
            self._psm.on_flush_batch(batch.messages, batch.batch_id, self.client_id)
            for wire in batch.wire_messages:
                if self._document is None:
                    break
                try:
                    self._document.submit(wire)
                except DriverError:
                    # Same policy as flush(): a failed send invalidates the
                    # connection; this group is already pending under the
                    # current identity, so the next replay re-sends it.
                    self._drop_connection()
                    break

    # ---------------------------------------------------------------- protocol
    def submit_protocol_message(self, mtype: str, contents: Any) -> None:
        """Send a protocol-level message (e.g. quorum propose) through the
        current connection, sharing the op clientSeq counter (the reference
        routes proposals through the same DeltaManager outbound path)."""
        if (
            self._outbox is None
            or self._outbox.client_id == ""
            or self._document is None
            or not self.joined
        ):
            raise RuntimeError("protocol message requires a joined write connection")
        self.flush()
        if self._document is None:
            raise RuntimeError("connection dropped during flush")
        self._inflight_proposals.append({"type": mtype, "contents": contents})
        self._document.submit(self._outbox.mint_direct(mtype, contents, self.ref_seq))

    # --------------------------------------------------------------------- gc
    def run_gc(self) -> dict[str, Any]:
        """One GC round (ref container-runtime/src/gc/): mark reachability
        from root datastores through handle strings, age unreferenced
        nodes, and submit a sequenced gcDelete op for sweep-ready ones.
        Returns {"unreferenced": {...}, "swept": [...]}."""
        from .gc import mark

        result = mark(self)
        self.gc_state.unreferenced_since = result.unreferenced
        sweep_ready = [
            key
            for key, since in result.unreferenced.items()
            if self.ref_seq - since >= self.gc_sweep_after_ops
        ]
        if sweep_ready and self._document is not None:
            self._submit_datastore_op(
                RUNTIME_ADDRESS,
                {"runtimeOp": "gcDelete", "ids": sorted(sweep_ready)},
                None,
            )
            self.flush()
        return {"unreferenced": dict(result.unreferenced), "swept": sweep_ready}

    # -------------------------------------------------------------- checkpoint
    def summarize(self) -> dict[str, Any]:
        """Runtime state checkpoint: quorum short-id table + every datastore
        (ref ContainerRuntime.summarize; incremental tree walk lives in
        runtime/summary.py)."""
        out = {
            "seq": self.ref_seq,
            "minSeq": self.min_seq,
            "quorum": dict(self._quorum),
            "datastores": {k: ds.summarize() for k, ds in self._datastores.items()},
            "blobs": self.blobs.summarize(),
            "gc": self.gc_state.to_json(),
        }
        if self.attributor is not None:
            out["attribution"] = self.attributor.summarize()
        return out

    def load_snapshot(self, summary: dict[str, Any]) -> None:
        """Boot from a checkpoint (ref Container.load snapshot path). Must be
        called before any datastore creation or op processing."""
        if self._datastores or self.ref_seq != 0:
            raise RuntimeError("load_snapshot on a non-fresh runtime")
        from .gc import GCState

        self.last_summary_ref_seq = summary["seq"]
        self.ref_seq = summary["seq"]
        self.min_seq = summary.get("minSeq", 0)
        self._quorum = dict(summary["quorum"])
        self.blobs.load(summary.get("blobs", {}))
        self.gc_state = GCState.from_json(summary.get("gc", {}))
        if "attribution" in summary:
            # A snapshot carrying attribution implies the document tracks
            # it: enable and restore regardless of this client's option.
            from ..framework.attributor import OpStreamAttributor

            self.attributor = OpStreamAttributor()
            self.attributor.load(summary["attribution"])
        for ds_id, ds_summary in summary["datastores"].items():
            self.create_datastore(ds_id).load(ds_summary)

    @property
    def quorum_table(self) -> dict[str, int]:
        """client id -> short (join-order) id for current write clients."""
        return dict(self._quorum)

    def build_summary_tree(self) -> dict[str, Any]:
        """The incremental runtime summary subtree (ref SummarizerNode walk,
        summarizerNode.ts:61): channels untouched since the last acked
        summary emit handles into it instead of content."""
        from .summary import blob, tree

        covered = self.last_summary_ref_seq
        entries = {
            "seq": blob(self.ref_seq),
            "minSeq": blob(self.min_seq),
            "quorum": blob(dict(self._quorum)),
            "blobs": blob(self.blobs.summarize()),
            "gc": blob(self.gc_state.to_json()),
            "datastores": tree(
                {
                    ds_id: ds.summary_tree(
                        covered, f"runtime/datastores/{ds_id}"
                    )
                    for ds_id, ds in self._datastores.items()
                }
            ),
        }
        if self.attributor is not None:
            entries["attribution"] = blob(self.attributor.summarize())
        return tree(entries)

    # ------------------------------------------------------------------- stash
    def get_pending_local_state(self) -> str:
        """Serialize pending-op state for offline resume (container.ts:1152)."""
        self.flush()
        return self._psm.get_local_state(self.ref_seq)

    def _maybe_apply_stash(self, catch_up_done: bool) -> None:
        if self._stash is None:
            return
        if not catch_up_done and self.ref_seq < self._stash["refSeq"]:
            return
        if catch_up_done and self.ref_seq < self._stash["refSeq"]:
            raise RuntimeError(
                f"stash taken at seq {self._stash['refSeq']} but the op log "
                f"only reaches {self.ref_seq}; stale service?"
            )
        stash, self._stash = self._stash, None
        for entry in stash["pending"]:
            contents = entry["contents"]
            if contents["address"] == RUNTIME_ADDRESS:
                # Stashed attach op: re-create the structure locally, then
                # let the pending replay resubmit it verbatim.
                self._apply_runtime_op(contents["contents"], self.ref_seq)
                self._psm.add_stashed(
                    contents, None, entry["batchId"], entry.get("clientId", "")
                )
                continue
            md = self._datastores[contents["address"]].apply_stashed(
                contents["contents"]
            )
            self._psm.add_stashed(
                contents, md, entry["batchId"], entry.get("clientId", "")
            )
