"""Outbound batching / grouping / compression / chunking and inbound inverse.

Reference parity: container-runtime/src/opLifecycle — ``Outbox.flush``
(outbox.ts:196,339), ``OpGroupingManager.groupBatch/ungroupOp``
(opGroupingManager.ts:66,125,181), ``OpCompressor.compressBatch``
(opCompressor.ts:27,40 — lz4 there; zlib here, the algorithm is a config
knob, the wire shape is what matters), ``OpSplitter`` chunking of oversized
payloads (opSplitter.ts:45), inbound reassembly
``RemoteMessageProcessor.process`` (remoteMessageProcessor.ts:94,130), and
fork detection via batch ids (duplicateBatchDetector.ts).

A *batch* is the atomicity unit: all ops minted in one JS-turn/host-step
flush together, are sequenced contiguously (the sequencer does not interleave
within a grouped message), and are applied by replicas as one unit.

Wire shapes (all JSON-compatible, carried in ``UnsequencedMessage.contents``):

    grouped batch: {"type": "groupedBatch", "contents": [op, op, ...]}
    compressed:    {"type": "compressed", "data": <base64 zlib(json(list))>}
    chunk:         {"type": "chunk", "chunkId": i, "total": n, "data": str}

Compression wraps the whole grouped batch; chunking wraps the (possibly
compressed) serialized payload when it exceeds the service's max message
size (reference: 716,800 B client cap vs 1 MB socket limit).
"""

from __future__ import annotations

import base64
import json
import zlib
from dataclasses import dataclass, field
from typing import Any

from ..protocol.messages import MessageType, SequencedMessage, UnsequencedMessage

GROUPED_BATCH_TYPE = "groupedBatch"
COMPRESSED_TYPE = "compressed"
CHUNK_TYPE = "chunk"


@dataclass
class BatchMessage:
    """One runtime message staged for the next flush."""

    contents: dict[str, Any]
    local_metadata: Any = None


@dataclass
class FlushedBatch:
    """What a flush produced: wire messages + the local bookkeeping record."""

    wire_messages: list[UnsequencedMessage]
    # The pre-grouping runtime messages, for pending-state replay.
    messages: list[BatchMessage]
    batch_id: str = ""


class Outbox:
    """Stages runtime messages during a host turn; flush emits wire batches.

    Grouping: a multi-message batch becomes ONE wire message (grouped batch)
    so the sequencer stamps it one sequence number and replicas ungroup it
    into per-op messages with synthetic contiguous ordering — exactly the
    reference's op-grouping design (opGroupingManager.ts:66).
    """

    def __init__(
        self,
        client_id: str,
        *,
        compression_threshold: int = 4096,
        max_chunk_size: int = 716_800,
        group_single: bool = False,
    ) -> None:
        self.client_id = client_id
        self.compression_threshold = compression_threshold
        self.max_chunk_size = max_chunk_size
        self.group_single = group_single
        self._staged: list[BatchMessage] = []
        self._client_seq = 0
        self._batch_counter = 0

    # ------------------------------------------------------------------ stage
    def submit(self, contents: dict[str, Any], local_metadata: Any = None) -> None:
        self._staged.append(BatchMessage(contents, local_metadata))

    @property
    def is_empty(self) -> bool:
        return not self._staged

    def _next_client_seq(self) -> int:
        self._client_seq += 1
        return self._client_seq

    def peek_staged(self) -> BatchMessage | None:
        """Newest staged message without removing it (atomic rollback:
        the channel-level undo must succeed BEFORE the op leaves the
        outbox, or a failed rollback would orphan applied state)."""
        return self._staged[-1] if self._staged else None

    def pop_staged(self) -> BatchMessage | None:
        """Remove and return the most recently staged message (rollback path,
        ref Outbox/BatchManager rollback for ensureNoDataModelChanges)."""
        return self._staged.pop() if self._staged else None

    # ------------------------------------------------------------------ flush
    def flush(self, ref_seq: int, batch_id: str | None = None) -> FlushedBatch | None:
        """Emit everything staged as one atomic batch (or None if empty).

        ``batch_id`` overrides the generated id — used by reconnect replay,
        which must preserve the ORIGINAL batch id for fork detection.
        """
        if not self._staged:
            return None
        staged, self._staged = self._staged, []
        self._batch_counter += 1
        # Batch id = (client, first clientSeq of the batch): stable across
        # resubmit-dedup, mirroring the reference's batchId fork detection.
        first_seq = self._client_seq + 1
        if batch_id is None:
            batch_id = f"{self.client_id}_[{first_seq}]"

        if len(staged) == 1 and not self.group_single:
            payload: dict[str, Any] = staged[0].contents
        else:
            payload = {
                "type": GROUPED_BATCH_TYPE,
                "contents": [m.contents for m in staged],
            }

        serialized = json.dumps(payload, separators=(",", ":"))
        if len(serialized) >= self.compression_threshold:
            data = base64.b64encode(zlib.compress(serialized.encode())).decode()
            payload = {"type": COMPRESSED_TYPE, "data": data}
            serialized = json.dumps(payload, separators=(",", ":"))

        wire: list[UnsequencedMessage] = []
        if len(serialized) > self.max_chunk_size:
            chunks = [
                serialized[i : i + self.max_chunk_size]
                for i in range(0, len(serialized), self.max_chunk_size)
            ]
            for i, chunk in enumerate(chunks):
                wire.append(
                    UnsequencedMessage(
                        client_id=self.client_id,
                        client_seq=self._next_client_seq(),
                        ref_seq=ref_seq,
                        type=MessageType.OP,
                        contents={
                            "type": CHUNK_TYPE,
                            "chunkId": i,
                            "total": len(chunks),
                            "data": chunk,
                        },
                        metadata={"batchId": batch_id} if i == len(chunks) - 1 else None,
                    )
                )
        else:
            wire.append(
                UnsequencedMessage(
                    client_id=self.client_id,
                    client_seq=self._next_client_seq(),
                    ref_seq=ref_seq,
                    type=MessageType.OP,
                    contents=payload,
                    metadata={"batchId": batch_id},
                )
            )
        return FlushedBatch(wire_messages=wire, messages=staged, batch_id=batch_id)

    def park(self, batch_id: str) -> FlushedBatch | None:
        """Drain staged messages WITHOUT minting wire messages or consuming
        clientSeq numbers — used when disconnected or pre-join, where the
        batch goes straight to pending state and replays later (wire
        identity is assigned by the replay flush)."""
        if not self._staged:
            return None
        staged, self._staged = self._staged, []
        return FlushedBatch(wire_messages=[], messages=staged, batch_id=batch_id)

    def mint_direct(self, mtype: str, contents: Any, ref_seq: int) -> UnsequencedMessage:
        """A standalone non-OP wire message (protocol propose/summarize)
        sharing this connection's clientSeq counter — the sequencer enforces
        per-client contiguity, so ALL outbound traffic must thread through
        one counter. Caller must flush staged ops first to keep submission
        order consistent."""
        assert not self._staged, "flush before minting a direct message"
        return UnsequencedMessage(
            client_id=self.client_id,
            client_seq=self._next_client_seq(),
            ref_seq=ref_seq,
            type=mtype,
            contents=contents,
        )


@dataclass
class InboundRuntimeMessage:
    """One ungrouped runtime message with its sequencing info.

    ``seq`` is the wire sequence number of the carrying message; ``index``
    disambiguates position within a grouped batch (the reference synthesizes
    fractional clientSequenceNumbers; an explicit index is cleaner).
    """

    contents: dict[str, Any]
    client_id: str
    seq: int
    min_seq: int
    ref_seq: int
    index: int
    batch_id: str | None = None


class RemoteMessageProcessor:
    """Inbound inverse: unchunk -> decompress -> ungroup.

    Stateful only for chunk reassembly (per sending client), like the
    reference's OpSplitter chunk cache.
    """

    def __init__(self) -> None:
        self._chunks: dict[str, list[str]] = {}

    def process(self, msg: SequencedMessage) -> list[InboundRuntimeMessage]:
        contents = msg.contents
        batch_id = (msg.metadata or {}).get("batchId") if msg.metadata else None

        if isinstance(contents, dict) and contents.get("type") == CHUNK_TYPE:
            buf = self._chunks.setdefault(msg.client_id, [])
            if contents["chunkId"] != len(buf):
                raise ValueError(
                    f"out-of-order chunk {contents['chunkId']} from "
                    f"{msg.client_id!r} (expected {len(buf)})"
                )
            buf.append(contents["data"])
            if len(buf) < contents["total"]:
                return []
            del self._chunks[msg.client_id]
            contents = json.loads("".join(buf))

        if isinstance(contents, dict) and contents.get("type") == COMPRESSED_TYPE:
            raw = zlib.decompress(base64.b64decode(contents["data"]))
            contents = json.loads(raw)

        if isinstance(contents, dict) and contents.get("type") == GROUPED_BATCH_TYPE:
            inner = contents["contents"]
        else:
            inner = [contents]

        return [
            InboundRuntimeMessage(
                contents=c,
                client_id=msg.client_id,
                seq=msg.seq,
                min_seq=msg.min_seq,
                ref_seq=msg.ref_seq,
                index=i,
                batch_id=batch_id,
            )
            for i, c in enumerate(inner)
        ]


class DuplicateBatchDetector:
    """Container fork detection via batch ids (duplicateBatchDetector.ts).

    Two containers rehydrated from the same stashed pending state would
    resubmit the same batch id; the second sequenced copy must be dropped
    (and signals a fork). Tracks ids above the collab-window floor only.
    """

    def __init__(self) -> None:
        self._seen: dict[str, int] = {}

    def observe(self, batch_id: str | None, seq: int, min_seq: int) -> bool:
        """Returns True if this batch is a duplicate (must be ignored)."""
        # Evict ids at/below the new collab-window floor: no correctly
        # behaving client can resubmit a batch older than the MSN.
        for bid in [b for b, s in self._seen.items() if s <= min_seq]:
            del self._seen[bid]
        if batch_id is None:
            return False
        if batch_id in self._seen:
            return True
        self._seen[batch_id] = seq
        return False
