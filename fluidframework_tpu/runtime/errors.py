"""Runtime error taxonomy (ref container-runtime DataProcessingError family)."""

from __future__ import annotations


class DataProcessingError(RuntimeError):
    """Inbound op processing hit a corrupt/inconsistent state; the container
    closes itself rather than continue diverged (ref DataProcessingError)."""


class ContainerForkError(DataProcessingError):
    """A remote batch carried one of OUR pending batch ids under a different
    identity: two containers are submitting the same local state (ref
    'Forked Container Error', pendingStateManager.ts:626)."""
