"""Versioned DDS snapshot formats — re-export shim.

The format registry moved to ``protocol.snapshot_formats`` (the contracts
tier), so DDS summarize paths can stamp/upgrade without an upward edge
into the runtime.  The datastore and the corpus tooling keep importing
from here.
"""

from __future__ import annotations

from ..protocol.snapshot_formats import (
    CURRENT_FORMATS,
    FORMAT_KEY,
    UPGRADERS,
    current_format,
    upgrade,
)

__all__ = [
    "CURRENT_FORMATS",
    "FORMAT_KEY",
    "UPGRADERS",
    "current_format",
    "upgrade",
]
