"""Versioned DDS snapshot formats.

Reference parity: the reference evolves per-DDS snapshot formats behind
explicit versions (merge-tree snapshotV1.ts vs snapshotlegacy.ts, tree's
versioned editManagerCodecs/messageCodecs) and pins them with a committed
golden corpus (packages/test/snapshots: real snapshot files validated
against every supported read-version on every run).

Here every channel summary is stamped ``{"fmt": N, ...}`` at the datastore
boundary; loading strips the stamp and runs any upgraders from the file's
version to the current one. Version-1 files (or files from before
stamping existed) load unchanged: v1 IS the shipping layout. The golden
corpus lives in ``tests/snapshots/`` with the scripted documents that
produced it in ``fluidframework_tpu/testing/snapshot_corpus.py`` —
regenerating requires a deliberate ``python -m fluidframework_tpu.testing.
snapshot_corpus`` run, so format drift always shows up as a reviewed diff.
"""

from __future__ import annotations

from typing import Any, Callable

FORMAT_KEY = "fmt"

# Current write-format per channel type; unlisted types are version 1.
CURRENT_FORMATS: dict[str, int] = {}

# channel type -> list of upgraders; UPGRADERS[t][k] rewrites a version
# k+1 summary dict into version k+2. Empty today: every type is at v1.
UPGRADERS: dict[str, list[Callable[[dict], dict]]] = {}


def current_format(channel_type: str) -> int:
    return CURRENT_FORMATS.get(channel_type, 1)


def stamp(channel_type: str, summary: dict[str, Any]) -> dict[str, Any]:
    """Attach the write-format version to a freshly-built summary."""
    out = dict(summary)
    out[FORMAT_KEY] = current_format(channel_type)
    return out


def upgrade(channel_type: str, summary: dict[str, Any]) -> dict[str, Any]:
    """Strip the stamp and lift the payload to the current format.
    Unstamped summaries are version 1 (the pre-stamping layout)."""
    out = dict(summary)
    fmt = out.pop(FORMAT_KEY, 1)
    cur = current_format(channel_type)
    if fmt > cur:
        raise ValueError(
            f"snapshot of {channel_type!r} uses format {fmt}, newer than "
            f"this build's {cur} — refusing a lossy downgrade read"
        )
    for upgrader in UPGRADERS.get(channel_type, [])[fmt - 1 : cur - 1]:
        out = upgrader(out)
    return out
