"""BlobManager: attachment blobs with upload, dedup, and summary linkage.

Reference parity: container-runtime/src/blobManager/blobManager.ts:237 —
large binary payloads do NOT ride the op stream; they upload to storage
first, a sequenced BlobAttach op ties the storage id into the document, and
summaries carry the attached-blob table so loading clients can resolve
handles.  Content addressing gives upload dedup for free (identical
payloads share one storage id — ref blobManager dedup of pending uploads).

Handles are plain strings ``blob:<id>`` so they can be stored in any DDS
value; the GC reference scan (runtime/gc.py) recognizes them.
"""

from __future__ import annotations

from typing import Callable


BLOB_PREFIX = "blob:"


class BlobManager:
    def __init__(
        self,
        upload: Callable[[str], str],
        read: Callable[[str], str],
        submit_attach: Callable[[str], None],
    ) -> None:
        self._upload = upload
        self._read = read
        self._submit_attach = submit_attach
        # blob id -> attached (sequenced) flag; pending ids await their ack.
        self._attached: set[str] = set()
        self._pending: set[str] = set()

    # ------------------------------------------------------------------ write
    def create_blob(self, content: str) -> str:
        """Upload + stage the attach op; returns the handle immediately
        (optimistic, like any local op — usable before the ack)."""
        blob_id = self._upload(content)
        if blob_id in self._attached or blob_id in self._pending:
            return BLOB_PREFIX + blob_id  # dedup: already on its way
        self._pending.add(blob_id)
        self._submit_attach(blob_id)
        return BLOB_PREFIX + blob_id

    def on_attach(self, blob_id: str) -> None:
        """A sequenced BlobAttach (ours or a remote's)."""
        self._pending.discard(blob_id)
        self._attached.add(blob_id)

    def delete(self, blob_id: str) -> None:
        """GC sweep removes an unreferenced blob from the table."""
        self._attached.discard(blob_id)

    # ------------------------------------------------------------------- read
    def get_blob(self, handle: str) -> str:
        assert handle.startswith(BLOB_PREFIX), f"not a blob handle: {handle!r}"
        blob_id = handle[len(BLOB_PREFIX):]
        if blob_id not in self._attached and blob_id not in self._pending:
            raise KeyError(f"blob {blob_id!r} is not attached to this document")
        return self._read(blob_id)

    @property
    def attached_ids(self) -> list[str]:
        return sorted(self._attached)

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict:
        return {"attached": sorted(self._attached)}

    def load(self, data: dict) -> None:
        self._attached = set(data.get("attached", []))
