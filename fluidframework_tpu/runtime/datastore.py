"""DataStoreRuntime: hosts channels, routes envelopes, owns the registry.

Reference parity: datastore/src/dataStoreRuntime.ts — ``FluidDataStoreRuntime``
(:258), ``ISharedObjectRegistry`` (:156, type string -> IChannelFactory),
``createChannel`` (:699), envelope routing via ChannelDeltaConnection.

Envelope nesting (ref channelCollection.ts:290): a datastore-level op is
``{"address": <channel id>, "contents": <dds op>}``; the container adds one
more ``{"address": <datastore id>, "contents": ...}`` wrapper.
"""

from __future__ import annotations

from typing import Any, Callable

from ..protocol.channel import (
    Channel,
    ChannelDeltaConnection,
    ChannelFactory,
    ChannelMessage,
    MessageCollection,
    MessageEnvelope,
    bunch_contiguous,
)


class DataStoreRuntime:
    """One data store: a registry-driven collection of channels."""

    def __init__(
        self,
        ds_id: str,
        registry: dict[str, ChannelFactory],
        submit_fn: Callable[[dict, Any], None],
        quorum_fn: Callable[[str], int],
        client_id_fn: Callable[[], str],
        members_fn: Callable[[], list[str]] | None = None,
        ref_seq_fn: Callable[[], int] | None = None,
        root: bool = True,
    ) -> None:
        self.id = ds_id
        # GC roots are always reachable; non-root (dynamically created)
        # stores survive only while a handle to them exists (ref aliased/
        # root datastores vs handle-reachable ones, container-runtime gc).
        self.is_root = root
        self._registry = registry
        self._submit = submit_fn
        self._quorum = quorum_fn
        self._client_id = client_id_fn
        self._members = members_fn
        self._ref_seq = ref_seq_fn
        self._channels: dict[str, Channel] = {}
        # channel id -> seq of its last sequenced change (summary dirtiness;
        # ref SummarizerNode invalidate on op). Channels created while live
        # are marked dirty from creation so summaries never emit handles
        # into snapshots that predate them (the attach op re-marks at its
        # own seq on every replica).
        self.changed_seqs: dict[str, int] = {}

    # ------------------------------------------------------------- channels
    def create_channel(self, channel_type: str, channel_id: str) -> Channel:
        ch = self._create_channel(channel_type, channel_id)
        # Dirty from creation: a summary handle may only reference channels
        # the previous snapshot already carries. (Detached creation marks 0,
        # which the initial snapshot covers; the attach op re-marks at its
        # own seq on every replica.)
        if self._ref_seq is not None:
            self.changed_seqs[channel_id] = max(
                self.changed_seqs.get(channel_id, 0), self._ref_seq()
            )
        return ch

    def _create_channel(self, channel_type: str, channel_id: str) -> Channel:
        if channel_id in self._channels:
            raise ValueError(f"channel {channel_id!r} already exists")
        factory = self._registry.get(channel_type)
        if factory is None:
            raise KeyError(
                f"no factory for channel type {channel_type!r} "
                f"(registered: {sorted(self._registry)})"
            )
        channel = factory.create(channel_id)
        self._bind(channel)
        return channel

    def _bind(self, channel: Channel) -> None:
        cid = channel.id

        def submit(contents: Any, local_metadata: Any, internal: bool = False) -> None:
            self._submit({"address": cid, "contents": contents}, local_metadata, internal)

        channel.connect(
            ChannelDeltaConnection(
                submit, self._quorum, self._client_id, self._members, self._ref_seq
            )
        )
        self._channels[cid] = channel

    def get_channel(self, channel_id: str) -> Channel:
        return self._channels[channel_id]

    @property
    def channels(self) -> dict[str, Channel]:
        return dict(self._channels)

    # --------------------------------------------------------------- inbound
    def process_messages(
        self, envelope: MessageEnvelope, messages: list[tuple[dict, bool, Any]]
    ) -> None:
        """Route a bunch of datastore-level messages to channels.

        ``messages`` items are (datastore-op, local, local_metadata); runs of
        contiguous same-channel messages become one MessageCollection (the
        bunching seam, containerRuntime.ts:3428).
        """
        def dispatch(addr: str, run: list[ChannelMessage]) -> None:
            if addr not in self._channels:
                raise KeyError(f"datastore {self.id!r}: unknown channel {addr!r}")
            self.changed_seqs[addr] = envelope.seq  # summary dirty tracking
            self._channels[addr].process_messages(
                MessageCollection(envelope=envelope, messages=run)
            )

        bunch_contiguous(
            (
                (
                    contents["address"],
                    ChannelMessage(
                        contents=contents["contents"],
                        local=local,
                        local_metadata=local_metadata,
                    ),
                )
                for contents, local, local_metadata in messages
            ),
            dispatch,
        )

    # ---------------------------------------------------- reconnect / stash
    def resubmit(self, contents: dict, local_metadata: Any, squash: bool = False) -> None:
        self._channels[contents["address"]].resubmit(
            contents["contents"], local_metadata, squash
        )

    def apply_stashed(self, contents: dict) -> Any:
        return self._channels[contents["address"]].apply_stashed(contents["contents"])

    def on_min_seq(self, min_seq: int) -> None:
        for ch in self._channels.values():
            ch.on_min_seq(min_seq)

    def on_client_leave(self, client_id: str, seq: int) -> None:
        for ch in self._channels.values():
            ch.on_client_leave(client_id, seq)

    def rollback(self, contents: dict, local_metadata: Any) -> None:
        self._channels[contents["address"]].rollback(contents["contents"], local_metadata)

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        from .snapshot_formats import current_format

        return {
            "root": self.is_root,
            "channels": {
                cid: {
                    "type": ch.channel_type,
                    "fmt": current_format(ch.channel_type),
                    "summary": ch.summarize(),
                }
                for cid, ch in self._channels.items()
            }
        }

    def load(self, summary: dict[str, Any]) -> None:
        from .snapshot_formats import upgrade

        self.is_root = summary.get("root", True)
        for cid, entry in summary["channels"].items():
            if "meta" in entry:
                # Materialized incremental channel tree ({"meta", "forest"}):
                # the channel FACTORY reassembles the flat summary from the
                # per-chunk pieces (the load-side mirror of the generic
                # summary_tree emit hook — symmetric, no DDS import here).
                meta = entry["meta"]
                factory = self._registry.get(meta["type"])
                if factory is None or not hasattr(factory, "assemble_incremental"):
                    raise KeyError(
                        f"channel type {meta['type']!r} wrote an incremental "
                        "summary but its factory has no assemble_incremental"
                    )
                entry = {
                    "type": meta["type"],
                    "fmt": meta.get("fmt", 1),
                    "summary": factory.assemble_incremental(
                        meta["summary"],
                        [
                            entry["forest"][k]
                            for k in sorted(entry["forest"], key=int)
                        ],
                        meta.get("fmt", 1),
                    ),
                }
            # _create_channel: snapshot-loaded channels are covered by that
            # snapshot, not dirty.
            channel = self._create_channel(entry["type"], cid)
            # A None summary is structure-only (detached attach writes the
            # channel layout; content replays as trailing ops).
            if entry["summary"] is not None:
                channel.load(
                    upgrade(entry["type"], entry["summary"], entry.get("fmt", 1))
                )

    def summary_tree(self, covered_seq: int | None, prefix: str) -> dict[str, Any]:
        """Incremental summary subtree: a channel whose last sequenced
        change is at or below ``covered_seq`` (the last acked summary's
        refSeq) emits a handle to its previous summary content
        (ref SummarizerNode handle reuse)."""
        from .snapshot_formats import current_format
        from .summary import blob, handle, tree

        channels: dict[str, Any] = {}
        for cid, ch in self._channels.items():
            path = f"{prefix}/channels/{cid}"
            if covered_seq is not None and self.changed_seqs.get(cid, 0) <= covered_seq:
                channels[cid] = handle(path)
            elif hasattr(ch, "summary_tree"):
                # WITHIN-channel incrementality (SharedTree chunked forest,
                # ref incrementalSummarizationUtils): the channel emits its
                # own tree of blobs + handles.
                channels[cid] = ch.summary_tree(covered_seq, path)
            else:
                channels[cid] = blob(
                    {
                        "type": ch.channel_type,
                        "fmt": current_format(ch.channel_type),
                        "summary": ch.summarize(),
                    }
                )
        return tree({"channels": tree(channels)})

    def structure_summary(self) -> dict[str, Any]:
        """Layout-only summary: channel ids + types, no state."""
        return {
            "root": self.is_root,
            "channels": {
                cid: {"type": ch.channel_type, "summary": None}
                for cid, ch in self._channels.items()
            }
        }
