"""PendingStateManager: local-op bookkeeping across sequencing and reconnect.

Reference parity: container-runtime/src/pendingStateManager.ts:283 —
tracks every flushed-but-unsequenced runtime message with its local
metadata; when the client's own messages come back sequenced, zips the
stored metadata onto them (processInboundMessages, containerRuntime.ts:3280);
on reconnect, replays the whole pending list through per-channel resubmit
(replayPendingStates, run only after catch-up so in-flight ops from the old
connection identity ack normally first); serializes to a stash for offline
resume (initialMessages, pendingStateManager.ts:291).

Batch ids are preserved across resubmission (derived from the ORIGINAL
flush identity, pendingStateManager.ts:476-492) so container forks are
detectable: a rehydrated twin resubmitting the same stash produces batches
with identical ids under a different client id.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any

from .errors import DataProcessingError
from .op_lifecycle import BatchMessage


@dataclass
class PendingMessage:
    contents: dict[str, Any]
    local_metadata: Any
    batch_id: str
    # Connection identity the message was flushed under ("" if never sent —
    # stashed ops awaiting first submission).
    client_id: str


class PendingStateManager:
    def __init__(self) -> None:
        self._pending: list[PendingMessage] = []

    @property
    def has_pending(self) -> bool:
        return bool(self._pending)

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    @property
    def head_client_id(self) -> str | None:
        return self._pending[0].client_id if self._pending else None

    def pending_batch_ids(self) -> set[str]:
        return {p.batch_id for p in self._pending}

    # ----------------------------------------------------------------- flush
    def on_flush_batch(
        self, messages: list[BatchMessage], batch_id: str, client_id: str
    ) -> None:
        for m in messages:
            self._pending.append(
                PendingMessage(m.contents, m.local_metadata, batch_id, client_id)
            )

    # --------------------------------------------------------------- inbound
    def match_inbound(self, contents: dict[str, Any]) -> Any:
        """Pop the head pending message for an own sequenced op; returns its
        local metadata. Mismatched content means a forked/corrupt op stream —
        fail fast (the reference closes the container with a
        DataProcessingError)."""
        if not self._pending:
            raise DataProcessingError(
                "own op sequenced but no pending message recorded"
            )
        head = self._pending.pop(0)
        if head.contents != contents:
            raise DataProcessingError(
                "pending state mismatch: sequenced own op does not match the "
                f"next pending message (expected {head.contents!r}, got {contents!r})"
            )
        return head.local_metadata

    # ------------------------------------------------------------- reconnect
    def restore(self, messages: list[PendingMessage]) -> None:
        """Put taken-but-not-replayed messages back verbatim (a replay
        aborted by a connection failure re-stages the untouched tail)."""
        self._pending.extend(messages)

    def take_pending_for_replay(self) -> list[list[PendingMessage]]:
        """Remove and return all pending messages grouped by original batch
        (order preserved); the caller re-stages each group through channel
        resubmit and flushes it under the ORIGINAL batch id."""
        pending, self._pending = self._pending, []
        groups: list[list[PendingMessage]] = []
        for p in pending:
            if groups and groups[-1][0].batch_id == p.batch_id:
                groups[-1].append(p)
            else:
                groups.append([p])
        return groups

    # ------------------------------------------------------------------ stash
    def add_stashed(
        self,
        contents: dict[str, Any],
        local_metadata: Any,
        batch_id: str,
        client_id: str = "",
    ) -> None:
        self._pending.append(
            PendingMessage(contents, local_metadata, batch_id, client_id)
        )

    def get_local_state(self, ref_seq: int) -> str:
        """Serialize pending messages for offline stash. Metadata is dropped:
        stashed ops are re-applied via apply_stashed on rehydrate, which
        regenerates it (the reference's applyStashedOp contract). ``ref_seq``
        records the sequence number the pending state is relative to, so
        rehydration can apply the stash at the exact same point in the
        op stream (ref applyStashedOpsAt). ``clientId`` records the identity
        each entry was flushed under ("" = never sent): rehydration uses it
        to recognize stashed ops that were ALREADY sequenced before the
        stash was taken (ref savedOps matching in pendingStateManager.ts)."""
        return json.dumps(
            {
                "refSeq": ref_seq,
                "pending": [
                    {
                        "contents": p.contents,
                        "batchId": p.batch_id,
                        "clientId": p.client_id,
                    }
                    for p in self._pending
                ],
            }
        )

    @staticmethod
    def parse_local_state(state: str) -> dict[str, Any]:
        return json.loads(state)
