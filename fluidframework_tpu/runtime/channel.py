"""Compatibility shim: the channel contract moved DOWN to
``protocol.channel`` (base layer) so DDS modules import it without an
upward edge — the same move Fluid made keeping datastore-definitions in
its contracts tier (fftpu-check rule ``layer-upward-import``).  Existing
``runtime.channel`` importers keep working through this re-export.
"""

from ..protocol.channel import (  # noqa: F401
    Channel,
    ChannelDeltaConnection,
    ChannelFactory,
    ChannelMessage,
    MessageCollection,
    MessageEnvelope,
    bunch_contiguous,
)

__all__ = [
    "Channel",
    "ChannelDeltaConnection",
    "ChannelFactory",
    "ChannelMessage",
    "MessageCollection",
    "MessageEnvelope",
    "bunch_contiguous",
]
