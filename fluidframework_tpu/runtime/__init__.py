"""Runtime layer: container/datastore orchestration around the DDS kernels.

Reference parity: packages/runtime/container-runtime (ContainerRuntime, op
lifecycle, pending state) and packages/runtime/datastore (FluidDataStoreRuntime,
the concrete side of the IChannelFactory plugin boundary,
datastore-definitions/src/channel.ts:140,203,233,294).
"""

from ..protocol.channel import Channel, ChannelFactory, ChannelDeltaConnection
from .datastore import DataStoreRuntime
from .container_runtime import ContainerRuntime
from .op_lifecycle import (
    Outbox,
    RemoteMessageProcessor,
    DuplicateBatchDetector,
    GROUPED_BATCH_TYPE,
    COMPRESSED_TYPE,
    CHUNK_TYPE,
)
from .pending_state import PendingStateManager

__all__ = [
    "Channel",
    "ChannelFactory",
    "ChannelDeltaConnection",
    "DataStoreRuntime",
    "ContainerRuntime",
    "Outbox",
    "RemoteMessageProcessor",
    "DuplicateBatchDetector",
    "PendingStateManager",
    "GROUPED_BATCH_TYPE",
    "COMPRESSED_TYPE",
    "CHUNK_TYPE",
]
