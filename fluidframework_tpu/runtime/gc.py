"""Garbage collection over the summary reference graph.

Reference parity: container-runtime/src/gc/ — the runtime periodically
marks every node (datastore, attachment blob) reachable from the roots via
serialized handles, ages unreferenced nodes, and eventually SWEEPS them.
Two phases, exactly the reference's split:

- **mark**: walk handle references out of the reachable datastores' channel
  summaries to a fixpoint; record the sequence number at which a node first
  became unreferenced (the reference records timestamps;
  sequence distance is the deterministic analog).
- **sweep**: nodes unreferenced for at least ``sweep_after_ops`` are
  deleted via a SEQUENCED gcDelete runtime op, so every replica removes
  them at the same point in the total order (the reference's sweep-ready
  GC op) and late ops to deleted routes are dropped as tombstoned.

Handles come in two wire shapes, both GC-visible: plain strings
(``fluid:<datastore id>`` for datastores, ``blob:<id>`` for attachment
blobs — blob_manager.py) and the aqueduct IFluidHandle dict
(``{"__fluid_handle__": "/<ds id>[/<channel id>]"}`` — framework/
aqueduct.py make_handle; segments are percent-encoded).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .handles import is_handle, parse_handle_url, HANDLE_KEY

DS_PREFIX = "fluid:"
BLOB_PREFIX = "blob:"


def scan_handles(value: Any, ds_refs: set[str], blob_refs: set[str]) -> None:
    """Deep-scan a JSON-ish summary value for handle references."""
    if isinstance(value, str):
        if value.startswith(DS_PREFIX):
            ds_refs.add(value[len(DS_PREFIX):])
        elif value.startswith(BLOB_PREFIX):
            blob_refs.add(value[len(BLOB_PREFIX):])
    elif isinstance(value, dict):
        if is_handle(value):
            parts = parse_handle_url(value[HANDLE_KEY])
            if parts:
                ds_refs.add(parts[0])
        for v in value.values():
            scan_handles(v, ds_refs, blob_refs)
    elif isinstance(value, (list, tuple)):
        for v in value:
            scan_handles(v, ds_refs, blob_refs)


@dataclass
class GCState:
    """Ages + tombstones; part of the runtime summary so a reloading
    summarizer continues aging where the last one left off."""

    unreferenced_since: dict[str, int] = field(default_factory=dict)
    tombstoned: set[str] = field(default_factory=set)

    def to_json(self) -> dict:
        return {
            "unreferencedSince": dict(sorted(self.unreferenced_since.items())),
            "tombstoned": sorted(self.tombstoned),
        }

    @staticmethod
    def from_json(data: dict) -> "GCState":
        return GCState(
            unreferenced_since=dict(data.get("unreferencedSince", {})),
            tombstoned=set(data.get("tombstoned", [])),
        )


@dataclass
class MarkResult:
    reachable_ds: set[str]
    referenced_blobs: set[str]
    unreferenced: dict[str, int]  # node key -> since seq


def mark(runtime) -> MarkResult:
    """The mark phase over the live runtime (roots -> handle fixpoint).
    Node keys: ``ds/<id>`` and ``blob/<id>``."""
    roots = {
        ds_id for ds_id, ds in runtime.datastores.items() if ds.is_root
    }
    reachable = set(roots)
    blob_refs: set[str] = set()
    frontier = list(roots)
    while frontier:
        ds_id = frontier.pop()
        ds = runtime.datastores.get(ds_id)
        if ds is None:
            continue
        ds_refs: set[str] = set()
        scan_handles(ds.summarize(), ds_refs, blob_refs)
        for ref in ds_refs:
            if ref not in reachable:
                reachable.add(ref)
                frontier.append(ref)
    unreferenced: dict[str, int] = {}
    seq = runtime.ref_seq
    prev = runtime.gc_state.unreferenced_since
    for ds_id in runtime.datastores:
        if ds_id not in reachable:
            key = f"ds/{ds_id}"
            unreferenced[key] = prev.get(key, seq)
    for blob_id in runtime.blobs.attached_ids:
        if blob_id not in blob_refs:
            key = f"blob/{blob_id}"
            unreferenced[key] = prev.get(key, seq)
    return MarkResult(reachable, blob_refs, unreferenced)
