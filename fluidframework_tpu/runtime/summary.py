"""Summarization subsystem: incremental summary trees, election, heuristics.

Reference parity: container-runtime/src/summary/ — ``SummaryManager``
(summaryManager.ts:95) + ``OrderedClientElection`` (orderedClientElection.ts)
pick one client to summarize; ``RunningSummarizer`` (runningSummarizer.ts)
applies op-count/size heuristics; the ``SummarizerNode`` tree walk
(summarizerNode.ts:61) emits HANDLES for subtrees unchanged since the last
acked summary so uploads are incremental; the server side (scribe,
scribe/lambda.ts:65) validates, stores, and acks. ``ISummaryTree`` =
tree/blob/handle nodes (summaryFormat.ts); refreshLatestSummary
(summarizerNode.ts:392) advances the baseline on ack.

Flow (call stack SURVEY §3.5):
  elected client: build tree (handles for clean channels) → upload to
  storage → submit "summarize" op {handle, refSeq} → server scribe
  materializes handles against the previous snapshot, stores the full
  snapshot at refSeq, emits summaryAck → every client refreshes its
  summary baseline and op counter.
"""

from __future__ import annotations

from typing import Any

from ..protocol.messages import MessageType, SequencedMessage

# The ISummaryTree node builders moved to the contracts tier
# (protocol.snapshot_formats) so DDS summarize paths can mint
# blobs/handles without an upward edge into this layer; re-exported here
# for the runtime/test callers.
from ..protocol.snapshot_formats import blob, handle, tree


# ---------------------------------------------------------------------------
# Scribe summary-ack records (server half of the summary protocol)
# ---------------------------------------------------------------------------

# Client id the scribe service stamps on the acks it feeds back through the
# ordered log (ref scribe/lambda.ts emitting summaryAck as a service
# message; never a quorum member, so consumers treat it as protocol-only).
SCRIBE_CLIENT_ID = "__scribe__"


def make_scribe_ack(doc_id: str, seq: int, commit_sha: str) -> SequencedMessage:
    """The summaryAck record the scribe produces back into the ordered log
    once a summary commit is durably stored: every consumer sees, in the
    total order, that state up to ``seq`` is recoverable from
    ``commit_sha`` (boot-from-summary + log compaction both key off it)."""
    return SequencedMessage(
        client_id=SCRIBE_CLIENT_ID, client_seq=0, ref_seq=seq, seq=seq,
        min_seq=0, type=MessageType.SUMMARY_ACK,
        contents={"doc": doc_id, "seq": int(seq), "commit": commit_sha},
    )


def parse_scribe_ack(msg: Any) -> tuple[str, int, str] | None:
    """(doc, seq, commit_sha) when ``msg`` is a scribe summaryAck record;
    None for every other payload (tolerant: the op topic interleaves)."""
    if getattr(msg, "type", None) != MessageType.SUMMARY_ACK:
        return None
    c = getattr(msg, "contents", None)
    if not isinstance(c, dict) or "commit" not in c or "doc" not in c:
        return None
    return str(c["doc"]), int(c["seq"]), str(c["commit"])


# ---------------------------------------------------------------------------
# ISummaryTree node builders + handle resolution
# ---------------------------------------------------------------------------
# blob/tree/handle: re-exported from protocol.snapshot_formats (see top).


def count_nodes(node: dict) -> dict[str, int]:
    """Diagnostic: how many blobs vs handles a summary tree carries (the
    incrementality measure the reference's summary telemetry reports)."""
    out = {"blob": 0, "handle": 0, "tree": 0}
    stack = [node]
    while stack:
        n = stack.pop()
        out[n["type"]] += 1
        if n["type"] == "tree":
            stack.extend(n["entries"].values())
    return out


def materialize(node: dict, prev: dict | None, path: str = "") -> Any:
    """Resolve a summary tree into plain nested content, replacing handle
    nodes with the content at the same path of the previous materialized
    summary (what gitrest does when a summary references parent trees)."""
    kind = node["type"]
    if kind == "blob":
        return node["content"]
    if kind == "tree":
        return {
            name: materialize(child, prev, f"{path}/{name}" if path else name)
            for name, child in node["entries"].items()
        }
    if kind == "handle":
        if node["path"] != path:
            raise ValueError(f"handle path {node['path']!r} at {path!r}")
        if prev is None:
            raise ValueError(f"handle at {path!r} with no previous summary")
        cur = prev
        for part in path.split("/"):
            if not isinstance(cur, dict) or part not in cur:
                raise ValueError(f"previous summary lacks {path!r}")
            cur = cur[part]
        return cur
    raise ValueError(f"unknown summary node type {kind!r}")


# ---------------------------------------------------------------------------
# Client-side manager (election + heuristics + submit)
# ---------------------------------------------------------------------------


class SummaryConfig:
    """RunningSummarizer heuristics knobs (ref ISummaryConfiguration,
    runningSummarizer.ts):

    - ``max_ops``: summarize once this many ops accumulate since the last
      acked summary (ref maxOps);
    - ``max_time_s``: also summarize after this much wall time, provided at
      least ``min_ops`` ops accumulated (ref maxTime/minOpsForLastSummary);
    - ``max_ack_wait_s``: an in-flight summary with no ack/nack after this
      long counts as failed (ref maxAckWaitTime);
    - ``retry_delays``: back-off ladder between failed attempts (ref the
      regular/last-try retry schedule); the ladder caps at its final entry;
    - ``reelection_ops``: with no summary ack for this many ops, election
      rotates to the next client in join order
      (ref summarizerClientElection.ts maxOpsSinceLastSummary).
    """

    def __init__(
        self,
        max_ops: int = 50,
        max_time_s: float | None = None,
        min_ops: int = 1,
        max_ack_wait_s: float = 120.0,
        retry_delays: tuple[float, ...] = (0.0, 5.0, 30.0),
        reelection_ops: int | None = None,
    ) -> None:
        self.max_ops = max_ops
        self.max_time_s = max_time_s
        self.min_ops = min_ops
        self.max_ack_wait_s = max_ack_wait_s
        self.retry_delays = retry_delays
        self.reelection_ops = reelection_ops


# Client-id suffix marking a non-interactive summarizer client: excluded
# from election on every replica (the reference distinguishes summarizer
# clients via IClient.details.capabilities.interactive; a wire-visible id
# suffix is this host plane's deterministic equivalent).
SUMMARIZER_SUFFIX = "/summarizer"


def elected_summarizer(runtime, config: "SummaryConfig") -> str | None:
    """The deterministic election rule every replica runs: interactive
    candidates (summarizer clients excluded) in join order, rotated once
    per reelection window without an acked summary."""
    q = runtime.quorum_table
    candidates = sorted(
        (cid for cid in q if not cid.endswith(SUMMARIZER_SUFFIX)),
        key=lambda cid: q[cid],
    )
    if not candidates:
        return None
    r = config.reelection_ops
    rounds = (runtime.ops_since_summary_ack // r) if r else 0
    return candidates[rounds % len(candidates)]


class SummaryManager:
    """Drives summarization for one container runtime.

    Election (ref OrderedClientElection + SummarizerClientElection): joined
    write clients ordered by short id (join order) are the candidate ring;
    normally the first candidate summarizes.  When no summary has been
    acked for ``reelection_ops`` sequenced ops, every replica
    deterministically advances the election to the next candidate — an
    unresponsive summarizer is walked away from without any extra protocol
    (the shared op counter IS the election clock; the reference encodes the
    same advance in its serialized election state).  The reference spawns a
    hidden summarizer client; here the elected interactive client
    summarizes directly at a moment with no local pending ops — same
    protocol, one process fewer.

    Call ``tick(now)`` from the host loop (the reference wires this to op
    events + timers; tests inject ``now``); it submits at most one summary
    and then waits for the ack/nack — or the ack-wait timeout — before
    trying again, backing off through the retry ladder across failures.
    """

    def __init__(
        self,
        runtime,
        storage,
        config: SummaryConfig | None = None,
        protocol_summarize=None,
        act_as_summarizer: bool = False,
    ) -> None:
        self._runtime = runtime
        self._storage = storage
        self.config = config or SummaryConfig()
        # A spawned hidden summarizer client acts without winning election
        # itself — its PARENT interactive client was elected and delegates
        # (ref summaryManager.ts spawn -> summarizer.ts run).
        self._act_as_summarizer = act_as_summarizer
        self._protocol_summarize = protocol_summarize or (lambda: {})
        self._inflight_handle: str | None = None
        self._inflight_since = 0.0
        self._last_summary_time: float | None = None  # set on first tick
        self._next_attempt_time = 0.0
        self._now = 0.0  # last tick clock, for clock-less ack callbacks
        self.submitted = 0
        self.acked = 0
        self.failures = 0  # consecutive failures (nack / ack timeout)
        runtime.on_summary_ack = self._on_ack
        runtime.on_summary_nack = self._on_nack

    # ------------------------------------------------------------------ state
    def elected_summarizer(self) -> str | None:
        """client id of the current summarizer.

        Deterministic on every replica: candidates in join order, rotated
        once per ``reelection_ops`` window without an acked summary."""
        return elected_summarizer(self._runtime, self.config)

    def is_elected(self) -> bool:
        if self._act_as_summarizer:
            return self._runtime.joined
        return (
            self._runtime.joined
            and self.elected_summarizer() == self._runtime.client_id
        )

    # ------------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> bool:
        """Summarize if warranted; returns True when a summary was submitted."""
        import time as _time

        now = _time.monotonic() if now is None else now
        self._now = now
        if self._last_summary_time is None:
            self._last_summary_time = now
        if self._inflight_handle is not None:
            if now - self._inflight_since >= self.config.max_ack_wait_s:
                # The ack never came (stalled scribe / dropped op): count a
                # failure and retry through the ladder (ref maxAckWaitTime).
                self._record_failure()
            return False
        if (
            not self.is_elected()
            or self._runtime.pending_op_count > 0
            or now < self._next_attempt_time
        ):
            return False
        ops = self._runtime.ops_since_summary_ack
        due_ops = ops >= self.config.max_ops
        due_time = (
            self.config.max_time_s is not None
            and ops >= self.config.min_ops
            and now - self._last_summary_time >= self.config.max_time_s
        )
        if not (due_ops or due_time):
            return False
        root = tree(
            {
                "runtime": self._runtime.build_summary_tree(),
                "protocol": blob(self._protocol_summarize()),
            }
        )
        h = self._storage.upload_summary(root)
        self._inflight_handle = h
        self._inflight_since = now
        try:
            self._runtime.submit_protocol_message(
                MessageType.SUMMARIZE, {"handle": h, "refSeq": self._runtime.ref_seq}
            )
        except RuntimeError:
            # Connection dropped during flush: the proposal never reached the
            # stream, so no ack/nack will ever clear it — treat as a failure
            # so the elected client can summarize again after reconnect.
            self._record_failure()
            return False
        self.submitted += 1
        return True

    def _record_failure(self) -> None:
        self._inflight_handle = None
        self.failures += 1
        delays = self.config.retry_delays
        delay = delays[min(self.failures - 1, len(delays) - 1)] if delays else 0.0
        self._next_attempt_time = self._now + delay
        # Retry WITHOUT handles: whatever failed to resolve against the
        # previous snapshot will upload as a full blob next time (the
        # reference's safe-retry after summary nack).
        self._runtime.last_summary_ref_seq = None

    def _on_ack(self, contents: dict) -> None:
        if contents.get("handle") == self._inflight_handle:
            self._inflight_handle = None
            self.acked += 1
            self.failures = 0
            self._next_attempt_time = 0.0
            self._last_summary_time = self._now

    def _on_nack(self, contents: dict) -> None:
        if contents.get("handle") == self._inflight_handle:
            self._record_failure()


class HiddenSummaryManager:
    """Summarization through a SPAWNED non-interactive client (ref
    summaryManager.ts:95 spawning the hidden summarizer container,
    summarizer.ts:89).

    The interactive parent watches election; while elected, it keeps a
    second container alive under ``<client>/summarizer`` that does the
    actual summarizing.  The hidden client never carries local pending ops
    — the parent can keep editing (even with unflushed changes) without
    ever blocking a summary, the property the reference spawns a separate
    client for.  Losing election closes the hidden client (its leave
    sequences, releasing its MSN hold)."""

    def __init__(self, parent, doc_id: str, service_factory, registry,
                 config: SummaryConfig | None = None) -> None:
        self._parent = parent
        self._doc_id = doc_id
        self._factory = service_factory
        self._registry = registry
        self.config = config or SummaryConfig()
        self.summarizer = None           # the hidden Container, when alive
        self._inner: SummaryManager | None = None

    # ------------------------------------------------------------------ state
    def parent_elected(self) -> bool:
        rt = self._parent.runtime
        return rt.joined and elected_summarizer(rt, self.config) == rt.client_id

    @property
    def submitted(self) -> int:
        return self._inner.submitted if self._inner else 0

    @property
    def acked(self) -> int:
        return self._inner.acked if self._inner else 0

    # ------------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> bool:
        from ..loader.container import Container

        if not self.parent_elected():
            self.stop()
            return False
        if self.summarizer is None:
            self.summarizer = Container.load(
                self._doc_id, self._factory, self._registry,
                f"{self._parent.runtime.client_id}{SUMMARIZER_SUFFIX}",
                _summarizer=True,
            )
            self._inner = SummaryManager(
                self.summarizer.runtime,
                self.summarizer._storage,
                config=self.config,
                protocol_summarize=self.summarizer.protocol.summarize,
                act_as_summarizer=True,
            )
        return self._inner.tick(now)

    def stop(self) -> None:
        if self.summarizer is not None:
            self.summarizer.close()
            self.summarizer = None
            self._inner = None
