"""Summarization subsystem: incremental summary trees, election, heuristics.

Reference parity: container-runtime/src/summary/ — ``SummaryManager``
(summaryManager.ts:95) + ``OrderedClientElection`` (orderedClientElection.ts)
pick one client to summarize; ``RunningSummarizer`` (runningSummarizer.ts)
applies op-count/size heuristics; the ``SummarizerNode`` tree walk
(summarizerNode.ts:61) emits HANDLES for subtrees unchanged since the last
acked summary so uploads are incremental; the server side (scribe,
scribe/lambda.ts:65) validates, stores, and acks. ``ISummaryTree`` =
tree/blob/handle nodes (summaryFormat.ts); refreshLatestSummary
(summarizerNode.ts:392) advances the baseline on ack.

Flow (call stack SURVEY §3.5):
  elected client: build tree (handles for clean channels) → upload to
  storage → submit "summarize" op {handle, refSeq} → server scribe
  materializes handles against the previous snapshot, stores the full
  snapshot at refSeq, emits summaryAck → every client refreshes its
  summary baseline and op counter.
"""

from __future__ import annotations

from typing import Any

from ..protocol.messages import MessageType


# ---------------------------------------------------------------------------
# ISummaryTree node builders + handle resolution
# ---------------------------------------------------------------------------


def blob(content: Any) -> dict:
    return {"type": "blob", "content": content}


def tree(entries: dict[str, Any]) -> dict:
    return {"type": "tree", "entries": entries}


def handle(path: str) -> dict:
    """Reference to the same path in the previous acked summary."""
    return {"type": "handle", "path": path}


def count_nodes(node: dict) -> dict[str, int]:
    """Diagnostic: how many blobs vs handles a summary tree carries (the
    incrementality measure the reference's summary telemetry reports)."""
    out = {"blob": 0, "handle": 0, "tree": 0}
    stack = [node]
    while stack:
        n = stack.pop()
        out[n["type"]] += 1
        if n["type"] == "tree":
            stack.extend(n["entries"].values())
    return out


def materialize(node: dict, prev: dict | None, path: str = "") -> Any:
    """Resolve a summary tree into plain nested content, replacing handle
    nodes with the content at the same path of the previous materialized
    summary (what gitrest does when a summary references parent trees)."""
    kind = node["type"]
    if kind == "blob":
        return node["content"]
    if kind == "tree":
        return {
            name: materialize(child, prev, f"{path}/{name}" if path else name)
            for name, child in node["entries"].items()
        }
    if kind == "handle":
        if node["path"] != path:
            raise ValueError(f"handle path {node['path']!r} at {path!r}")
        if prev is None:
            raise ValueError(f"handle at {path!r} with no previous summary")
        cur = prev
        for part in path.split("/"):
            if not isinstance(cur, dict) or part not in cur:
                raise ValueError(f"previous summary lacks {path!r}")
            cur = cur[part]
        return cur
    raise ValueError(f"unknown summary node type {kind!r}")


# ---------------------------------------------------------------------------
# Client-side manager (election + heuristics + submit)
# ---------------------------------------------------------------------------


class SummaryConfig:
    """RunningSummarizer heuristics knobs (ref ISummaryConfiguration)."""

    def __init__(self, max_ops: int = 50) -> None:
        self.max_ops = max_ops


class SummaryManager:
    """Drives summarization for one container runtime.

    Election (ref OrderedClientElection): the joined write client with the
    LOWEST short id (earliest join order) is the summarizer; everyone runs
    the same deterministic rule, so exactly one client acts. The reference
    spawns a hidden summarizer client; here the elected interactive client
    summarizes directly at a moment with no local pending ops — same
    protocol, one process fewer.

    Call ``tick()`` from the host loop (the reference wires this to op
    events + timers); it submits at most one summary and then waits for the
    ack/nack before trying again.
    """

    def __init__(
        self,
        runtime,
        storage,
        config: SummaryConfig | None = None,
        protocol_summarize=None,
    ) -> None:
        self._runtime = runtime
        self._storage = storage
        self.config = config or SummaryConfig()
        self._protocol_summarize = protocol_summarize or (lambda: {})
        self._inflight_handle: str | None = None
        self.submitted = 0
        self.acked = 0
        runtime.on_summary_ack = self._on_ack
        runtime.on_summary_nack = self._on_nack

    # ------------------------------------------------------------------ state
    def elected_summarizer(self) -> str | None:
        """client id of the current summarizer (lowest short id in quorum)."""
        q = self._runtime.quorum_table
        if not q:
            return None
        return min(q, key=lambda cid: q[cid])

    def is_elected(self) -> bool:
        return (
            self._runtime.joined
            and self.elected_summarizer() == self._runtime.client_id
        )

    # ------------------------------------------------------------------- tick
    def tick(self) -> bool:
        """Summarize if warranted; returns True when a summary was submitted."""
        if (
            not self.is_elected()
            or self._inflight_handle is not None
            or self._runtime.ops_since_summary_ack < self.config.max_ops
            or self._runtime.pending_op_count > 0
        ):
            return False
        root = tree(
            {
                "runtime": self._runtime.build_summary_tree(),
                "protocol": blob(self._protocol_summarize()),
            }
        )
        h = self._storage.upload_summary(root)
        self._inflight_handle = h
        try:
            self._runtime.submit_protocol_message(
                MessageType.SUMMARIZE, {"handle": h, "refSeq": self._runtime.ref_seq}
            )
        except RuntimeError:
            # Connection dropped during flush: the proposal never reached the
            # stream, so no ack/nack will ever clear it — treat as a nack so
            # the elected client can summarize again after reconnect.
            self._inflight_handle = None
            return False
        self.submitted += 1
        return True

    def _on_ack(self, contents: dict) -> None:
        if contents.get("handle") == self._inflight_handle:
            self._inflight_handle = None
            self.acked += 1

    def _on_nack(self, contents: dict) -> None:
        if contents.get("handle") == self._inflight_handle:
            self._inflight_handle = None  # heuristics will retry next tick
            # Retry WITHOUT handles: whatever failed to resolve against the
            # previous snapshot will upload as a full blob next time (the
            # reference's safe-retry after summary nack).
            self._runtime.last_summary_ref_seq = None
