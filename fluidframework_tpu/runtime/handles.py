"""The IFluidHandle wire shape — single source of truth.

A handle is ``{"__fluid_handle__": "/<ds id>[/<channel id>]"}`` with
percent-encoded segments. Both the framework layer (aqueduct: minting and
resolving) and the runtime layer (gc: reference scanning) read this module,
so the shape cannot silently diverge between the code that writes handles
and the collector that must keep their targets alive.
"""

from __future__ import annotations

from typing import Any
from urllib.parse import quote, unquote

HANDLE_KEY = "__fluid_handle__"


def make_handle_url(ds_id: str, channel_id: str | None = None) -> str:
    url = "/" + quote(ds_id, safe="")
    if channel_id is not None:
        url += "/" + quote(channel_id, safe="")
    return url


def parse_handle_url(url: str) -> list[str]:
    """Decoded path segments (the inverse of make_handle_url)."""
    return [unquote(p) for p in url.strip("/").split("/") if p]


def is_handle(value: Any) -> bool:
    return isinstance(value, dict) and isinstance(value.get(HANDLE_KEY), str)
