"""DeltaManager: the ordered inbound pump with gap repair.

Reference parity: container-loader/src/deltaManager.ts (:154) — inbound ops
are delivered strictly in sequence order: duplicates (seq <= last processed)
are dropped, out-of-order arrivals are parked and the missing range is
fetched from delta storage (``fetchMissingDeltas`` :560); outbound ops ride
the current connection. The manager ALSO implements the document-adapter
contract the ContainerRuntime connects to (connect/disconnect/submit), so
the runtime is agnostic to whether it is wired straight to a LocalDocument
(unit tests) or through driver + loader (this path).

Handler chain: every in-order sequenced message flows to the protocol
handler first (quorum/proposals), then to the runtime subscriber.
"""

from __future__ import annotations

from typing import Any, Callable

from ..driver.definitions import DocumentService
from ..protocol.messages import Nack, SequencedMessage, SignalMessage
from .connection_manager import ConnectionManager
from .protocol import ProtocolHandler


class DeltaManager:
    def __init__(
        self,
        service: DocumentService,
        protocol: ProtocolHandler,
        base_client_id: str,
        last_processed_seq: int = 0,
    ) -> None:
        self._service = service
        self._storage = service.connect_to_delta_storage()
        self.protocol = protocol
        self.connection_manager = ConnectionManager(service, base_client_id)
        self.last_processed_seq = last_processed_seq
        self._runtime_handler: Callable[[SequencedMessage], None] | None = None
        self._signal_listeners: list[Callable[[SignalMessage], None]] = []
        self._parked: dict[int, SequencedMessage] = {}  # out-of-order arrivals
        self._paused = False
        self._pause_buffer: list[SequencedMessage] = []

    # ------------------------------------------------------- handler plumbing
    def on_signal(self, listener: Callable[[SignalMessage], None]) -> None:
        self._signal_listeners.append(listener)

    def _deliver(self, msg: SequencedMessage) -> None:
        """In-order delivery point: protocol first, then runtime."""
        self.last_processed_seq = msg.seq
        self.protocol.process_message(msg)
        if self._runtime_handler is not None:
            self._runtime_handler(msg)

    def _on_stream(self, msg: SequencedMessage) -> None:
        if self._paused:
            self._pause_buffer.append(msg)
            return
        self._process_inbound(msg)

    def _process_inbound(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.last_processed_seq:
            return  # duplicate (reconnect overlap)
        if msg.seq > self.last_processed_seq + 1:
            # Gap: park this op, repair from delta storage (deltaManager.ts:560).
            self._parked[msg.seq] = msg
            self._fetch_missing(self.last_processed_seq + 1, msg.seq - 1)
        else:
            self._deliver(msg)
        # Drain any parked ops that are now contiguous.
        while self.last_processed_seq + 1 in self._parked:
            self._deliver(self._parked.pop(self.last_processed_seq + 1))

    # Zero-progress reads are retried: get_deltas is allowed to return fewer
    # ops than asked (a networked delta store can lag the broadcast stream
    # briefly), so only a persistently empty window is an unrepairable gap.
    GAP_FETCH_RETRIES = 8

    def _fetch_missing(self, from_seq: int, to_seq: int) -> None:
        stalls = 0
        while from_seq <= to_seq:
            got = self._storage.get_deltas(from_seq, to_seq)
            for m in got:
                if m.seq == self.last_processed_seq + 1:
                    self._deliver(m)
            if self.last_processed_seq + 1 == from_seq:
                stalls += 1
                if stalls >= self.GAP_FETCH_RETRIES:
                    raise RuntimeError(
                        f"delta storage cannot supply seq {from_seq} "
                        f"(requested [{from_seq}, {to_seq}]) after "
                        f"{stalls} attempts: unrepairable gap"
                    )
                continue
            stalls = 0
            from_seq = self.last_processed_seq + 1

    def _on_signal_msg(self, sig: SignalMessage) -> None:
        for listener in self._signal_listeners:
            listener(sig)

    # ----------------------------------------------------------- pause/resume
    def pause(self) -> None:
        """Hold inbound processing (ref DeltaQueue pause — used by the
        summarizer to snapshot at a stable seq)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False
        buffered, self._pause_buffer = self._pause_buffer, []
        for msg in buffered:
            self._process_inbound(msg)

    def process_slice(self, max_ops: int, max_seconds: float | None = None) -> int:
        """Process up to ``max_ops`` buffered inbound ops (and stop early
        when ``max_seconds`` of wall clock elapses) WITHOUT unpausing — the
        DeltaScheduler's time-slicing primitive (ref deltaScheduler.ts:25:
        inbound processing yields every 50 ms so the host stays responsive).
        Returns the number processed; pending remainder stays buffered."""
        import time as _time

        assert self._paused, "process_slice requires a paused delta manager"
        t0 = _time.perf_counter()
        n = 0
        while self._pause_buffer and n < max_ops:
            if max_seconds is not None and _time.perf_counter() - t0 >= max_seconds:
                break
            self._process_inbound(self._pause_buffer.pop(0))
            n += 1
        return n

    @property
    def inbound_backlog(self) -> int:
        return len(self._pause_buffer)

    # -------------------------------------------------------- nack backoff
    def wait_backoff(self, sleep: Callable[[float], None]) -> float:
        """Consume the connection manager's advisory reconnect delay (the
        jittered, retry_after-floored value the last nack produced) through
        the host-supplied clock; returns the delay waited.  Raises once the
        cumulative backoff crosses the manager's deadline — a host looping
        on this primitive cannot retry forever against a front that keeps
        shedding it (the admission contract's client half)."""
        cm = self.connection_manager
        if cm.backoff_exhausted:
            from ..driver.definitions import DriverError

            raise DriverError(
                f"reconnect backoff deadline exhausted after "
                f"{cm.backoff.spent_s:.1f}s of accumulated waiting",
                can_retry=False,
            )
        delay = cm.next_backoff_s
        if delay <= 0.0:
            delay = cm.backoff.next_delay(cm.last_retry_after_s)
        sleep(delay)
        cm.backoff.consume(delay)  # only time actually waited counts
        cm.next_backoff_s = 0.0
        return delay

    # ---------------------------------------- document adapter (runtime side)
    def connect(
        self,
        client_id: str,
        subscriber: Callable[[SequencedMessage], None],
        on_nack: Callable[[Nack], None] | None = None,
    ) -> SequencedMessage:
        """ContainerRuntime's document.connect: open a write connection,
        repair the snapshot→stream gap synchronously, return the join.

        ``client_id`` must be the ConnectionManager's ``next_client_id()``
        (the Container hands it down)."""
        assert client_id == self.connection_manager.next_client_id()
        self._runtime_handler = subscriber
        conn = self.connection_manager.open(
            self._on_stream, on_nack, self._on_signal_msg, mode="write"
        )
        self._catch_up(conn.checkpoint_seq)
        self.connection_manager.reset_backoff()
        return conn.join_msg

    def connect_read(self, subscriber: Callable[[SequencedMessage], None]) -> None:
        """Read-mode connect: stream + catch-up, no join, no submit."""
        self._runtime_handler = subscriber
        conn = self.connection_manager.open(
            self._on_stream, None, self._on_signal_msg, mode="read"
        )
        self._catch_up(conn.checkpoint_seq)

    def _catch_up(self, checkpoint_seq: int) -> None:
        if checkpoint_seq > self.last_processed_seq:
            self._fetch_missing(self.last_processed_seq + 1, checkpoint_seq)

    def disconnect(self, client_id: str) -> None:
        self.connection_manager.close()

    def submit(self, wire: Any) -> None:
        conn = self.connection_manager.connection
        if conn is None or not conn.connected:
            from ..driver.definitions import DriverError

            raise DriverError("submit while disconnected")
        conn.submit(wire)

    def submit_signal(self, content: Any) -> None:
        conn = self.connection_manager.connection
        if conn is None or not conn.connected:
            from ..driver.definitions import DriverError

            raise DriverError("signal while disconnected")
        conn.submit_signal(content)

    # Attachment blob passthroughs (the runtime's BlobManager talks to its
    # "document", which through the loader is this adapter; storage owns
    # blobs — ref blobManager uploads via the storage service).  One cached
    # storage service per document: per-call construction would re-mint the
    # storage token for every blob op.
    def _blob_storage(self):
        if not hasattr(self, "_blob_storage_svc"):
            self._blob_storage_svc = self._service.connect_to_storage()
        return self._blob_storage_svc

    def upload_blob(self, content: str) -> str:
        return self._blob_storage().upload_blob_content(content)

    def read_blob(self, blob_id: str) -> str:
        return self._blob_storage().read_blob_content(blob_id)

class DeltaScheduler:
    """Drives a paused DeltaManager in slices (ref DeltaScheduler's 50 ms
    budget, deltaScheduler.ts:25-33): call ``run_slice()`` from the host
    loop; processing yields control between slices so UI/host work
    interleaves with catch-up storms."""

    DEFAULT_BUDGET_S = 0.05  # the reference's 50 ms slice

    def __init__(self, dm: "DeltaManager", ops_per_slice: int = 100,
                 seconds_per_slice: float | None = DEFAULT_BUDGET_S) -> None:
        self._dm = dm
        self.ops_per_slice = ops_per_slice
        self.seconds_per_slice = seconds_per_slice
        dm.pause()

    def run_slice(self) -> int:
        return self._dm.process_slice(self.ops_per_slice, self.seconds_per_slice)

    def drain(self) -> int:
        n = 0
        while self._dm.inbound_backlog:
            n += self.run_slice()
        return n

    def stop(self) -> None:
        """Return the delta manager to immediate (unsliced) processing."""
        self._dm.resume()
