"""Loader layer: container lifecycle, delta stream pump, connection state.

Reference parity: packages/loader/container-loader — Container (load/attach/
close), DeltaManager (inbound ordering + gap fetch), ConnectionManager
(reconnect, read/write modes), ProtocolHandler (quorum join/leave/propose).
"""

from .connection_manager import ConnectionManager
from .container import Container
from .delta_manager import DeltaManager
from .protocol import ProtocolHandler, Quorum

__all__ = [
    "ConnectionManager",
    "Container",
    "DeltaManager",
    "ProtocolHandler",
    "Quorum",
]
