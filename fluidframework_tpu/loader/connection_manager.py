"""Connection state machine: open/close, identity epochs, backoff, modes.

Reference parity: container-loader/src/connectionManager.ts (:140) — each
(re)connection is a fresh identity (the reference's server assigns a new
clientId per socket; here the manager derives ``base~epochN``), reconnects
apply exponential backoff (tracked as a delay value — the host owns the
clock), and connections are "read" or "write": read connections never join
the quorum and cannot submit (read→write escalation reconnects in write
mode, connectionManager.ts read/write escalation).

Backoff policy (ISSUE 10 flow-control contract): delays are exponential
with FULL JITTER — uniform in ``(0, min(cap, initial * 2^attempt)]`` — so a
nack storm (the front shedding under overload) does not resynchronize every
backed-off client into a thundering herd at the same retry instant.  A
server-supplied ``retry_after`` (the admission nack's load-derived hint) is
honored as a FLOOR under the jittered delay, never shortened.  Cumulative
consumed backoff is tracked against a deadline: a host that keeps retrying
past ``backoff_deadline_s`` of accumulated waiting gets ``exhausted`` and
should surface the failure instead of spinning forever.
"""

from __future__ import annotations

import random
from typing import Any, Callable

from ..driver.definitions import DeltaConnection, DocumentService
from ..protocol.messages import Nack, SequencedMessage, SignalMessage


class BackoffPolicy:
    """Jittered exponential backoff with a retry_after floor + deadline.

    Shared by the ConnectionManager (reconnect delays) and the chaos/soak
    clients (in-connection resubmit delays).  ``rng`` is injectable so
    seeded harnesses stay deterministic; the host owns the clock — this
    class only COMPUTES delays (``next_delay``) and accounts the consumed
    total against the deadline."""

    INITIAL_S = 0.5
    MAX_S = 8.0

    def __init__(
        self,
        rng: random.Random | None = None,
        initial_s: float = INITIAL_S,
        max_s: float = MAX_S,
        deadline_s: float = 60.0,
    ) -> None:
        self._rng = rng if rng is not None else random.Random()
        self.initial_s = initial_s
        self.max_s = max_s
        self.deadline_s = deadline_s
        self.attempts = 0
        self.spent_s = 0.0

    def next_delay(self, retry_after: float = 0.0) -> float:
        """The next advisory delay: full-jitter exponential, floored at the
        server's ``retry_after`` hint.  Computing a delay escalates the
        ladder but does NOT consume deadline — only time actually waited
        counts (``consume``): a burst of shed submits produces one nack
        per op, and a client that never slept must not arrive at its
        reconnect with the deadline already burned."""
        cap = min(self.max_s, self.initial_s * (2.0 ** self.attempts))
        self.attempts += 1
        # 1 ms floor: a zero delay would defeat the jitter's decorrelation
        # (and hosts assert the advisory delay is nonzero after a nack).
        return max(retry_after, self._rng.uniform(0.0, cap), 1e-3)

    def consume(self, waited_s: float) -> None:
        """Account time ACTUALLY waited against the deadline."""
        self.spent_s += waited_s

    @property
    def exhausted(self) -> bool:
        """True once the accumulated waiting crossed the deadline: the host
        should fail the operation rather than keep retrying."""
        return self.spent_s > self.deadline_s

    def reset(self) -> None:
        """Successful (re)admission: the next failure starts fresh."""
        self.attempts = 0
        self.spent_s = 0.0


class ConnectionManager:
    INITIAL_BACKOFF_S = BackoffPolicy.INITIAL_S
    MAX_BACKOFF_S = BackoffPolicy.MAX_S

    def __init__(
        self,
        service: DocumentService,
        base_client_id: str,
        backoff_rng: random.Random | None = None,
        backoff_deadline_s: float = 60.0,
    ) -> None:
        self._service = service
        self._base = base_client_id
        self._epoch = 0
        self.connection: DeltaConnection | None = None
        self.connect_count = 0
        self.next_backoff_s = 0.0  # advisory delay before the next attempt
        self.backoff = BackoffPolicy(
            rng=backoff_rng, deadline_s=backoff_deadline_s
        )
        # The last nack's server-supplied hint, kept so a host computing its
        # own schedule still sees the floor the front asked for.
        self.last_retry_after_s = 0.0

    # --------------------------------------------------------------- identity
    def next_client_id(self) -> str:
        """The identity the NEXT connection will use (stable until open)."""
        return self._base if self._epoch == 0 else f"{self._base}~r{self._epoch}"

    @property
    def client_id(self) -> str | None:
        return self.connection.client_id if self.connection else None

    @property
    def connected(self) -> bool:
        return self.connection is not None and self.connection.connected

    @property
    def mode(self) -> str | None:
        return self.connection.mode if self.connection else None

    # ------------------------------------------------------------------ open
    def open(
        self,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
    ) -> DeltaConnection:
        if self.connected:
            raise RuntimeError("already connected")
        client_id = self.next_client_id()
        self._epoch += 1

        def on_nack(nack: Nack) -> None:
            # The connection already tore itself down; escalate backoff so
            # the next attempt is delayed (ref reconnect-on-nack with delay;
            # retry_after from the server is a floor, never a shortcut).
            self._bump_backoff(nack.retry_after)
            if nack_listener is not None:
                nack_listener(nack)

        conn = self._service.connect_to_delta_stream(
            client_id, listener, on_nack, signal_listener, mode=mode
        )
        self.connection = conn
        self.connect_count += 1
        return conn

    def close(self) -> None:
        if self.connection is not None:
            self.connection.disconnect()
            self.connection = None

    def reset_backoff(self) -> None:
        self.next_backoff_s = 0.0
        self.last_retry_after_s = 0.0
        self.backoff.reset()

    @property
    def backoff_exhausted(self) -> bool:
        """Cumulative advisory delays crossed the deadline: the host should
        surface a connection failure instead of retrying further."""
        return self.backoff.exhausted

    def _bump_backoff(self, retry_after: float = 0.0) -> None:
        self.last_retry_after_s = max(self.last_retry_after_s, retry_after)
        self.next_backoff_s = self.backoff.next_delay(retry_after)
