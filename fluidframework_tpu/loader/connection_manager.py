"""Connection state machine: open/close, identity epochs, backoff, modes.

Reference parity: container-loader/src/connectionManager.ts (:140) — each
(re)connection is a fresh identity (the reference's server assigns a new
clientId per socket; here the manager derives ``base~epochN``), reconnects
apply exponential backoff (tracked as a delay value — the host owns the
clock), and connections are "read" or "write": read connections never join
the quorum and cannot submit (read→write escalation reconnects in write
mode, connectionManager.ts read/write escalation).
"""

from __future__ import annotations

from typing import Any, Callable

from ..driver.definitions import DeltaConnection, DocumentService
from ..protocol.messages import Nack, SequencedMessage, SignalMessage


class ConnectionManager:
    INITIAL_BACKOFF_S = 0.5
    MAX_BACKOFF_S = 8.0

    def __init__(self, service: DocumentService, base_client_id: str) -> None:
        self._service = service
        self._base = base_client_id
        self._epoch = 0
        self.connection: DeltaConnection | None = None
        self.connect_count = 0
        self.next_backoff_s = 0.0  # advisory delay before the next attempt

    # --------------------------------------------------------------- identity
    def next_client_id(self) -> str:
        """The identity the NEXT connection will use (stable until open)."""
        return self._base if self._epoch == 0 else f"{self._base}~r{self._epoch}"

    @property
    def client_id(self) -> str | None:
        return self.connection.client_id if self.connection else None

    @property
    def connected(self) -> bool:
        return self.connection is not None and self.connection.connected

    @property
    def mode(self) -> str | None:
        return self.connection.mode if self.connection else None

    # ------------------------------------------------------------------ open
    def open(
        self,
        listener: Callable[[SequencedMessage], None],
        nack_listener: Callable[[Nack], None] | None = None,
        signal_listener: Callable[[SignalMessage], None] | None = None,
        mode: str = "write",
    ) -> DeltaConnection:
        if self.connected:
            raise RuntimeError("already connected")
        client_id = self.next_client_id()
        self._epoch += 1

        def on_nack(nack: Nack) -> None:
            # The connection already tore itself down; escalate backoff so
            # the next attempt is delayed (ref reconnect-on-nack with delay;
            # retry_after from the server overrides).
            self._bump_backoff(nack.retry_after)
            if nack_listener is not None:
                nack_listener(nack)

        conn = self._service.connect_to_delta_stream(
            client_id, listener, on_nack, signal_listener, mode=mode
        )
        self.connection = conn
        self.connect_count += 1
        return conn

    def close(self) -> None:
        if self.connection is not None:
            self.connection.disconnect()
            self.connection = None

    def reset_backoff(self) -> None:
        self.next_backoff_s = 0.0

    def _bump_backoff(self, retry_after: float = 0.0) -> None:
        if retry_after > 0:
            self.next_backoff_s = retry_after
        elif self.next_backoff_s == 0.0:
            self.next_backoff_s = self.INITIAL_BACKOFF_S
        else:
            self.next_backoff_s = min(self.next_backoff_s * 2, self.MAX_BACKOFF_S)
