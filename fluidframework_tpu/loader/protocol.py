"""Protocol handler: quorum membership and consensus proposals.

Reference parity: container-loader/src/protocol.ts (:105) over protocol-base
``ProtocolOpHandler`` (protocol.ts:52) and ``Quorum`` (quorum.ts:449):

- joins/leaves are sequenced system messages maintaining the member table;
- a *proposal* (``MessageType.PROPOSE``) is a (key, value) pair that becomes
  **accepted once the MSN reaches its sequence number** — at that point every
  connected client has processed it, so all replicas commit it at the same
  op-stream position (the reference's zero-vote approval model);
- accepted values are a consistent key→value map used for container-level
  consensus (e.g. the "code" proposal selecting the runtime package).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..protocol.messages import MessageType, SequencedMessage


@dataclass
class QuorumMember:
    client_id: str
    short_client: int
    join_seq: int


@dataclass
class PendingProposal:
    seq: int
    key: str
    value: Any
    client_id: str


class Quorum:
    """Member table + accepted-value map (ref quorum.ts:449)."""

    def __init__(self) -> None:
        self.members: dict[str, QuorumMember] = {}
        self.values: dict[str, tuple[Any, int]] = {}  # key -> (value, accept seq)
        self.pending: list[PendingProposal] = []  # ordered by seq

    def get(self, key: str) -> Any:
        entry = self.values.get(key)
        return entry[0] if entry else None

    def has(self, key: str) -> bool:
        return key in self.values


class ProtocolHandler:
    """Applies protocol-level sequenced messages; tracks quorum state.

    ``on_accept(key, value, seq)`` callbacks fire when a proposal commits.
    ``attributes`` carries (seq, min_seq) for summary/restore
    (ref IProtocolState).
    """

    def __init__(self) -> None:
        self.quorum = Quorum()
        self.seq = 0
        self.min_seq = 0
        self._accept_listeners: list[Callable[[str, Any, int], None]] = []
        self._member_listeners: list[Callable[[str, str], None]] = []

    def on_accept(self, listener: Callable[[str, Any, int], None]) -> None:
        self._accept_listeners.append(listener)

    def on_member_change(self, listener: Callable[[str, str], None]) -> None:
        """``listener(kind, client_id)`` with kind "join"/"leave" — fires on
        sequenced quorum membership changes (the Audience's write-member
        feed; container.ts wires audience off protocol the same way)."""
        self._member_listeners.append(listener)

    # ------------------------------------------------------------------ apply
    def process_message(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.seq:
            return  # catch-up replay duplicate
        self.seq = msg.seq
        self.min_seq = max(self.min_seq, msg.min_seq)

        if msg.type == MessageType.JOIN:
            cid = msg.contents["clientId"]
            self.quorum.members[cid] = QuorumMember(
                client_id=cid,
                short_client=msg.contents["short"],
                join_seq=msg.seq,
            )
            for fn in list(self._member_listeners):
                fn("join", cid)
        elif msg.type == MessageType.LEAVE:
            if self.quorum.members.pop(msg.contents["clientId"], None) is not None:
                for fn in list(self._member_listeners):
                    fn("leave", msg.contents["clientId"])
        elif msg.type == MessageType.PROPOSE:
            self.quorum.pending.append(
                PendingProposal(
                    seq=msg.seq,
                    key=msg.contents["key"],
                    value=msg.contents["value"],
                    client_id=msg.client_id,
                )
            )

        # Accept every pending proposal the MSN has passed (quorum.ts
        # "commit on msn >= sequenceNumber").
        while self.quorum.pending and self.quorum.pending[0].seq <= self.min_seq:
            p = self.quorum.pending.pop(0)
            self.quorum.values[p.key] = (p.value, p.seq)
            for listener in self._accept_listeners:
                listener(p.key, p.value, p.seq)

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        """Protocol state for the snapshot (ref IProtocolState / scribe's
        protocol tree): members, accepted values, still-pending proposals."""
        return {
            "seq": self.seq,
            "minSeq": self.min_seq,
            "members": [
                {"clientId": m.client_id, "short": m.short_client, "joinSeq": m.join_seq}
                for m in self.quorum.members.values()
            ],
            "values": {k: [v, s] for k, (v, s) in self.quorum.values.items()},
            "pending": [
                {"seq": p.seq, "key": p.key, "value": p.value, "clientId": p.client_id}
                for p in self.quorum.pending
            ],
        }

    def load(self, state: dict[str, Any]) -> None:
        self.seq = state["seq"]
        self.min_seq = state["minSeq"]
        for m in state["members"]:
            self.quorum.members[m["clientId"]] = QuorumMember(
                client_id=m["clientId"],
                short_client=m["short"],
                join_seq=m["joinSeq"],
            )
        self.quorum.values = {k: (v[0], v[1]) for k, v in state["values"].items()}
        self.quorum.pending = [
            PendingProposal(
                seq=p["seq"], key=p["key"], value=p["value"], client_id=p["clientId"]
            )
            for p in state["pending"]
        ]
