"""Container: the loader-level lifecycle object tying all layers together.

Reference parity: container-loader/src/container.ts — ``Container.load``
(:324) = snapshot fetch → runtime boot → delta-stream connect → gap replay;
``createDetached`` (:382) + ``attach``; ``getPendingLocalState`` (:1152);
close semantics. The Container owns the ProtocolHandler (quorum/proposals),
the DeltaManager (ordered pump + gap repair) and the ContainerRuntime
(op application), and drives reconnect/escalation.

Layering note (SURVEY §1): the ContainerRuntime never sees the driver — it
talks to the DeltaManager through the same document-adapter contract the
unit tests use to wire it straight to a LocalDocument.
"""

from __future__ import annotations

from typing import Any, Callable

from ..driver.definitions import DocumentServiceFactory
from ..protocol.messages import MessageType, SignalMessage
from ..runtime.container_runtime import ContainerRuntime
from .audience import Audience
from .delta_manager import DeltaManager
from .protocol import ProtocolHandler


class Container:
    """One loaded collaborative document (ref IContainer)."""

    def __init__(self, runtime: ContainerRuntime, registry: dict[str, Any]) -> None:
        self.runtime = runtime
        self._registry = registry
        self.protocol: ProtocolHandler | None = None
        self.delta_manager: DeltaManager | None = None
        self._storage = None
        self._service = None
        self.attached = False
        self._stash: str | None = None
        self._mode = "write"
        # Cleanups run at close (e.g. a spawned hidden summarizer must
        # leave when its parent does, or it pins the MSN forever).
        self._close_hooks: list[Callable[[], None]] = []
        # Full connected-membership surface: write members from sequenced
        # joins/leaves, read members from the service's clientJoin/
        # clientLeave system signals (ref audience.ts; VERDICT r3 #3).
        self.audience = Audience()

    def _wire_audience(self) -> None:
        self.protocol.on_member_change(
            lambda kind, cid: (
                self.audience.add_member(cid, {"mode": "write"})
                if kind == "join"
                else self.audience.remove_member(cid)
            )
        )
        self.delta_manager.on_signal(self._audience_signal)

    def _audience_signal(self, sig: SignalMessage) -> None:
        # Membership events come ONLY from the service identity (empty
        # sender — connects reject empty client ids, so app signals cannot
        # spoof audience membership or crash dispatch via the duplicate-add
        # assertion).
        if sig.client_id != "":
            return
        c = sig.contents
        if not isinstance(c, dict):
            return
        if c.get("type") == "clientJoin":
            self.audience.add_member(c["clientId"], dict(c["details"]))
        elif c.get("type") == "clientLeave":
            self.audience.remove_member(c["clientId"])

    # ------------------------------------------------------------------ load
    @staticmethod
    def load(
        doc_id: str,
        service_factory: DocumentServiceFactory,
        registry: dict[str, Any],
        client_id: str,
        stash: str | None = None,
        mode: str = "write",
        track_attribution: bool = False,
        _summarizer: bool = False,
    ) -> "Container":
        """Boot from the service: latest snapshot + trailing ops + live
        stream (call stack SURVEY §3.1)."""
        from ..runtime.summary import SUMMARIZER_SUFFIX

        if client_id.endswith(SUMMARIZER_SUFFIX) and not _summarizer:
            # The suffix IS the non-interactive marker every replica's
            # election trusts; an interactive client wearing it would be
            # silently unelectable (and a lone one would never summarize).
            raise ValueError(
                f"client id suffix {SUMMARIZER_SUFFIX!r} is reserved for "
                "spawned summarizer clients"
            )
        service = service_factory.create_document_service(doc_id)
        storage = service.connect_to_storage()
        # Like the reference's mixinAttributor, attribution tracking is a
        # runtime OPTION that must be configured uniformly across a
        # document's clients; snapshots carrying an attribution table also
        # enable it on loaders regardless of their own option.
        runtime = ContainerRuntime(
            registry, container_id=client_id,
            track_attribution=track_attribution,
        )
        protocol = ProtocolHandler()
        snap = storage.get_latest_snapshot()
        base_seq = 0
        if snap is not None:
            base_seq, summary = snap
            runtime.load_snapshot(summary["runtime"])
            protocol.load(summary["protocol"])
        c = Container(runtime, registry)
        c._service = service
        c._storage = storage
        c.protocol = protocol
        c.delta_manager = DeltaManager(
            service, protocol, base_client_id=client_id, last_processed_seq=base_seq
        )
        # Members already in the snapshot's quorum predate our hooks.
        for cid in protocol.quorum.members:
            c.audience.add_member(cid, {"mode": "write"})
        c._wire_audience()
        c.attached = True
        c._stash = stash
        c.connect(mode=mode)
        return c

    # ------------------------------------------------- detached create/attach
    @staticmethod
    def create_detached(
        registry: dict[str, Any],
        container_id: str = "detached",
        track_attribution: bool = False,
    ) -> "Container":
        """A container with no service: build structure + edit locally;
        everything parks as pending until attach (ref createDetached :382)."""
        return Container(
            ContainerRuntime(
                registry, container_id=container_id,
                track_attribution=track_attribution,
            ),
            registry,
        )

    def attach(
        self,
        doc_id: str,
        service_factory: DocumentServiceFactory,
        client_id: str,
    ) -> None:
        """Bind a detached container to a document: write a structure-only
        snapshot at seq 0 (the channel layout; detached content replays as
        trailing ops on join — the reference bakes detached state into the
        initial summary, an equivalent bootstrap), then connect."""
        if self.attached:
            raise RuntimeError("already attached")
        service = service_factory.create_document_service(doc_id)
        storage = service.connect_to_storage()
        if storage.get_latest_snapshot() is None:
            structure = {
                "runtime": {
                    "seq": 0,
                    "minSeq": 0,
                    "quorum": {},
                    "datastores": {
                        ds_id: ds.structure_summary()
                        for ds_id, ds in self.runtime.datastores.items()
                    },
                },
                "protocol": ProtocolHandler().summarize(),
            }
            storage.write_snapshot(0, structure)
        self._service = service
        self._storage = storage
        self.protocol = ProtocolHandler()
        self.delta_manager = DeltaManager(
            service, self.protocol, base_client_id=client_id, last_processed_seq=0
        )
        self._wire_audience()
        self.attached = True
        self.connect()

    # ------------------------------------------------------------- connection
    def connect(self, mode: str | None = None) -> None:
        """(Re)open a connection in ``mode`` — defaults to the container's
        current mode, so reconnect never silently escalates read→write."""
        if not self.attached:
            raise RuntimeError("connect before attach")
        mode = self._mode if mode is None else mode
        self._mode = mode
        self.audience.set_current_client_id(
            self.delta_manager.connection_manager.next_client_id()
        )
        if mode == "write":
            stash, self._stash = self._stash, None
            self.runtime.connect(
                self.delta_manager,
                self.delta_manager.connection_manager.next_client_id(),
                stash=stash,
            )
        else:
            self.delta_manager.connect_read(self.runtime.process_sequenced)

    def disconnect(self) -> None:
        if self.runtime.has_document:
            self.runtime.disconnect()
        else:
            self.delta_manager.connection_manager.close()

    def reconnect(self) -> None:
        """New connection epoch; pending ops resubmit after the new join
        sequences (call stack SURVEY §3.6)."""
        self.disconnect()
        self.connect()

    def reconnect_with_backoff(
        self,
        sleep: Callable[[float], None] | None = None,
        max_attempts: int = 16,
    ) -> int:
        """Reconnect honoring the nack/backoff contract: wait the advisory
        jittered delay (floored at the server's ``retryAfter``) before each
        attempt, retry transient failures, and give up when the connection
        manager's cumulative-backoff deadline is exhausted.  Pending local
        ops replay on the successful rejoin (the existing reconnect
        machinery).  Returns the attempts taken; ``sleep`` is injectable so
        deterministic harnesses can virtualize the clock."""
        import time as _time

        from ..driver.definitions import DriverError

        sleep = _time.sleep if sleep is None else sleep
        self.disconnect()
        last: Exception | None = None
        for attempt in range(1, max_attempts + 1):
            self.delta_manager.wait_backoff(sleep)  # raises once exhausted
            try:
                self.connect()
                return attempt
            except (DriverError, OSError) as e:
                if isinstance(e, DriverError) and not e.can_retry:
                    # Fatal rejection (auth, protocol): no amount of
                    # waiting readmits this client.
                    raise
                # The next iteration's wait_backoff computes an escalated
                # delay itself (next_backoff_s is consumed/zeroed).
                last = e
        raise DriverError(
            f"reconnect failed after {max_attempts} attempts: {last}",
            can_retry=False,
        )

    def escalate_to_write(self) -> None:
        """read → write escalation (ref connectionManager read/write modes):
        reconnect in write mode; parked local edits replay on join."""
        self.delta_manager.connection_manager.close()
        self.connect(mode="write")

    @property
    def connected(self) -> bool:
        return (
            self.delta_manager is not None
            and self.delta_manager.connection_manager.connected
        )

    @property
    def joined(self) -> bool:
        return self.runtime.joined

    def close(self, error: Exception | None = None) -> None:
        for hook in list(self._close_hooks):
            hook()
        self._close_hooks.clear()
        if self.delta_manager is not None:
            self.delta_manager.connection_manager.close()
        self.runtime.close(error)

    # --------------------------------------------------------------- proposals
    def propose(self, key: str, value: Any) -> None:
        """Quorum proposal; accepted (on every replica) once the MSN passes
        its sequence number (ref quorum.ts propose)."""
        self.runtime.submit_protocol_message(
            MessageType.PROPOSE, {"key": key, "value": value}
        )

    # ---------------------------------------------------------------- signals
    def submit_signal(self, content: Any) -> None:
        self.delta_manager.submit_signal(content)

    def on_signal(self, listener: Callable[[SignalMessage], None]) -> None:
        self.delta_manager.on_signal(listener)

    # ------------------------------------------------------------- checkpoint
    def summarize_to_storage(self) -> int:
        """Write a full snapshot at the current seq (client-driven summary;
        the election/heuristics live in runtime/summary.py). Requires no
        local pending ops — the reference's summarizer is a dedicated client
        with none, so acked state == full state."""
        if self.runtime.pending_op_count:
            raise RuntimeError("cannot summarize with pending local ops")
        seq = self.runtime.ref_seq
        self._storage.write_snapshot(
            seq,
            {"runtime": self.runtime.summarize(), "protocol": self.protocol.summarize()},
        )
        return seq

    def make_summary_manager(self, config=None):
        """Wire a SummaryManager (election + heuristics + incremental
        summary upload) for this container (ref SummaryManager spawn,
        summaryManager.ts:95)."""
        from ..runtime.summary import SummaryManager

        return SummaryManager(
            self.runtime,
            self._storage,
            config=config,
            protocol_summarize=self.protocol.summarize,
        )

    def make_hidden_summarizer(self, doc_id: str, service_factory, config=None):
        """Summarize through a spawned hidden client while this interactive
        client holds the election (ref summaryManager.ts:95 +
        summarizer.ts:89 — the summarizer is its own non-interactive
        container, so interactive pending edits never block a summary)."""
        from ..runtime.summary import HiddenSummaryManager

        hs = HiddenSummaryManager(
            self, doc_id, service_factory, self._registry, config=config
        )
        self._close_hooks.append(hs.stop)
        return hs

    # ------------------------------------------------------------------ stash
    def get_pending_local_state(self) -> str:
        if self._stash is not None:
            # A stash held through a read-mode session was never applied
            # (only a write connection replays it): hand back the original
            # rather than the runtime's empty pending set, so offline edits
            # survive a read-only load/save cycle.
            return self._stash
        return self.runtime.get_pending_local_state()
