"""Audience: every client connected to the op stream, read connections
included.

Reference parity: container-loader/src/audience.ts (VERDICT r3 missing #3).
The quorum only ever holds WRITE clients (a read connection never produces a
sequenced join); the Audience is the loader's full-membership surface:

- write members arrive/depart with sequenced join/leave messages;
- read members arrive/depart with the service's clientJoin/clientLeave
  system signals (nexus broadcasts them; the connect handshake's
  initialClients primes late subscribers) — signal delivery is unreliable,
  so duplicate adds with identical payloads are tolerated silently
  (audience.ts:56);
- ``get_self`` names this connection's own membership
  (audience.ts getSelf/setCurrentClientId — the member record may lag the
  id when the audience hasn't caught up yet).
"""

from __future__ import annotations

from typing import Any, Callable


class Audience:
    """clientId -> member details ({"mode": "read"|"write", ...})."""

    def __init__(self) -> None:
        self._members: dict[str, dict[str, Any]] = {}
        self._current_client_id: str | None = None
        self._add_listeners: list[Callable[[str, dict], None]] = []
        self._remove_listeners: list[Callable[[str, dict], None]] = []
        self._self_listeners: list[Callable[[str | None, str], None]] = []

    # ------------------------------------------------------------ membership
    def add_member(self, client_id: str, details: dict[str, Any]) -> None:
        """Add a client (audience.ts addMember:52).  A duplicate add must
        carry the identical payload (signal redelivery), never a different
        one (that would be two clients under one id)."""
        existing = self._members.get(client_id)
        if existing is not None:
            if existing != details:
                raise AssertionError(
                    f"audience member {client_id!r} re-added with different "
                    f"payload (ref assert 0x4b2): {existing} != {details}"
                )
            return
        self._members[client_id] = details
        for fn in list(self._add_listeners):
            fn(client_id, details)

    def remove_member(self, client_id: str) -> bool:
        """Remove a client; returns whether it was present
        (audience.ts removeMember:71)."""
        details = self._members.pop(client_id, None)
        if details is None:
            return False
        for fn in list(self._remove_listeners):
            fn(client_id, details)
        return True

    def get_members(self) -> dict[str, dict[str, Any]]:
        return dict(self._members)

    def get_member(self, client_id: str) -> dict[str, Any] | None:
        return self._members.get(client_id)

    # ------------------------------------------------------------------ self
    def set_current_client_id(self, client_id: str) -> None:
        if self._current_client_id != client_id:
            old = self._current_client_id
            self._current_client_id = client_id
            for fn in list(self._self_listeners):
                fn(old, client_id)

    def get_self(self) -> dict[str, Any] | None:
        if self._current_client_id is None:
            return None
        return {
            "clientId": self._current_client_id,
            "client": self.get_member(self._current_client_id),
        }

    # ---------------------------------------------------------------- events
    def on_add_member(self, fn: Callable[[str, dict], None]) -> Callable[[], None]:
        self._add_listeners.append(fn)
        return lambda: self._add_listeners.remove(fn)

    def on_remove_member(self, fn: Callable[[str, dict], None]) -> Callable[[], None]:
        self._remove_listeners.append(fn)
        return lambda: self._remove_listeners.remove(fn)

    def on_self_changed(
        self, fn: Callable[[str | None, str], None]
    ) -> Callable[[], None]:
        self._self_listeners.append(fn)
        return lambda: self._self_listeners.remove(fn)
