"""Agent scheduler: exclusive distributed task assignment with handoff.

Reference parity: packages/framework/agent-scheduler —
``AgentScheduler`` (scheduler.ts): clients ``pick`` tasks with a worker
callback; consensus guarantees at most one assignee per task across the
session; when the assignee leaves or releases, the next volunteer's worker
starts (task handoff); ``pickedTasks`` lists what this client currently
runs. The "leader" convention (a well-known task id every client picks)
gives leader election, as the reference's LeaderElection built on it.

Built over the consensus-gated TaskManager DDS (dds/small.py, the
task-queue semantics the reference's scheduler gets from
ConsensusRegisterCollection): this layer adds worker lifecycle — start on
assignment, stop on loss — which is exactly what scheduler.ts adds over
its consensus primitives.
"""

from __future__ import annotations

from typing import Callable

LEADER_TASK = "__leader__"


class AgentScheduler:
    def __init__(self, task_manager) -> None:
        self._tm = task_manager
        # task -> (worker, stop) registered by THIS client.
        self._workers: dict[str, tuple[Callable[[], None], Callable[[], None] | None]] = {}
        self._running: set[str] = set()
        # Tasks with a volunteer op in flight (submitted, not yet observed
        # in the sequenced queue) — prevents duplicate re-volunteers while
        # waiting for our own ack.
        self._pending_volunteer: set[str] = set()
        self._tm.assignment_listeners.append(self._on_assignment)

    # ---------------------------------------------------------------- picking
    def pick(
        self,
        task_id: str,
        worker: Callable[[], None],
        on_lost: Callable[[], None] | None = None,
    ) -> None:
        """Volunteer for ``task_id``; ``worker`` runs when (and each time)
        this client becomes the assignee, ``on_lost`` when assignment is
        taken away (connection loss handoff)."""
        if task_id in self._workers:
            raise ValueError(f"already picked {task_id!r}")
        self._workers[task_id] = (worker, on_lost)
        self._pending_volunteer.add(task_id)
        try:
            self._tm.volunteer(task_id)
        except BaseException:
            # Submission failed (e.g. detached channel): leave no residue —
            # the caller may retry pick() after attaching.
            del self._workers[task_id]
            self._pending_volunteer.discard(task_id)
            raise

    def release(self, task_id: str) -> None:
        """Stop volunteering (ref release): the next volunteer takes over."""
        if task_id not in self._workers:
            raise ValueError(f"never picked {task_id!r}")
        del self._workers[task_id]
        self._running.discard(task_id)
        self._pending_volunteer.discard(task_id)
        self._tm.abandon(task_id)

    def picked_tasks(self) -> list[str]:
        """Tasks this client is CURRENTLY assigned (ref pickedTasks)."""
        return sorted(self._running)

    # ------------------------------------------------------------- leadership
    def volunteer_for_leadership(
        self,
        on_elected: Callable[[], None],
        on_lost: Callable[[], None] | None = None,
    ) -> None:
        self.pick(LEADER_TASK, on_elected, on_lost)

    @property
    def leader(self) -> str | None:
        return self._tm.assignee(LEADER_TASK)

    @property
    def is_leader(self) -> bool:
        return self._tm.assigned(LEADER_TASK)

    # ---------------------------------------------------------------- internal
    def _my_id(self) -> str | None:
        conn = getattr(self._tm, "_connection", None)
        return conn.client_id() if conn is not None else None

    def _on_assignment(
        self, task_id: str, assignee: str | None, reason: str = "change"
    ) -> None:
        if task_id not in self._workers:
            return
        if reason == "complete":
            # The task is FINISHED (complete() clears the queue so nobody
            # picks it up again) — drop it entirely instead of treating the
            # eviction as a reconnect and resurrecting it. No on_lost:
            # normal completion is not a lost assignment. An in-flight
            # volunteer of ours is harmless: the DDS drops volunteers
            # authored before the completion (completed_at tombstone).
            self._running.discard(task_id)
            self._pending_volunteer.discard(task_id)
            del self._workers[task_id]
            return
        queued = self._tm.queued(task_id)
        if queued:
            self._pending_volunteer.discard(task_id)
        mine = assignee is not None and assignee == self._my_id()
        if mine and task_id not in self._running:
            self._running.add(task_id)
            worker, _lost = self._workers[task_id]
            worker()
        elif not mine and task_id in self._running:
            self._running.discard(task_id)
            _worker, lost = self._workers[task_id]
            if lost is not None:
                lost()
        if not mine and not queued and task_id not in self._pending_volunteer:
            # Evicted from the queue entirely — a reconnect sequenced our
            # old identity's leave. Re-volunteer under the current identity
            # (ref scheduler.ts re-pick on reconnect) so picked tasks are
            # never silently lost.
            try:
                self._pending_volunteer.add(task_id)
                self._tm.volunteer(task_id)
            except RuntimeError:
                self._pending_volunteer.discard(task_id)
                # disconnected right now: resume() re-enters later

    def resume(self) -> None:
        """Re-volunteer every picked-but-unqueued task (call after a
        reconnect if no queue event has fired yet)."""
        for task_id in self._workers:
            if (
                not self._tm.queued(task_id)
                and task_id not in self._pending_volunteer
            ):
                try:
                    self._pending_volunteer.add(task_id)
                    self._tm.volunteer(task_id)
                except RuntimeError:
                    self._pending_volunteer.discard(task_id)
