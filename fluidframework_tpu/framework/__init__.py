"""Framework / public API layer (SURVEY §2.4).

Reference parity: packages/framework/* — the app-facing surface above the
runtime: ``fluid-static``'s FluidContainer + schema bootstrap, ``aqueduct``'s
DataObject authoring model, ``presence`` (ephemeral state over signals),
``undo-redo`` revertible stacks, the ``attributor`` (who-wrote-what from the
op stream), and the service-client façade (tinylicious-client analog).
"""

from .aqueduct import DataObject, DataObjectFactory
from .attributor import OpStreamAttributor
from .fluid_static import ContainerSchema, FluidContainer
from .interceptions import InterceptedSharedMap, InterceptedSharedString
from .oldest_client import OldestClientObserver
from .presence import Presence
from .service_client import LocalServiceClient
from .tree_agent import TreeAgent, render_schema_prompt
from .undo_redo import UndoRedoStackManager

__all__ = [
    "ContainerSchema",
    "DataObject",
    "DataObjectFactory",
    "FluidContainer",
    "InterceptedSharedMap",
    "InterceptedSharedString",
    "LocalServiceClient",
    "OldestClientObserver",
    "OpStreamAttributor",
    "Presence",
    "TreeAgent",
    "UndoRedoStackManager",
    "render_schema_prompt",
]
