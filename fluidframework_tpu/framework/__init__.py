"""Framework / public API layer (SURVEY §2.4).

Reference parity: packages/framework/* — the app-facing surface above the
runtime: ``fluid-static``'s FluidContainer + schema bootstrap, ``aqueduct``'s
DataObject authoring model, ``presence`` (ephemeral state over signals),
``undo-redo`` revertible stacks, the ``attributor`` (who-wrote-what from the
op stream), and the service-client façade (tinylicious-client analog).
"""

from .agent_scheduler import AgentScheduler
from .aqueduct import DataObject, DataObjectFactory
from .attributor import OpStreamAttributor
from .fluid_static import ContainerSchema, FluidContainer
from .interceptions import InterceptedSharedMap, InterceptedSharedString
from .oldest_client import OldestClientObserver
from .presence import Presence
from .request_handler import (
    RequestParser,
    RuntimeRequestHandlerBuilder,
    datastore_request_handler,
)
from .service_client import LocalServiceClient, NetworkServiceClient
from .synthesize import DependencyContainer
from .tree_agent import TreeAgent, render_schema_prompt
from .undo_redo import UndoRedoStackManager

__all__ = [
    "AgentScheduler",
    "ContainerSchema",
    "DataObject",
    "DataObjectFactory",
    "DependencyContainer",
    "FluidContainer",
    "InterceptedSharedMap",
    "InterceptedSharedString",
    "LocalServiceClient",
    "NetworkServiceClient",
    "OldestClientObserver",
    "OpStreamAttributor",
    "Presence",
    "RequestParser",
    "RuntimeRequestHandlerBuilder",
    "TreeAgent",
    "UndoRedoStackManager",
    "datastore_request_handler",
    "render_schema_prompt",
]
