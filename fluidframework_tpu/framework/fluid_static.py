"""fluid-static: FluidContainer + schema-driven initial objects.

Reference parity: packages/framework/fluid-static — ``IFluidContainer``/
``FluidContainer`` (fluidContainer.ts) wrap the loader Container behind an
app-simple surface, and ``rootDataObject.ts`` bootstraps the channels named
in a ContainerSchema so every client finds them under ``initialObjects``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ..dds.channels import default_registry
from ..loader.container import Container

ROOT_DATASTORE = "rootDO"


@dataclass
class ContainerSchema:
    """Declares the initial channels every client expects (ref
    ContainerSchema.initialObjects: name -> DDS type string)."""

    initial_objects: dict[str, str]
    registry: dict[str, Any] = field(default_factory=default_registry)


class FluidContainer:
    """App-facing wrapper over the loader Container (ref FluidContainer)."""

    def __init__(self, container: Container, schema: ContainerSchema) -> None:
        self.container = container
        self.schema = schema

    # ------------------------------------------------------------- lifecycle
    @staticmethod
    def create_detached(schema: ContainerSchema, client_id: str = "creator") -> "FluidContainer":
        c = Container.create_detached(schema.registry, container_id=client_id)
        ds = c.runtime.create_datastore(ROOT_DATASTORE)
        for name, channel_type in schema.initial_objects.items():
            ds.create_channel(channel_type, name)
        return FluidContainer(c, schema)

    def attach(self, doc_id: str, service_factory, client_id: str) -> str:
        self.container.attach(doc_id, service_factory, client_id)
        return doc_id

    @staticmethod
    def load(
        doc_id: str, service_factory, schema: ContainerSchema, client_id: str, **kw
    ) -> "FluidContainer":
        c = Container.load(doc_id, service_factory, schema.registry, client_id, **kw)
        fc = FluidContainer(c, schema)
        # Contract check: the document must carry the schema's objects.
        ds = c.runtime.datastore(ROOT_DATASTORE)
        for name, channel_type in schema.initial_objects.items():
            ch = ds.get_channel(name)
            if ch.channel_type != channel_type:
                raise ValueError(
                    f"initial object {name!r} is {ch.channel_type!r}, "
                    f"schema expects {channel_type!r}"
                )
        return fc

    @staticmethod
    def view_version(schema: ContainerSchema, summary: dict) -> "FluidContainer":
        """A read-only view of a container at a stored snapshot version,
        never connected to the service (ref AzureClient.viewContainerVersion
        via loadContainerPaused)."""
        c = Container.create_detached(schema.registry, container_id="version-view")
        c.runtime.load_snapshot(summary["runtime"])
        return FluidContainer(c, schema)

    # ----------------------------------------------------------------- access
    @property
    def initial_objects(self) -> dict[str, Any]:
        ds = self.container.runtime.datastore(ROOT_DATASTORE)
        return {name: ds.get_channel(name) for name in self.schema.initial_objects}

    @property
    def connected(self) -> bool:
        return self.container.connected

    def flush(self) -> None:
        self.container.runtime.flush()

    def disconnect(self) -> None:
        self.container.disconnect()

    def connect(self) -> None:
        self.container.connect()

    def close(self) -> None:
        self.container.close()

    @property
    def is_dirty(self) -> bool:
        """Unacked local changes exist (ref IFluidContainer.isDirty)."""
        return self.container.runtime.pending_op_count > 0
