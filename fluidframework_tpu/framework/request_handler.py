"""Runtime request routing: URL paths to runtime objects.

Reference parity: packages/framework/request-handler —
``RuntimeRequestHandlerBuilder`` (runtimeRequestHandlerBuilder.ts) chains
handlers until one produces a response, and the stock handlers resolve
data stores / channels by path. ``RequestParser`` mirrors
runtime-utils' parser: split, unescape, expose ``path_parts``.
"""

from __future__ import annotations

from typing import Any, Callable
from urllib.parse import unquote


class RequestParser:
    """Parsed request: path segments + header bag (requestParser.ts)."""

    def __init__(self, url: str, headers: dict[str, Any] | None = None) -> None:
        self.url = url
        self.headers = dict(headers or {})
        self.path_parts = [unquote(p) for p in url.strip("/").split("/") if p]

    def sub_request(self, start: int) -> "RequestParser":
        """Tail of the path from ``start``, WITHOUT re-decoding: segments
        are already unquoted, so rebuilding a url and re-parsing would
        corrupt any segment containing '%' or an encoded '/'."""
        sub = RequestParser.__new__(RequestParser)
        sub.url = "/".join(self.path_parts[start:])
        sub.headers = dict(self.headers)
        sub.path_parts = list(self.path_parts[start:])
        return sub


def ok(value: Any) -> dict:
    return {"status": 200, "value": value}


def not_found(url: str) -> dict:
    return {"status": 404, "value": f"no route for {url!r}"}


Handler = Callable[[RequestParser, Any], dict | None]


class RuntimeRequestHandlerBuilder:
    """Compose handlers; the first non-None response wins (builder.ts)."""

    def __init__(self) -> None:
        self._handlers: list[Handler] = []

    def push(self, *handlers: Handler) -> "RuntimeRequestHandlerBuilder":
        self._handlers.extend(handlers)
        return self

    def build(self) -> Callable[[str, Any], dict]:
        handlers = list(self._handlers)

        def route(url: str, runtime: Any, headers: dict | None = None) -> dict:
            request = RequestParser(url, headers)
            for handler in handlers:
                response = handler(request, runtime)
                if response is not None:
                    return response
            return not_found(url)

        return route


# ----------------------------------------------------------- stock handlers

def datastore_request_handler(request: RequestParser, runtime) -> dict | None:
    """/<datastoreId>[/<channelId>] -> datastore or channel
    (requestHandlers.ts defaultDataStore/root routing)."""
    parts = request.path_parts
    if not parts:
        return None
    try:
        ds = runtime.datastore(parts[0])
    except KeyError:
        return None
    if len(parts) == 1:
        return ok(ds)
    if len(parts) == 2:
        try:
            return ok(ds.get_channel(parts[1]))
        except KeyError:
            return None
    return None


def default_route_handler(default_path: str) -> Handler:
    """'/' resolves to a default datastore (defaultRouteRequestHandler)."""

    def handler(request: RequestParser, runtime) -> dict | None:
        if request.path_parts:
            return None
        try:
            return ok(runtime.datastore(default_path))
        except KeyError:
            return None

    return handler


def create_fluid_object_handler(objects: dict[str, Any]) -> Handler:
    """Serve registered singletons by name (ref createFluidObjectResponse)."""

    def handler(request: RequestParser, runtime) -> dict | None:
        if len(request.path_parts) == 1 and request.path_parts[0] in objects:
            return ok(objects[request.path_parts[0]])
        return None

    return handler
