"""tree-agent: LLM-driven editing of a SharedTree behind a typed guardrail.

Reference parity: packages/framework/tree-agent — the schema is rendered
into a prompt, the model returns edit commands, and the agent validates +
applies them through the typed view, feeding errors back for retry. The
LLM itself is a pluggable callable (``llm(prompt) -> str``); nothing here
performs network I/O, so tests drive it with deterministic fakes and hosts
plug in a real model client.

Command protocol (the JSON the model must emit — a list of):
  {"op": "setValue", "path": [[field, idx], ...], "value": ...}
  {"op": "setField", "path": [...], "field": str, "value": ...}
  {"op": "insert", "path": [...], "field": str, "index": int, "items": [...]}
  {"op": "remove", "path": [...], "field": str, "index": int, "count": int}
"""

from __future__ import annotations

import json
from typing import Any, Callable

from ..dds.tree.changeset import make_insert, make_remove, make_set_value
from ..dds.tree.schema import FieldKind, SchemaRegistry, leaf


def render_schema_prompt(registry: SchemaRegistry) -> str:
    """Schema -> textual system prompt (ref tree-agent schema prompting)."""
    lines = ["The document tree follows this schema:"]
    for name, node in registry.nodes.items():
        fields = ", ".join(
            f"{k}: {fs.kind.value}<{'|'.join(sorted(fs.allowed_types))}>"
            for k, fs in node.fields.items()
        )
        lines.append(f"- node {name} {{ {fields} }}")
    if registry.root is not None:
        lines.append(
            f"- root: {registry.root.kind.value}"
            f"<{'|'.join(sorted(registry.root.allowed_types))}>"
        )
    lines.append(
        "Respond ONLY with a JSON list of edit commands using ops "
        "setValue/setField/insert/remove as documented."
    )
    return "\n".join(lines)


class TreeAgentError(Exception):
    pass


class TreeAgent:
    """Drives edits on a SharedTreeChannel from natural-language asks."""

    def __init__(self, channel, llm: Callable[[str], str], max_attempts: int = 3) -> None:
        self._channel = channel
        self._llm = llm
        self._max_attempts = max_attempts

    # ------------------------------------------------------------- execution
    @staticmethod
    def _apply_commands(commands: list[dict], forest_like, submit) -> None:
        """Apply one command list against ``forest_like`` (its node_at for
        state-dependent commands) through ``submit(change)``."""
        for cmd in commands:
            op = cmd.get("op")
            path = [tuple(p) for p in cmd.get("path", [])]
            if op == "setValue":
                submit(make_set_value(path, cmd["value"]))
            elif op == "setField":
                node = forest_like.node_at(path)
                count = len(node.fields.get(cmd["field"], []))
                if count:
                    submit(make_remove(path, cmd["field"], 0, count))
                submit(make_insert(path, cmd["field"], 0, [leaf(cmd["value"])]))
            elif op == "insert":
                items = [leaf(v) for v in cmd["items"]]
                submit(make_insert(path, cmd["field"], cmd["index"], items))
            elif op == "remove":
                submit(make_remove(path, cmd["field"], cmd["index"], cmd["count"]))
            else:
                raise TreeAgentError(f"unknown command op {op!r}")

    def _validate_on_probe(self, commands: list[dict]) -> None:
        """Dry-run the WHOLE list on a throwaway forest clone (schema check
        included) so a mid-list failure never leaves partial edits behind."""
        from ..dds.tree.changeset import apply_node_change
        from ..dds.tree.forest import Forest

        probe = Forest()
        probe.load_json(self._channel.forest.to_json())
        self._apply_commands(
            commands, probe, lambda ch: apply_node_change(probe.root, ch)
        )
        errors = self._channel.schema.check_forest(probe)
        if errors:
            raise TreeAgentError(f"edits violate the schema: {errors}")

    def run(self, instruction: str) -> list[dict]:
        """Ask the model for edits and apply them; malformed output and
        schema violations feed back as retry context (ref tool-loop
        retries). Commands are validated atomically on a probe before
        touching the live tree, and every attempt sees the CURRENT state.
        Returns the applied command list."""
        feedback = ""
        for _ in range(self._max_attempts):
            prompt = (
                render_schema_prompt(self._channel.schema)
                + "\nCurrent tree (JSON): "
                + json.dumps(self._channel.forest.to_json())
                + "\nInstruction: "
                + instruction
                + feedback
            )
            raw = self._llm(prompt)
            try:
                commands = json.loads(raw)
                if not isinstance(commands, list):
                    raise ValueError("expected a JSON list of commands")
                self._validate_on_probe(commands)
            except Exception as e:  # noqa: BLE001 — feeds back to the model
                feedback = f"\nYour previous response failed: {e!r}. Try again."
                continue
            self._apply_commands(
                commands, self._channel.forest, self._channel.submit_change
            )
            return commands
        raise TreeAgentError(f"no valid edit after {self._max_attempts} attempts")
