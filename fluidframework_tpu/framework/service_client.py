"""Service-client façade: create/get containers against a service.

Reference parity: packages/service-clients —
- ``TinyliciousClient``/``AzureClient`` (AzureClient.ts): createContainer /
  getContainer hiding loader+driver wiring behind a ContainerSchema,
  container services (audience), getContainerVersions, and
  viewContainerVersion (a paused, read-only container at a stored version);
- ``OdspClient``: the same surface over a virtualizing storage path.

Three deployment shapes share one base:
- ``LocalServiceClient`` — in-process service (unit tests, single process);
- ``NetworkServiceClient`` — a real service plane over TCP/HTTP with
  token-provider auth (the AzureClient/TinyliciousClient deployment shape);
- either with ``virtualize=True`` — storage reads/writes go through
  odsp-style snapshot virtualization with a persistent cache (OdspClient).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from ..driver.local_driver import LocalDocumentServiceFactory
from ..driver.service_registry import local_service_class
from ..driver.virtual_storage import VirtualizedDocumentServiceFactory
from .fluid_static import ContainerSchema, FluidContainer

if TYPE_CHECKING:
    from ..server.local_service import LocalService


class Audience:
    """Joined write clients of a container (ref IServiceAudience)."""

    def __init__(self, container) -> None:
        self._container = container

    def members(self) -> dict[str, int]:
        """client id -> join-order short id."""
        return self._container.runtime.quorum_table

    @property
    def my_id(self) -> str | None:
        return self._container.runtime.client_id


class _ServiceClientBase:
    """Shared create/get/version surface; subclasses supply the driver
    factory (the only thing that differs between deployments — the same
    swap the reference makes between Tinylicious/Azure/Odsp clients)."""

    def __init__(self, factory, virtualize: bool = False, cache_dir: str | None = None) -> None:
        self._factory = (
            VirtualizedDocumentServiceFactory(factory, cache_dir=cache_dir)
            if virtualize
            else factory
        )
        self._counter = 0

    # ------------------------------------------------------------- lifecycle
    def create_container(
        self, schema: ContainerSchema, doc_id: str, client_id: str = "creator"
    ) -> tuple[FluidContainer, dict[str, Any]]:
        fc = FluidContainer.create_detached(schema, client_id=client_id)
        fc.attach(doc_id, self._factory, client_id)
        return fc, self._services(fc)

    def get_container(
        self, doc_id: str, schema: ContainerSchema, client_id: str | None = None
    ) -> tuple[FluidContainer, dict[str, Any]]:
        if client_id is None:
            self._counter += 1
            client_id = f"client-{self._counter}"
        fc = FluidContainer.load(doc_id, self._factory, schema, client_id)
        return fc, self._services(fc)

    # -------------------------------------------------------------- versions
    def _storage(self, doc_id: str):
        return self._factory.create_document_service(doc_id).connect_to_storage()

    def get_container_versions(self, doc_id: str, max_count: int = 5) -> list[dict]:
        """Newest-first stored snapshot versions (ref getContainerVersions)."""
        return self._storage(doc_id).get_versions(max_count)

    def view_container_version(
        self, doc_id: str, schema: ContainerSchema, version_id: str
    ) -> FluidContainer:
        """Read-only container at a specific stored version, never
        connected (ref viewContainerVersion/loadContainerPaused)."""
        snap = self._storage(doc_id).get_snapshot_version(version_id)
        if snap is None:
            raise KeyError(f"no snapshot version {version_id!r} for {doc_id!r}")
        _seq, summary = snap
        return FluidContainer.view_version(schema, summary)

    def _services(self, fc: FluidContainer) -> dict[str, Any]:
        return {"audience": Audience(fc.container)}


class LocalServiceClient(_ServiceClientBase):
    """Client for the in-process service (ref TinyliciousClient shape; a
    networked deployment swaps the DocumentServiceFactory, nothing else)."""

    def __init__(
        self,
        service: LocalService | None = None,
        virtualize: bool = False,
        cache_dir: str | None = None,
    ) -> None:
        # Default service resolves through the provider seam
        # (driver.service_registry), not a direct server-tier import.
        self.service = service or local_service_class()()
        super().__init__(
            LocalDocumentServiceFactory(self.service),
            virtualize=virtualize,
            cache_dir=cache_dir,
        )


class NetworkServiceClient(_ServiceClientBase):
    """Client bound to a network service plane (ref AzureClient: endpoint +
    token provider; here host + nexus/alfred ports). ``sync()`` pumps the
    underlying connections to quiescence — the deterministic stand-in for
    background dispatch."""

    def __init__(
        self,
        host: str,
        port: int,
        http_port: int,
        token_provider=None,
        virtualize: bool = False,
        cache_dir: str | None = None,
    ) -> None:
        from ..driver.network_driver import NetworkDocumentServiceFactory

        self.network_factory = NetworkDocumentServiceFactory(
            host, port, http_port, token_provider=token_provider
        )
        super().__init__(
            self.network_factory, virtualize=virtualize, cache_dir=cache_dir
        )

    def sync(self) -> int:
        return self.network_factory.sync_all()
