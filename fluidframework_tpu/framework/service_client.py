"""Service-client façade: create/get containers against a service.

Reference parity: packages/service-clients — ``TinyliciousClient`` /
``AzureClient`` (AzureClient.ts createContainer/getContainer): the
three-line app entry that hides loader/driver wiring behind a schema, and
exposes container "services" (audience).
"""

from __future__ import annotations

from typing import Any

from ..driver.local_driver import LocalDocumentServiceFactory
from ..server.local_service import LocalService
from .fluid_static import ContainerSchema, FluidContainer


class Audience:
    """Joined write clients of a container (ref IServiceAudience)."""

    def __init__(self, container) -> None:
        self._container = container

    def members(self) -> dict[str, int]:
        """client id -> join-order short id."""
        return self._container.runtime.quorum_table

    @property
    def my_id(self) -> str | None:
        return self._container.runtime.client_id


class LocalServiceClient:
    """Client for the in-process service (ref TinyliciousClient shape; a
    networked deployment swaps the DocumentServiceFactory, nothing else)."""

    def __init__(self, service: LocalService | None = None) -> None:
        self.service = service or LocalService()
        self._factory = LocalDocumentServiceFactory(self.service)
        self._counter = 0

    def create_container(
        self, schema: ContainerSchema, doc_id: str, client_id: str = "creator"
    ) -> tuple[FluidContainer, dict[str, Any]]:
        fc = FluidContainer.create_detached(schema, client_id=client_id)
        fc.attach(doc_id, self._factory, client_id)
        return fc, self._services(fc)

    def get_container(
        self, doc_id: str, schema: ContainerSchema, client_id: str | None = None
    ) -> tuple[FluidContainer, dict[str, Any]]:
        if client_id is None:
            self._counter += 1
            client_id = f"client-{self._counter}"
        fc = FluidContainer.load(doc_id, self._factory, schema, client_id)
        return fc, self._services(fc)

    def _services(self, fc: FluidContainer) -> dict[str, Any]:
        return {"audience": Audience(fc.container)}
