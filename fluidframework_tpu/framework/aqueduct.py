"""aqueduct: the DataObject high-level authoring model.

Reference parity: packages/framework/aqueduct — ``DataObject`` (a datastore
with a root SharedMap under which apps organize state and handles to other
channels) and ``DataObjectFactory`` (type name + channel registry +
first-time initialization hook), the authoring pattern nearly every Fluid
example app uses.
"""

from __future__ import annotations

from typing import Any, Callable

from ..runtime.container_runtime import ContainerRuntime
from ..runtime.datastore import DataStoreRuntime

ROOT_MAP_ID = "root"


class DataObject:
    """A datastore wrapped in the aqueduct conventions: a ``root`` SharedMap
    plus named helper channels (ref PureDataObject/DataObject)."""

    def __init__(self, datastore: DataStoreRuntime) -> None:
        self._ds = datastore

    @property
    def id(self) -> str:
        return self._ds.id

    @property
    def root(self):
        """The root SharedMap (ref DataObject.root)."""
        return self._ds.get_channel(ROOT_MAP_ID)

    def channel(self, name: str):
        return self._ds.get_channel(name)

    def create_channel(self, channel_type: str, name: str):
        return self._ds.create_channel(channel_type, name)


class DataObjectFactory:
    """Creates/loads DataObjects of one named type (ref DataObjectFactory).

    ``initial_channels``: name -> DDS type string, created (with the root
    map) on first-time initialization. ``initializing_first_time`` runs once
    on the creating client, before attach (ref initializingFirstTime).
    """

    def __init__(
        self,
        object_type: str,
        initial_channels: dict[str, str] | None = None,
        initializing_first_time: Callable[[DataObject], None] | None = None,
    ) -> None:
        self.object_type = object_type
        self.initial_channels = dict(initial_channels or {})
        self._init_hook = initializing_first_time

    def create(self, runtime: ContainerRuntime, ds_id: str) -> DataObject:
        ds = runtime.create_datastore(ds_id)
        ds.create_channel("sharedMap", ROOT_MAP_ID)
        for name, channel_type in self.initial_channels.items():
            ds.create_channel(channel_type, name)
        obj = DataObject(ds)
        # Sequence the new datastore's layout BEFORE any content op (the
        # init hook's edits included) so remote replicas instantiate it
        # first (ref attach ops).
        runtime.submit_datastore_attach(ds_id)
        if self._init_hook is not None:
            self._init_hook(obj)
        return obj

    def get(self, runtime: ContainerRuntime, ds_id: str) -> DataObject:
        """Bind to an existing datastore created by this factory elsewhere."""
        ds = runtime.datastore(ds_id)
        for name in (ROOT_MAP_ID, *self.initial_channels):
            ds.get_channel(name)  # raises if the layout doesn't match
        return DataObject(ds)
