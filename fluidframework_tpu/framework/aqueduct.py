"""aqueduct: the DataObject high-level authoring model.

Reference parity: packages/framework/aqueduct — ``DataObject`` (a datastore
with a root SharedMap under which apps organize state and handles to other
channels) and ``DataObjectFactory`` (type name + channel registry + the
PureDataObject initialization lifecycle: ``initializingFirstTime`` on the
creating client only, ``initializingFromExisting`` on every later load,
``hasInitialized`` after either), the authoring pattern nearly every Fluid
example app uses. Handles (``DataObject.handle`` /
``resolve_handle``) are serializable references resolvable on any replica
— stored in maps like the reference stores IFluidHandles, resolved through
the request-routing layer.
"""

from __future__ import annotations

from typing import Any, Callable

from ..runtime.container_runtime import ContainerRuntime
from ..runtime.datastore import DataStoreRuntime

from ..runtime.handles import HANDLE_KEY, is_handle, make_handle_url

ROOT_MAP_ID = "root"


def make_handle(ds_id: str, channel_id: str | None = None) -> dict:
    """A serializable reference to a datastore (or one of its channels) —
    the IFluidHandle wire shape (runtime/handles.py; segments
    percent-encoded so ids containing '/' or '%' round-trip)."""
    return {HANDLE_KEY: make_handle_url(ds_id, channel_id)}


def resolve_handle(runtime: ContainerRuntime, handle: dict):
    """Resolve a stored handle on THIS replica (ref handle.get()): routes
    the handle's URL through the request layer."""
    from .request_handler import RuntimeRequestHandlerBuilder, datastore_request_handler

    if not is_handle(handle):
        # is_handle also requires a STRING url: a malformed
        # {"__fluid_handle__": None} raises here, not deep in the parser.
        raise TypeError(f"not a handle: {handle!r}")
    route = RuntimeRequestHandlerBuilder().push(datastore_request_handler).build()
    response = route(handle[HANDLE_KEY], runtime)
    if response["status"] != 200:
        raise KeyError(f"handle target not found: {handle[HANDLE_KEY]!r}")
    value = response["value"]
    return DataObject(value) if isinstance(value, DataStoreRuntime) else value


class DataObject:
    """A datastore wrapped in the aqueduct conventions: a ``root`` SharedMap
    plus named helper channels (ref PureDataObject/DataObject)."""

    def __init__(self, datastore: DataStoreRuntime) -> None:
        self._ds = datastore

    @property
    def id(self) -> str:
        return self._ds.id

    @property
    def root(self):
        """The root SharedMap (ref DataObject.root)."""
        return self._ds.get_channel(ROOT_MAP_ID)

    @property
    def handle(self) -> dict:
        """Serializable reference to this object (ref this.handle) —
        storable in any map/cell and resolvable on every replica."""
        return make_handle(self._ds.id)

    def channel_handle(self, name: str) -> dict:
        return make_handle(self._ds.id, name)

    def channel(self, name: str):
        return self._ds.get_channel(name)

    def create_channel(self, channel_type: str, name: str):
        return self._ds.create_channel(channel_type, name)


class DataObjectFactory:
    """Creates/loads DataObjects of one named type (ref DataObjectFactory).

    ``initial_channels``: name -> DDS type string, created (with the root
    map) on first-time initialization. ``initializing_first_time`` runs
    once on the creating client, AFTER the datastore attach is staged (its
    edits ride as ops following the layout — remote replicas instantiate
    the datastore first); ``initializing_from_existing`` runs on every
    later load; ``has_initialized`` after either.
    """

    def __init__(
        self,
        object_type: str,
        initial_channels: dict[str, str] | None = None,
        initializing_first_time: Callable[[DataObject], None] | None = None,
        initializing_from_existing: Callable[[DataObject], None] | None = None,
        has_initialized: Callable[[DataObject], None] | None = None,
    ) -> None:
        self.object_type = object_type
        self.initial_channels = dict(initial_channels or {})
        self._first_time = initializing_first_time
        self._from_existing = initializing_from_existing
        self._has_initialized = has_initialized

    def create(self, runtime: ContainerRuntime, ds_id: str) -> DataObject:
        ds = runtime.create_datastore(ds_id)
        ds.create_channel("sharedMap", ROOT_MAP_ID)
        for name, channel_type in self.initial_channels.items():
            ds.create_channel(channel_type, name)
        obj = DataObject(ds)
        # Sequence the new datastore's layout BEFORE any content op (the
        # init hook's edits included) so remote replicas instantiate it
        # first (ref attach ops).
        runtime.submit_datastore_attach(ds_id)
        if self._first_time is not None:
            self._first_time(obj)
        if self._has_initialized is not None:
            self._has_initialized(obj)
        return obj

    def get(self, runtime: ContainerRuntime, ds_id: str) -> DataObject:
        """Bind to an existing datastore created by this factory elsewhere
        (ref initializingFromExisting -> hasInitialized lifecycle)."""
        ds = runtime.datastore(ds_id)
        for name in (ROOT_MAP_ID, *self.initial_channels):
            ds.get_channel(name)  # raises if the layout doesn't match
        obj = DataObject(ds)
        if self._from_existing is not None:
            self._from_existing(obj)
        if self._has_initialized is not None:
            self._has_initialized(obj)
        return obj
