"""View bindings: reactive reads over channels (the react-hooks analog).

Reference parity: packages/framework/react (+ quill-react) binds UI
components to DDSes — a hook subscribes to a channel, exposes a snapshot,
and re-renders the component when relevant ops land.  A Python host plane
has no React, but the contract is the same three pieces, idiomatically:

- ``use_channel(runtime, ds, channel, selector)`` returns a ``Binding``
  whose ``value`` is the selector's latest result and which invokes
  registered callbacks ONLY when a processed batch touched that channel
  AND the selected value actually changed (the hooks' shallow-compare
  rerender gate);
- ``Binding.map`` derives further bindings;
- dispose() unhooks (the unmount path — repeated mount/unmount must not
  accumulate listeners, mirroring useEffect cleanup).

Local (optimistic) edits invalidate through the same feed once their ops
sequence; for immediate local echo, read ``value`` — selectors always
compute against the live channel.
"""

from __future__ import annotations

from typing import Any, Callable


class Binding:
    """One subscribed view over a channel (a mounted hook instance)."""

    def __init__(
        self,
        runtime,
        datastore_id: str,
        channel_id: str,
        selector: Callable[[Any], Any],
    ) -> None:
        self._runtime = runtime
        self._key = (datastore_id, channel_id)
        self._channel = runtime.datastore(datastore_id).get_channel(channel_id)
        self._selector = selector
        self._listeners: list[Callable[[Any], None]] = []
        self._last = self._compute()
        self._disposed = False
        runtime.op_processed_listeners.append(self._on_batch)

    def _compute(self) -> Any:
        return self._selector(self._channel)

    # ----------------------------------------------------------------- reads
    @property
    def value(self) -> Any:
        """The selector over the LIVE channel (includes local optimistic
        state, like a hook reading during render)."""
        return self._compute()

    # ---------------------------------------------------------------- events
    def on_change(self, fn: Callable[[Any], None]) -> Callable[[], None]:
        """fn(new_value) when a sequenced batch changed the selected value;
        returns the unsubscribe handle."""
        self._listeners.append(fn)

        def off() -> None:
            if fn in self._listeners:
                self._listeners.remove(fn)

        return off

    def _on_batch(self, touched: set) -> None:
        if self._key not in touched:
            return
        new = self._compute()
        if new == self._last:
            return  # the rerender gate: irrelevant ops don't notify
        self._last = new
        for fn in list(self._listeners):
            fn(new)

    # ------------------------------------------------------------ derivation
    def map(self, fn: Callable[[Any], Any]) -> "Binding":
        """A derived binding selecting ``fn(selector(channel))``."""
        return Binding(
            self._runtime, self._key[0], self._key[1],
            lambda ch, s=self._selector: fn(s(ch)),
        )

    # ------------------------------------------------------------- lifecycle
    def dispose(self) -> None:
        if not self._disposed:
            self._disposed = True
            if self._on_batch in self._runtime.op_processed_listeners:
                self._runtime.op_processed_listeners.remove(self._on_batch)
            self._listeners.clear()


def use_channel(runtime, datastore_id: str, channel_id: str,
                selector: Callable[[Any], Any]) -> Binding:
    """The generic hook (ref react useSharedObject)."""
    return Binding(runtime, datastore_id, channel_id, selector)


def use_shared_map(runtime, datastore_id: str, channel_id: str) -> Binding:
    """Snapshot of a SharedMap as a plain dict (ref useSharedMap)."""
    return use_channel(
        runtime, datastore_id, channel_id,
        lambda ch: {k: ch.get(k) for k in sorted(ch.keys())},
    )


def use_shared_string(runtime, datastore_id: str, channel_id: str) -> Binding:
    """The live text (ref quill-react's text binding)."""
    return use_channel(runtime, datastore_id, channel_id, lambda ch: ch.text)


def use_tree(runtime, datastore_id: str, channel_id: str,
             selector: Callable[[Any], Any] | None = None) -> Binding:
    """SharedTree binding: selector over the channel (e.g. a typed-view
    read); defaults to the root-field JSON (ref useTree)."""
    return use_channel(
        runtime, datastore_id, channel_id,
        selector or (lambda ch: [n.to_json() for n in ch.forest.root_field]),
    )
