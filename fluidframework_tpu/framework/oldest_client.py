"""Oldest-client observer: deterministic leader hint from the quorum.

Reference parity: packages/framework/oldest-client-observer — every client
computes "am I the oldest (earliest-joined) write client?" from the quorum;
used to elect one client for singleton duties without extra coordination
(the SummaryManager uses the same rule internally)."""

from __future__ import annotations


class OldestClientObserver:
    def __init__(self, runtime) -> None:
        self._runtime = runtime

    @property
    def oldest_client_id(self) -> str | None:
        q = self._runtime.quorum_table
        return min(q, key=lambda cid: q[cid]) if q else None

    def is_oldest(self) -> bool:
        return (
            self._runtime.joined
            and self.oldest_client_id == self._runtime.client_id
        )
