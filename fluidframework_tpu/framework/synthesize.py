"""Dependency synthesizer: typed provider registry with scope chaining.

Reference parity: packages/framework/synthesize — ``DependencyContainer``
(dependencyContainer.ts): register providers under keys, synthesize an
object exposing OPTIONAL dependencies (None when absent) and REQUIRED ones
(resolution fails when absent), with parent-container fallback. Providers
may be plain values, factories (called once, memoized — the reference's
async provider resolution collapsed to lazy call), or instances.
"""

from __future__ import annotations

from typing import Any, Callable


class DependencyContainer:
    def __init__(self, parent: "DependencyContainer | None" = None) -> None:
        self._providers: dict[str, Any] = {}
        self._resolved: dict[str, Any] = {}
        self.parent = parent

    # ------------------------------------------------------------- registry
    def register(self, key: str, provider: Any) -> None:
        if key in self._providers:
            raise ValueError(f"provider already registered for {key!r}")
        self._providers[key] = provider

    def unregister(self, key: str) -> None:
        self._providers.pop(key, None)
        self._resolved.pop(key, None)

    def has(self, key: str, exclude_parents: bool = False) -> bool:
        if key in self._providers:
            return True
        if exclude_parents or self.parent is None:
            return False
        return self.parent.has(key)

    @property
    def registered_types(self) -> list[str]:
        return sorted(self._providers)

    # ----------------------------------------------------------- resolution
    def resolve(self, key: str) -> Any:
        if key in self._resolved:
            return self._resolved[key]
        if key in self._providers:
            provider = self._providers[key]
            value = provider() if callable(provider) else provider
            self._resolved[key] = value
            return value
        if self.parent is not None:
            return self.parent.resolve(key)
        raise KeyError(f"no provider for {key!r}")

    def synthesize(
        self,
        optional: list[str] | None = None,
        required: list[str] | None = None,
    ) -> "SynthesizedObject":
        """An object with one attribute per requested key: required keys
        must resolve (raise otherwise), optional keys default to None."""
        values: dict[str, Any] = {}
        for key in required or []:
            values[key] = self.resolve(key)  # raises when absent
        for key in optional or []:
            try:
                values[key] = self.resolve(key)
            except KeyError:
                values[key] = None
        return SynthesizedObject(values)


class SynthesizedObject:
    def __init__(self, values: dict[str, Any]) -> None:
        self._values = dict(values)

    def __getattr__(self, key: str) -> Any:
        try:
            return self._values[key]
        except KeyError as e:
            raise AttributeError(key) from e

    def __getitem__(self, key: str) -> Any:
        return self._values[key]

    def keys(self) -> list[str]:
        return sorted(self._values)
