"""undo-redo: revertible stacks over DDS local edits.

Reference parity: packages/framework/undo-redo — ``UndoRedoStackManager``
with revertibles capturing enough to build an INVERSE op against the
*current* state (not a state rollback): map sets capture the previous value;
string inserts track their range (sliding under later edits, via the
string's interval machinery); string removes capture the removed text and
re-insert at the slid position; tree edits invert the enriched changeset and
rebase the inverse over everything applied since.

Close/open semantics: edits captured between ``close_current_operation``
calls revert as one unit (ref UndoRedoStackManager operation stacks).
"""

from __future__ import annotations

from typing import Any

from ..dds.channels import SharedMapChannel, SharedStringChannel
from ..dds.markers import strip_markers
from ..dds.sequence_intervals import transform_position
from ..dds.tree.changeset import invert_node_change, rebase_node_change
from ..dds.tree.shared_tree import SharedTreeChannel


class _MapRevertible:
    def __init__(self, channel: SharedMapChannel, op: dict, prev: Any, had: bool) -> None:
        self._ch = channel
        self._op = op
        self._prev = prev
        self._had = had

    def revert(self) -> "_MapRevertible":
        key = self._op["key"]
        now_had = key in self._ch.keys()
        now_val = self._ch.get(key)
        if self._had:
            self._ch.set(key, self._prev)
        else:
            self._ch.delete(key)
        return _MapRevertible(self._ch, {"type": "set", "key": key}, now_val, now_had)


class _StringRangeTracker:
    """Tracks a range created by one local string op through converged
    events: the op's OWN event (matched by localSeq) establishes the range
    in converged coordinates (the reference's local-reference anchors);
    every other event slides it. Positions read back in the local view are
    exact once the channel has no unacked local edits of its own."""

    def __init__(self, channel: SharedStringChannel, local_seq: int, pos: int, length: int) -> None:
        self._ch = channel
        self._ls = local_seq
        # Sub-ranges [start, end): provisional local coords until our own
        # op's converged events land; a pending insert split before ack
        # yields several fragments, each tracked separately.
        self.ranges: list[list[int]] = [[pos, pos + length]]
        self._synced = False
        channel._converged_listeners.append(self._on_event)

    def _on_event(self, kind: str, pos: int, length: int, ls) -> None:
        if ls == self._ls:
            if not self._synced:
                self.ranges = []
                self._synced = True
            if kind == "insert":
                self.ranges.append([pos, pos + length])
            else:  # our own remove sequenced: track its collapse point
                self.ranges.append([pos, pos])
            return
        new_ranges: list[list[int]] = []
        for s0, e0 in self.ranges:
            if kind == "insert" and s0 < pos < e0:
                # Foreign content landed INSIDE the tracked range: the range
                # splits around it (the reference's tracking group follows
                # the split segments, never the foreign middle).
                new_ranges.append([s0, pos])
                new_ranges.append([pos + length, e0 + length])
                continue
            # Start shifts past an insert landing exactly on it (foreign
            # content stays outside); end keeps the stay-bias.
            s1 = transform_position(s0, kind, pos, length, after=True)
            e1 = max(s1, transform_position(e0, kind, pos, length))
            new_ranges.append([s1, e1])
        self.ranges = new_ranges

    @property
    def start(self) -> int:
        return self.ranges[0][0] if self.ranges else 0

    @property
    def end(self) -> int:
        return self.ranges[0][1] if self.ranges else 0

    def release(self) -> None:
        try:
            self._ch._converged_listeners.remove(self._on_event)
        except ValueError:
            pass


class _StringInsertRevertible:
    """Undo an insert = remove the inserted range at its slid position."""

    def __init__(self, channel: SharedStringChannel, local_seq: int, pos: int, length: int) -> None:
        self._ch = channel
        self._range = _StringRangeTracker(channel, local_seq, pos, length)

    def revert(self):
        self._range.release()
        # Tracked ranges are converged-coordinate once synced; translate them
        # into the local view (which may differ while unacked local edits are
        # in flight) before touching the string. Pending local inserts inside
        # a tracked range survive as holes in the mapped spans.
        local_spans: list[tuple[int, int]] = []
        for start, end in self._range.ranges:
            if end <= start:
                continue
            if self._range._synced:
                local_spans.extend(self._ch.backend.converged_spans_to_local(start, end))
            else:
                local_spans.append((start, end))
        # Remove every surviving fragment back-to-front; each removal hands
        # back its own re-insert revertible.
        inverses = []
        for start, end in sorted(local_spans, reverse=True):
            # Position-space slice (markers kept so indices are exact);
            # markers inside the range are not re-created by a later undo
            # (only their text survives capture).
            removed = strip_markers(self._ch.position_text()[start:end])
            ls = self._ch.remove_range(start, end)
            inverses.append(_StringRemoveRevertible(self._ch, ls, start, removed))
        return inverses or None

    def release(self) -> None:
        self._range.release()


class _StringRemoveRevertible:
    """Undo a remove = re-insert the captured text at the slid position."""

    def __init__(self, channel: SharedStringChannel, local_seq: int, pos: int, text: str) -> None:
        self._ch = channel
        self._text = text
        self._range = _StringRangeTracker(channel, local_seq, pos, 0)

    def revert(self) -> "_StringInsertRevertible":
        self._range.release()
        pos = self._range.start
        if self._range._synced:
            pos = self._ch.backend.converged_to_local(pos)
        ls = self._ch.insert_text(pos, self._text)
        return _StringInsertRevertible(self._ch, ls, pos, len(self._text))

    def release(self) -> None:
        self._range.release()


class _TreeRevertible:
    """Undo a tree edit = submit its inverse, rebased over every change the
    forest has applied since capture (the channel's applied_log carries
    local edits and bridged remote commits in exact application order, so
    the inverse lands in current coordinates)."""

    def __init__(self, channel: SharedTreeChannel, change) -> None:
        self._ch = channel
        self._inverse = invert_node_change(change)
        self._log_mark = len(channel.applied_log)

    def revert(self) -> "_TreeRevertible":
        inv = self._inverse
        for applied in self._ch.applied_log[self._log_mark :]:
            inv = rebase_node_change(inv, applied, a_after=True)
        self._ch.submit_change(inv)
        return _TreeRevertible(self._ch, inv)


class UndoRedoStackManager:
    """Groups revertibles into operations and drives undo/redo stacks."""

    def __init__(self) -> None:
        self._undo: list[list] = []
        self._redo: list[list] = []
        self._current: list = []

    # ------------------------------------------------------------ subscribe
    def capture_map_set(self, channel: SharedMapChannel, key: str, value: Any) -> None:
        had = key in channel.keys()
        prev = channel.get(key)
        channel.set(key, value)
        self._push(_MapRevertible(channel, {"type": "set", "key": key}, prev, had))

    def capture_map_delete(self, channel: SharedMapChannel, key: str) -> None:
        had = key in channel.keys()
        prev = channel.get(key)
        channel.delete(key)
        self._push(_MapRevertible(channel, {"type": "delete", "key": key}, prev, had))

    def capture_string_insert(self, channel: SharedStringChannel, pos: int, text: str) -> None:
        ls = channel.insert_text(pos, text)
        self._push(_StringInsertRevertible(channel, ls, pos, len(text)))

    def capture_string_remove(self, channel: SharedStringChannel, pos1: int, pos2: int) -> None:
        # pos1/pos2 are positions; slice the position-indexed view (markers
        # in range are removed but not re-created by undo).
        removed = strip_markers(channel.position_text()[pos1:pos2])
        ls = channel.remove_range(pos1, pos2)
        self._push(_StringRemoveRevertible(channel, ls, pos1, removed))

    def capture_tree_change(self, channel: SharedTreeChannel, change) -> None:
        channel.submit_change(change)
        # submit_change enriched the change in place: invertible now.
        self._push(_TreeRevertible(channel, change))

    # ----------------------------------------------------------- operations
    @staticmethod
    def _release_group(group: list) -> None:
        for r in group:
            release = getattr(r, "release", None)
            if release is not None:
                release()

    def _push(self, revertible) -> None:
        self._current.append(revertible)
        for group in self._redo:
            self._release_group(group)
        self._redo.clear()

    def close_current_operation(self) -> None:
        if self._current:
            self._undo.append(self._current)
            self._current = []

    @property
    def undoable(self) -> int:
        return len(self._undo) + (1 if self._current else 0)

    @property
    def redoable(self) -> int:
        return len(self._redo)

    @staticmethod
    def _revert_group(op: list) -> list:
        inverses: list = []
        for r in reversed(op):
            inv = r.revert()
            if inv is None:
                continue
            inverses.extend(inv if isinstance(inv, list) else [inv])
        return inverses

    def undo(self) -> bool:
        """Revert the newest operation; each revert hands back its own
        inverse revertible(s), which become the redo entry (symmetric
        stacks)."""
        self.close_current_operation()
        if not self._undo:
            return False
        self._redo.append(self._revert_group(self._undo.pop()))
        return True

    def redo(self) -> bool:
        if not self._redo:
            return False
        self._undo.append(self._revert_group(self._redo.pop()))
        return True

    def dispose(self) -> None:
        """Release every tracked revertible (stale listeners unhook)."""
        for stack in (self._undo, self._redo, [self._current]):
            for group in stack:
                self._release_group(group)
        self._undo.clear()
        self._redo.clear()
        self._current.clear()
