"""Presence: ephemeral per-session state over signals (never sequenced).

Reference parity: packages/framework/presence* —
``PresenceDatastoreManagerImpl`` (presence-runtime/src/runtime/
presenceDatastoreManager.ts:195): per-client latest-value workspaces
broadcast via ``runtime.submitSignal`` (:343) with a batched outbound queue
(:473), and a join handshake: a newcomer broadcasts "join" and current
members respond with their state so the newcomer catches up (protocol.ts).
Presence data rides signals only — no ops, no sequence numbers, no summary
footprint.
"""

from __future__ import annotations

from typing import Any, Callable


class Presence:
    """One client's view of a presence workspace on a container."""

    def __init__(self, container) -> None:
        self._container = container
        self._client_id = container.runtime.client_id
        # state key -> client id -> value (latest received wins)
        self._remote: dict[str, dict[str, Any]] = {}
        self._local: dict[str, Any] = {}
        self._queue: dict[str, Any] = {}  # batched unflushed local sets
        self._listeners: list[Callable[[str, str, Any], None]] = []
        container.on_signal(self._on_signal)
        # Join handshake: ask current members for their state.
        container.submit_signal({"presence": "join"})

    # ------------------------------------------------------------------ write
    def set(self, key: str, value: Any) -> None:
        """Queue a local state update (batched; ref queued signal sends)."""
        self._local[key] = value
        self._queue[key] = value

    def flush(self) -> None:
        """Broadcast queued updates as ONE signal (ref batch queue :473)."""
        if not self._queue:
            return
        updates, self._queue = self._queue, {}
        self._container.submit_signal({"presence": "update", "states": updates})

    def set_now(self, key: str, value: Any) -> None:
        self.set(key, value)
        self.flush()

    # ------------------------------------------------------------------- read
    def local(self, key: str) -> Any:
        return self._local.get(key)

    def states(self, key: str) -> dict[str, Any]:
        """client id -> latest value, including our own."""
        out = dict(self._remote.get(key, {}))
        if key in self._local:
            out[self._my_id()] = self._local[key]
        return out

    def remote_states(self, key: str) -> dict[str, Any]:
        return dict(self._remote.get(key, {}))

    def on_update(self, listener: Callable[[str, str, Any], None]) -> None:
        """listener(client_id, key, value) per received remote update."""
        self._listeners.append(listener)

    def _my_id(self) -> str:
        return self._container.runtime.client_id or self._client_id or ""

    # ---------------------------------------------------------------- inbound
    def _on_signal(self, sig) -> None:
        content = sig.contents
        if not isinstance(content, dict) or "presence" not in content:
            return
        if sig.client_id == self._my_id():
            return
        kind = content["presence"]
        if kind == "join":
            # A newcomer asked for state: respond with ours (ref join
            # response broadcast). Flush queued values first so the response
            # is complete.
            self.flush()
            if self._local:
                self._container.submit_signal(
                    {"presence": "update", "states": dict(self._local)}
                )
        elif kind == "update":
            for key, value in content["states"].items():
                self._remote.setdefault(key, {})[sig.client_id] = value
                for listener in self._listeners:
                    listener(sig.client_id, key, value)
        elif kind == "leave":
            self._drop_client(sig.client_id)

    def _drop_client(self, client_id: str) -> None:
        for per_key in self._remote.values():
            per_key.pop(client_id, None)

    def leave(self) -> None:
        """Announce departure (ref disconnect cleanup): peers drop our state."""
        self._container.submit_signal({"presence": "leave"})
        self._queue.clear()
