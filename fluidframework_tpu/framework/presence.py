"""Presence: ephemeral per-session state over signals (never sequenced).

Reference parity: packages/framework/presence* —
``PresenceDatastoreManagerImpl`` (presence-runtime/src/runtime/
presenceDatastoreManager.ts:195): per-client latest-value workspaces
broadcast via ``runtime.submitSignal`` (:343) with a batched outbound queue
(:473), and a join handshake: a newcomer broadcasts "join" and current
members respond with their state so the newcomer catches up (protocol.ts).
Presence data rides signals only — no ops, no sequence numbers, no summary
footprint.

The typed surface mirrors presence-definitions:
- ``states_workspace(id)`` -> workspace of value managers: ``latest``
  (one value per attendee, latestTypes.ts) and ``latest_map``
  (per-attendee keyed items, latestMapTypes.ts);
- ``notifications_workspace(id)`` -> named fire-and-forget notification
  emitters (notificationsTypes.ts — broadcast, never retained);
- attendee events (``on_attendee_joined``/``on_attendee_left``,
  presenceTypes.ts Attendee) derived from the same signal fabric.
"""

from __future__ import annotations

from typing import Any, Callable


def _subscribe(listeners: list, fn) -> Callable[[], None]:
    """Append + return an idempotent unsubscribe handle."""
    listeners.append(fn)

    def unsubscribe() -> None:
        if fn in listeners:
            listeners.remove(fn)

    return unsubscribe


class Presence:
    """One client's view of a presence workspace on a container."""

    def __init__(self, container, clock=None) -> None:
        import time

        self._container = container
        # One clock domain per instance (tests inject a simulated clock).
        self._clock = clock if clock is not None else time.monotonic
        self._client_id = container.runtime.client_id
        # state key -> client id -> value (latest received wins)
        self._remote: dict[str, dict[str, Any]] = {}
        self._local: dict[str, Any] = {}
        self._queue: dict[str, Any] = {}  # batched unflushed local sets
        # Tightest queued update's flush-by time (allowableUpdateLatency).
        self._flush_deadline: float | None = None
        self._listeners: list[Callable[[str, str, Any], None]] = []
        # Attendees: client ids seen on the presence fabric.
        self._attendees: set[str] = set()
        self._joined_listeners: list[Callable[[str], None]] = []
        self._left_listeners: list[Callable[[str], None]] = []
        self._notification_listeners: dict[str, list] = {}
        container.on_signal(self._on_signal)
        # Sequenced LEAVE (crash/disconnect without a voluntary leave()
        # signal) also departs the fabric — the reference derives attendee
        # disconnect from the audience, not from a courtesy signal.
        self._unsub_member_left = _subscribe(
            container.runtime.member_left_listeners, self._drop_client
        )
        # Loader containers expose the full Audience (read members
        # included): attendee lifecycle keys off its membership events, so
        # read-only clients that never op still join/leave the fabric
        # (ref presence attendee status from audience removeMember).
        audience = getattr(container, "audience", None)
        self._unsub_audience: list[Callable[[], None]] = []
        if audience is not None:
            self._unsub_audience = [
                audience.on_add_member(self._on_audience_add),
                audience.on_remove_member(
                    lambda cid, _d: self._drop_client(cid)
                ),
            ]
        # Join handshake: ask current members for their state.
        container.submit_signal({"presence": "join"})

    def _on_audience_add(self, client_id: str, _details: dict) -> None:
        if client_id != self._my_id():
            self._saw(client_id)

    # ------------------------------------------------------------------ write
    def set(self, key: str, value: Any,
            allowed_latency_s: float | None = None,
            now: float | None = None) -> None:
        """Queue a local state update (batched; ref queued signal sends).

        ``allowed_latency_s`` is the reference's allowableUpdateLatency
        (presenceDatastoreManager.ts:473): the update may coalesce with
        later ones, but must be on the wire within that window — ``tick``
        flushes once the TIGHTEST queued deadline passes.  None = wait for
        an explicit flush (or a tighter co-queued update's deadline).
        ``now`` defaults to the presence CLOCK (constructor-injectable) so
        simulated and wall clocks never mix within one instance."""
        self._local[key] = value
        self._queue[key] = value
        if allowed_latency_s is not None:
            now = self._clock() if now is None else now
            deadline = now + allowed_latency_s
            if self._flush_deadline is None or deadline < self._flush_deadline:
                self._flush_deadline = deadline

    def tick(self, now: float | None = None) -> bool:
        """Flush iff a queued update's latency window has lapsed; returns
        whether a signal went out (the host loop's timer hook)."""
        now = self._clock() if now is None else now
        if self._flush_deadline is not None and now >= self._flush_deadline:
            had_updates = bool(self._queue)
            self.flush()
            return had_updates
        return False

    def flush(self) -> None:
        """Broadcast queued updates as ONE signal (ref batch queue :473)."""
        self._flush_deadline = None
        if not self._queue:
            return
        updates, self._queue = self._queue, {}
        self._container.submit_signal({"presence": "update", "states": updates})

    def set_now(self, key: str, value: Any) -> None:
        self.set(key, value)
        self.flush()

    # ------------------------------------------------------------------- read
    def local(self, key: str) -> Any:
        return self._local.get(key)

    def states(self, key: str) -> dict[str, Any]:
        """client id -> latest value, including our own."""
        out = dict(self._remote.get(key, {}))
        if key in self._local:
            out[self._my_id()] = self._local[key]
        return out

    def remote_states(self, key: str) -> dict[str, Any]:
        return dict(self._remote.get(key, {}))

    def on_update(self, listener: Callable[[str, str, Any], None]) -> Callable[[], None]:
        """listener(client_id, key, value) per received remote update;
        returns an unsubscribe handle (repeated acquisition of value
        managers must not accumulate permanent listeners)."""
        return _subscribe(self._listeners, listener)

    def _my_id(self) -> str:
        return self._container.runtime.client_id or self._client_id or ""

    # -------------------------------------------------------------- attendees
    def attendees(self) -> set[str]:
        """Remote client ids currently on the presence fabric."""
        return set(self._attendees)

    def on_attendee_joined(self, fn: Callable[[str], None]) -> Callable[[], None]:
        return _subscribe(self._joined_listeners, fn)

    def on_attendee_left(self, fn: Callable[[str], None]) -> Callable[[], None]:
        return _subscribe(self._left_listeners, fn)

    def _saw(self, client_id: str) -> None:
        if client_id not in self._attendees:
            self._attendees.add(client_id)
            for fn in list(self._joined_listeners):
                fn(client_id)

    # ------------------------------------------------------------- workspaces
    def states_workspace(self, workspace_id: str) -> "StatesWorkspace":
        """Typed value-manager workspace (ref StatesWorkspace)."""
        return StatesWorkspace(self, workspace_id)

    def notifications_workspace(self, workspace_id: str) -> "NotificationsWorkspace":
        """Fire-and-forget notification emitters (ref NotificationsWorkspace:
        broadcast only, never retained, no late-joiner catch-up)."""
        return NotificationsWorkspace(self, workspace_id)

    def _emit_notification(self, channel: str, name: str, payload: Any) -> None:
        self._container.submit_signal(
            {"presence": "notify", "ch": channel, "name": name, "payload": payload}
        )

    # ---------------------------------------------------------------- inbound
    def _on_signal(self, sig) -> None:
        content = sig.contents
        if not isinstance(content, dict) or "presence" not in content:
            return
        if sig.client_id == self._my_id():
            return
        kind = content["presence"]
        if kind != "leave":
            self._saw(sig.client_id)
        if kind == "join":
            # A newcomer asked for state: respond with ours (ref join
            # response broadcast). Flush queued values first so the response
            # is complete. Respond EVEN when stateless — the response is
            # also how the newcomer learns we exist (attendees()).
            self.flush()
            self._container.submit_signal(
                {"presence": "update", "states": dict(self._local)}
            )
        elif kind == "update":
            for key, value in content["states"].items():
                self._remote.setdefault(key, {})[sig.client_id] = value
                for listener in self._listeners:
                    listener(sig.client_id, key, value)
        elif kind == "notify":
            for fn in list(self._notification_listeners.get(content["ch"], [])):
                fn(sig.client_id, content["name"], content["payload"])
        elif kind == "leave":
            self._drop_client(sig.client_id)

    def _drop_client(self, client_id: str) -> None:
        for per_key in self._remote.values():
            per_key.pop(client_id, None)
        if client_id in self._attendees:
            self._attendees.discard(client_id)
            for fn in list(self._left_listeners):
                fn(client_id)

    def leave(self) -> None:
        """Announce departure (ref disconnect cleanup): peers drop our state."""
        self._container.submit_signal({"presence": "leave"})
        self._queue.clear()
        self._flush_deadline = None  # nothing left to flush: no phantom tick

    def dispose(self) -> None:
        """Detach from the runtime (unregisters the LEAVE listener) and drop
        local listeners — constructing Presence repeatedly on one container
        must not accumulate permanent registrations."""
        self._unsub_member_left()
        for unsub in self._unsub_audience:
            unsub()
        self._unsub_audience = []
        self._listeners.clear()
        self._joined_listeners.clear()
        self._left_listeners.clear()
        self._notification_listeners.clear()


# ---------------------------------------------------------------------------
# Typed workspaces (ref presence-definitions value managers)
# ---------------------------------------------------------------------------

def _esc(part: str) -> str:
    """Escape the ':' namespace separator inside user-chosen ids, so a
    Latest key containing ':' can never collide with a LatestMap item path
    (the same user-key-collision class the snapshot format stamp avoids)."""
    return part.replace("%", "%25").replace(":", "%3A")


def _unesc(part: str) -> str:
    return part.replace("%3A", ":").replace("%25", "%")


class Latest:
    """One value per attendee (ref LatestRaw, latestTypes.ts): ``local``
    get/set, per-attendee remote reads, update events."""

    def __init__(self, ws: "StatesWorkspace", key: str, initial: Any = None,
                 allowed_latency_s: float | None = None) -> None:
        self._p = ws._presence
        self._key = f"{_esc(ws.workspace_id)}:{_esc(key)}"
        # Per-manager allowableUpdateLatency (ref latestTypes.ts settings).
        self.allowed_latency_s = allowed_latency_s
        if initial is not None:
            self._p.set(self._key, initial, allowed_latency_s)

    @property
    def local(self) -> Any:
        return self._p.local(self._key)

    @local.setter
    def local(self, value: Any) -> None:
        self._p.set(self._key, value, self.allowed_latency_s)

    def get_remote(self, client_id: str) -> Any:
        return self._p.remote_states(self._key).get(client_id)

    def get_remotes(self) -> dict[str, Any]:
        return self._p.remote_states(self._key)

    def on_updated(self, fn: Callable[[str, Any], None]) -> Callable[[], None]:
        key = self._key

        def listener(client_id: str, k: str, value: Any) -> None:
            if k == key:
                fn(client_id, value)

        return self._p.on_update(listener)


class LatestMap:
    """Per-attendee keyed items (ref LatestMapRaw, latestMapTypes.ts):
    each attendee holds a map; items update independently."""

    def __init__(self, ws: "StatesWorkspace", key: str) -> None:
        self._p = ws._presence
        self._prefix = f"{_esc(ws.workspace_id)}:{_esc(key)}:"

    def set_item(self, item: str, value: Any) -> None:
        self._p.set(self._prefix + _esc(item), value)

    def local_item(self, item: str) -> Any:
        return self._p.local(self._prefix + _esc(item))

    def get_remote(self, client_id: str) -> dict[str, Any]:
        out = {}
        for full_key, per_client in self._p._remote.items():
            if full_key.startswith(self._prefix) and client_id in per_client:
                out[_unesc(full_key[len(self._prefix):])] = per_client[client_id]
        return out

    def on_item_updated(self, fn: Callable[[str, str, Any], None]) -> Callable[[], None]:
        prefix = self._prefix

        def listener(client_id: str, k: str, value: Any) -> None:
            if k.startswith(prefix):
                fn(client_id, _unesc(k[len(prefix):]), value)

        return self._p.on_update(listener)


class StatesWorkspace:
    def __init__(self, presence: Presence, workspace_id: str) -> None:
        self._presence = presence
        self.workspace_id = workspace_id

    def latest(self, key: str, initial: Any = None,
               allowed_latency_s: float | None = None) -> Latest:
        return Latest(self, key, initial, allowed_latency_s)

    def latest_map(self, key: str) -> LatestMap:
        return LatestMap(self, key)

    def flush(self) -> None:
        self._presence.flush()


class NotificationsWorkspace:
    def __init__(self, presence: Presence, workspace_id: str) -> None:
        self._presence = presence
        self.workspace_id = workspace_id

    def emit(self, name: str, payload: Any = None) -> None:
        """Broadcast immediately; never queued, never retained."""
        self._presence._emit_notification(self.workspace_id, name, payload)

    def on_notification(self, fn: Callable[[str, str, Any], None]) -> Callable[[], None]:
        """fn(client_id, name, payload) per received notification;
        returns an unsubscribe handle."""
        return _subscribe(
            self._presence._notification_listeners.setdefault(self.workspace_id, []),
            fn,
        )
