"""Presence: ephemeral per-session state over signals (never sequenced).

Reference parity: packages/framework/presence* —
``PresenceDatastoreManagerImpl`` (presence-runtime/src/runtime/
presenceDatastoreManager.ts:195): per-client latest-value workspaces
broadcast via ``runtime.submitSignal`` (:343) with a batched outbound queue
(:473), and a join handshake: a newcomer broadcasts "join" and current
members respond with their state so the newcomer catches up (protocol.ts).
Presence data rides signals only — no ops, no sequence numbers, no summary
footprint.

The typed surface mirrors presence-definitions:
- ``states_workspace(id)`` -> workspace of value managers: ``latest``
  (one value per attendee, latestTypes.ts) and ``latest_map``
  (per-attendee keyed items, latestMapTypes.ts);
- ``notifications_workspace(id)`` -> named fire-and-forget notification
  emitters (notificationsTypes.ts — broadcast, never retained);
- attendee events (``on_attendee_joined``/``on_attendee_left``,
  presenceTypes.ts Attendee) derived from the same signal fabric.
"""

from __future__ import annotations

from typing import Any, Callable


def _subscribe(listeners: list, fn) -> Callable[[], None]:
    """Append + return an idempotent unsubscribe handle."""
    listeners.append(fn)

    def unsubscribe() -> None:
        if fn in listeners:
            listeners.remove(fn)

    return unsubscribe


class Presence:
    """One client's view of a presence workspace on a container."""

    def __init__(self, container, clock=None,
                 attendee_timeout_s: float = 30.0) -> None:
        import time

        self._container = container
        # One clock domain per instance (tests inject a simulated clock).
        self._clock = clock if clock is not None else time.monotonic
        self._client_id = container.runtime.client_id
        # state key -> client id -> (rev, value).  Revisions are per-key
        # per-writer monotonic stamps (ref datastore rev): a lost or
        # reordered signal can never let stale state clobber newer state —
        # receivers keep the highest rev (signal-loss recovery).
        self._remote: dict[str, dict[str, tuple[Any, Any]]] = {}
        self._local: dict[str, Any] = {}
        self._rev: dict[str, int] = {}  # our own per-key revision counters
        # Wire revisions are [epoch, n]: the epoch (instance birth stamp)
        # makes a RESTARTED client's fresh counters beat its own pre-crash
        # cached revs (a lost leave signal must not mute the comeback).
        self._epoch = time.time_ns()
        # Heartbeat cadence: refresh peers' last-seen view of us even when
        # idle, so expiry only ever fires on genuinely gone peers.
        self._last_heartbeat: float | None = None
        self._queue: dict[str, Any] = {}  # batched unflushed local sets
        # Tightest queued update's flush-by time (allowableUpdateLatency).
        self._flush_deadline: float | None = None
        self._listeners: list[Callable[[str, str, Any], None]] = []
        # Attendees: client ids seen on the presence fabric, with a
        # last-activity stamp; signal-silent attendees NOT covered by the
        # audience expire after ``attendee_timeout_s`` (ref attendee
        # disconnected-after-inactivity).
        self._attendees: set[str] = set()
        self._last_seen: dict[str, float] = {}
        self._attendee_timeout = attendee_timeout_s
        # Joiner catch-up responses pending our (ranked) jitter window
        # (ref presenceDatastoreManager.ts:195 joiningClients).
        self._pending_catchup: dict[str, float] = {}
        # joiner -> time we last saw a catch-up covering OUR state; a join
        # signal processed AFTER the primary's (synchronous fan-out
        # reentrancy) must not schedule a redundant backup.
        self._recent_catchup: dict[str, float] = {}
        self._joined_listeners: list[Callable[[str], None]] = []
        self._left_listeners: list[Callable[[str], None]] = []
        self._notification_listeners: dict[str, list] = {}
        container.on_signal(self._on_signal)
        # Sequenced LEAVE (crash/disconnect without a voluntary leave()
        # signal) also departs the fabric — the reference derives attendee
        # disconnect from the audience, not from a courtesy signal.
        self._unsub_member_left = _subscribe(
            container.runtime.member_left_listeners, self._drop_client
        )
        # Loader containers expose the full Audience (read members
        # included): attendee lifecycle keys off its membership events, so
        # read-only clients that never op still join/leave the fabric
        # (ref presence attendee status from audience removeMember).
        audience = getattr(container, "audience", None)
        self._unsub_audience: list[Callable[[], None]] = []
        if audience is not None:
            self._unsub_audience = [
                audience.on_add_member(self._on_audience_add),
                audience.on_remove_member(
                    lambda cid, _d: self._drop_client(cid)
                ),
            ]
        # Join handshake: ask current members for their state.
        container.submit_signal({"presence": "join"})

    def _on_audience_add(self, client_id: str, _details: dict) -> None:
        if client_id != self._my_id():
            self._saw(client_id)

    # ------------------------------------------------------------------ write
    def set(self, key: str, value: Any,
            allowed_latency_s: float | None = None,
            now: float | None = None) -> None:
        """Queue a local state update (batched; ref queued signal sends).

        ``allowed_latency_s`` is the reference's allowableUpdateLatency
        (presenceDatastoreManager.ts:473): the update may coalesce with
        later ones, but must be on the wire within that window — ``tick``
        flushes once the TIGHTEST queued deadline passes.  None = wait for
        an explicit flush (or a tighter co-queued update's deadline).
        ``now`` defaults to the presence CLOCK (constructor-injectable) so
        simulated and wall clocks never mix within one instance."""
        self._local[key] = value
        self._rev[key] = self._rev.get(key, 0) + 1
        self._queue[key] = value
        if allowed_latency_s is not None:
            now = self._clock() if now is None else now
            deadline = now + allowed_latency_s
            if self._flush_deadline is None or deadline < self._flush_deadline:
                self._flush_deadline = deadline

    def tick(self, now: float | None = None) -> bool:
        """Timer hook: flush lapsed latency windows, send due joiner
        catch-ups, emit idle heartbeats, expire signal-silent attendees;
        returns whether STATE went out (heartbeats are housekeeping and
        do not count)."""
        now = self._clock() if now is None else now
        sent = False
        if self._flush_deadline is not None and now >= self._flush_deadline:
            had_updates = bool(self._queue)
            self.flush()
            sent = sent or had_updates
        connected = getattr(self._container, "connected", True)
        for joiner, deadline in list(self._pending_catchup.items()):
            if now >= deadline and connected:
                del self._pending_catchup[joiner]
                self._send_catchup(joiner)
                sent = True
        # Idle keepalive: a silent-but-connected peer must keep refreshing
        # everyone's last-seen stamp or expiry would falsely fire on it.
        # Any outbound presence signal counts (flush/_send_catchup stamp
        # too), so actively-updating clients emit no redundant hb; a
        # DISCONNECTED client skips — submitting would raise, and peers
        # are supposed to see it go quiet.
        if self._attendee_timeout is not None and self._attendees and connected:
            interval = self._attendee_timeout / 3.0
            if (
                self._last_heartbeat is None
                or now - self._last_heartbeat >= interval
            ):
                self._last_heartbeat = now
                self._container.submit_signal({"presence": "hb"})
        self._expire_attendees(now)
        # Bounded bookkeeping: served-joiner stamps age out.
        for joiner, t in list(self._recent_catchup.items()):
            if now - t > 60.0:
                del self._recent_catchup[joiner]
        return sent

    def flush(self) -> None:
        """Broadcast queued updates as ONE signal (ref batch queue :473)."""
        self._flush_deadline = None
        if not self._queue:
            return
        updates, self._queue = self._queue, {}
        self._last_heartbeat = self._clock()  # state traffic IS a keepalive
        self._container.submit_signal({
            "presence": "update",
            "states": {k: [self._wire_rev(k), v] for k, v in updates.items()},
        })

    def _wire_rev(self, key: str) -> list:
        return [self._epoch, self._rev.get(key, 0)]

    @staticmethod
    def _rev_lt(a, b) -> bool:
        """rev a < rev b; wire revs are [epoch, n] lists."""
        return tuple(a) < tuple(b)

    def set_now(self, key: str, value: Any) -> None:
        self.set(key, value)
        self.flush()

    # ------------------------------------------------------------------- read
    def local(self, key: str) -> Any:
        return self._local.get(key)

    def states(self, key: str) -> dict[str, Any]:
        """client id -> latest value, including our own."""
        out = {c: v for c, (_r, v) in self._remote.get(key, {}).items()}
        if key in self._local:
            out[self._my_id()] = self._local[key]
        return out

    def remote_states(self, key: str) -> dict[str, Any]:
        return {c: v for c, (_r, v) in self._remote.get(key, {}).items()}

    def on_update(self, listener: Callable[[str, str, Any], None]) -> Callable[[], None]:
        """listener(client_id, key, value) per received remote update;
        returns an unsubscribe handle (repeated acquisition of value
        managers must not accumulate permanent listeners)."""
        return _subscribe(self._listeners, listener)

    def _my_id(self) -> str:
        return self._container.runtime.client_id or self._client_id or ""

    # -------------------------------------------------------------- attendees
    def attendees(self) -> set[str]:
        """Remote client ids currently on the presence fabric."""
        return set(self._attendees)

    def on_attendee_joined(self, fn: Callable[[str], None]) -> Callable[[], None]:
        return _subscribe(self._joined_listeners, fn)

    def on_attendee_left(self, fn: Callable[[str], None]) -> Callable[[], None]:
        return _subscribe(self._left_listeners, fn)

    def _saw(self, client_id: str) -> None:
        self._last_seen[client_id] = self._clock()
        if client_id not in self._attendees:
            self._attendees.add(client_id)
            for fn in list(self._joined_listeners):
                fn(client_id)

    def _expire_attendees(self, now: float) -> None:
        """Drop attendees silent beyond the timeout and not vouched for by
        the audience (signal-only peers whose leave signal was lost)."""
        if self._attendee_timeout is None:
            return
        audience = getattr(self._container, "audience", None)
        covered = set()
        if audience is not None:
            covered = set(audience.get_members())
        for cid in list(self._attendees):
            if cid in covered:
                continue
            if now - self._last_seen.get(cid, now) > self._attendee_timeout:
                self._drop_client(cid)

    # ------------------------------------------------------------- workspaces
    def states_workspace(self, workspace_id: str) -> "StatesWorkspace":
        """Typed value-manager workspace (ref StatesWorkspace)."""
        return StatesWorkspace(self, workspace_id)

    def notifications_workspace(self, workspace_id: str) -> "NotificationsWorkspace":
        """Fire-and-forget notification emitters (ref NotificationsWorkspace:
        broadcast only, never retained, no late-joiner catch-up)."""
        return NotificationsWorkspace(self, workspace_id)

    def _emit_notification(self, channel: str, name: str, payload: Any) -> None:
        self._container.submit_signal(
            {"presence": "notify", "ch": channel, "name": name, "payload": payload}
        )

    # ---------------------------------------------------------------- inbound
    def _on_signal(self, sig) -> None:
        content = sig.contents
        if not isinstance(content, dict) or "presence" not in content:
            return
        if sig.client_id == self._my_id():
            return
        kind = content["presence"]
        if kind != "leave":
            self._saw(sig.client_id)
        if kind == "join":
            # A newcomer asked for state (ref joiningClients catch-up,
            # presenceDatastoreManager.ts:195).  Every member knows the
            # whole datastore (own + cached remote state), so ONE response
            # suffices: members rank deterministically and the first
            # responds at once; the rest schedule a jittered backup
            # response, suppressed when an earlier responder's catch-up
            # already covered their state (thundering-herd avoidance).
            self.flush()
            rank = self._catchup_rank(sig.client_id)
            now = self._clock()
            if rank == 0:
                self._send_catchup(sig.client_id)
            elif now - self._recent_catchup.get(sig.client_id, -1e9) > 1.0:
                self._pending_catchup[sig.client_id] = now + 0.05 * rank
        elif kind == "update":
            self._merge_states(sig.client_id, content["states"])
        elif kind == "catchup":
            # Full-datastore relay: merge EVERY client's entries by rev —
            # this is also how members recover state their own lost
            # signals missed.
            for cid, states in content["data"].items():
                if cid == self._my_id():
                    continue
                self._saw(cid)
                self._merge_states(cid, states)
            joiner = content["for"]
            mine = content["data"].get(self._my_id())
            if mine is not None:
                # Our state was relayed to the joiner: stand down (and
                # remember, in case the join itself arrives after the
                # primary's response in the synchronous fan-out).  If the
                # relay was STALE — the responder missed some of our
                # updates — broadcast just the newer entries as a
                # correction, which also heals the responder.
                stale = {
                    k: [self._wire_rev(k), v]
                    for k, v in self._local.items()
                    if k not in mine
                    or self._rev_lt(mine[k][0], self._wire_rev(k))
                }
                if stale:
                    self._container.submit_signal(
                        {"presence": "update", "states": stale}
                    )
                self._pending_catchup.pop(joiner, None)
                self._recent_catchup[joiner] = self._clock()
        elif kind == "notify":
            for fn in list(self._notification_listeners.get(content["ch"], [])):
                fn(sig.client_id, content["name"], content["payload"])
        elif kind == "leave":
            self._drop_client(sig.client_id)

    def _merge_states(self, client_id: str, states: dict[str, Any]) -> None:
        """Merge one client's {key: [[epoch, n], value]} entries, highest
        rev wins (stale/reordered signals never regress state; a fresh
        epoch beats any pre-restart rev)."""
        for key, (rev, value) in states.items():
            slot = self._remote.setdefault(key, {})
            cur = slot.get(client_id)
            if cur is not None and not self._rev_lt(cur[0], rev):
                continue
            slot[client_id] = (rev, value)
            for listener in self._listeners:
                listener(client_id, key, value)

    def _catchup_rank(self, joiner: str) -> int:
        """Our deterministic position among the members able to answer a
        join (stable id sort): rank 0 answers immediately, the rest are
        jittered backups."""
        candidates = sorted(
            (self._attendees | {self._my_id()}) - {joiner}
        )
        return candidates.index(self._my_id())

    def _send_catchup(self, joiner: str) -> None:
        """Broadcast the full known datastore for a joiner."""
        data: dict[str, dict[str, Any]] = {}
        me = self._my_id()
        for key, value in self._local.items():
            data.setdefault(me, {})[key] = [self._wire_rev(key), value]
        # Stateless members (self included) still announce: the joiner
        # learns the whole attendee set from one response, and their
        # backup responses stand down.
        data.setdefault(me, {})
        for cid in self._attendees:
            if cid != joiner:
                data.setdefault(cid, {})
        for key, per_client in self._remote.items():
            for cid, (rev, value) in per_client.items():
                data.setdefault(cid, {})[key] = [rev, value]
        self._last_heartbeat = self._clock()
        self._container.submit_signal(
            {"presence": "catchup", "for": joiner, "data": data}
        )

    def _drop_client(self, client_id: str) -> None:
        for per_key in self._remote.values():
            per_key.pop(client_id, None)
        self._last_seen.pop(client_id, None)
        self._pending_catchup.pop(client_id, None)
        self._recent_catchup.pop(client_id, None)
        if client_id in self._attendees:
            self._attendees.discard(client_id)
            for fn in list(self._left_listeners):
                fn(client_id)

    def leave(self) -> None:
        """Announce departure (ref disconnect cleanup): peers drop our state."""
        self._container.submit_signal({"presence": "leave"})
        self._queue.clear()
        self._flush_deadline = None  # nothing left to flush: no phantom tick

    def dispose(self) -> None:
        """Detach from the runtime (unregisters the LEAVE listener) and drop
        local listeners — constructing Presence repeatedly on one container
        must not accumulate permanent registrations."""
        self._unsub_member_left()
        for unsub in self._unsub_audience:
            unsub()
        self._unsub_audience = []
        self._listeners.clear()
        self._joined_listeners.clear()
        self._left_listeners.clear()
        self._notification_listeners.clear()


# ---------------------------------------------------------------------------
# Typed workspaces (ref presence-definitions value managers)
# ---------------------------------------------------------------------------

def _esc(part: str) -> str:
    """Escape the ':' namespace separator inside user-chosen ids, so a
    Latest key containing ':' can never collide with a LatestMap item path
    (the same user-key-collision class the snapshot format stamp avoids)."""
    return part.replace("%", "%25").replace(":", "%3A")


def _unesc(part: str) -> str:
    return part.replace("%3A", ":").replace("%25", "%")


class Latest:
    """One value per attendee (ref LatestRaw, latestTypes.ts): ``local``
    get/set, per-attendee remote reads, update events."""

    def __init__(self, ws: "StatesWorkspace", key: str, initial: Any = None,
                 allowed_latency_s: float | None = None) -> None:
        self._p = ws._presence
        self._key = f"{_esc(ws.workspace_id)}:{_esc(key)}"
        # Per-manager allowableUpdateLatency (ref latestTypes.ts settings).
        self.allowed_latency_s = allowed_latency_s
        if initial is not None:
            self._p.set(self._key, initial, allowed_latency_s)

    @property
    def local(self) -> Any:
        return self._p.local(self._key)

    @local.setter
    def local(self, value: Any) -> None:
        self._p.set(self._key, value, self.allowed_latency_s)

    def get_remote(self, client_id: str) -> Any:
        return self._p.remote_states(self._key).get(client_id)

    def get_remotes(self) -> dict[str, Any]:
        return self._p.remote_states(self._key)

    def on_updated(self, fn: Callable[[str, Any], None]) -> Callable[[], None]:
        key = self._key

        def listener(client_id: str, k: str, value: Any) -> None:
            if k == key:
                fn(client_id, value)

        return self._p.on_update(listener)


class LatestMap:
    """Per-attendee keyed items (ref LatestMapRaw, latestMapTypes.ts):
    each attendee holds a map; items update independently."""

    def __init__(self, ws: "StatesWorkspace", key: str) -> None:
        self._p = ws._presence
        self._prefix = f"{_esc(ws.workspace_id)}:{_esc(key)}:"

    def set_item(self, item: str, value: Any) -> None:
        self._p.set(self._prefix + _esc(item), value)

    def local_item(self, item: str) -> Any:
        return self._p.local(self._prefix + _esc(item))

    def get_remote(self, client_id: str) -> dict[str, Any]:
        out = {}
        for full_key, per_client in self._p._remote.items():
            if full_key.startswith(self._prefix) and client_id in per_client:
                _rev, value = per_client[client_id]
                out[_unesc(full_key[len(self._prefix):])] = value
        return out

    def on_item_updated(self, fn: Callable[[str, str, Any], None]) -> Callable[[], None]:
        prefix = self._prefix

        def listener(client_id: str, k: str, value: Any) -> None:
            if k.startswith(prefix):
                fn(client_id, _unesc(k[len(prefix):]), value)

        return self._p.on_update(listener)


class StatesWorkspace:
    def __init__(self, presence: Presence, workspace_id: str) -> None:
        self._presence = presence
        self.workspace_id = workspace_id

    def latest(self, key: str, initial: Any = None,
               allowed_latency_s: float | None = None) -> Latest:
        return Latest(self, key, initial, allowed_latency_s)

    def latest_map(self, key: str) -> LatestMap:
        return LatestMap(self, key)

    def flush(self) -> None:
        self._presence.flush()


class NotificationsWorkspace:
    def __init__(self, presence: Presence, workspace_id: str) -> None:
        self._presence = presence
        self.workspace_id = workspace_id

    def emit(self, name: str, payload: Any = None) -> None:
        """Broadcast immediately; never queued, never retained."""
        self._presence._emit_notification(self.workspace_id, name, payload)

    def on_notification(self, fn: Callable[[str, str, Any], None]) -> Callable[[], None]:
        """fn(client_id, name, payload) per received notification;
        returns an unsubscribe handle."""
        return _subscribe(
            self._presence._notification_listeners.setdefault(self.workspace_id, []),
            fn,
        )
