"""DDS interceptions: wrap a channel so local edits pass a hook first.

Reference parity: packages/framework/dds-interceptions —
createSharedMapWithInterception / createDirectoryWithInterception /
createSharedStringWithInterception: the wrapper forwards reads untouched
and routes every local WRITE through a callback that may enrich it (the
canonical use: stamping attribution properties onto edits)."""

from __future__ import annotations

from typing import Any, Callable


class InterceptedSharedMap:
    """Write-intercepting view over a SharedMapChannel."""

    def __init__(self, channel, interceptor: Callable[[str, Any], Any]) -> None:
        self._ch = channel
        self._hook = interceptor

    def set(self, key: str, value: Any) -> None:
        self._ch.set(key, self._hook(key, value))

    def delete(self, key: str) -> None:
        self._ch.delete(key)

    def __getattr__(self, name: str):  # reads pass through
        return getattr(self._ch, name)


class InterceptedSharedString:
    """Insert-intercepting view over a SharedStringChannel: the hook returns
    annotation properties applied to every inserted range (the reference's
    attribution-stamping string interception)."""

    def __init__(self, channel, props_hook: Callable[[], dict[int, int]]) -> None:
        self._ch = channel
        self._hook = props_hook

    def insert_text(self, pos: int, text: str) -> int:
        ls = self._ch.insert_text(pos, text)
        for prop, value in self._hook().items():
            self._ch.annotate_range(pos, pos + len(text), prop, value)
        return ls

    def __getattr__(self, name: str):
        return getattr(self._ch, name)
