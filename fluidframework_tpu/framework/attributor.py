"""Attributor: who-wrote-what, derived from the op stream.

Reference parity: packages/framework/attributor — ``OpStreamAttributor``
(src/attributor.ts:87) maps sequence numbers to {user, timestamp} as ops are
processed, and the summary codecs (src/encoders.ts, lz4Encoder.ts) compress
the table with client-id interning plus timestamp delta-encoding before it
rides a summary blob. DDSes store attribution KEYS (seq numbers) — e.g.
merge-tree segments already carry their insert/remove stamps — and resolve
them through this table.
"""

from __future__ import annotations

from typing import Any


class OpStreamAttributor:
    """seq -> {client, timestamp} for every sequenced op observed."""

    def __init__(self) -> None:
        # seq -> (client, timestamp in INTEGER ms): one quantization, done
        # at record time — re-deriving ms from a float at summarize time
        # can disagree with the stored value by 1ms (float truncation), so
        # the integer IS the stored truth everywhere.
        self._entries: dict[int, tuple[str, int]] = {}

    def record(self, seq: int, client_id: str, timestamp: float) -> None:
        self._entries[seq] = (client_id, int(timestamp * 1000))

    def observe(self, msg) -> None:
        """Feed one SequencedMessage (wire shape)."""
        self.record(msg.seq, msg.client_id, msg.timestamp or 0.0)

    def get(self, seq: int) -> dict[str, Any] | None:
        e = self._entries.get(seq)
        return {"client": e[0], "timestamp": e[1] / 1000} if e else None

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------ summary
    def summarize(self) -> dict:
        """Interned + delta-encoded table (ref encoders.ts: string interning
        for client ids, delta encoding for timestamps/seqs — the dominant
        size terms in long sessions)."""
        seqs = sorted(self._entries)
        clients: list[str] = []
        index: dict[str, int] = {}
        seq_deltas: list[int] = []
        client_ids: list[int] = []
        ts_deltas: list[int] = []
        prev_seq = 0
        prev_ts = 0
        for s in seqs:
            client, ts_ms = self._entries[s]
            if client not in index:
                index[client] = len(clients)
                clients.append(client)
            seq_deltas.append(s - prev_seq)
            prev_seq = s
            ts_deltas.append(ts_ms - prev_ts)
            prev_ts = ts_ms
            client_ids.append(index[client])
        return {
            "clients": clients,
            "seqDeltas": seq_deltas,
            "clientIdx": client_ids,
            "tsDeltas": ts_deltas,
        }

    def load(self, data: dict) -> None:
        self._entries = {}
        seq = 0
        ts_ms = 0
        for d_seq, ci, d_ts in zip(
            data["seqDeltas"], data["clientIdx"], data["tsDeltas"]
        ):
            seq += d_seq
            ts_ms += d_ts
            self._entries[seq] = (data["clients"][ci], ts_ms)

    def trim(self, min_seq: int) -> None:
        """Drop entries at or below the collab-window floor — a HOST POLICY
        hook, deliberately not automatic: attribution keys on long-lived
        content reference arbitrarily old seqs, so the default (like the
        reference's attributor) retains the full table and lets summaries
        carry it; hosts that only need in-window attribution bound memory
        here."""
        self._entries = {s: e for s, e in self._entries.items() if s > min_seq}
