"""Batched SharedTree kernels: rebase position arithmetic + chunk updates.

Reference parity: the hot paths of SharedTree sequenced-edit integration —
EditManager rebase (tree/src/shared-tree-core/editManager.ts:542,808, the
per-commit sequence-field mark transforms in feature-libraries/
sequence-field/) and chunked-forest value updates
(feature-libraries/chunked-forest/uniformChunk.ts:42).

TPU design, not a port: the host algebra (dds/tree/changeset.py) walks mark
lists; on device a changeset over one field is a fixed-width columnar
encoding (kinds[M], counts[M]), and rebasing a BATCH of pending edits over
it is pure broadcast arithmetic — for every query position, the net shift
is "inserts at-or-before minus removed-below", computed as an [B, M]
masked reduction with no data-dependent control flow. The same sided
tie-break contract as the host algebra (changeset.py rebase_marks) is a
single >= / > mask choice, so host and device stay bit-identical (enforced
by tests/test_tree_kernel.py differential fuzz).

Shapes: D docs × M marks × B query positions; everything int32; vmap/
shard_map over the doc axis is the scale-out path (documents are the
embarrassing axis, SURVEY §2.6.2).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# The mark kind numbering is the protocol-layer schema (shared with the
# pooled columns); TreeMarkKind is re-exported here for existing callers.
from ..protocol.mark_schema import (  # noqa: F401  (re-export shim)
    DEVICE_CODE_OFFSET,
    K_INSERT,
    K_MODIFY,
    K_REMOVE,
    K_SKIP,
    TreeMarkKind,
)

I32 = jnp.int32


def encode_marks(marks, max_marks: int) -> tuple[np.ndarray, np.ndarray]:
    """Columnar encode a host mark list (changeset.py Mark objects) to
    (kinds[M], counts[M]) int32 arrays. Insert counts are content lengths.

    Dispatches on the protocol mark-schema class tag ``m.K`` — no upward
    import of the dds changeset classes."""
    kinds = np.zeros((max_marks,), np.int32)
    counts = np.zeros((max_marks,), np.int32)
    assert len(marks) <= max_marks, "mark list exceeds kernel width"
    for i, m in enumerate(marks):
        k = m.K
        if k == K_SKIP:
            kinds[i], counts[i] = TreeMarkKind.SKIP, m.count
        elif k == K_INSERT:
            kinds[i], counts[i] = TreeMarkKind.INSERT, len(m.content)
        elif k == K_REMOVE:
            kinds[i], counts[i] = TreeMarkKind.REMOVE, m.count
        elif k == K_MODIFY:
            kinds[i], counts[i] = TreeMarkKind.MODIFY, 1
        else:
            raise TypeError(m)
    return kinds, counts


def _mark_geometry(kinds: jnp.ndarray, counts: jnp.ndarray):
    """Per-mark input-space start offsets and effect sizes.

    input-consuming marks: SKIP/REMOVE consume `count`, MODIFY consumes 1,
    INSERT consumes 0. Returns (in_start[M], ins_len[M], rm_len[M])."""
    consumed = jnp.where(
        (kinds == TreeMarkKind.SKIP) | (kinds == TreeMarkKind.REMOVE),
        counts,
        jnp.where(kinds == TreeMarkKind.MODIFY, 1, 0),
    )
    in_start = jnp.cumsum(consumed) - consumed
    ins_len = jnp.where(kinds == TreeMarkKind.INSERT, counts, 0)
    rm_len = jnp.where(kinds == TreeMarkKind.REMOVE, counts, 0)
    return in_start, ins_len, rm_len


def rebase_insert_positions(
    positions: jnp.ndarray,  # int32[B] insert positions (boundary coords)
    b_kinds: jnp.ndarray,    # int32[M]
    b_counts: jnp.ndarray,   # int32[M]
    a_after: bool,
) -> jnp.ndarray:
    """Where does each pending INSERT land after change b applies?

    Mirrors rebase_marks for a = [Skip(p), Insert(..)]: b's removes pull the
    boundary to the range start; b's inserts at the same boundary shift the
    pending insert right iff the pending one is the later-sequenced side
    (a_after=True, the >= mask) — the host tie-break contract."""
    in_start, ins_len, rm_len = _mark_geometry(b_kinds, b_counts)
    p = positions[:, None]  # [B, 1]
    # Removal below the boundary: overlap of [in_start, in_start+rm) with [0, p).
    rm_below = jnp.clip(p - in_start, 0, rm_len[None, :])  # [B, M]
    # b-insert shift: at the same post-removal boundary the earlier-sequenced
    # content stays left for the later side.
    ins_at = in_start[None, :]
    shift = jnp.where(
        (p >= ins_at) if a_after else (p > ins_at), ins_len[None, :], 0
    )
    return positions + jnp.sum(shift, axis=1) - jnp.sum(rm_below, axis=1)


def rebase_node_positions(
    positions: jnp.ndarray,  # int32[B] node indices (modify/remove-1 targets)
    b_kinds: jnp.ndarray,
    b_counts: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Where does each targeted NODE land after change b — and does it
    survive? Mirrors rebase_marks for a = [Skip(p), Modify/Remove(1)]:
    a node inside a b-removed range is dropped (mask 0)."""
    in_start, ins_len, rm_len = _mark_geometry(b_kinds, b_counts)
    p = positions[:, None]
    rm_below = jnp.clip(p - in_start, 0, rm_len[None, :])
    # Node positions: a b-insert AT the node's index lands before it (the
    # node's content moves right) — always the >= mask for occupied slots.
    shift = jnp.where(p >= in_start[None, :], ins_len[None, :], 0)
    dropped = jnp.any(
        (rm_len[None, :] > 0) & (p >= in_start[None, :]) & (p < (in_start + rm_len)[None, :]),
        axis=1,
    )
    out = positions + jnp.sum(shift, axis=1) - jnp.sum(rm_below, axis=1)
    return out, (~dropped).astype(I32)


# ---------------------------------------------------------------------------
# Uniform-chunk value updates (the columnar forest hot path)
# ---------------------------------------------------------------------------


class ChunkState(NamedTuple):
    """One numeric column of a uniform chunk, with per-row attribution."""

    values: jnp.ndarray   # int32[N]
    val_seq: jnp.ndarray  # int32[N] seq of winning write


def init_chunk(values: np.ndarray) -> ChunkState:
    v = jnp.asarray(values, I32)
    return ChunkState(values=v, val_seq=jnp.zeros_like(v))


def apply_value_sets(
    s: ChunkState,
    idx: jnp.ndarray,   # int32[B] row indices (< 0 = padding)
    vals: jnp.ndarray,  # int32[B]
    seqs: jnp.ndarray,  # int32[B] distinct, > 0 (sequence order of the writes)
) -> ChunkState:
    """Apply a sequenced batch of value overwrites in ONE scatter pass: for
    rows hit multiple times the highest-seq write wins (LWW by total order),
    matching sequential host application exactly.

    Determinism: duplicate-index ``set`` scatters have unspecified order, so
    the winner per row is picked first with a commutative scatter-MAX of
    seqs, and only winning lanes scatter values. Padding lanes (idx < 0) are
    routed out of bounds HIGH (negative indices wrap in XLA, N drops)."""
    n = s.values.shape[0]
    valid = idx >= 0
    safe_idx = jnp.where(valid, idx, n)  # n = dropped by mode="drop"
    best = jnp.zeros((n,), I32).at[safe_idx].max(
        jnp.where(valid, seqs, 0), mode="drop"
    )
    win = valid & (seqs == best[jnp.where(valid, idx, 0)])
    win_idx = jnp.where(win, idx, n)
    values = s.values.at[win_idx].set(vals, mode="drop")
    val_seq = s.val_seq.at[win_idx].set(seqs, mode="drop")
    return ChunkState(values=values, val_seq=val_seq)


def batched_value_engine(n_docs: int):
    """The D-doc batched form: vmap of apply_value_sets over the doc axis —
    the tree analog of the merge-tree doc-batch engine (document sharding is
    the primary parallel axis, SURVEY §2.6.2)."""
    return jax.jit(jax.vmap(apply_value_sets))


# ---------------------------------------------------------------------------
# Columnar forest: a uniform chunk as mutable device state
# ---------------------------------------------------------------------------
# The reference's UniformChunk (chunked-forest/uniformChunk.ts:42) stores a
# shape-uniform subtree as columnar value arrays.  ForestState is that idea
# as REPLICA STATE: one document's root field of uniform leaf nodes, living
# on device, mutated by sequenced trunk-coordinate changesets.  Structural
# edits are index-map gathers (no data-dependent loops); a batch of D docs
# is vmap over the leading axis (models/tree_batch_engine.py).

# Forest op row layout (int32[8]):
#   0 kind | 1 seq | 2 pos | 3 count | 4 dst | 5 value | 6..7 unused
FOREST_OP_FIELDS = 8

ERR_NODE_OVERFLOW = 1
ERR_FOREST_RANGE = 2


class ForestOpKind:
    NOOP = 0
    INSERT = 1   # count nodes at pos, values from the payload row
    REMOVE = 2   # count nodes at pos
    SET = 3      # value at pos
    MOVE = 4     # count nodes from pos to boundary dst (pre-move coords)


class ForestState(NamedTuple):
    values: jnp.ndarray   # int32[N] leaf value column
    val_seq: jnp.ndarray  # int32[N] seq of last write (attribution)
    nnode: jnp.ndarray    # int32 scalar live node count
    error: jnp.ndarray    # int32 scalar bitmask


def init_forest(capacity: int = 1024) -> ForestState:
    return ForestState(
        values=jnp.zeros((capacity,), I32),
        val_seq=jnp.zeros((capacity,), I32),
        nnode=jnp.zeros((), I32),
        error=jnp.zeros((), I32),
    )


def _forest_gather(s: ForestState, src: jnp.ndarray, n_new) -> ForestState:
    """Rebuild the columns through a source-index map (-1 = fresh slot,
    filled by the caller afterwards)."""
    safe = jnp.clip(src, 0, s.values.shape[0] - 1)
    take = src >= 0
    return s._replace(
        values=jnp.where(take, s.values[safe], 0),
        val_seq=jnp.where(take, s.val_seq[safe], 0),
        nnode=n_new,
    )


def apply_forest_op(s: ForestState, op: jnp.ndarray, payload: jnp.ndarray) -> ForestState:
    """Apply one trunk-coordinate structural/value op to one document."""
    kind, seq, pos, count, dst, value = op[0], op[1], op[2], op[3], op[4], op[5]
    N = s.values.shape[0]
    idx = jnp.arange(N, dtype=I32)
    n = s.nnode

    def do_noop(s):
        return s

    def do_insert(s):
        over = n + count > N
        bad = pos > n
        ok = ~(over | bad)
        src = jnp.where(idx < pos, idx, jnp.where(idx < pos + count, -1, idx - count))
        out = _forest_gather(s, src, n + count)
        fresh = (idx >= pos) & (idx < pos + count)
        pay = payload[jnp.clip(idx - pos, 0, payload.shape[0] - 1)]
        return jax.lax.cond(
            ok,
            lambda _: out._replace(
                values=jnp.where(fresh, pay, out.values),
                val_seq=jnp.where(fresh, seq, out.val_seq),
            ),
            lambda _: s._replace(
                error=s.error
                | jnp.where(over, ERR_NODE_OVERFLOW, 0)
                | jnp.where(bad, ERR_FOREST_RANGE, 0)
            ),
            None,
        )

    def do_remove(s):
        bad = pos + count > n
        src = jnp.where(idx < pos, idx, idx + count)
        out = _forest_gather(s, src, n - count)
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: out,
            None,
        )

    def do_set(s):
        bad = pos >= n
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: s._replace(
                values=s.values.at[pos].set(value),
                val_seq=s.val_seq.at[pos].set(seq),
            ),
            None,
        )

    def do_move(s):
        # Move [pos, pos+count) to pre-move boundary dst: compose the
        # remove map with the insert map (dst' = post-remove boundary).
        bad = (pos + count > n) | (dst > n)
        dstp = jnp.where(dst > pos + count, dst - count, jnp.minimum(dst, pos))
        # For each output slot: inside the landed block -> moved source;
        # else the surviving nodes in order (skip the moved range).
        in_block = (idx >= dstp) & (idx < dstp + count)
        u = jnp.where(idx < dstp, idx, idx - count)      # rank among survivors
        surv = jnp.where(u < pos, u, u + count)          # survivor rank -> old idx
        src = jnp.where(in_block, pos + (idx - dstp), surv)
        out = _forest_gather(s, src, n)
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: out,
            None,
        )

    return jax.lax.switch(
        kind, [do_noop, do_insert, do_remove, do_set, do_move], s
    )


def apply_forest_ops(
    s: ForestState, ops: jnp.ndarray, payloads: jnp.ndarray
) -> ForestState:
    """Apply a [B]-op batch to one document in order (lax.scan); batch over
    documents with vmap (the doc axis is the parallel one)."""

    def step(carry, xs):
        op, payload = xs
        return apply_forest_op(carry, op, payload), None

    out, _ = jax.lax.scan(step, s, (ops, payloads))
    return out


def forest_values(s: ForestState) -> np.ndarray:
    """Host view of the live value column."""
    n = int(s.nnode)
    return np.asarray(s.values)[:n]


# ---------------------------------------------------------------------------
# Nested columnar forest: (parent, field, index) SoA beside the value column
# ---------------------------------------------------------------------------
# General chunked-forest shapes on device (VERDICT r3 next #3; ref
# chunked-forest/uniformChunk.ts:42 generalized beyond the flat value
# column).  Design: STABLE ROWS — each node is a row whose position in the
# tree is its (parent row id, field id, sibling index) columns, NOT its row
# order.  Structural edits become masked column arithmetic:
#
# - insert: bump sibling indices >= pos, append fresh rows;
# - remove: clear alive on the range, propagate death down the parent
#   chain (bounded by MAX_PATH+1 — the deepest node a path op can create),
#   close the sibling index gap;
# - move (contiguous, same field): pure index rewrites, no data movement;
# - set: resolve the row, write the value column.
#
# Ops address their target FIELD by a bounded-depth path of (field, index)
# steps from the virtual root — resolution is MAX_PATH equality reductions
# over the columns, data-independent control flow throughout.  Because
# ordering lives in index columns, compaction is a stable gather plus a
# parent-id remap.  The doc axis vmaps/shard_maps as everywhere else.

MAX_PATH = 6           # path steps per op (target field may sit one deeper)
_TGT = 3 + 2 * MAX_PATH  # target-block base after the path pairs
NESTED_OP_FIELDS = _TGT + 7
# Op row layout (int32[NESTED_OP_FIELDS]):
#  0 kind | 1 seq | 2 depth | 3.._TGT-1 (f_k, i_k) path pairs |
#  _TGT fld | +1 pos | +2 count | +3 dst | +4 value | +5 vkind | +6 ntype

VKIND_NONE = 0
VKIND_INT = 1
# Pooled kinds: the row's value column is an OFFSET into the per-doc
# word pool and a new vlen column holds the span length — the exact
# text-pool pattern of the merge-tree kernel (text/seg_start/seg_len),
# generalized to arbitrary leaf values (ref chunked-forest/
# uniformChunk.ts:42 stores arbitrary values columnar the same way).
# For pooled INSERT/SET ops the op's `value` slot carries the word count
# and the payload row carries the words themselves.
VKIND_STR = 2    # words = codepoints
VKIND_F64 = 3    # words = the two int32 halves of the float64 bit pattern
VKIND_BOOL = 4   # inline like INT (value column is 0/1)

_POOLED = (VKIND_STR, VKIND_F64)


def _is_pooled(vkind):
    return (vkind == VKIND_STR) | (vkind == VKIND_F64)


class NestedOpKind:
    NOOP = 0
    INSERT = 1   # count nodes (one ntype/vkind run) at pos; payload = values
    REMOVE = 2   # count subtrees at pos
    SET = 3      # value of the node at (field, pos)
    MOVE = 4     # count nodes from pos to boundary dst (input coords)
    REPLACE_FIELD = 5  # kill ALL siblings (+ descendants), insert count fresh
    #                    nodes — the optional/value field-kind whole-content
    #                    set (field_kinds.OptionalChange) on device


class NestedForestState(NamedTuple):
    parent: jnp.ndarray   # int32[N] parent row id (-1 = virtual root)
    field_id: jnp.ndarray # int32[N] interned field key
    index: jnp.ndarray    # int32[N] sibling index within (parent, field)
    ntype: jnp.ndarray    # int32[N] interned node type
    value: jnp.ndarray    # int32[N] inline value, or pool offset (pooled)
    vkind: jnp.ndarray    # int32[N] VKIND_*
    vlen: jnp.ndarray     # int32[N] pool span length (pooled kinds only)
    val_seq: jnp.ndarray  # int32[N] seq of winning value write
    alive: jnp.ndarray    # int32[N] 0/1
    pool: jnp.ndarray     # int32[P] append-only word pool (str/f64 values)
    pool_end: jnp.ndarray # int32 scalar pool watermark
    nrow: jnp.ndarray     # int32 scalar allocation watermark
    error: jnp.ndarray    # int32 scalar bitmask


ERR_POOL_OVERFLOW = 4


def init_nested_forest(
    capacity: int = 1024, pool_capacity: int = 4096
) -> NestedForestState:
    z = jnp.zeros((capacity,), I32)
    return NestedForestState(
        parent=jnp.full((capacity,), -1, I32),
        field_id=z, index=z, ntype=z, value=z, vkind=z, vlen=z, val_seq=z,
        alive=z,
        pool=jnp.zeros((pool_capacity,), I32),
        pool_end=jnp.zeros((), I32),
        nrow=jnp.zeros((), I32),
        error=jnp.zeros((), I32),
    )


def _resolve_parent(s: NestedForestState, op: jnp.ndarray):
    """Walk the op's path steps to the parent row id.  Returns (parent, ok);
    parent = -1 means the virtual root (depth 0)."""
    depth = op[2]
    parent = jnp.asarray(-1, I32)
    ok = jnp.asarray(True)
    for k in range(MAX_PATH):
        f, i = op[3 + 2 * k], op[4 + 2 * k]
        active = k < depth
        mask = (
            (s.alive == 1)
            & (s.parent == parent)
            & (s.field_id == f)
            & (s.index == i)
        )
        found = jnp.any(mask)
        hit = jnp.argmax(mask).astype(I32)
        parent = jnp.where(active, jnp.where(found, hit, -2), parent)
        ok = ok & jnp.where(active, found, True)
    return parent, ok


def _sibling_mask(s: NestedForestState, parent, fld):
    return (s.alive == 1) & (s.parent == parent) & (s.field_id == fld)


def _kill_with_descendants(s: NestedForestState, target) -> jnp.ndarray:
    """Alive column with ``target`` rows dead and death propagated down
    the parent chain.  Tree depth through this kernel is bounded by
    MAX_PATH + 1 (the deepest addressable field), so a static unroll
    covers every level."""
    N = s.parent.shape[0]
    alive = jnp.where(target, 0, s.alive)
    for _ in range(MAX_PATH + 1):
        pk = jnp.clip(s.parent, 0, N - 1)
        parent_dead = (s.parent >= 0) & (alive[pk] == 0)
        alive = jnp.where(parent_dead, 0, alive)
    return alive


def _fresh_run(
    s: NestedForestState, *, count, parent, fld, indices, seq,
    vkind, ntype, wlen, payload, pool, alive, index_others,
) -> NestedForestState:
    """Allocate ``count`` fresh rows (one vkind/ntype run) — the shared
    row-write of INSERT and REPLACE_FIELD.  ``indices`` gives each fresh
    row's sibling index from its allocation offset j; ``index_others`` is
    the (possibly shifted) index column for existing rows; ``alive`` the
    pre-allocation alive column."""
    N = s.parent.shape[0]
    idx = jnp.arange(N, dtype=I32)
    fresh = (idx >= s.nrow) & (idx < s.nrow + count)
    j = idx - s.nrow
    pay = payload[jnp.clip(j, 0, payload.shape[0] - 1)]
    pooled = _is_pooled(vkind)
    inline = (vkind == VKIND_INT) | (vkind == VKIND_BOOL)
    row_val = jnp.where(pooled, s.pool_end, jnp.where(inline, pay, 0))
    return s._replace(
        parent=jnp.where(fresh, parent, s.parent),
        field_id=jnp.where(fresh, fld, s.field_id),
        index=jnp.where(fresh, indices(j), index_others),
        ntype=jnp.where(fresh, ntype, s.ntype),
        value=jnp.where(fresh, row_val, s.value),
        vkind=jnp.where(fresh, vkind, s.vkind),
        vlen=jnp.where(fresh, wlen, s.vlen),
        val_seq=jnp.where(fresh, seq, s.val_seq),
        alive=jnp.where(fresh, 1, alive),
        pool=pool,
        pool_end=s.pool_end + wlen,
        nrow=s.nrow + count,
    )


def apply_nested_op(
    s: NestedForestState, op: jnp.ndarray, payload: jnp.ndarray
) -> NestedForestState:
    kind, seq = op[0], op[1]
    fld, pos, count, dst = op[_TGT], op[_TGT + 1], op[_TGT + 2], op[_TGT + 3]
    value, vkind, ntype = op[_TGT + 4], op[_TGT + 5], op[_TGT + 6]
    N = s.parent.shape[0]
    idx = jnp.arange(N, dtype=I32)
    parent, okp = _resolve_parent(s, op)
    sib = _sibling_mask(s, parent, fld)
    n_sib = jnp.sum(sib.astype(I32))

    def fail(s, over, bad, pool_over=False):
        return s._replace(
            error=s.error
            | jnp.where(over, ERR_NODE_OVERFLOW, 0)
            | jnp.where(bad, ERR_FOREST_RANGE, 0)
            | jnp.where(pool_over, ERR_POOL_OVERFLOW, 0)
        )

    pooled = _is_pooled(vkind)
    # For pooled INSERT/SET the op's value slot is the word count; the
    # payload row holds the words destined for the pool.
    wlen = jnp.where(pooled, value, 0)
    P = s.pool.shape[0]
    W = payload.shape[0]

    def _pool_append(s):
        """Append payload[:wlen] to the pool; returns (pool, over)."""
        over = s.pool_end + wlen > P
        tpos = jnp.arange(W, dtype=I32)
        dst = jnp.where((tpos < wlen) & ~over, s.pool_end + tpos, P)
        return s.pool.at[dst].set(payload, mode="drop"), over

    def do_noop(s):
        return s

    def do_insert(s):
        over = s.nrow + count > N
        bad = ~okp | (pos > n_sib)
        pool, pool_over = _pool_append(s)
        shifted = jnp.where(sib & (s.index >= pos), s.index + count, s.index)
        out = _fresh_run(
            s, count=count, parent=parent, fld=fld,
            indices=lambda j: pos + j, seq=seq, vkind=vkind, ntype=ntype,
            wlen=wlen, payload=payload, pool=pool, alive=s.alive,
            index_others=shifted,
        )
        return jax.lax.cond(
            okp & ~over & ~bad & ~pool_over,
            lambda _: out,
            lambda _: fail(s, over, bad, pool_over),
            None,
        )

    def do_remove(s):
        bad = ~okp | (pos + count > n_sib)
        target = sib & (s.index >= pos) & (s.index < pos + count)
        alive = _kill_with_descendants(s, target)
        closed = jnp.where(sib & (s.index >= pos + count), s.index - count, s.index)
        out = s._replace(alive=alive, index=closed)
        return jax.lax.cond(
            ~bad, lambda _: out, lambda _: fail(s, False, bad), None
        )

    def do_set(s):
        hit = sib & (s.index == pos)
        bad = ~okp | ~jnp.any(hit)
        pool, pool_over = _pool_append(s)
        new_val = jnp.where(pooled, s.pool_end, value)
        out = s._replace(
            value=jnp.where(hit, new_val, s.value),
            vkind=jnp.where(hit, vkind, s.vkind),
            vlen=jnp.where(hit, wlen, s.vlen),
            val_seq=jnp.where(hit, seq, s.val_seq),
            pool=pool,
            pool_end=s.pool_end + wlen,
        )
        return jax.lax.cond(
            ~bad & ~pool_over,
            lambda _: out,
            lambda _: fail(s, False, bad, pool_over),
            None,
        )

    def do_replace_field(s):
        # The optional-kind whole-content set: clear the field (subtree
        # kill like REMOVE over every sibling), then insert the fresh run
        # at index 0 (same row/pool mechanics as INSERT).
        over = s.nrow + count > N
        bad = ~okp
        pool, pool_over = _pool_append(s)
        alive = _kill_with_descendants(s, sib)
        out = _fresh_run(
            s, count=count, parent=parent, fld=fld,
            indices=lambda j: j, seq=seq, vkind=vkind, ntype=ntype,
            wlen=wlen, payload=payload, pool=pool, alive=alive,
            index_others=s.index,
        )
        return jax.lax.cond(
            okp & ~over & ~pool_over,
            lambda _: out,
            lambda _: fail(s, over, bad, pool_over),
            None,
        )

    def do_move(s):
        # Contiguous same-field block [pos, pos+count) to boundary dst,
        # both in input coordinates: pure sibling-index rewrites.
        bad = ~okp | (pos + count > n_sib) | (dst > n_sib)
        dstp = jnp.where(dst > pos + count, dst - count, jnp.minimum(dst, pos))
        moved = sib & (s.index >= pos) & (s.index < pos + count)
        # Survivor rank: order among non-moved siblings.
        u = jnp.where(s.index > pos + count - 1, s.index - count, s.index)
        new_surv = jnp.where(u >= dstp, u + count, u)
        new_idx = jnp.where(
            moved, dstp + (s.index - pos),
            jnp.where(sib, new_surv, s.index),
        )
        out = s._replace(index=new_idx)
        return jax.lax.cond(
            ~bad, lambda _: out, lambda _: fail(s, False, bad), None
        )

    return jax.lax.switch(
        kind,
        [do_noop, do_insert, do_remove, do_set, do_move, do_replace_field],
        s,
    )


def apply_nested_ops(
    s: NestedForestState, ops: jnp.ndarray, payloads: jnp.ndarray
) -> NestedForestState:
    """Apply a [B]-op batch to one document in order; vmap over docs."""

    def step(carry, xs):
        op, payload = xs
        return apply_nested_op(carry, op, payload), None

    out, _ = jax.lax.scan(step, s, (ops, payloads))
    return out


def apply_nested_megastep(
    s: NestedForestState, ops: jnp.ndarray, payloads: jnp.ndarray
) -> NestedForestState:
    """Apply a [K, D, B] op ring to a [D, ...] forest batch in ONE fused
    program (``lax.scan`` over K slices of ``vmap(apply_nested_ops)``) —
    the tree engine's megastep dispatch amortizer.  Bit-identical to K
    sequential batched dispatches: slices apply in order against the
    carried state, and error/overflow bits latch on device for a single
    per-megastep readback."""

    def body(st: NestedForestState, xs):
        o, p = xs
        return jax.vmap(apply_nested_ops)(st, o, p), None

    out, _ = jax.lax.scan(body, s, (ops, payloads))
    return out


def compact_nested(s: NestedForestState) -> NestedForestState:
    """Drop dead rows: stable gather of live rows to the prefix plus a
    parent-id remap — trivial BECAUSE ordering lives in the index columns,
    not in row order.  The word pool compacts in the same pass: live
    pooled spans pack to the front (searchsorted span gather) and the
    value column's offsets are rewritten, reclaiming dead/overwritten
    string and float storage."""
    N = s.parent.shape[0]
    alive = s.alive == 1
    new_id = jnp.cumsum(alive.astype(I32)) - 1          # old row -> new row
    n_alive = jnp.sum(alive.astype(I32))
    order = jnp.argsort(~alive, stable=True)            # live rows first
    take = jnp.arange(N) < n_alive

    def g(col, fill=0):
        return jnp.where(take, col[order], fill)

    old_parent = s.parent[order]
    pk = jnp.clip(old_parent, 0, N - 1)
    parent = jnp.where(old_parent < 0, -1, new_id[pk])

    # ------------------------------------------------------------- pool pack
    value_g = g(s.value)
    vkind_g = g(s.vkind)
    vlen_g = g(s.vlen)
    P = s.pool.shape[0]
    span = jnp.where(take & _is_pooled(vkind_g), vlen_g, 0)   # [N] words owned
    ends = jnp.cumsum(span)                                   # inclusive ends
    new_off = ends - span                                     # exclusive starts
    total = ends[-1] if N > 0 else jnp.zeros((), I32)
    t = jnp.arange(P, dtype=I32)
    # Which packed row does output word t belong to?  searchsorted over the
    # cumulative ends; src = that row's OLD offset + intra-span position.
    r = jnp.searchsorted(ends, t, side="right").astype(I32)
    rk = jnp.clip(r, 0, N - 1)
    src = value_g[rk] + (t - new_off[rk])
    pool = jnp.where(t < total, s.pool[jnp.clip(src, 0, P - 1)], 0)
    value_packed = jnp.where(take & _is_pooled(vkind_g), new_off, value_g)

    return NestedForestState(
        parent=jnp.where(take, parent, -1),
        field_id=g(s.field_id), index=g(s.index), ntype=g(s.ntype),
        value=value_packed, vkind=vkind_g, vlen=vlen_g, val_seq=g(s.val_seq),
        alive=jnp.where(take, 1, 0),
        pool=pool,
        pool_end=total,
        nrow=n_alive,
        error=s.error,
    )


def nested_to_json(
    s: NestedForestState,
    field_names: dict[int, str],
    type_names: dict[int, str],
) -> list[dict]:
    """Materialize the columns as the host forest's root-field JSON
    (forest.Node.to_json shape) for differential equality."""
    nrow = int(s.nrow)
    parent = np.asarray(s.parent)[:nrow]
    field_id = np.asarray(s.field_id)[:nrow]
    index = np.asarray(s.index)[:nrow]
    ntype = np.asarray(s.ntype)[:nrow]
    value = np.asarray(s.value)[:nrow]
    vkind = np.asarray(s.vkind)[:nrow]
    vlen = np.asarray(s.vlen)[:nrow]
    alive = np.asarray(s.alive)[:nrow]
    pool = np.asarray(s.pool)

    # parent -> {field -> [(index, row)]}: one O(N) pass, O(1) per lookup.
    children: dict[int, dict[int, list[tuple[int, int]]]] = {}
    for r in range(nrow):
        if alive[r]:
            children.setdefault(int(parent[r]), {}).setdefault(
                int(field_id[r]), []
            ).append((int(index[r]), r))

    def node_json(r: int) -> dict:
        out: dict = {"t": type_names[int(ntype[r])]}
        v = decode_pooled_value(
            int(vkind[r]), int(value[r]), int(vlen[r]), pool
        )
        if v is not None:
            out["v"] = v
        fields = {
            field_names[f]: [node_json(cr) for _i, cr in sorted(rows)]
            for f, rows in children.get(r, {}).items()
        }
        if fields:
            out["f"] = fields
        return out

    return [node_json(r) for _i, r in sorted(children.get(-1, {}).get(0, []))]


def decode_pooled_value(vkind: int, value: int, vlen: int, pool: np.ndarray):
    """Host decode of one row's value columns back to the Python leaf."""
    import struct

    if vkind == VKIND_INT:
        return int(value)
    if vkind == VKIND_BOOL:
        return bool(value)
    if vkind == VKIND_STR:
        return "".join(chr(int(c)) for c in pool[value : value + vlen])
    if vkind == VKIND_F64:
        lo, hi = int(pool[value]) & 0xFFFFFFFF, int(pool[value + 1]) & 0xFFFFFFFF
        return struct.unpack("<d", struct.pack("<II", lo, hi))[0]
    return None


def encode_pooled_words(v) -> tuple[int, int, list[int] | None]:
    """Python leaf -> (vkind, inline value-or-wordcount, pool words).

    Inverse of decode_pooled_value; bool before int (bool is an int
    subclass), f64 as its two little-endian int32 halves, str as
    codepoints.  Raises ValueError for values the columns cannot carry
    (out-of-int32-range ints, exotic types) — callers route those
    documents to their host fallback."""
    import struct

    if v is None:
        return VKIND_NONE, 0, None
    if isinstance(v, bool):
        return VKIND_BOOL, int(v), None
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return VKIND_INT, v, None
        raise ValueError(f"int leaf out of int32 range: {v!r}")
    if isinstance(v, float):
        lo, hi = struct.unpack("<ii", struct.pack("<d", v))
        return VKIND_F64, 2, [lo, hi]
    if isinstance(v, str):
        return VKIND_STR, len(v), [ord(c) for c in v]
    raise ValueError(f"unsupported leaf value type: {v!r}")


# ---------------------------------------------------------------------------
# Batched rebase-window kernel (PR 19): the EditManager fold as a
# [windows x commits] tensor program
# ---------------------------------------------------------------------------
#
# The host fold (dds/tree/editmanager.py add_sequenced) threads one incoming
# commit c through a peer's inflight window x_0..x_{C-1} via the mirrored
# bridge pair rebase_pair(c, x_i) -> (c', x_i').  Here that whole window is
# ONE lax.scan under jit, vmapped over windows: each commit is a bounded
# path-shaped encoding (interior [Skip(p), Modify] levels as (field, pos)
# pairs + one flat leaf mark list as padded int32 columns), and one pair
# step runs the three rebase phases as masked column passes:
#
#   (1) fate-run decomposition of the "over" side: per-mark consume /
#       produce geometry (in_start/in_end/out_start cumsums, gone and
#       nested-Modify masks) — _b_runs without the Python walk;
#   (2) the collision scan as batched segment intersection: every a-mark's
#       input span against every b-run in one [M, M] overlap table (the
#       per-span Modify-site comparison is the modA & modB & overlap mask);
#   (3) the two-leg bridge fold: both rebase_pair legs (a_after=True for
#       the incoming commit, False for the window entry) emitted from the
#       same atom table by a coalescing scan, with the nonstructural-entry
#       identity short-circuit preserved as a mask — an unchanged span
#       compares columnar-equal and the host reuses the ORIGINAL span
#       object, keeping the span-reuse cache valid.
#
# Object payloads (insert content, nested NodeChanges, detached subtrees)
# never ride the device: every output mark carries a source-index range
# into the ORIGINAL commit's columns (composed across scan steps for the
# carried c), and the host decode re-attaches payloads from those handles.
# Anything the columns cannot express — moves, Modify-vs-Modify payload
# collisions, detached-payload Removes that actually shift, output
# overflow — sets a per-step invalid flag; the host finishes the window on
# the pooled fold (the fuzz oracle), counted in rebase_fallbacks and never
# silent.

REBASE_MAX_MARKS = 12   # M: widest leaf mark list a window entry may carry
REBASE_MAX_DEPTH = 4    # PD: deepest interior [Skip, Modify] path


class RebaseEnc(NamedTuple):
    """Device encoding of one eligible single-change pooled Commit.

    Interior levels 0..dep-1 are exactly [Skip(pos[l]), Modify] chains
    (the nested-commit wire norm); level ``dep`` is the leaf: a flat mark
    list over field ``fld[dep]``, or a value-only NodeChange when
    ``fld[dep] < 0``.  ``val[l]`` flags a value overwrite at level l (the
    value tuples themselves stay host-side).  ``slo/shi`` map each leaf
    mark to its source-index range in the ORIGINAL commit's columns —
    the object-payload handles."""

    dep: jnp.ndarray   # [] int32   number of interior levels
    fld: jnp.ndarray   # [PD+1]     interned field ids; fld[dep] < 0 = value leaf
    pos: jnp.ndarray   # [PD]       interior skip offsets
    val: jnp.ndarray   # [PD+1]     value-present flags
    kind: jnp.ndarray  # [M]        leaf device-coded kinds (0 pads)
    cnt: jnp.ndarray   # [M]        leaf counts (a column)
    det: jnp.ndarray   # [M]        Remove-with-detached flags
    n: jnp.ndarray     # [] int32   live leaf marks
    slo: jnp.ndarray   # [M]        source range lo (original mark index)
    shi: jnp.ndarray   # [M]        source range hi (inclusive)


class _LegOut(NamedTuple):
    kind: jnp.ndarray  # [M] rebased mark kinds
    cnt: jnp.ndarray   # [M]
    lo: jnp.ndarray    # [M] source range into the leg's own input marks
    hi: jnp.ndarray    # [M]
    n: jnp.ndarray     # []
    bad: jnp.ndarray   # [] bool: collision / out-of-order / overflow
    ident: jnp.ndarray  # [] bool: output columnar-equal to the input


def _flat_leg(ak, ac, bk, bc, a_after: bool) -> _LegOut:
    """One bridge leg over flat move-free columns: rebase a over b.

    Byte-matches mark_pool._rebase_cols (itself byte-matched to
    changeset.rebase_marks): fate runs for b, per-a-mark placements, and
    the sorted gap-and-coalesce emission — but as one fixed-shape masked
    program.  ``a_after`` is static (each bridge leg compiles once)."""
    TK = TreeMarkKind
    M = ak.shape[0]
    a_live = ak != TK.NOOP
    b_live = bk != TK.NOOP

    # --- phase 1: fate-run decomposition of b ------------------------------
    consB = jnp.where((bk == TK.SKIP) | (bk == TK.REMOVE), bc,
                      jnp.where(bk == TK.MODIFY, 1, 0))
    prodB = jnp.where((bk == TK.SKIP) | (bk == TK.INSERT), bc,
                      jnp.where(bk == TK.MODIFY, 1, 0))
    inS = jnp.cumsum(consB) - consB
    inE = inS + consB
    outS = jnp.cumsum(prodB) - prodB
    tail_in = jnp.sum(consB)
    tail_out = jnp.sum(prodB)
    goneB = b_live & (bk == TK.REMOVE)
    modB = b_live & (bk == TK.MODIFY)
    runB = b_live & (consB > 0)  # input-consuming runs partition [0, tail_in)

    consA = jnp.where((ak == TK.SKIP) | (ak == TK.REMOVE), ac,
                      jnp.where(ak == TK.MODIFY, 1, 0))
    a_in = jnp.cumsum(consA) - consA

    # --- insert-boundary placement (the sided boundary map) ----------------
    p = a_in[:, None]                                   # [M, 1]
    covB = runB[None, :] & (inS[None, :] < p) & (p <= inE[None, :])
    before_run = jnp.where(goneB[None, :], outS[None, :],
                           outS[None, :] + (p - inS[None, :]))
    has_cov = jnp.any(covB, axis=1)
    before = jnp.sum(jnp.where(covB, before_run, 0), axis=1)
    before = jnp.where(
        a_in == 0, 0,
        jnp.where(has_cov, before, tail_out + (a_in - tail_in)))
    prods_at = jnp.sum(
        jnp.where((bk == TK.INSERT)[None, :] & b_live[None, :]
                  & (inS[None, :] == p), bc[None, :], 0), axis=1)
    bp = before + (prods_at if a_after else 0)

    # --- phase 2: node placement as batched segment intersection -----------
    isnode = a_live & ((ak == TK.REMOVE) | (ak == TK.MODIFY))
    modA = a_live & (ak == TK.MODIFY)
    s_j = a_in[:, None]
    e_j = (a_in + consA)[:, None]
    lo = jnp.maximum(s_j, inS[None, :])
    hi = jnp.minimum(e_j, inE[None, :])
    overlap = runB[None, :] & (hi > lo)
    seg_ok = overlap & isnode[:, None] & ~goneB[None, :]
    seg_pos = outS[None, :] + (lo - inS[None, :])
    seg_cnt = hi - lo
    # Modify-site collision: nested payloads would have to rebase host-side.
    coll = jnp.any(modA[:, None] & modB[None, :] & overlap)
    # tail segment (beyond b's context: implicit trailing skip)
    tlo = jnp.maximum(a_in, tail_in)
    tail_ok = isnode & (e_j[:, 0] > tlo)
    tail_pos = tail_out + (tlo - tail_in)
    tail_cnt = e_j[:, 0] - tlo

    # --- atom table: (a-mark j) x (insert | b-run segs | tail) -------------
    # Row-major (j, slot) order IS the host placement sort order
    # (out positions are monotone in input position; insert-before-node at
    # ties is slot order; an out-of-order placement flags `bad` below).
    NS = M + 2
    atom_ok = jnp.concatenate([
        (a_live & (ak == TK.INSERT))[:, None], seg_ok, tail_ok[:, None]],
        axis=1)
    atom_pos = jnp.concatenate([bp[:, None], seg_pos, tail_pos[:, None]],
                               axis=1)
    atom_cnt = jnp.concatenate([ac[:, None], seg_cnt, tail_cnt[:, None]],
                               axis=1)
    atom_kind = jnp.broadcast_to(ak[:, None], (M, NS))
    atom_src = jnp.broadcast_to(jnp.arange(M, dtype=I32)[:, None], (M, NS))

    flat = lambda x: x.reshape((M * NS,))

    # --- phase 3: coalescing emission as parallel prefix passes ------------
    # The _Builder walk (merge adjacent same-kind marks, write skip gaps)
    # recast without a serial scan: forward-fill each live atom's
    # PREDECESSOR, derive merge/start/skip-gap decisions per atom, take
    # merge-group totals as cumsum differences, then match output slots
    # against atoms in one [M, T] reduction.  Everything is a parallel
    # prefix, a gather, or a small masked sum — no scatters (XLA CPU
    # lowers those to per-index loops) and no serial scan; the kernel's
    # only remaining serial axis is the window fold itself.
    T = M * NS
    ok0 = flat(atom_ok) & (flat(atom_cnt) > 0)
    kk = flat(atom_kind)
    pos_f = flat(atom_pos)
    cnt_f = flat(atom_cnt)
    j_f = flat(atom_src)
    mc = jnp.where(ok0, cnt_f, 0)
    consumed = jnp.where(kk == TK.REMOVE, cnt_f,
                         jnp.where(kk == TK.MODIFY, 1, 0))
    end_f = pos_f + consumed
    ar = jnp.arange(T, dtype=I32)
    # index of the last live atom STRICTLY before each position (-1: none,
    # i.e. the builder's initial state — cursor 0, no pending kind)
    lastok = jax.lax.cummax(jnp.where(ok0, ar, -1))
    prev_idx = jnp.concatenate([jnp.full((1,), -1, I32), lastok[:-1]])
    has_prev = prev_idx >= 0
    safe = jnp.maximum(prev_idx, 0)
    gap = pos_f - jnp.where(has_prev, end_f[safe], 0)
    prev_kind = jnp.where(has_prev, kk[safe], TK.NOOP)
    merge = ok0 & (prev_kind == kk) & (gap == 0) & \
        ((kk == TK.REMOVE) | (kk == TK.INSERT))
    start = ok0 & ~merge
    wskip = start & (gap > 0)
    grp = jnp.cumsum(start.astype(I32))   # 1-based merge-group ids
    nsk = jnp.cumsum(wskip.astype(I32))   # skips emitted up to here
    # merge groups are contiguous atom ranges: group totals fall out of
    # inclusive cumsums between a start atom and the next start
    csum = jnp.cumsum(mc)
    nsa = jax.lax.cummin(jnp.where(start, ar, T), reverse=True)
    gend = jnp.minimum(jnp.concatenate([nsa[1:], jnp.full((1,), T, I32)]) - 1,
                       T - 1)
    gsum = csum[gend] - csum + mc              # group cnt total (at starts)
    ghi = jax.lax.cummax(jnp.where(ok0, j_f, -1))[gend]  # last source j
    # output slots: group g's mark lands after g-1 marks and every skip
    # gap at or before its start atom; its own gap skip sits one before
    slot = grp - 1 + nsk
    out_n = grp[-1] + nsk[-1]
    # slot is monotone and only jumps at start atoms (by 2 over a skip
    # gap), so each output slot s resolves to one atom by binary search:
    # an exact hit is that slot's mark; an s+1 hit means s is the skip
    # gap written just before that mark.
    srange = jnp.arange(M, dtype=I32)
    hit = jnp.minimum(jnp.searchsorted(slot, srange, side="left"), T - 1)
    sl = slot[hit]
    is_mark = start[hit] & (sl == srange)
    is_skip = wskip[hit] & (sl == srange + 1)
    ok_k = jnp.where(is_mark, kk[hit], jnp.where(is_skip, TK.SKIP, 0))
    ok_c = jnp.where(is_mark, gsum[hit], jnp.where(is_skip, gap[hit], 0))
    ok_lo = jnp.where(is_mark, j_f[hit], 0)    # first source j of the group
    ok_hi = jnp.where(is_mark, ghi[hit], 0)
    bad = coll | jnp.any(ok0 & (gap < 0)) | (out_n > M)
    a_n = jnp.sum(a_live.astype(I32))
    ident = (out_n == a_n) & jnp.all(ok_k == ak) & jnp.all(ok_c == ac)
    return _LegOut(ok_k, ok_c, ok_lo, ok_hi, out_n, bad, ident)


def _synth_interior(p):
    """[Skip(p), Modify] (or [Modify] at p == 0) as padded columns."""
    TK = TreeMarkKind
    M = REBASE_MAX_MARKS
    k0 = jnp.where(p > 0, TK.SKIP, TK.MODIFY)
    k1 = jnp.where(p > 0, TK.MODIFY, TK.NOOP)
    kind = jnp.zeros((M,), I32).at[0].set(k0).at[1].set(k1)
    cnt = jnp.zeros((M,), I32).at[0].set(jnp.where(p > 0, p, 1)) \
        .at[1].set(jnp.where(p > 0, 1, 0))
    return kind, cnt


class RebaseStepOut(NamedTuple):
    valid: jnp.ndarray   # [] this step's device result is usable
    id_c: jnp.ndarray    # [] c came through bit-identical
    id_x: jnp.ndarray    # [] x came through bit-identical
    x: "RebaseEnc"       # rebased window entry (src into its own marks)
    stage: "RebaseEnc"   # c after this step (src into the ORIGINAL c)
    x_drop: jnp.ndarray  # [PD+1] value-LWW drops applied to x


def _pair_step(c: RebaseEnc, x: RebaseEnc, elig):
    """One mirrored bridge pair rebase_pair(c, x) on encodings.

    Walks the common interior path to the divergence level, then either
    short-circuits (disjoint fields / positions / value-only leaves — the
    identity mask) or runs both flat legs at the diverging field.  Returns
    (c', step outputs, step_ok)."""
    TK = TreeMarkKind
    PD = REBASE_MAX_DEPTH
    li = jnp.arange(PD, dtype=I32)
    match = (li < c.dep) & (li < x.dep) & (c.fld[:PD] == x.fld[:PD]) & \
        (c.pos == x.pos)
    lstar = jnp.sum(jnp.cumprod(match.astype(I32)))
    c_int = lstar < c.dep
    x_int = lstar < x.dep
    f_c = c.fld[lstar]
    f_x = x.fld[lstar]
    case_d = (f_c < 0) | (f_x < 0)
    case_a = ~case_d & (f_c != f_x)
    engage = ~case_d & ~case_a & ~(c_int & x_int)  # flat pair runs

    # flat lists at the divergence level (interior side synthesized)
    sk_c, sc_c = _synth_interior(c.pos[jnp.minimum(lstar, PD - 1)])
    sk_x, sc_x = _synth_interior(x.pos[jnp.minimum(lstar, PD - 1)])
    Ak = jnp.where(c_int, sk_c, c.kind)
    Ac = jnp.where(c_int, sc_c, c.cnt)
    Bk = jnp.where(x_int, sk_x, x.kind)
    Bc = jnp.where(x_int, sc_x, x.cnt)

    legC = _flat_leg(Ak, Ac, Bk, Bc, a_after=True)
    legX = _flat_leg(Bk, Bc, Ak, Ac, a_after=False)

    # detached-payload Removes may pass through untouched, never transform
    det_c = ~c_int & jnp.any(c.det > 0) & ~legC.ident
    det_x = ~x_int & jnp.any(x.det > 0) & ~legX.ident
    step_bad = engage & (legC.bad | legX.bad | det_c | det_x)
    step_ok = elig & ~step_bad

    # value LWW along the shared spine (levels 0..lstar)
    lvl = jnp.arange(PD + 1, dtype=I32)
    drop_x = (c.val > 0) & (x.val > 0) & (lvl <= lstar)

    # interior fate: did the synthesized Modify survive, and where?
    surv_c = jnp.any((legC.kind == TK.MODIFY) & (jnp.arange(REBASE_MAX_MARKS)
                                                 < legC.n))
    surv_x = jnp.any((legX.kind == TK.MODIFY) & (jnp.arange(REBASE_MAX_MARKS)
                                                 < legX.n))
    npos_c = jnp.where(legC.kind[0] == TK.SKIP, legC.cnt[0], 0)
    npos_x = jnp.where(legX.kind[0] == TK.SKIP, legX.cnt[0], 0)

    def rebuild(side: RebaseEnc, leg: _LegOut, is_int, surv, npos, drops):
        # interior side: position update or truncation to an empty leaf
        t_dep = jnp.where(is_int & ~surv, lstar, side.dep)
        t_pos = jnp.where(is_int & surv & (li == lstar), npos, side.pos)
        t_val = jnp.where((lvl <= t_dep) & ~drops, side.val, 0)
        # leaf side: the leg output with composed source ranges
        glo = side.slo[leg.lo]
        ghi = side.shi[leg.hi]
        live = jnp.arange(REBASE_MAX_MARKS) < leg.n
        leaf = ~is_int
        t_kind = jnp.where(leaf, jnp.where(live, leg.kind, 0), side.kind)
        t_cnt = jnp.where(leaf, jnp.where(live, leg.cnt, 0), side.cnt)
        t_det = jnp.where(leaf, jnp.where(
            live & (leg.kind == TK.REMOVE), side.det[leg.lo], 0), side.det)
        t_n = jnp.where(leaf, leg.n, jnp.where(is_int & ~surv, 0, side.n))
        t_slo = jnp.where(leaf, jnp.where(live, glo, 0), side.slo)
        t_shi = jnp.where(leaf, jnp.where(live, ghi, 0), side.shi)
        # truncated interior: empty leaf at lstar over the same field
        t_kind = jnp.where(is_int & ~surv, 0, t_kind)
        t_cnt = jnp.where(is_int & ~surv, 0, t_cnt)
        t_det = jnp.where(is_int & ~surv, 0, t_det)
        t_slo = jnp.where(is_int & ~surv, 0, t_slo)
        t_shi = jnp.where(is_int & ~surv, 0, t_shi)
        return RebaseEnc(t_dep, side.fld, t_pos, t_val, t_kind, t_cnt,
                         t_det, t_n, t_slo, t_shi)

    changed_c = engage & jnp.where(c_int, ~(surv_c & (npos_c == c.pos[
        jnp.minimum(lstar, PD - 1)])), ~legC.ident)
    changed_x = engage & jnp.where(x_int, ~(surv_x & (npos_x == x.pos[
        jnp.minimum(lstar, PD - 1)])), ~legX.ident)

    new_c = rebuild(c, legC, c_int, surv_c, npos_c,
                    jnp.zeros((PD + 1,), jnp.bool_))
    new_x = rebuild(x, legX, x_int, surv_x, npos_x, drop_x)

    apply_c = step_ok & engage & changed_c
    pick = lambda f, a, b: jax.tree_util.tree_map(
        lambda u, v: jnp.where(f, u, v), a, b)
    out_c = pick(apply_c, new_c, c)
    # x's value drops apply in EVERY case; marks only when the pair engaged
    base_x = RebaseEnc(x.dep, x.fld, x.pos,
                       jnp.where(drop_x, 0, x.val), x.kind, x.cnt, x.det,
                       x.n, x.slo, x.shi)
    apply_x = step_ok & engage & changed_x
    out_x = pick(apply_x, new_x, base_x)

    any_drop = jnp.any(drop_x & (x.val > 0))
    id_c = step_ok & ~(engage & changed_c)
    id_x = step_ok & ~(engage & changed_x) & ~any_drop
    return out_c, RebaseStepOut(step_ok, id_c, id_x, out_x, out_c,
                                drop_x.astype(I32)), step_ok


def rebase_window_kernel(c: RebaseEnc, xs: RebaseEnc, elig: jnp.ndarray):
    """Fold one incoming commit through a whole inflight window on device.

    ``xs`` fields carry a leading [C] axis; ``elig[i]`` gates each step
    (host pads windows and marks host-only entries ineligible).  Prefix
    validity: the first bad/ineligible step kills every later step's
    ``valid`` bit — the host finishes the suffix on the pooled fold.
    Returns (final c encoding, per-step RebaseStepOut stack)."""

    def step(carry, inp):
        cc, dead = carry
        x, el = inp
        nc, out, ok = _pair_step(cc, x, el & ~dead)
        dead = dead | ~ok
        return (nc, dead), out

    (final_c, _dead), outs = jax.lax.scan(
        step, (c, jnp.asarray(False)), (xs, elig.astype(jnp.bool_)))
    return final_c, outs


# One compiled program per (C,) window bucket; the W axis is vmapped so
# thousands of windows ride one dispatch (bench config5's microbench).
rebase_window_jit = jax.jit(rebase_window_kernel)
rebase_window_batched = jax.jit(jax.vmap(rebase_window_kernel))


def rebase_flat_pair_kernel(ak, ac, bk, bc):
    """Both bridge legs of one flat pair (differential-test surface)."""
    return (_flat_leg(ak, ac, bk, bc, a_after=True),
            _flat_leg(bk, bc, ak, ac, a_after=False))
