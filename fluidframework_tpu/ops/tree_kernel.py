"""Batched SharedTree kernels: rebase position arithmetic + chunk updates.

Reference parity: the hot paths of SharedTree sequenced-edit integration —
EditManager rebase (tree/src/shared-tree-core/editManager.ts:542,808, the
per-commit sequence-field mark transforms in feature-libraries/
sequence-field/) and chunked-forest value updates
(feature-libraries/chunked-forest/uniformChunk.ts:42).

TPU design, not a port: the host algebra (dds/tree/changeset.py) walks mark
lists; on device a changeset over one field is a fixed-width columnar
encoding (kinds[M], counts[M]), and rebasing a BATCH of pending edits over
it is pure broadcast arithmetic — for every query position, the net shift
is "inserts at-or-before minus removed-below", computed as an [B, M]
masked reduction with no data-dependent control flow. The same sided
tie-break contract as the host algebra (changeset.py rebase_marks) is a
single >= / > mask choice, so host and device stay bit-identical (enforced
by tests/test_tree_kernel.py differential fuzz).

Shapes: D docs × M marks × B query positions; everything int32; vmap/
shard_map over the doc axis is the scale-out path (documents are the
embarrassing axis, SURVEY §2.6.2).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32


class TreeMarkKind:
    NOOP = 0   # padding
    SKIP = 1
    INSERT = 2
    REMOVE = 3
    MODIFY = 4


def encode_marks(marks, max_marks: int) -> tuple[np.ndarray, np.ndarray]:
    """Columnar encode a host mark list (changeset.py Mark objects) to
    (kinds[M], counts[M]) int32 arrays. Insert counts are content lengths."""
    from ..dds.tree.changeset import Insert, Modify, Remove, Skip

    kinds = np.zeros((max_marks,), np.int32)
    counts = np.zeros((max_marks,), np.int32)
    assert len(marks) <= max_marks, "mark list exceeds kernel width"
    for i, m in enumerate(marks):
        if isinstance(m, Skip):
            kinds[i], counts[i] = TreeMarkKind.SKIP, m.count
        elif isinstance(m, Insert):
            kinds[i], counts[i] = TreeMarkKind.INSERT, len(m.content)
        elif isinstance(m, Remove):
            kinds[i], counts[i] = TreeMarkKind.REMOVE, m.count
        elif isinstance(m, Modify):
            kinds[i], counts[i] = TreeMarkKind.MODIFY, 1
        else:
            raise TypeError(m)
    return kinds, counts


def _mark_geometry(kinds: jnp.ndarray, counts: jnp.ndarray):
    """Per-mark input-space start offsets and effect sizes.

    input-consuming marks: SKIP/REMOVE consume `count`, MODIFY consumes 1,
    INSERT consumes 0. Returns (in_start[M], ins_len[M], rm_len[M])."""
    consumed = jnp.where(
        (kinds == TreeMarkKind.SKIP) | (kinds == TreeMarkKind.REMOVE),
        counts,
        jnp.where(kinds == TreeMarkKind.MODIFY, 1, 0),
    )
    in_start = jnp.cumsum(consumed) - consumed
    ins_len = jnp.where(kinds == TreeMarkKind.INSERT, counts, 0)
    rm_len = jnp.where(kinds == TreeMarkKind.REMOVE, counts, 0)
    return in_start, ins_len, rm_len


def rebase_insert_positions(
    positions: jnp.ndarray,  # int32[B] insert positions (boundary coords)
    b_kinds: jnp.ndarray,    # int32[M]
    b_counts: jnp.ndarray,   # int32[M]
    a_after: bool,
) -> jnp.ndarray:
    """Where does each pending INSERT land after change b applies?

    Mirrors rebase_marks for a = [Skip(p), Insert(..)]: b's removes pull the
    boundary to the range start; b's inserts at the same boundary shift the
    pending insert right iff the pending one is the later-sequenced side
    (a_after=True, the >= mask) — the host tie-break contract."""
    in_start, ins_len, rm_len = _mark_geometry(b_kinds, b_counts)
    p = positions[:, None]  # [B, 1]
    # Removal below the boundary: overlap of [in_start, in_start+rm) with [0, p).
    rm_below = jnp.clip(p - in_start, 0, rm_len[None, :])  # [B, M]
    # b-insert shift: at the same post-removal boundary the earlier-sequenced
    # content stays left for the later side.
    ins_at = in_start[None, :]
    shift = jnp.where(
        (p >= ins_at) if a_after else (p > ins_at), ins_len[None, :], 0
    )
    return positions + jnp.sum(shift, axis=1) - jnp.sum(rm_below, axis=1)


def rebase_node_positions(
    positions: jnp.ndarray,  # int32[B] node indices (modify/remove-1 targets)
    b_kinds: jnp.ndarray,
    b_counts: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Where does each targeted NODE land after change b — and does it
    survive? Mirrors rebase_marks for a = [Skip(p), Modify/Remove(1)]:
    a node inside a b-removed range is dropped (mask 0)."""
    in_start, ins_len, rm_len = _mark_geometry(b_kinds, b_counts)
    p = positions[:, None]
    rm_below = jnp.clip(p - in_start, 0, rm_len[None, :])
    # Node positions: a b-insert AT the node's index lands before it (the
    # node's content moves right) — always the >= mask for occupied slots.
    shift = jnp.where(p >= in_start[None, :], ins_len[None, :], 0)
    dropped = jnp.any(
        (rm_len[None, :] > 0) & (p >= in_start[None, :]) & (p < (in_start + rm_len)[None, :]),
        axis=1,
    )
    out = positions + jnp.sum(shift, axis=1) - jnp.sum(rm_below, axis=1)
    return out, (~dropped).astype(I32)


# ---------------------------------------------------------------------------
# Uniform-chunk value updates (the columnar forest hot path)
# ---------------------------------------------------------------------------


class ChunkState(NamedTuple):
    """One numeric column of a uniform chunk, with per-row attribution."""

    values: jnp.ndarray   # int32[N]
    val_seq: jnp.ndarray  # int32[N] seq of winning write


def init_chunk(values: np.ndarray) -> ChunkState:
    v = jnp.asarray(values, I32)
    return ChunkState(values=v, val_seq=jnp.zeros_like(v))


def apply_value_sets(
    s: ChunkState,
    idx: jnp.ndarray,   # int32[B] row indices (< 0 = padding)
    vals: jnp.ndarray,  # int32[B]
    seqs: jnp.ndarray,  # int32[B] distinct, > 0 (sequence order of the writes)
) -> ChunkState:
    """Apply a sequenced batch of value overwrites in ONE scatter pass: for
    rows hit multiple times the highest-seq write wins (LWW by total order),
    matching sequential host application exactly.

    Determinism: duplicate-index ``set`` scatters have unspecified order, so
    the winner per row is picked first with a commutative scatter-MAX of
    seqs, and only winning lanes scatter values. Padding lanes (idx < 0) are
    routed out of bounds HIGH (negative indices wrap in XLA, N drops)."""
    n = s.values.shape[0]
    valid = idx >= 0
    safe_idx = jnp.where(valid, idx, n)  # n = dropped by mode="drop"
    best = jnp.zeros((n,), I32).at[safe_idx].max(
        jnp.where(valid, seqs, 0), mode="drop"
    )
    win = valid & (seqs == best[jnp.where(valid, idx, 0)])
    win_idx = jnp.where(win, idx, n)
    values = s.values.at[win_idx].set(vals, mode="drop")
    val_seq = s.val_seq.at[win_idx].set(seqs, mode="drop")
    return ChunkState(values=values, val_seq=val_seq)


def batched_value_engine(n_docs: int):
    """The D-doc batched form: vmap of apply_value_sets over the doc axis —
    the tree analog of the merge-tree doc-batch engine (document sharding is
    the primary parallel axis, SURVEY §2.6.2)."""
    return jax.jit(jax.vmap(apply_value_sets))


# ---------------------------------------------------------------------------
# Columnar forest: a uniform chunk as mutable device state
# ---------------------------------------------------------------------------
# The reference's UniformChunk (chunked-forest/uniformChunk.ts:42) stores a
# shape-uniform subtree as columnar value arrays.  ForestState is that idea
# as REPLICA STATE: one document's root field of uniform leaf nodes, living
# on device, mutated by sequenced trunk-coordinate changesets.  Structural
# edits are index-map gathers (no data-dependent loops); a batch of D docs
# is vmap over the leading axis (models/tree_batch_engine.py).

# Forest op row layout (int32[8]):
#   0 kind | 1 seq | 2 pos | 3 count | 4 dst | 5 value | 6..7 unused
FOREST_OP_FIELDS = 8

ERR_NODE_OVERFLOW = 1
ERR_FOREST_RANGE = 2


class ForestOpKind:
    NOOP = 0
    INSERT = 1   # count nodes at pos, values from the payload row
    REMOVE = 2   # count nodes at pos
    SET = 3      # value at pos
    MOVE = 4     # count nodes from pos to boundary dst (pre-move coords)


class ForestState(NamedTuple):
    values: jnp.ndarray   # int32[N] leaf value column
    val_seq: jnp.ndarray  # int32[N] seq of last write (attribution)
    nnode: jnp.ndarray    # int32 scalar live node count
    error: jnp.ndarray    # int32 scalar bitmask


def init_forest(capacity: int = 1024) -> ForestState:
    return ForestState(
        values=jnp.zeros((capacity,), I32),
        val_seq=jnp.zeros((capacity,), I32),
        nnode=jnp.zeros((), I32),
        error=jnp.zeros((), I32),
    )


def _forest_gather(s: ForestState, src: jnp.ndarray, n_new) -> ForestState:
    """Rebuild the columns through a source-index map (-1 = fresh slot,
    filled by the caller afterwards)."""
    safe = jnp.clip(src, 0, s.values.shape[0] - 1)
    take = src >= 0
    return s._replace(
        values=jnp.where(take, s.values[safe], 0),
        val_seq=jnp.where(take, s.val_seq[safe], 0),
        nnode=n_new,
    )


def apply_forest_op(s: ForestState, op: jnp.ndarray, payload: jnp.ndarray) -> ForestState:
    """Apply one trunk-coordinate structural/value op to one document."""
    kind, seq, pos, count, dst, value = op[0], op[1], op[2], op[3], op[4], op[5]
    N = s.values.shape[0]
    idx = jnp.arange(N, dtype=I32)
    n = s.nnode

    def do_noop(s):
        return s

    def do_insert(s):
        over = n + count > N
        bad = pos > n
        ok = ~(over | bad)
        src = jnp.where(idx < pos, idx, jnp.where(idx < pos + count, -1, idx - count))
        out = _forest_gather(s, src, n + count)
        fresh = (idx >= pos) & (idx < pos + count)
        pay = payload[jnp.clip(idx - pos, 0, payload.shape[0] - 1)]
        return jax.lax.cond(
            ok,
            lambda _: out._replace(
                values=jnp.where(fresh, pay, out.values),
                val_seq=jnp.where(fresh, seq, out.val_seq),
            ),
            lambda _: s._replace(
                error=s.error
                | jnp.where(over, ERR_NODE_OVERFLOW, 0)
                | jnp.where(bad, ERR_FOREST_RANGE, 0)
            ),
            None,
        )

    def do_remove(s):
        bad = pos + count > n
        src = jnp.where(idx < pos, idx, idx + count)
        out = _forest_gather(s, src, n - count)
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: out,
            None,
        )

    def do_set(s):
        bad = pos >= n
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: s._replace(
                values=s.values.at[pos].set(value),
                val_seq=s.val_seq.at[pos].set(seq),
            ),
            None,
        )

    def do_move(s):
        # Move [pos, pos+count) to pre-move boundary dst: compose the
        # remove map with the insert map (dst' = post-remove boundary).
        bad = (pos + count > n) | (dst > n)
        dstp = jnp.where(dst > pos + count, dst - count, jnp.minimum(dst, pos))
        # For each output slot: inside the landed block -> moved source;
        # else the surviving nodes in order (skip the moved range).
        in_block = (idx >= dstp) & (idx < dstp + count)
        u = jnp.where(idx < dstp, idx, idx - count)      # rank among survivors
        surv = jnp.where(u < pos, u, u + count)          # survivor rank -> old idx
        src = jnp.where(in_block, pos + (idx - dstp), surv)
        out = _forest_gather(s, src, n)
        return jax.lax.cond(
            bad,
            lambda _: s._replace(error=s.error | ERR_FOREST_RANGE),
            lambda _: out,
            None,
        )

    return jax.lax.switch(
        kind, [do_noop, do_insert, do_remove, do_set, do_move], s
    )


def apply_forest_ops(
    s: ForestState, ops: jnp.ndarray, payloads: jnp.ndarray
) -> ForestState:
    """Apply a [B]-op batch to one document in order (lax.scan); batch over
    documents with vmap (the doc axis is the parallel one)."""

    def step(carry, xs):
        op, payload = xs
        return apply_forest_op(carry, op, payload), None

    out, _ = jax.lax.scan(step, s, (ops, payloads))
    return out


def forest_values(s: ForestState) -> np.ndarray:
    """Host view of the live value column."""
    n = int(s.nnode)
    return np.asarray(s.values)[:n]
