"""Columnar merge-tree kernel: sequenced-op application as tensor ops.

This is the TPU-native replacement for the reference's merge-tree apply path
(merge-tree/src/client.ts Client.applyMsg -> mergeTree.ts insertSegments /
markRangeRemoved / annotateRange + blockUpdatePathLengths).  The reference
maintains a B-tree of segments with per-block PartialSequenceLengths so CPU
position resolution is O(log n); here the segment store is a flat SoA of
int32 arrays and every position query is a perspective-masked prefix sum —
O(S) work but fully data-parallel on the VPU, and `vmap`-able over a
document axis so one device step applies ops for thousands of docs.

Semantics are bit-identical to ``dds/mergetree_ref.py`` (the oracle), which
itself mirrors the reference:

- visibility = hasOccurred(insert) && !any(hasOccurred(remove_r))
- insert boundary tie-break = reference breakTie (mergeTree.ts:1811)
- overlapping removes kept in R slots per segment (reference seg.removes)
- annotate per-(segment, prop) LWW by stamp key
- ack rewrites pending stamp keys (localSeq -> seq) in place

Design notes (TPU):

- All state is int32, and every per-segment array is 1-D over the segment
  axis ([S], so [D, S] after vmap).  The R remove slots and P prop slots are
  tuples of such arrays rather than [S,R]/[R,S] matrices: trailing dims of
  2-8 get lane-padded to 128 on TPU (16-64x physical blowup), and XLA's
  layout assignment can pick the small axis as minor even for [R,S].  Tuples
  of 2-D-after-vmap leaves make every layout trivially optimal.
- Within one document, ops are inherently sequential (each op's position
  depends on prior ops); `lax.scan` applies an op batch per doc.  The
  document axis supplies the parallelism (`vmap`, sharded by `shard_map`).
- Mutation = masked gather/select: inserting a segment shifts the suffix of
  every per-segment array by one slot (a vectorized O(S) move, not a
  data-dependent loop).
- Capacity overflow (segments, text pool, remove slots) sets an error bit
  instead of trapping; the host inspects error flags and reacts (grow +
  re-replay, or route the doc to the host oracle).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..protocol.stamps import ALL_ACKED, LOCAL_BASE, NO_REMOVE

I32 = jnp.int32

# Error flag bits.
ERR_SEG_OVERFLOW = 1
ERR_TEXT_OVERFLOW = 2
ERR_REM_OVERFLOW = 4
ERR_POS_RANGE = 8
ERR_OB_OVERFLOW = 16

# Error lanes (host recovery policy dispatch): capacity bits are recoverable
# by growing the implicated axis and replaying; anything else (today only
# ERR_POS_RANGE alone) means the op stream itself is malformed — growing
# cannot fix it, the document must leave the device batch (quarantine).
ERR_CAPACITY_MASK = (
    ERR_SEG_OVERFLOW | ERR_TEXT_OVERFLOW | ERR_REM_OVERFLOW | ERR_OB_OVERFLOW
)


def is_capacity_error(bits: int) -> bool:
    """True iff the latched bits are recoverable by growth + replay.
    ERR_POS_RANGE *alongside* a capacity bit is usually a cascade (an op
    referencing content a capacity overflow dropped), which replay at
    grown capacity resolves — so any capacity bit keeps the doc on the
    grow lane."""
    return bits != 0 and (bits & ERR_CAPACITY_MASK) != 0


def is_poison_error(bits: int) -> bool:
    """True iff the bits indicate a malformed op stream (quarantine lane)."""
    return bits != 0 and (bits & ERR_CAPACITY_MASK) == 0

# Obliterate endpoint sides (ref sequencePlace.ts Side; mergetree_ref.py).
SIDE_BEFORE = 0
SIDE_AFTER = 1


class OpKind:
    NOOP = 0
    INSERT = 1
    REMOVE = 2
    ANNOTATE = 3
    ACK = 4
    OBLITERATE = 5  # always sided: plain {pos1,pos2} encodes as (pos1,B)..(pos2-1,A)


# Op row layout (int32[OP_FIELDS]):
#   0 kind | 1 key | 2 client | 3 ref_seq | 4 pos1 | 5 pos2 | 6 a | 7 b
# a/b meaning per kind: INSERT a=text_len, REMOVE -, ANNOTATE a=prop_slot
# b=value, ACK a=local_seq b=seq, OBLITERATE a=side1 b=side2 (pos1/pos2 are
# the endpoint CHARACTER positions, already in sided form).
OP_FIELDS = 8


class DocState(NamedTuple):
    """SoA replica state for one document (or [D, ...] for a doc batch)."""

    text: jnp.ndarray         # int32[T] codepoint pool (append-only)
    text_end: jnp.ndarray     # int32 scalar
    nseg: jnp.ndarray         # int32 scalar: live segment count
    seg_start: jnp.ndarray    # int32[S] offset into text pool
    seg_len: jnp.ndarray      # int32[S]
    ins_key: jnp.ndarray      # int32[S] insert stamp key
    ins_client: jnp.ndarray   # int32[S] insert short client id
    seg_uid: jnp.ndarray      # int32[S] stable identity (obliterate anchors)
    seg_obpre: jnp.ndarray    # int32[S] newest concurrent ob key at insert (-1)
    rem_keys: tuple           # R x int32[S] remove stamp keys (NO_REMOVE empty)
    rem_clients: tuple        # R x int32[S]
    prop_keys: tuple          # P x int32[S] LWW stamp key per prop (-1 unset)
    prop_vals: tuple          # P x int32[S]
    uid_next: jnp.ndarray     # int32 scalar
    # Obliterate window table (ref MergeTree.obliterates): OB slots, key=-1
    # free.  Anchors reference segments by uid; sides follow mergetree_ref.
    ob_key: jnp.ndarray       # int32[OB]
    ob_client: jnp.ndarray    # int32[OB]
    ob_start_uid: jnp.ndarray  # int32[OB]
    ob_end_uid: jnp.ndarray    # int32[OB]
    ob_start_side: jnp.ndarray  # int32[OB]
    ob_end_side: jnp.ndarray    # int32[OB]
    ob_ref_seq: jnp.ndarray     # int32[OB] refSeq the obliterate was issued at
    min_seq: jnp.ndarray      # int32 scalar (collab-window floor)
    error: jnp.ndarray        # int32 scalar bitmask


def init_state(
    max_segments: int = 512,
    remove_slots: int = 4,
    prop_slots: int = 4,
    text_capacity: int = 8192,
    ob_slots: int = 8,
) -> DocState:
    S, R, P, T, OB = max_segments, remove_slots, prop_slots, text_capacity, ob_slots
    return DocState(
        text=jnp.zeros((T,), I32),
        text_end=jnp.zeros((), I32),
        nseg=jnp.zeros((), I32),
        seg_start=jnp.zeros((S,), I32),
        seg_len=jnp.zeros((S,), I32),
        ins_key=jnp.zeros((S,), I32),
        ins_client=jnp.full((S,), -1, I32),
        seg_uid=jnp.full((S,), -1, I32),
        seg_obpre=jnp.full((S,), -1, I32),
        rem_keys=tuple(jnp.full((S,), NO_REMOVE, I32) for _ in range(R)),
        rem_clients=tuple(jnp.full((S,), -1, I32) for _ in range(R)),
        prop_keys=tuple(jnp.full((S,), -1, I32) for _ in range(P)),
        prop_vals=tuple(jnp.zeros((S,), I32) for _ in range(P)),
        uid_next=jnp.zeros((), I32),
        ob_key=jnp.full((OB,), -1, I32),
        ob_client=jnp.full((OB,), -1, I32),
        ob_start_uid=jnp.full((OB,), -1, I32),
        ob_end_uid=jnp.full((OB,), -1, I32),
        ob_start_side=jnp.zeros((OB,), I32),
        ob_end_side=jnp.zeros((OB,), I32),
        ob_ref_seq=jnp.full((OB,), -1, I32),
        min_seq=jnp.zeros((), I32),
        error=jnp.zeros((), I32),
    )


def make_noop(op_fields: int = OP_FIELDS) -> np.ndarray:
    return np.zeros((op_fields,), np.int32)


def encode_insert(
    pos: int,
    text: str,
    op_key: int,
    op_client: int,
    ref_seq: int,
    max_insert_len: int,
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Encode one insert as (op_row, payload) pairs, chunking long text.

    Chunks share the op's stamp and are emitted BACK-TO-FRONT, all at
    ``pos``: with the >=-tiebreak each later-emitted chunk lands immediately
    before the previously placed one, whether that one is alive or was
    swallowed by a concurrent obliterate — so the final order is the
    original text order, equivalent to the reference's single unbounded
    segment.  This is THE insert encoding; every ingest path must use it so
    chunk placement can never diverge between host adapters.
    """
    out: list[tuple[np.ndarray, np.ndarray]] = []
    for i in reversed(range(0, len(text), max_insert_len)):
        chunk = text[i : i + max_insert_len]
        payload = np.zeros((max_insert_len,), np.int32)
        payload[: len(chunk)] = [ord(ch) for ch in chunk]
        op = np.array(
            [OpKind.INSERT, op_key, op_client, ref_seq, pos, 0, len(chunk), 0],
            np.int32,
        )
        out.append((op, payload))
    return out


def encode_obliterate(
    pos1: int,
    side1: int,
    pos2: int,
    side2: int,
    op_key: int,
    op_client: int,
    ref_seq: int,
) -> np.ndarray:
    """Encode a sided obliterate op row.  The plain wire form {pos1, pos2}
    encodes as ``encode_obliterate(pos1, SIDE_BEFORE, pos2-1, SIDE_AFTER)``."""
    return np.array(
        [OpKind.OBLITERATE, op_key, op_client, ref_seq, pos1, pos2, side1, side2],
        np.int32,
    )


def encode_insert_batch(
    pos: np.ndarray,
    texts: list[str],
    op_keys: np.ndarray,
    op_clients: np.ndarray,
    ref_seqs: np.ndarray,
    max_insert_len: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized ``encode_insert`` over N wire inserts at once.

    Returns ``(ops[M, OP_FIELDS], payloads[M, L], owner[M])`` where M is
    the total chunk-row count and ``owner[i]`` is the input index each row
    came from.  Row-for-row identical to mapping ``encode_insert`` over
    the inputs — including the back-to-front chunk emission order for
    texts longer than one payload row (see ``encode_insert``: this IS the
    insert encoding; chunk placement must never diverge between paths) —
    but the whole batch costs two array builds and one codepoint scatter
    instead of per-op numpy allocations and per-char Python loops.
    """
    n = len(texts)
    L = max_insert_len
    lens = np.fromiter((len(t) for t in texts), np.int64, n)
    nchunks = -(-lens // L)  # empty text -> 0 rows, matching encode_insert
    m = int(nchunks.sum())
    ops = np.zeros((m, OP_FIELDS), np.int32)
    payloads = np.zeros((m, L), np.int32)
    owner = np.repeat(np.arange(n), nchunks)
    if m == 0:
        return ops, payloads, owner
    # Chunk index within each message, in EMISSION order (back-to-front):
    # row k of message i covers text[(nchunks[i]-1-k)*L :].
    row0 = np.concatenate(([0], np.cumsum(nchunks)[:-1]))
    local = np.arange(m) - np.repeat(row0, nchunks)
    chunk_idx = np.repeat(nchunks, nchunks) - 1 - local
    chunk_start = chunk_idx * L
    chunk_len = np.minimum(L, np.repeat(lens, nchunks) - chunk_start)
    ops[:, 0] = OpKind.INSERT
    ops[:, 1] = np.repeat(np.asarray(op_keys, np.int64), nchunks)
    ops[:, 2] = np.repeat(np.asarray(op_clients, np.int64), nchunks)
    ops[:, 3] = np.repeat(np.asarray(ref_seqs, np.int64), nchunks)
    ops[:, 4] = np.repeat(np.asarray(pos, np.int64), nchunks)
    ops[:, 6] = chunk_len
    # One utf-32 decode covers every codepoint in the batch; each chunk
    # row is a scatter from the flat pool.
    codes = np.frombuffer(
        "".join(texts).encode("utf-32-le"), dtype=np.uint32
    ).astype(np.int32)
    text_off = np.concatenate(([0], np.cumsum(lens)[:-1]))
    src_base = np.repeat(text_off, nchunks) + chunk_start
    row = np.repeat(np.arange(m), chunk_len)
    within = np.arange(int(chunk_len.sum())) - np.repeat(
        np.concatenate(([0], np.cumsum(chunk_len)[:-1])), chunk_len
    )
    payloads[row, within] = codes[np.repeat(src_base, chunk_len) + within]
    return ops, payloads, owner


def encode_obliterate_batch(
    pos1: np.ndarray,
    side1: np.ndarray,
    pos2: np.ndarray,
    side2: np.ndarray,
    op_keys: np.ndarray,
    op_clients: np.ndarray,
    ref_seqs: np.ndarray,
) -> np.ndarray:
    """Vectorized ``encode_obliterate``: N sided obliterates -> ops[N, 8]."""
    n = len(op_keys)
    ops = np.empty((n, OP_FIELDS), np.int32)
    ops[:, 0] = OpKind.OBLITERATE
    ops[:, 1] = op_keys
    ops[:, 2] = op_clients
    ops[:, 3] = ref_seqs
    ops[:, 4] = pos1
    ops[:, 5] = pos2
    ops[:, 6] = side1
    ops[:, 7] = side2
    return ops


def _any_tree(masks) -> jnp.ndarray:
    return functools.reduce(jnp.logical_or, masks)


def _min_tree(arrays) -> jnp.ndarray:
    return functools.reduce(jnp.minimum, arrays)


# --------------------------------------------------------------------------
# Visibility / geometry primitives
# --------------------------------------------------------------------------

def _alive(s: DocState) -> jnp.ndarray:
    return jnp.arange(s.seg_len.shape[0], dtype=I32) < s.nseg


def _visible(s: DocState, ref_seq, client) -> jnp.ndarray:
    """Perspective mask over segments (ref perspective.ts isSegmentPresent)."""
    ins_occ = (s.ins_key <= ref_seq) | (s.ins_client == client)
    rem_occ = _any_tree(
        [(k <= ref_seq) | (c == client) for k, c in zip(s.rem_keys, s.rem_clients)]
    )
    return _alive(s) & ins_occ & ~rem_occ


def _vis_lengths(s: DocState, vis: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    vlen = jnp.where(vis, s.seg_len, 0)
    excl = jnp.cumsum(vlen) - vlen  # exclusive prefix
    return vlen, excl


def _first_true(mask: jnp.ndarray, default: jnp.ndarray) -> jnp.ndarray:
    idx = jnp.argmax(mask)
    return jnp.where(jnp.any(mask), idx.astype(I32), default)


def _shift_right(arr, k, newval):
    """arr with a slot opened at k: [0..k-1] keep, [k]=newval, [k+1..] shifted."""
    idx = jnp.arange(arr.shape[0], dtype=I32)
    prev = arr[jnp.maximum(idx - 1, 0)]
    return jnp.where(idx < k, arr, jnp.where(idx == k, newval, prev))


class _NewSeg(NamedTuple):
    seg_start: jnp.ndarray
    seg_len: jnp.ndarray
    ins_key: jnp.ndarray
    ins_client: jnp.ndarray
    seg_uid: jnp.ndarray
    seg_obpre: jnp.ndarray
    rem_keys: tuple
    rem_clients: tuple
    prop_keys: tuple
    prop_vals: tuple


def _open_slot(s: DocState, k, do: jnp.ndarray, new: _NewSeg) -> DocState:
    """Conditionally (``do``) shift all per-segment arrays right at ``k`` and
    write the new segment's values there.  Capacity overflow sets error."""
    S = s.seg_len.shape[0]
    overflow = do & (s.nseg >= S)
    do = do & ~overflow

    def sh(arr, newval):
        return jnp.where(do, _shift_right(arr, k, newval), arr)

    return s._replace(
        seg_start=sh(s.seg_start, new.seg_start),
        seg_len=sh(s.seg_len, new.seg_len),
        ins_key=sh(s.ins_key, new.ins_key),
        ins_client=sh(s.ins_client, new.ins_client),
        seg_uid=sh(s.seg_uid, new.seg_uid),
        seg_obpre=sh(s.seg_obpre, new.seg_obpre),
        rem_keys=tuple(sh(a, v) for a, v in zip(s.rem_keys, new.rem_keys)),
        rem_clients=tuple(sh(a, v) for a, v in zip(s.rem_clients, new.rem_clients)),
        prop_keys=tuple(sh(a, v) for a, v in zip(s.prop_keys, new.prop_keys)),
        prop_vals=tuple(sh(a, v) for a, v in zip(s.prop_vals, new.prop_vals)),
        nseg=s.nseg + do.astype(I32),
        error=s.error | jnp.where(overflow, ERR_SEG_OVERFLOW, 0),
    )


def _ensure_boundary(s: DocState, pos, ref_seq, client) -> DocState:
    """Split the segment containing ``pos`` strictly inside it, if any.

    Mirrors the reference's split-on-walk (ensureIntervalBoundary /
    insertingWalk split path): after this, ``pos`` falls on a segment
    boundary of the perspective-visible sequence.  Obliterate anchors on the
    split segment follow the half holding their endpoint char: Before sides
    keep the left half's uid, After sides move to the right half.
    """
    vis = _visible(s, ref_seq, client)
    vlen, excl = _vis_lengths(s, vis)
    mid = vis & (excl < pos) & (pos < excl + vlen)
    k = _first_true(mid, jnp.asarray(0, I32))  # default unused when ~do
    do = jnp.any(mid)
    off = pos - excl[k]
    old_uid = s.seg_uid[k]
    right_uid = s.uid_next
    right = _NewSeg(
        seg_start=s.seg_start[k] + off,
        seg_len=s.seg_len[k] - off,
        ins_key=s.ins_key[k],
        ins_client=s.ins_client[k],
        seg_uid=right_uid,
        seg_obpre=s.seg_obpre[k],
        rem_keys=tuple(a[k] for a in s.rem_keys),
        rem_clients=tuple(a[k] for a in s.rem_clients),
        prop_keys=tuple(a[k] for a in s.prop_keys),
        prop_vals=tuple(a[k] for a in s.prop_vals),
    )
    s2 = _open_slot(s, k + 1, do, right)
    # Trim the left half (only when the split actually happened).
    new_len = jnp.where(do, off, s2.seg_len[k])
    moved_start = do & (s2.ob_start_uid == old_uid) & (s2.ob_start_side == SIDE_AFTER)
    moved_end = do & (s2.ob_end_uid == old_uid) & (s2.ob_end_side == SIDE_AFTER)
    return s2._replace(
        seg_len=s2.seg_len.at[k].set(new_len),
        uid_next=s2.uid_next + do.astype(I32),
        ob_start_uid=jnp.where(moved_start, right_uid, s2.ob_start_uid),
        ob_end_uid=jnp.where(moved_end, right_uid, s2.ob_end_uid),
    )


# --------------------------------------------------------------------------
# Op branches
# --------------------------------------------------------------------------

def _tiebreak(s: DocState, op_key) -> jnp.ndarray:
    """Reference breakTie (mergeTree.ts:1811) as a per-segment mask.

    Equal keys (>=) win the tie — grouped-batch ops share a sequence number
    and the issuer placed the later op's segment in front by localSeq (see
    mergetree_ref._tiebreak); same-stamp insert CHUNKS rely on this too
    (encode_insert emits them back-to-front at one position)."""
    rem0 = _min_tree(s.rem_keys)  # removes[0] = earliest remove stamp
    rem_clause = (rem0 < LOCAL_BASE) & (rem0 > op_key)
    return (op_key >= s.ins_key) | rem_clause


def _ob_anchor_indices(s: DocState) -> tuple[jnp.ndarray, ...]:
    """Per obliterate slot: segment indices of its start/end anchor uids
    ([OB] each) plus found masks.  OB is small (<=8), so the [OB, S]
    comparison matrix is cheap."""
    alive = _alive(s)
    m_start = (s.ob_start_uid[:, None] == s.seg_uid[None, :]) & alive[None, :]
    m_end = (s.ob_end_uid[:, None] == s.seg_uid[None, :]) & alive[None, :]
    s_idx = jnp.argmax(m_start, axis=1).astype(I32)
    e_idx = jnp.argmax(m_end, axis=1).astype(I32)
    return s_idx, m_start.any(axis=1), e_idx, m_end.any(axis=1)


def _obliterate_new_segment(s: DocState, k, key, client, ref_seq):
    """The insert-time obliterate rule (ref mergeTree.ts blockInsert
    :1647-1745): decide whether the segment about to land at index ``k`` is
    swallowed by concurrent obliterates, and with which remove stamps.

    Returns (rem_keys, rem_clients, obpre, overflow): the new segment's
    remove slots (sorted ascending, NO_REMOVE padded), its
    obliteratePrecedingInsertion stamp key (-1 none), and whether the
    candidate stamps overflowed the R slots."""
    return _obliterate_swallow(s, _ob_anchor_indices(s), k, key, client, ref_seq)


def _obliterate_swallow(s: DocState, anchors, k, key, client, ref_seq):
    """Swallow analysis shared by the single-lane and segment-parallel
    inserts: ``anchors`` carries the (start idx, found, end idx, found)
    tuple in whatever index space ``k`` lives in (absolute for the single
    lane, global for the sharded layout).  Everything here reads only the
    replicated obliterate window table, so the sharded path can run it
    identically on every shard."""
    R = len(s.rem_keys)
    OB = s.ob_key.shape[0]
    used = s.ob_key >= 0
    s_idx, s_found, e_idx, e_found = anchors
    # New segment lands at k: inside the anchor window iff strictly after
    # the start anchor and at/before the end anchor (pre-insert indices).
    inside = used & s_found & e_found & (s_idx < k) & (e_idx >= k)
    concurrent = inside & (s.ob_key > ref_seq)
    others = concurrent & (s.ob_client != client)
    any_conc = jnp.any(concurrent)
    conc_keys = jnp.where(concurrent, s.ob_key, -1)
    newest_i = jnp.argmax(conc_keys)
    newest_key = conc_keys[newest_i]
    newest_client = s.ob_client[newest_i]
    acked_conc = concurrent & (s.ob_key < LOCAL_BASE)
    any_acked = jnp.any(acked_conc)
    na_keys = jnp.where(acked_conc, s.ob_key, -1)
    na_i = jnp.argmax(na_keys)
    na_key = na_keys[na_i]
    na_client = s.ob_client[na_i]
    unacked_conc = concurrent & (s.ob_key >= LOCAL_BASE)
    ou_keys = jnp.where(unacked_conc, s.ob_key, NO_REMOVE)
    ou_i = jnp.argmin(ou_keys)
    mark = jnp.any(others) & any_conc & (newest_client != client)
    include_acked = ~any_acked | (na_key == newest_key) | (na_client != client)
    is_oldest_unacked = unacked_conc & (jnp.arange(OB, dtype=I32) == ou_i)
    cand = mark & ((others & acked_conc & include_acked) | is_oldest_unacked)
    # Extract the R smallest candidate stamps into sorted remove slots.
    ckeys = jnp.where(cand, s.ob_key, NO_REMOVE)
    rem_k, rem_c = [], []
    for _ in range(R):
        i = jnp.argmin(ckeys)
        kk = ckeys[i]
        rem_k.append(kk)
        rem_c.append(jnp.where(kk < NO_REMOVE, s.ob_client[i], -1))
        ckeys = ckeys.at[i].set(NO_REMOVE)
    overflow = jnp.any(ckeys < NO_REMOVE)
    obpre = jnp.where(any_conc, newest_key, -1)
    return tuple(rem_k), tuple(rem_c), obpre, overflow


def _no_obliterate_swallow(s: DocState):
    """Cheap branch of the insert-time obliterate rule: empty ob table means
    the new segment is never swallowed."""
    R = len(s.rem_keys)
    no = jnp.full((), NO_REMOVE, I32)
    neg = jnp.full((), -1, I32)
    return (
        tuple(no for _ in range(R)),
        tuple(neg for _ in range(R)),
        neg,
        jnp.zeros((), bool),
    )


def _do_insert(s: DocState, op, payload, ob_flag) -> DocState:
    pos, key, client, ref_seq = op[4], op[1], op[2], op[3]
    text_len = op[6]
    s = _ensure_boundary(s, pos, ref_seq, client)
    vis = _visible(s, ref_seq, client)
    vlen, excl = _vis_lengths(s, vis)
    total = jnp.sum(vlen)
    # Boundary walk: insert before the first segment at/after pos that is
    # visible or wins the tie-break; else append at nseg.
    stop = _alive(s) & (excl >= pos) & ((vlen > 0) | _tiebreak(s, key))
    k = _first_true(stop, s.nseg)

    # Copy payload into the text pool (masked scatter, OOB indices dropped).
    T = s.text.shape[0]
    tpos = jnp.arange(payload.shape[0], dtype=I32)
    text_over = s.text_end + text_len > T
    dst = jnp.where((tpos < text_len) & ~text_over, s.text_end + tpos, T)
    text = s.text.at[dst].set(payload, mode="drop")

    # The [OB,S] swallow analysis only runs when an obliterate can exist.
    # A PYTHON-bool ob_flag specializes the trace outright (no cond at all
    # — apply_ops hoists the runtime branch to whole-scan level so the op
    # body stays one fused kernel); a traced scalar falls back to lax.cond
    # (scalar, so it stays a real branch under vmap).
    if isinstance(ob_flag, bool):
        new_rem_k, new_rem_c, obpre, rem_over = (
            _obliterate_new_segment(s, k, key, client, ref_seq)
            if ob_flag
            else _no_obliterate_swallow(s)
        )
    else:
        new_rem_k, new_rem_c, obpre, rem_over = jax.lax.cond(
            ob_flag,
            lambda s: _obliterate_new_segment(s, k, key, client, ref_seq),
            _no_obliterate_swallow,
            s,
        )
    P = len(s.prop_keys)
    zero = jnp.zeros((), I32)
    new = _NewSeg(
        seg_start=s.text_end,
        seg_len=text_len,
        ins_key=key,
        ins_client=client,
        seg_uid=s.uid_next,
        seg_obpre=obpre,
        rem_keys=new_rem_k,
        rem_clients=new_rem_c,
        prop_keys=tuple(jnp.full((), -1, I32) for _ in range(P)),
        prop_vals=tuple(zero for _ in range(P)),
    )
    ok = ~text_over & (pos <= total)
    s = _open_slot(s, k, ok, new)
    return s._replace(
        text=jnp.where(text_over, s.text, text),
        text_end=s.text_end + jnp.where(ok, text_len, 0),
        uid_next=s.uid_next + ok.astype(I32),
        error=s.error
        | jnp.where(text_over, ERR_TEXT_OVERFLOW, 0)
        | jnp.where(pos > total, ERR_POS_RANGE, 0)
        | jnp.where(ok & rem_over, ERR_REM_OVERFLOW, 0),
    )


def _mark_range(s: DocState, op) -> tuple[DocState, jnp.ndarray]:
    """Split at both boundaries; return mask of visible segments inside."""
    pos1, pos2, client, ref_seq = op[4], op[5], op[2], op[3]
    s = _ensure_boundary(s, pos1, ref_seq, client)
    s = _ensure_boundary(s, pos2, ref_seq, client)
    vis = _visible(s, ref_seq, client)
    vlen, excl = _vis_lengths(s, vis)
    total = jnp.sum(vlen)
    mark = vis & (excl >= pos1) & (excl + vlen <= pos2) & (vlen > 0)
    s = s._replace(error=s.error | jnp.where(pos2 > total, ERR_POS_RANGE, 0))
    return s, mark


def _splice_remove_stamp(s: DocState, mark, key, client):
    """Place a remove stamp into the first free slot of every marked
    segment; returns (rem_keys, rem_clients, overflow)."""
    rem_keys = list(s.rem_keys)
    rem_clients = list(s.rem_clients)
    placed = jnp.zeros_like(mark)
    for r in range(len(rem_keys)):
        sel = mark & (rem_keys[r] == NO_REMOVE) & ~placed
        rem_keys[r] = jnp.where(sel, key, rem_keys[r])
        rem_clients[r] = jnp.where(sel, client, rem_clients[r])
        placed = placed | sel
    return tuple(rem_keys), tuple(rem_clients), jnp.any(mark & ~placed)


def _do_remove(s: DocState, op, payload) -> DocState:
    key, client = op[1], op[2]
    s, mark = _mark_range(s, op)
    rem_keys, rem_clients, overflow = _splice_remove_stamp(s, mark, key, client)
    return s._replace(
        rem_keys=rem_keys,
        rem_clients=rem_clients,
        error=s.error | jnp.where(overflow, ERR_REM_OVERFLOW, 0),
    )


def _annotate_marked(s: DocState, mark, op) -> DocState:
    """The annotate LWW write against an already-computed mark mask
    (shared by the single-lane and segment-parallel paths)."""
    key, prop_slot, value = op[1], op[6], op[7]
    prop_keys = list(s.prop_keys)
    prop_vals = list(s.prop_vals)
    for p in range(len(prop_keys)):
        # LWW by stamp key: pending local writes outrank acked remotes.
        # Ties (>=) go to the later-applied op (grouped-batch shared seqs).
        win = (prop_slot == p) & mark & (key >= prop_keys[p])
        prop_keys[p] = jnp.where(win, key, prop_keys[p])
        prop_vals[p] = jnp.where(win, value, prop_vals[p])
    return s._replace(prop_keys=tuple(prop_keys), prop_vals=tuple(prop_vals))


def _do_annotate(s: DocState, op, payload) -> DocState:
    s, mark = _mark_range(s, op)
    return _annotate_marked(s, mark, op)


def _obliterate_visit(s: DocState, vis, key, client, ref_seq):
    """The obliterate marking visit rule (ref nodeMap mergeTree.ts:2990-3001
    + markRemoved splice, walking RemoteObliteratePerspective for remote
    ops), shared by the single-lane and segment-parallel paths (purely
    element-wise over the segment axis): a REMOTE obliterate visits — and
    splices into — every window segment except those dead in both views:
    acked-removed AND invisible at the op's refSeq AND not a local pending
    insert.  A LOCAL obliterate marks exactly the segments visible to the
    op's (local) perspective.  Returns (visit, skip) masks."""
    rem_min = _min_tree(s.rem_keys)
    has_acked_rem = rem_min < LOCAL_BASE
    is_local_ins = s.ins_key >= LOCAL_BASE
    # Concurrent-inserted segments are spliced even when acked-removed (the
    # obliterater's replica swallowed them at insert time), unless an older
    # remove stamp from the same client already covers them (then the extra
    # stamp would be unobservable and the issuer never added it).
    ins_conc = ~((s.ins_key <= ref_seq) | (s.ins_client == client))
    # The issuer swallowed a concurrent insert at INSERT time by appending
    # its OLDEST covering pending obliterate; our stamp already exists there
    # iff some same-client stamp came from an obliterate pending at the
    # issuer when the insert arrived: ins_seq < k <= key (== key is an
    # earlier op of the same grouped batch, sharing our sequence number).
    same_client_stamp = _any_tree(
        [
            (c == client) & (k > s.ins_key) & (k <= key)
            for k, c in zip(s.rem_keys, s.rem_clients)
        ]
    )
    visit = jnp.where(
        key >= LOCAL_BASE,
        vis,
        ~has_acked_rem | vis | is_local_ins | (ins_conc & ~same_client_stamp),
    )
    # Last-obliterater-wins: never mark a local pending insert whose newest
    # preceding obliterate is an (even newer) local pending one.
    skip = (s.ins_key >= LOCAL_BASE) & (s.seg_obpre >= LOCAL_BASE) & (key < LOCAL_BASE)
    return visit, skip


def _do_obliterate(s: DocState, op, payload) -> DocState:
    """Sided obliterate (ref mergeTree.ts obliterateRangeSided:2083): mark
    every not-yet-removed segment in the anchor window — concurrent inserts
    included — and record the obliterate for insert-time swallowing.

    pos1/pos2 are the endpoint CHARACTER positions in the op's perspective;
    op[6]/op[7] carry the sides (plain {pos1,pos2} ops encode as
    (pos1, Before) .. (pos2-1, After))."""
    key, client, ref_seq = op[1], op[2], op[3]
    pos1, pos2, side1, side2 = op[4], op[5], op[6], op[7]
    start_pos = pos1 + side1
    end_pos = pos2 + side2
    vis = _visible(s, ref_seq, client)
    vlen, _excl = _vis_lengths(s, vis)
    total = jnp.sum(vlen)
    valid = (0 <= pos1) & (pos1 <= pos2) & (pos2 < total) & (start_pos <= end_pos)
    s = _ensure_boundary(s, jnp.where(valid, start_pos, 0), ref_seq, client)
    s = _ensure_boundary(s, jnp.where(valid, end_pos, 0), ref_seq, client)
    vis = _visible(s, ref_seq, client)
    vlen, excl = _vis_lengths(s, vis)
    # Anchor segments: the visible segments containing the endpoint chars.
    cont_s = vis & (excl <= pos1) & (pos1 < excl + vlen)
    cont_e = vis & (excl <= pos2) & (pos2 < excl + vlen)
    s_idx = _first_true(cont_s, s.nseg)
    e_idx = _first_true(cont_e, s.nseg)
    lo = s_idx + (side1 == SIDE_AFTER).astype(I32)
    hi = e_idx - (side2 == SIDE_BEFORE).astype(I32)
    idx = jnp.arange(s.seg_len.shape[0], dtype=I32)
    visit, skip = _obliterate_visit(s, vis, key, client, ref_seq)
    mark = valid & _alive(s) & (idx >= lo) & (idx <= hi) & visit & ~skip
    # Splice the stamp into the first free remove slot (segments covered by
    # earlier removes already occupy lower slots).
    rem_keys, rem_clients, rem_over = _splice_remove_stamp(s, mark, key, client)
    # Record in the obliterate window table.
    free = s.ob_key < 0
    slot = _first_true(free, jnp.asarray(0, I32))
    has_free = jnp.any(free)
    rec = valid & has_free

    def put(arr, val):
        return arr.at[slot].set(jnp.where(rec, val, arr[slot]))

    return s._replace(
        rem_keys=rem_keys,
        rem_clients=rem_clients,
        ob_key=put(s.ob_key, key),
        ob_client=put(s.ob_client, client),
        ob_start_uid=put(s.ob_start_uid, s.seg_uid[s_idx]),
        ob_end_uid=put(s.ob_end_uid, s.seg_uid[e_idx]),
        ob_start_side=put(s.ob_start_side, side1),
        ob_end_side=put(s.ob_end_side, side2),
        ob_ref_seq=put(s.ob_ref_seq, ref_seq),
        error=s.error
        | jnp.where(~valid, ERR_POS_RANGE, 0)
        | jnp.where(valid & ~has_free, ERR_OB_OVERFLOW, 0)
        | jnp.where(rem_over, ERR_REM_OVERFLOW, 0),
    )


def _do_ack(s: DocState, op, payload) -> DocState:
    """Convert pending stamps (localSeq) to the acked seq; optionally
    re-stamp the client id (op[2] >= 0) and the obliterate's recorded refSeq
    (op[3] >= 0) — channel-hosted replicas stamp local pending ops with a
    sentinel client and learn their short id / wire refSeq only at ack
    (mirrors mergetree_ref.RefMergeTree.ack)."""
    local_seq, seq = op[6], op[7]
    new_client, new_ref = op[2], op[3]
    local_key = LOCAL_BASE + local_seq
    ins_hit = s.ins_key == local_key
    ob_hit = s.ob_key == local_key
    rw_c = new_client >= 0
    return s._replace(
        ins_key=jnp.where(ins_hit, seq, s.ins_key),
        ins_client=jnp.where(ins_hit & rw_c, new_client, s.ins_client),
        rem_keys=tuple(jnp.where(a == local_key, seq, a) for a in s.rem_keys),
        rem_clients=tuple(
            jnp.where((k == local_key) & rw_c, new_client, c)
            for k, c in zip(s.rem_keys, s.rem_clients)
        ),
        prop_keys=tuple(jnp.where(a == local_key, seq, a) for a in s.prop_keys),
        ob_key=jnp.where(ob_hit, seq, s.ob_key),
        ob_client=jnp.where(ob_hit & rw_c, new_client, s.ob_client),
        ob_ref_seq=jnp.where(ob_hit & (new_ref >= 0), new_ref, s.ob_ref_seq),
        seg_obpre=jnp.where(s.seg_obpre == local_key, seq, s.seg_obpre),
    )


def apply_op(
    s: DocState, op: jnp.ndarray, payload: jnp.ndarray, ob_flag=None
) -> DocState:
    """Apply one op row (+ its text payload row) to one document.

    ``ob_flag`` gates the obliterate machinery off the hot path: it must be
    True whenever the ob table may be nonempty or this op may be an
    OBLITERATE (default: computed per doc).  Batched callers MUST pass a
    scalar flag computed OUTSIDE vmap (any doc's table nonempty | any op in
    the batch is OBLITERATE): an unbatched predicate keeps lax.cond a real
    branch under vmap, a batched one degrades it to select-of-both-branches.
    """
    if ob_flag is None:
        ob_flag = jnp.any(s.ob_key >= 0) | (op[0] == OpKind.OBLITERATE)
    kind = op[0]
    if isinstance(ob_flag, bool):
        # Specialized trace (see _do_insert): with False the obliterate
        # branch is unreachable by the flag's contract, so it traces to
        # identity and the whole op body fuses with no interior cond.
        ob_branch = (
            (lambda s, op, p: _do_obliterate(s, op, p))
            if ob_flag
            else (lambda s, op, p: s)
        )
    else:
        ob_branch = lambda s, op, p: jax.lax.cond(  # noqa: E731
            ob_flag, lambda st: _do_obliterate(st, op, p), lambda st: st, s
        )
    branches = [
        lambda s, op, p: s,  # NOOP
        lambda s, op, p: _do_insert(s, op, p, ob_flag),
        _do_remove,
        _do_annotate,
        _do_ack,
        ob_branch,
    ]
    s = jax.lax.switch(kind, branches, s, op, payload)
    return s


def apply_ops(
    s: DocState, ops: jnp.ndarray, payloads: jnp.ndarray, ob_flag=None
) -> DocState:
    """Apply a batch of ops to one document, in order (lax.scan).

    ops: int32[B, OP_FIELDS]; payloads: int32[B, MAX_INSERT_LEN].
    This is the per-document sequential spine; parallelism comes from
    `jax.vmap(apply_ops)` over a leading document axis (pass ``ob_flag``
    with in_axes=None — see apply_op).
    """
    if ob_flag is None:
        ob_flag = jnp.any(s.ob_key >= 0) | jnp.any(ops[:, 0] == OpKind.OBLITERATE)

    def scan_spec(st: DocState, flag: bool) -> DocState:
        def step(carry, xs):
            op, payload = xs
            return apply_op(carry, op, payload, flag), None

        out, _ = jax.lax.scan(step, st, (ops, payloads))
        return out

    if isinstance(ob_flag, bool):
        return scan_spec(s, ob_flag)
    # Hoist the runtime branch to WHOLE-SCAN level: one cond per batch
    # instead of two per op, so the common no-obliterate path is a single
    # fully-fused scan body (conds inside a scan break XLA fusion and were
    # costing ~2x on obliterate-free workloads).
    return jax.lax.cond(
        ob_flag,
        lambda st: scan_spec(st, True),
        lambda st: scan_spec(st, False),
        s,
    )


def apply_megastep(
    s: DocState, ops: jnp.ndarray, payloads: jnp.ndarray
) -> DocState:
    """Apply a [K, D, B] op ring to a [D, ...] document batch in ONE fused
    program: ``lax.scan`` over the K slice axis, ``vmap`` over the D doc
    axis inside the scan body.

    This is the megastep dispatch amortizer: where the per-slice path pays
    one jit dispatch + one host->device upload per [D, B] slice, a megastep
    pays them once per K slices — error bits latch into the carried state
    on device and are read back once per megastep, never per slice.

    Semantics are bit-identical to K sequential ``apply_ops`` dispatches:
    each slice's obliterate gate is the same whole-batch scalar the
    per-slice dispatch computes (any doc's ob table nonempty | any op in
    the slice is an OBLITERATE), re-evaluated per slice from the CARRIED
    state — hoisting it to the scan carry keeps the common no-obliterate
    slice a single fully-fused scan body (see apply_ops).

    ops: int32[K, D, B, OP_FIELDS]; payloads: int32[K, D, B, L].
    """

    def body(st: DocState, xs):
        o, p = xs
        flag = jnp.any(st.ob_key >= 0) | jnp.any(o[..., 0] == OpKind.OBLITERATE)
        st = jax.vmap(apply_ops, in_axes=(0, 0, 0, None))(st, o, p, flag)
        return st, None

    out, _ = jax.lax.scan(body, s, (ops, payloads))
    return out


# --------------------------------------------------------------------------
# Segment-parallel apply (the docs x segs serving path)
# --------------------------------------------------------------------------
#
# One viral document serializes a whole lane: the [S] per-segment arrays are
# the per-op cost, and a hot doc's S is the largest on the box.  The
# segment-parallel variant block-shards those arrays over a named mesh axis
# (default "segs") — shard k owns the k-th contiguous run of the GLOBAL
# segment order, per-shard live counts vary (``nseg`` becomes int32[n_shards],
# one live count per shard), and the global order is the concatenation of the
# per-shard live prefixes.  The text pool, every scalar, and the obliterate
# window table stay REPLICATED, so stamp/uid/text values are bit-identical to
# the single-lane kernel and a gather of the live prefixes reproduces the
# single-lane state exactly (the byte-identity fuzz contract; the single-lane
# path is the oracle).
#
# Per op, the collective structure is the two-hop scheme of
# parallel/long_doc.py ("Parallel Batch-Dynamic Trees via Change
# Propagation" / "Data Structures for Mergeable Trees", PAPERS.md):
#
#   hop 1: all_gather of per-shard visible totals (and live counts) turns
#          local prefix sums into global coordinates,
#   local: masked prefix-sum / containment search inside the shard,
#   hop 2: pmin/psum combines per-shard one-hot candidates into the global
#          insert index / anchor index / owner decision.
#
# Mutations are OWNER-LOCAL: exactly one shard owns the op's landing
# segment, and the O(S_local) suffix shift of ``_open_slot`` runs under a
# real ``lax.cond`` on that shard only — legal here because a segment lane
# is a single-document program (no vmap to degrade the cond to a select).
# Range ops (remove/annotate/obliterate) are purely-local mask updates once
# the global prefix is known.  Inserts land shard-local; the layout re-blocks
# only at rebalance points (``seg_rebalance_state`` below, reusing the
# compaction gather's fill conventions).
#
# These functions use named-axis collectives and MUST run inside a
# ``shard_map`` over the segment axis (parallel.mesh.mesh_seg_program).

SEG_AXIS = "segs"

# Route the shard-local containment searches through the blocked Pallas
# kernel (ops/pallas_kernels.py) instead of the jnp membership mask.  The
# jnp/lax form is the oracle; the Pallas form streams the segment axis
# through VMEM on TPU (a long doc's shard still holds 100k+ segments).
# Trace-time flag: set it before the first segment-lane dispatch compiles.
SEG_RESOLVE_PALLAS = False


def _seg_prefix(s: DocState, vis, axis: str):
    """Hop 1: (vlen, excl_global, total, char_off) — one all_gather of
    per-shard visible totals turns the local exclusive prefix into global
    perspective-visible coordinates."""
    vlen = jnp.where(vis, s.seg_len, 0)
    totals = jax.lax.all_gather(jnp.sum(vlen), axis)  # [n_shards]
    my = jax.lax.axis_index(axis)
    char_off = jnp.sum(jnp.where(jnp.arange(totals.shape[0]) < my, totals, 0))
    excl = jnp.cumsum(vlen) - vlen + char_off
    return vlen, excl, jnp.sum(totals), char_off


def _seg_index_base(s: DocState, axis: str):
    """Hop 1b: (idx_off, nseg_total, counts) — the global segment-index
    base of this shard (global order = concatenation of per-shard live
    prefixes) from one all_gather of the live counts."""
    counts = jax.lax.all_gather(s.nseg, axis)  # [n_shards]
    my = jax.lax.axis_index(axis)
    idx_off = jnp.sum(jnp.where(jnp.arange(counts.shape[0]) < my, counts, 0))
    return idx_off, jnp.sum(counts), counts


def _seg_first_true(mask, idx_off, default, axis: str):
    """Hop 2: global index of the first set bit across shards (pmin of the
    per-shard one-hot candidates), else ``default``.  ``mask`` must only be
    set inside the shard's live prefix."""
    has = jnp.any(mask)
    big = jnp.asarray(2**31 - 1, I32)
    cand = jnp.where(has, idx_off + jnp.argmax(mask).astype(I32), big)
    best = jax.lax.pmin(cand, axis)
    return jnp.where(best == big, default, best)


def _seg_contains(vlen, q_local, strict: bool):
    """Shard-local containment search: (local index, hit) of the visible
    segment containing the local-coordinate query (``strict`` excludes
    boundary hits — the split predicate).  Behind ``SEG_RESOLVE_PALLAS``
    the blocked Pallas kernel is the fused inner loop; the jnp form is the
    oracle and the non-TPU fallback."""
    if SEG_RESOLVE_PALLAS:
        from .pallas_kernels import resolve_positions_blocked

        idx, off, hit = resolve_positions_blocked(vlen, q_local[None])
        idx, off, hit = idx[0], off[0], hit[0] != 0
        if strict:
            hit = hit & (off > 0)
        return idx.astype(I32), hit
    prefix = jnp.cumsum(vlen) - vlen
    if strict:
        inside = (prefix < q_local) & (q_local < prefix + vlen)
    else:
        inside = (vlen > 0) & (prefix <= q_local) & (q_local < prefix + vlen)
    return jnp.argmax(inside).astype(I32), jnp.any(inside)


def _open_slot_seg(s: DocState, k, do, new: _NewSeg, axis: str) -> DocState:
    """Owner-local ``_open_slot``: ``do`` is a SHARD-LOCAL scalar (exactly
    one shard owns the insert), so the O(S_local) suffix shift runs under a
    real branch on the owning shard only — the non-owners skip the heavy
    gather/select entirely.  Shard capacity overflow latches
    ERR_SEG_OVERFLOW globally (psum), exactly like the single-lane latch;
    host recovery re-blocks or re-provisions."""
    S = s.seg_len.shape[0]
    overflow = do & (s.nseg >= S)
    do = do & ~overflow
    R, Pn = len(s.rem_keys), len(s.prop_keys)
    flat = (
        s.seg_start, s.seg_len, s.ins_key, s.ins_client, s.seg_uid,
        s.seg_obpre, *s.rem_keys, *s.rem_clients, *s.prop_keys, *s.prop_vals,
    )
    vals = (
        new.seg_start, new.seg_len, new.ins_key, new.ins_client, new.seg_uid,
        new.seg_obpre, *new.rem_keys, *new.rem_clients, *new.prop_keys,
        *new.prop_vals,
    )
    shifted = jax.lax.cond(
        do,
        lambda t: tuple(_shift_right(a, k, v) for a, v in zip(t, vals)),
        lambda t: t,
        flat,
    )
    err = jax.lax.psum(jnp.where(overflow, ERR_SEG_OVERFLOW, 0), axis)
    return s._replace(
        seg_start=shifted[0], seg_len=shifted[1], ins_key=shifted[2],
        ins_client=shifted[3], seg_uid=shifted[4], seg_obpre=shifted[5],
        rem_keys=tuple(shifted[6 : 6 + R]),
        rem_clients=tuple(shifted[6 + R : 6 + 2 * R]),
        prop_keys=tuple(shifted[6 + 2 * R : 6 + 2 * R + Pn]),
        prop_vals=tuple(shifted[6 + 2 * R + Pn :]),
        nseg=s.nseg + do.astype(I32),
        error=s.error | err,
    )


def _ensure_boundary_seg(s: DocState, pos, ref_seq, client, axis: str) -> DocState:
    """Distributed ``_ensure_boundary``: the containing segment (if any) is
    strictly inside exactly one shard; that shard splits locally.  The split
    uid allocation and obliterate anchor side-moves replay identically on
    every shard from the replicated uid_next / ob table plus one psum
    broadcast of the split segment's old uid."""
    vis = _visible(s, ref_seq, client)
    vlen, excl, _total, char_off = _seg_prefix(s, vis, axis)
    k, hit = _seg_contains(vlen, pos - char_off, strict=True)
    do = jax.lax.psum(hit.astype(I32), axis) > 0
    off = pos - excl[k]
    old_uid = jax.lax.psum(jnp.where(hit, s.seg_uid[k], 0), axis)
    right_uid = s.uid_next
    right = _NewSeg(
        seg_start=s.seg_start[k] + off,
        seg_len=s.seg_len[k] - off,
        ins_key=s.ins_key[k],
        ins_client=s.ins_client[k],
        seg_uid=right_uid,
        seg_obpre=s.seg_obpre[k],
        rem_keys=tuple(a[k] for a in s.rem_keys),
        rem_clients=tuple(a[k] for a in s.rem_clients),
        prop_keys=tuple(a[k] for a in s.prop_keys),
        prop_vals=tuple(a[k] for a in s.prop_vals),
    )
    s2 = _open_slot_seg(s, k + 1, hit, right, axis)
    # Trim the left half (owner only; pre-overflow ``hit``/``do`` exactly as
    # the single-lane path uses its pre-overflow ``do``).
    new_len = jnp.where(hit, off, s2.seg_len[k])
    moved_start = do & (s2.ob_start_uid == old_uid) & (s2.ob_start_side == SIDE_AFTER)
    moved_end = do & (s2.ob_end_uid == old_uid) & (s2.ob_end_side == SIDE_AFTER)
    return s2._replace(
        seg_len=s2.seg_len.at[k].set(new_len),
        uid_next=s2.uid_next + do.astype(I32),
        ob_start_uid=jnp.where(moved_start, right_uid, s2.ob_start_uid),
        ob_end_uid=jnp.where(moved_end, right_uid, s2.ob_end_uid),
    )


def _ob_anchor_indices_seg(s: DocState, idx_off, axis: str):
    """``_ob_anchor_indices`` in global coordinates: local uid matches (uids
    are globally unique, so at most one shard hits per anchor), one psum
    pair combines the per-shard one-hots."""
    alive = _alive(s)
    m_start = (s.ob_start_uid[:, None] == s.seg_uid[None, :]) & alive[None, :]
    m_end = (s.ob_end_uid[:, None] == s.seg_uid[None, :]) & alive[None, :]
    ls = jnp.argmax(m_start, axis=1).astype(I32)
    le = jnp.argmax(m_end, axis=1).astype(I32)
    fs = m_start.any(axis=1)
    fe = m_end.any(axis=1)
    s_idx = jax.lax.psum(jnp.where(fs, idx_off + ls, 0), axis)
    e_idx = jax.lax.psum(jnp.where(fe, idx_off + le, 0), axis)
    s_found = jax.lax.psum(fs.astype(I32), axis) > 0
    e_found = jax.lax.psum(fe.astype(I32), axis) > 0
    return s_idx, s_found, e_idx, e_found


def _do_insert_seg(s: DocState, op, payload, ob_flag: bool, axis: str) -> DocState:
    pos, key, client, ref_seq = op[4], op[1], op[2], op[3]
    text_len = op[6]
    s = _ensure_boundary_seg(s, pos, ref_seq, client, axis)
    vis = _visible(s, ref_seq, client)
    vlen, excl, total, _off = _seg_prefix(s, vis, axis)
    idx_off, nseg_total, counts = _seg_index_base(s, axis)
    # Boundary walk in global coordinates: the stop mask is local, the
    # first stop across shards comes from one pmin (hop 2).
    stop = _alive(s) & (excl >= pos) & ((vlen > 0) | _tiebreak(s, key))
    k_g = _seg_first_true(stop, idx_off, nseg_total, axis)
    my = jax.lax.axis_index(axis)
    append = k_g >= nseg_total
    # Appends land on the LAST shard (any other placement would interleave
    # the new segment before a later shard's run and break global order).
    is_owner = jnp.where(
        append,
        my == counts.shape[0] - 1,
        (idx_off <= k_g) & (k_g < idx_off + s.nseg),
    )
    k_local = jnp.where(append, s.nseg, k_g - idx_off).astype(I32)

    # Payload lands in the REPLICATED text pool: every shard appends the
    # same bytes at the same (replicated) text_end, so seg_start values are
    # global offsets bit-identical to the single-lane pool.
    T = s.text.shape[0]
    tpos = jnp.arange(payload.shape[0], dtype=I32)
    text_over = s.text_end + text_len > T
    dst = jnp.where((tpos < text_len) & ~text_over, s.text_end + tpos, T)
    text = s.text.at[dst].set(payload, mode="drop")

    if ob_flag:
        anchors = _ob_anchor_indices_seg(s, idx_off, axis)
        new_rem_k, new_rem_c, obpre, rem_over = _obliterate_swallow(
            s, anchors, k_g, key, client, ref_seq
        )
    else:
        new_rem_k, new_rem_c, obpre, rem_over = _no_obliterate_swallow(s)
    Pn = len(s.prop_keys)
    zero = jnp.zeros((), I32)
    new = _NewSeg(
        seg_start=s.text_end,
        seg_len=text_len,
        ins_key=key,
        ins_client=client,
        seg_uid=s.uid_next,
        seg_obpre=obpre,
        rem_keys=new_rem_k,
        rem_clients=new_rem_c,
        prop_keys=tuple(jnp.full((), -1, I32) for _ in range(Pn)),
        prop_vals=tuple(zero for _ in range(Pn)),
    )
    ok = ~text_over & (pos <= total)
    s = _open_slot_seg(s, k_local, ok & is_owner, new, axis)
    return s._replace(
        text=jnp.where(text_over, s.text, text),
        text_end=s.text_end + jnp.where(ok, text_len, 0),
        uid_next=s.uid_next + ok.astype(I32),
        error=s.error
        | jnp.where(text_over, ERR_TEXT_OVERFLOW, 0)
        | jnp.where(pos > total, ERR_POS_RANGE, 0)
        | jnp.where(ok & rem_over, ERR_REM_OVERFLOW, 0),
    )


def _mark_range_seg(s: DocState, op, axis: str):
    """Distributed ``_mark_range``: split at both boundaries, then the
    in-range mask is a purely-local comparison against the global prefix."""
    pos1, pos2, client, ref_seq = op[4], op[5], op[2], op[3]
    s = _ensure_boundary_seg(s, pos1, ref_seq, client, axis)
    s = _ensure_boundary_seg(s, pos2, ref_seq, client, axis)
    vis = _visible(s, ref_seq, client)
    vlen, excl, total, _off = _seg_prefix(s, vis, axis)
    mark = vis & (excl >= pos1) & (excl + vlen <= pos2) & (vlen > 0)
    s = s._replace(error=s.error | jnp.where(pos2 > total, ERR_POS_RANGE, 0))
    return s, mark


def _do_remove_seg(s: DocState, op, payload, axis: str) -> DocState:
    key, client = op[1], op[2]
    s, mark = _mark_range_seg(s, op, axis)
    rem_keys, rem_clients, over_l = _splice_remove_stamp(s, mark, key, client)
    overflow = jax.lax.psum(over_l.astype(I32), axis) > 0
    return s._replace(
        rem_keys=rem_keys,
        rem_clients=rem_clients,
        error=s.error | jnp.where(overflow, ERR_REM_OVERFLOW, 0),
    )


def _do_annotate_seg(s: DocState, op, payload, axis: str) -> DocState:
    s, mark = _mark_range_seg(s, op, axis)
    return _annotate_marked(s, mark, op)


def _do_obliterate_seg(s: DocState, op, payload, axis: str) -> DocState:
    """Distributed ``_do_obliterate``: anchors resolve with the two hops,
    the visit/skip masks and the remove-stamp splice are local, and the
    obliterate window record replays identically on every shard from the
    psum-broadcast anchor uids."""
    key, client, ref_seq = op[1], op[2], op[3]
    pos1, pos2, side1, side2 = op[4], op[5], op[6], op[7]
    start_pos = pos1 + side1
    end_pos = pos2 + side2
    vis = _visible(s, ref_seq, client)
    _vlen, _excl, total, _off = _seg_prefix(s, vis, axis)
    valid = (0 <= pos1) & (pos1 <= pos2) & (pos2 < total) & (start_pos <= end_pos)
    s = _ensure_boundary_seg(s, jnp.where(valid, start_pos, 0), ref_seq, client, axis)
    s = _ensure_boundary_seg(s, jnp.where(valid, end_pos, 0), ref_seq, client, axis)
    vis = _visible(s, ref_seq, client)
    vlen, _excl2, _t2, char_off = _seg_prefix(s, vis, axis)
    idx_off, nseg_total, _counts = _seg_index_base(s, axis)
    ks, hs = _seg_contains(vlen, pos1 - char_off, strict=False)
    ke, he = _seg_contains(vlen, pos2 - char_off, strict=False)
    s_found = jax.lax.psum(hs.astype(I32), axis) > 0
    e_found = jax.lax.psum(he.astype(I32), axis) > 0
    s_idx = jnp.where(
        s_found, jax.lax.psum(jnp.where(hs, idx_off + ks, 0), axis), nseg_total
    )
    e_idx = jnp.where(
        e_found, jax.lax.psum(jnp.where(he, idx_off + ke, 0), axis), nseg_total
    )
    start_uid = jax.lax.psum(jnp.where(hs, s.seg_uid[ks], 0), axis)
    end_uid = jax.lax.psum(jnp.where(he, s.seg_uid[ke], 0), axis)
    lo = s_idx + (side1 == SIDE_AFTER).astype(I32)
    hi = e_idx - (side2 == SIDE_BEFORE).astype(I32)
    # Global index of local slot j inside the live prefix is idx_off + j
    # (dead slots are gated by the alive mask below).
    gidx = idx_off + jnp.arange(s.seg_len.shape[0], dtype=I32)
    visit, skip = _obliterate_visit(s, vis, key, client, ref_seq)
    mark = valid & _alive(s) & (gidx >= lo) & (gidx <= hi) & visit & ~skip
    rem_keys, rem_clients, over_l = _splice_remove_stamp(s, mark, key, client)
    rem_over = jax.lax.psum(over_l.astype(I32), axis) > 0
    free = s.ob_key < 0
    slot = _first_true(free, jnp.asarray(0, I32))
    has_free = jnp.any(free)
    rec = valid & has_free

    def put(arr, val):
        return arr.at[slot].set(jnp.where(rec, val, arr[slot]))

    return s._replace(
        rem_keys=rem_keys,
        rem_clients=rem_clients,
        ob_key=put(s.ob_key, key),
        ob_client=put(s.ob_client, client),
        ob_start_uid=put(s.ob_start_uid, start_uid),
        ob_end_uid=put(s.ob_end_uid, end_uid),
        ob_start_side=put(s.ob_start_side, side1),
        ob_end_side=put(s.ob_end_side, side2),
        ob_ref_seq=put(s.ob_ref_seq, ref_seq),
        error=s.error
        | jnp.where(~valid, ERR_POS_RANGE, 0)
        | jnp.where(valid & ~has_free, ERR_OB_OVERFLOW, 0)
        | jnp.where(rem_over, ERR_REM_OVERFLOW, 0),
    )


def apply_op_seg(
    s: DocState, op: jnp.ndarray, payload: jnp.ndarray, ob_flag: bool,
    axis: str = SEG_AXIS,
) -> DocState:
    """Segment-parallel ``apply_op``.  ``ob_flag`` must be a PYTHON bool
    (the scan level hoists the runtime gate — see ``apply_ops_seg``); ACK is
    the single-lane branch verbatim (purely element-wise over local arrays
    plus replicated ob-table rewrites)."""
    kind = op[0]
    branches = [
        lambda s, op, p: s,  # NOOP
        lambda s, op, p: _do_insert_seg(s, op, p, ob_flag, axis),
        lambda s, op, p: _do_remove_seg(s, op, p, axis),
        lambda s, op, p: _do_annotate_seg(s, op, p, axis),
        _do_ack,
        (lambda s, op, p: _do_obliterate_seg(s, op, p, axis))
        if ob_flag
        else (lambda s, op, p: s),
    ]
    return jax.lax.switch(kind, branches, s, op, payload)


def apply_ops_seg(
    s: DocState, ops: jnp.ndarray, payloads: jnp.ndarray, ob_flag=None,
    axis: str = SEG_AXIS,
) -> DocState:
    """Segment-parallel ``apply_ops``: one op batch for ONE document, in
    order, per-segment work sharded over ``axis``.  The runtime obliterate
    gate hoists to whole-scan level exactly like ``apply_ops`` (the flag is
    replicated, so every shard takes the same branch and the collectives
    inside stay matched)."""
    if ob_flag is None:
        ob_flag = jnp.any(s.ob_key >= 0) | jnp.any(ops[:, 0] == OpKind.OBLITERATE)

    def scan_spec(st: DocState, flag: bool) -> DocState:
        def step(carry, xs):
            op, payload = xs
            return apply_op_seg(carry, op, payload, flag, axis), None

        out, _ = jax.lax.scan(step, st, (ops, payloads))
        return out

    if isinstance(ob_flag, bool):
        return scan_spec(s, ob_flag)
    return jax.lax.cond(
        ob_flag,
        lambda st: scan_spec(st, True),
        lambda st: scan_spec(st, False),
        s,
    )


def apply_megastep_seg(
    s: DocState, ops: jnp.ndarray, payloads: jnp.ndarray, axis: str = SEG_AXIS
) -> DocState:
    """Segment-parallel megastep: apply a [K, B] op ring to ONE seg-sharded
    document in one fused program (lax.scan over the K slice axis, per-slice
    obliterate gate carried on device — the single-doc analog of
    ``apply_megastep``).

    This is a ``shard_map`` BODY over the segment axis
    (parallel.mesh.mesh_seg_program dispatches it): ``s`` arrives as the
    local shard view of a seg-sharded state — per-segment arrays [S_local],
    ``nseg`` boxed as int32[1] (this shard's live count), text/scalars/ob
    table replicated — and ops/payloads arrive replicated.
    """
    s = s._replace(nseg=s.nseg[0])

    def body(st: DocState, xs):
        o, p = xs
        flag = jnp.any(st.ob_key >= 0) | jnp.any(o[..., 0] == OpKind.OBLITERATE)
        st = apply_ops_seg(st, o, p, flag, axis)
        return st, None

    out, _ = jax.lax.scan(body, s, (ops, payloads))
    return out._replace(nseg=out.nseg[None])


def compact_seg(
    s: DocState, min_seq: jnp.ndarray, axis: str = SEG_AXIS
) -> DocState:
    """Zamboni on the seg-sharded layout (``shard_map`` body, like
    ``apply_megastep_seg``): ``set_min_seq`` is replicated arithmetic and
    eviction is a purely shard-local stable compaction — order is preserved
    within each shard, so the global concatenation order is preserved."""
    s = s._replace(nseg=s.nseg[0])
    out = compact(set_min_seq(s, min_seq))
    return out._replace(nseg=out.nseg[None])


# ----------------------------------------------------- host-side seg packing

# Dead-slot fill per per-segment field, shared by ``seg_shard_state`` and
# ``seg_gather_state`` (tuple-typed fields fill every element array).
# These MUST match the compaction gather's fills (``compact``'s dead-slot
# conventions) for gather-after-shard to be the identity the byte-identity
# fuzz asserts.
_SEG_FILL = {
    "seg_start": 0, "seg_len": 0, "ins_key": 0, "ins_client": -1,
    "seg_uid": -1, "seg_obpre": -1,
    "rem_keys": NO_REMOVE, "rem_clients": -1,
    "prop_keys": -1, "prop_vals": 0,
}


def _seg_repack(state: DocState, pack) -> dict:
    """Apply ``pack(arr, fill)`` to every per-segment field of ``state``
    per ``_SEG_FILL`` — the one place the fill conventions are spelled."""
    out = {}
    for f, fill in _SEG_FILL.items():
        v = getattr(state, f)
        out[f] = (
            tuple(pack(a, fill) for a in v)
            if isinstance(v, tuple) else pack(v, fill)
        )
    return out


def seg_shard_state(
    state: DocState,
    n_shards: int,
    s_local: int | None = None,
    text_capacity: int | None = None,
) -> DocState:
    """Host-side re-block of a single-doc DocState into the seg-sharded
    layout: the live segments split into ``n_shards`` balanced contiguous
    runs, per-segment arrays become [n_shards * s_local] (block-shard over
    the segment axis), ``nseg`` becomes int32[n_shards] per-shard live
    counts, and the text pool / scalars / obliterate table replicate
    verbatim (text offsets stay GLOBAL, so ``seg_gather_state`` round-trips
    byte-identically).  ``text_capacity`` optionally grows the replicated
    pool for a hot doc.  Leaves are numpy; the caller device_puts them with
    ``parallel.mesh.shard_seg_state``."""
    state = jax.tree.map(np.asarray, state)
    nseg = int(state.nseg)
    S_old = state.seg_len.shape[0]
    if s_local is None:
        s_local = -(-S_old // n_shards)
    base, extra = divmod(nseg, n_shards)
    counts = [base + (1 if i < extra else 0) for i in range(n_shards)]
    if max(counts) > s_local:
        raise ValueError(
            f"{nseg} live segments do not block into {n_shards} shards of "
            f"{s_local} slots"
        )
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))

    def blk(arr: np.ndarray, fill: int) -> np.ndarray:
        out = np.full((n_shards * s_local,), fill, np.int32)
        for i in range(n_shards):
            out[i * s_local : i * s_local + counts[i]] = arr[
                starts[i] : starts[i] + counts[i]
            ]
        return out

    T_old = state.text.shape[0]
    T = text_capacity if text_capacity is not None else T_old
    if T < int(state.text_end):
        raise ValueError(f"text_capacity {T} < text_end {int(state.text_end)}")
    text = np.zeros((T,), np.int32)
    keep = min(T, T_old)
    text[:keep] = state.text[:keep]
    return state._replace(
        text=text,
        nseg=np.asarray(counts, np.int32),
        **_seg_repack(state, blk),
    )


def seg_gather_state(state: DocState, max_segments: int | None = None) -> DocState:
    """Inverse of ``seg_shard_state`` (the compaction gather's fill
    conventions): concatenate the per-shard live prefixes back into one
    single-doc DocState in global segment order.  Because the text pool,
    stamps, and uids are replicated/global, the result is byte-identical
    to what the single-lane kernel would have produced — this is both the
    rebalance gather and the byte-identity fuzz surface."""
    state = jax.tree.map(np.asarray, state)
    counts = state.nseg.astype(np.int64)
    n_shards = int(counts.shape[0])
    s_local = state.seg_len.shape[0] // n_shards
    total = int(counts.sum())
    S = max_segments if max_segments is not None else state.seg_len.shape[0]
    if total > S:
        raise ValueError(f"{total} live segments exceed capacity {S}")

    def gat(arr: np.ndarray, fill: int) -> np.ndarray:
        out = np.full((S,), fill, np.int32)
        w = 0
        for i in range(n_shards):
            c = int(counts[i])
            out[w : w + c] = arr[i * s_local : i * s_local + c]
            w += c
        return out

    return state._replace(
        nseg=np.asarray(total, np.int32),
        **_seg_repack(state, gat),
    )


def seg_rebalance_state(
    state: DocState, s_local: int | None = None, text_capacity: int | None = None
) -> DocState:
    """Re-block a seg-sharded state so every shard holds an even share of
    the live segments again (inserts land shard-local between rebalance
    points, so runs skew over time).  Gather + re-shard, both order- and
    byte-preserving."""
    n_shards = int(np.asarray(state.nseg).shape[0])
    if s_local is None:
        s_local = np.asarray(state.seg_len).shape[0] // n_shards
    return seg_shard_state(
        seg_gather_state(state), n_shards, s_local, text_capacity
    )


def seg_occupancy(state: DocState) -> np.ndarray:
    """Per-shard live segment counts (the occupancy gauge)."""
    return np.asarray(state.nseg).astype(np.int64)


def canonical_doc(state: DocState) -> dict:
    """The live content of a SINGLE-DOC state as plain numpy — padding
    slots excluded (they hold shift remnants) — the byte-identity
    comparison surface for the segment-parallel fuzz.  Seg-sharded states
    gather first (``seg_gather_state``)."""
    state = jax.tree.map(np.asarray, state)
    n = int(state.nseg)
    te = int(state.text_end)
    out = {
        "text": state.text[:te].copy(),
        "text_end": te,
        "nseg": n,
        "uid_next": int(state.uid_next),
        "min_seq": int(state.min_seq),
        "error": int(state.error),
        "ob_key": state.ob_key.copy(),
        "ob_client": state.ob_client.copy(),
        "ob_start_uid": state.ob_start_uid.copy(),
        "ob_end_uid": state.ob_end_uid.copy(),
        "ob_start_side": state.ob_start_side.copy(),
        "ob_end_side": state.ob_end_side.copy(),
        "ob_ref_seq": state.ob_ref_seq.copy(),
    }
    for name in (
        "seg_start", "seg_len", "ins_key", "ins_client", "seg_uid", "seg_obpre"
    ):
        out[name] = getattr(state, name)[:n].copy()
    for name in ("rem_keys", "rem_clients", "prop_keys", "prop_vals"):
        for i, a in enumerate(getattr(state, name)):
            out[f"{name}{i}"] = a[:n].copy()
    return out


# --------------------------------------------------------------------------
# Compaction (zamboni)
# --------------------------------------------------------------------------

def _anchored_mask(s: DocState) -> jnp.ndarray:
    """Segments anchoring a live obliterate ([OB,S] uid match)."""
    used = s.ob_key >= 0
    return (
        (
            (s.seg_uid[None, :] == s.ob_start_uid[:, None])
            | (s.seg_uid[None, :] == s.ob_end_uid[:, None])
        )
        & used[:, None]
    ).any(axis=0)


def _gather_keep(s: DocState, keep: jnp.ndarray) -> DocState:
    """Stable-compact the per-segment arrays down to the kept ones."""
    order = jnp.argsort(~keep, stable=True)
    n_keep = jnp.sum(keep).astype(I32)
    idx = jnp.arange(keep.shape[0], dtype=I32)

    def g(arr, fill):
        return jnp.where(idx < n_keep, arr[order], fill)

    return s._replace(
        seg_start=g(s.seg_start, 0),
        seg_len=g(s.seg_len, 0),
        ins_key=g(s.ins_key, 0),
        ins_client=g(s.ins_client, -1),
        seg_uid=g(s.seg_uid, -1),
        seg_obpre=g(s.seg_obpre, -1),
        rem_keys=tuple(g(a, NO_REMOVE) for a in s.rem_keys),
        rem_clients=tuple(g(a, -1) for a in s.rem_clients),
        prop_keys=tuple(g(a, -1) for a in s.prop_keys),
        prop_vals=tuple(g(a, 0) for a in s.prop_vals),
        nseg=n_keep,
    )


def compact(s: DocState, ob_flag=None) -> DocState:
    """Evict segments whose winning remove is acked at or below min_seq.

    Reference zamboni.ts:33 — such segments are invisible to every legal
    perspective (refSeq >= minSeq), so dropping them is unobservable.
    Segments anchoring a live obliterate stay resident (their index position
    defines the obliterate's window for concurrent inserts).  ``ob_flag``
    gates the [OB,S] anchor-retention matrix (scalar; see apply_op).
    """
    if ob_flag is None:
        ob_flag = jnp.any(s.ob_key >= 0)
    alive = _alive(s)
    rem0 = _min_tree(s.rem_keys)
    dead = alive & (rem0 < LOCAL_BASE) & (rem0 <= s.min_seq)
    if isinstance(ob_flag, bool):
        anchored = _anchored_mask(s) if ob_flag else jnp.zeros_like(alive)
    else:
        anchored = jax.lax.cond(
            ob_flag, _anchored_mask, lambda s: jnp.zeros_like(alive), s
        )
    return _gather_keep(s, alive & ~(dead & ~anchored))


@jax.jit
def drop_squashed(s: DocState) -> DocState:
    """Drop squashed segments: pending insert later covered by a pending
    remove — under squash resubmission the pair cancels and the segment
    never materializes remotely (ref reSubmitCore(squash), channel.ts:160;
    mergetree_ref.RefMergeTree._squashed).  Obliterate anchors stay."""
    alive = _alive(s)
    pend_ins = s.ins_key >= LOCAL_BASE
    pend_rem = _any_tree(
        [(k >= LOCAL_BASE) & (k < NO_REMOVE) for k in s.rem_keys]
    )
    squashed = alive & pend_ins & pend_rem
    return _gather_keep(s, alive & ~(squashed & ~_anchored_mask(s)))


@jax.jit
def strip_stamp(s: DocState, key) -> DocState:
    """Erase every trace of the stamp ``key``: remove-slot stamps revert to
    NO_REMOVE and the matching obliterate record (if any) is freed.  Used
    when a pending op is retired without resubmission (its target content
    vanished during reconnect regeneration)."""
    hits = [k == key for k in s.rem_keys]
    ob_hit = s.ob_key == key
    return s._replace(
        rem_keys=tuple(
            jnp.where(h, NO_REMOVE, k) for h, k in zip(hits, s.rem_keys)
        ),
        rem_clients=tuple(
            jnp.where(h, -1, c) for h, c in zip(hits, s.rem_clients)
        ),
        ob_key=jnp.where(ob_hit, -1, s.ob_key),
    )


@jax.jit
def restamp(
    s: DocState,
    mask: jnp.ndarray,
    old_key,
    new_key,
    new_client,
    do_ins,
    do_rem,
    do_prop,
    do_ob,
) -> DocState:
    """Selectively rewrite stamp keys ``old_key`` -> ``new_key`` on the
    segments selected by ``mask`` ([S] bool), per stamp class (insert /
    remove / prop / obliterate-record).  ``new_client`` < 0 keeps clients.
    This is the device half of reconnect regeneration: the host plans the
    re-minted ops (kernel_backend.regenerate_pending) and re-stamps exactly
    the segments of each plan so every re-minted op acks independently
    (ref client.ts regeneratePendingOp mints new segment groups)."""
    rw_c = new_client >= 0
    ins_hit = do_ins & mask & (s.ins_key == old_key)
    rem_hits = [do_rem & mask & (k == old_key) for k in s.rem_keys]
    ob_hit = do_ob & (s.ob_key == old_key)
    return s._replace(
        ins_key=jnp.where(ins_hit, new_key, s.ins_key),
        ins_client=jnp.where(ins_hit & rw_c, new_client, s.ins_client),
        rem_keys=tuple(
            jnp.where(h, new_key, k) for h, k in zip(rem_hits, s.rem_keys)
        ),
        rem_clients=tuple(
            jnp.where(h & rw_c, new_client, c)
            for h, c in zip(rem_hits, s.rem_clients)
        ),
        prop_keys=tuple(
            jnp.where(do_prop & mask & (k == old_key), new_key, k)
            for k in s.prop_keys
        ),
        ob_key=jnp.where(ob_hit, new_key, s.ob_key),
        ob_client=jnp.where(ob_hit & rw_c, new_client, s.ob_client),
        # ob_preceding references follow the record's stamp rewrite (the
        # oracle mutates the shared Obliterate object in place).
        seg_obpre=jnp.where(
            do_ob & (s.seg_obpre == old_key), new_key, s.seg_obpre
        ),
    )


def set_min_seq(s: DocState, min_seq) -> DocState:
    """Advance the collab-window floor and release obliterates below it
    (ref Obliterates.setMinSeq)."""
    new_min = jnp.maximum(s.min_seq, jnp.asarray(min_seq, I32))
    expired = (s.ob_key >= 0) & (s.ob_key < LOCAL_BASE) & (s.ob_key <= new_min)
    return s._replace(
        min_seq=new_min,
        ob_key=jnp.where(expired, -1, s.ob_key),
    )


# --------------------------------------------------------------------------
# Host-side views (pull arrays off device; numpy)
# --------------------------------------------------------------------------

def _host_vis(s: DocState, ref_seq: int, view_client: int):
    nseg = int(s.nseg)
    ins_key = np.asarray(s.ins_key)[:nseg]
    ins_client = np.asarray(s.ins_client)[:nseg]
    rem_keys = np.stack([np.asarray(a)[:nseg] for a in s.rem_keys])
    rem_clients = np.stack([np.asarray(a)[:nseg] for a in s.rem_clients])
    ins_occ = (ins_key <= ref_seq) | (ins_client == view_client)
    # Padding slots (NO_REMOVE / client -1) must never match: a pure
    # observer legitimately views as client -1.
    rem_valid = rem_keys != NO_REMOVE
    rem_occ = (
        rem_valid & ((rem_keys <= ref_seq) | (rem_clients == view_client))
    ).any(axis=0)
    return nseg, ins_occ & ~rem_occ


def visible_text(
    s: DocState, ref_seq: int = ALL_ACKED, view_client: int = -3,
    raw: bool = False,
) -> str:
    """Materialize the perspective-visible text on the host.  Marker
    codepoints (the reserved U+E000..U+F8FF plane, dds/markers.py) are
    filtered here — markers hold positions but contribute no text, the
    reference's getText/getLength split.  ``raw=True`` keeps them so
    string indices equal positions."""
    from ..protocol.marker_plane import MARKER_CP_BASE, MARKER_CP_END

    nseg, vis = _host_vis(s, ref_seq, view_client)
    text = np.asarray(s.text)
    start = np.asarray(s.seg_start)[:nseg]
    length = np.asarray(s.seg_len)[:nseg]
    parts = [
        "".join(
            chr(c)
            for c in text[start[i] : start[i] + length[i]]
            if raw or not MARKER_CP_BASE <= c < MARKER_CP_END
        )
        for i in range(nseg)
        if vis[i]
    ]
    return "".join(parts)


def visible_length(s: DocState, ref_seq: int = ALL_ACKED, view_client: int = -3) -> int:
    """Perspective-visible character count without materializing the text
    (sum of visible segment lengths)."""
    nseg, vis = _host_vis(s, ref_seq, view_client)
    length = np.asarray(s.seg_len)[:nseg]
    return int(length[vis[:nseg]].sum()) if nseg else 0


def annotations(
    s: DocState, ref_seq: int = ALL_ACKED, view_client: int = -3
) -> list[dict[int, int]]:
    """Per visible character: {prop_slot: value} (differential-test view)."""
    nseg, vis = _host_vis(s, ref_seq, view_client)
    length = np.asarray(s.seg_len)[:nseg]
    prop_keys = np.stack([np.asarray(a)[:nseg] for a in s.prop_keys])
    prop_vals = np.stack([np.asarray(a)[:nseg] for a in s.prop_vals])
    out: list[dict[int, int]] = []
    for i in range(nseg):
        if not vis[i]:
            continue
        props = {
            p: int(prop_vals[p, i])
            for p in range(prop_keys.shape[0])
            if prop_keys[p, i] >= 0
        }
        out.extend(props for _ in range(length[i]))
    return out
