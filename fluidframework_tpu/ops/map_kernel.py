"""Columnar SharedMap kernel: batched last-writer-wins application.

Reference parity: map's ``MapKernel`` (packages/dds/map/src/mapKernel.ts) —
the *sequenced* state is simply every set/delete/clear applied in sequence
order (LWW by total order); the optimistic local overlay (pending keys
masking remote values, mapKernel.ts:707-852) lives host-side in
``dds/shared_map.py`` because it is per-client, not replicated state.

Unlike the merge-tree, map application has no intra-batch position
dependence, so a whole [B]-op batch collapses into ONE data-parallel
resolution (no lax.scan): for each key slot, the winning op is the last
set/delete after the last clear; keys untouched since the last clear are
wiped.  This makes SharedMap the cheapest DDS on TPU by far — a [D, K, B]
mask reduction per step.

Keys and values are host-interned to int32 ids (the channel adapter owns
the intern tables and reverse maps).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

I32 = jnp.int32

ERR_KEY_OVERFLOW = 1


class MapOpKind:
    NOOP = 0
    SET = 1
    DELETE = 2
    CLEAR = 3


class MapState(NamedTuple):
    """Per-map sequenced state over K interned key slots."""

    values: jnp.ndarray   # int32[K] interned value ids
    present: jnp.ndarray  # int32[K] 0/1
    val_seq: jnp.ndarray  # int32[K] seq of the winning write (attribution)
    error: jnp.ndarray    # int32 scalar


def init_state(max_keys: int = 256) -> MapState:
    K = max_keys
    return MapState(
        values=jnp.zeros((K,), I32),
        present=jnp.zeros((K,), I32),
        val_seq=jnp.zeros((K,), I32),
        error=jnp.zeros((), I32),
    )


def apply_batch(
    s: MapState,
    kinds: jnp.ndarray,   # int32[B]
    key_ids: jnp.ndarray, # int32[B] (-1 for clear/noop)
    values: jnp.ndarray,  # int32[B]
    seqs: jnp.ndarray,    # int32[B]
) -> MapState:
    """Apply B sequenced ops (already in sequence order) in one shot."""
    K = s.values.shape[0]
    B = kinds.shape[0]
    bpos = jnp.arange(B, dtype=I32) + 1  # 1-based op positions
    # Last clear position in the batch (0 = none).
    last_clear = jnp.max(jnp.where(kinds == MapOpKind.CLEAR, bpos, 0))
    # Per key: position of the last set/delete at/after the last clear.
    is_write = (kinds == MapOpKind.SET) | (kinds == MapOpKind.DELETE)
    eligible = is_write & (bpos > last_clear)
    hit = (key_ids[None, :] == jnp.arange(K, dtype=I32)[:, None]) & eligible[None, :]
    win = jnp.max(jnp.where(hit, bpos[None, :], 0), axis=1)  # [K], 0 = none
    wb = jnp.maximum(win - 1, 0)
    win_kind = kinds[wb]
    win_val = values[wb]
    win_seq = seqs[wb]
    has_win = win > 0
    cleared = (last_clear > 0) & ~has_win
    new_present = jnp.where(
        has_win,
        (win_kind == MapOpKind.SET).astype(I32),
        jnp.where(cleared, 0, s.present),
    )
    new_values = jnp.where(has_win & (win_kind == MapOpKind.SET), win_val, s.values)
    new_seq = jnp.where(
        has_win, win_seq, jnp.where(cleared, 0, s.val_seq)
    )
    return s._replace(values=new_values, present=new_present, val_seq=new_seq)


# Batched over a leading map/document axis.
apply_batch_fleet = jax.vmap(apply_batch)


def host_items(s: MapState) -> dict[int, int]:
    """{key_id: value_id} of present entries (host view)."""
    present = np.asarray(s.present).astype(bool)
    values = np.asarray(s.values)
    return {int(k): int(values[k]) for k in np.nonzero(present)[0]}


# --------------------------------------------------------------------------
# Summary-record codecs (the DDS-level checkpoint format map fleets were
# missing — same record shape as the string/tree engines: a JSON summary a
# cold consumer can boot from, replaying only the post-summary tail)
# --------------------------------------------------------------------------

def state_to_summary(s: MapState) -> dict:
    """MapState -> summary JSON: the sparse live slot set (slot, value,
    seq, present), exact — ``summary_to_state`` reproduces the arrays
    bit-for-bit.  Interning tables (key slot <-> name) are the channel
    adapter's to carry alongside (the kernel never sees names)."""
    values = np.asarray(s.values)
    present = np.asarray(s.present)
    val_seq = np.asarray(s.val_seq)
    live = np.nonzero((present != 0) | (val_seq != 0) | (values != 0))[0]
    return {
        "max_keys": int(values.shape[0]),
        "slots": [
            [int(k), int(values[k]), int(val_seq[k]), int(present[k])]
            for k in live
        ],
    }


def summary_to_state(summary: dict, max_keys: int | None = None) -> MapState:
    """Summary JSON -> a MapState identical to the one summarized.  Raises
    ValueError when a recorded slot does not fit ``max_keys`` (callers grow
    and retry, like the string engine's geometry fitting)."""
    K = int(max_keys if max_keys is not None else summary["max_keys"])
    values = np.zeros((K,), np.int32)
    present = np.zeros((K,), np.int32)
    val_seq = np.zeros((K,), np.int32)
    for k, v, seq, pres in summary["slots"]:
        if not 0 <= k < K:
            raise ValueError(f"summary slot {k} outside max_keys {K}")
        values[k], val_seq[k], present[k] = v, seq, pres
    return MapState(
        values=jnp.asarray(values),
        present=jnp.asarray(present),
        val_seq=jnp.asarray(val_seq),
        error=jnp.zeros((), I32),
    )
