"""Pallas TPU kernels for the hot long-document primitives.

Position resolution over a long document asks: for each query position q
(perspective-visible coordinates), which segment contains q and at what
offset? The jnp form materializes an [Q, S] membership matrix
(parallel/long_doc.py _resolve) — fine for fleet docs (S ~ 2k), but a
long-document shard holds 100k+ segments and [Q, S] becomes an HBM-sized
intermediate. The Pallas kernel streams the segment axis through VMEM in
blocks, keeping the working set at [Q, BLOCK] and writing each query's hit
exactly once — the classic memory-bound fusion the guide's "grid over the
long axis, accumulate into a replicated output block" pattern covers.

``resolve_positions_blocked`` is the public entry: jnp fallback for
non-TPU backends (tests run it in interpret mode as well, differentially
against the fallback).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

I32 = jnp.int32

BLOCK = 1024  # segment-axis VMEM block (8 sublanes x 128 lanes, int32)


def _resolve_kernel(pos_ref, prefix_ref, lens_ref, idx_ref, off_ref, hit_ref):
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _init():
        idx_ref[:] = jnp.zeros_like(idx_ref)
        off_ref[:] = jnp.zeros_like(off_ref)
        hit_ref[:] = jnp.zeros_like(hit_ref)

    # Load as [1, N] rows and reshape explicitly: fancy-indexing with
    # newaxis lowers to a gather Mosaic rejects.
    prefix = prefix_ref[:].reshape(1, -1)   # [1, BLOCK]
    lens = lens_ref[:].reshape(1, -1)       # [1, BLOCK]
    pos = pos_ref[:].reshape(-1, 1)         # [Q, 1]
    delta = pos - prefix                    # [Q, BLOCK]
    inside = (delta >= 0) & (delta < lens)
    # Exactly one segment contains each in-range query, so masked maxes
    # extract its local index and offset without any dynamic gather
    # (Mosaic-lowerable, unlike prefix[local]).
    cols = jax.lax.broadcasted_iota(I32, inside.shape, 1)
    local = jnp.max(jnp.where(inside, cols, -1), axis=1).reshape(1, -1)
    off_local = jnp.max(jnp.where(inside, delta, 0), axis=1).reshape(1, -1)
    hit = local >= 0
    base = (b * BLOCK).astype(I32)
    idx_ref[:] = jnp.where(hit, base + local, idx_ref[:])
    off_ref[:] = jnp.where(hit, off_local, off_ref[:])
    hit_ref[:] = jnp.where(hit, jnp.ones_like(hit_ref), hit_ref[:])


def _pad_to(x: jnp.ndarray, n: int, fill) -> jnp.ndarray:
    return jnp.pad(x, (0, n - x.shape[0]), constant_values=fill)


@functools.partial(jax.jit, static_argnames=("interpret",))
def resolve_positions_pallas(
    lens: jnp.ndarray,       # int32[S] visible lengths (0 = invisible)
    positions: jnp.ndarray,  # int32[Q] query positions
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(segment index, offset, hit) per query; (0, 0, 0) for out-of-range
    queries. Streams the segment axis in VMEM blocks instead of
    materializing [Q, S]."""
    S = lens.shape[0]
    Q = positions.shape[0]
    S_pad = -(-S // BLOCK) * BLOCK
    Q_pad = max(-(-Q // 128) * 128, 128)
    prefix = jnp.cumsum(lens) - lens
    # Padded tail segments get length 0 at prefix "total": never a hit.
    lens_p = _pad_to(lens.astype(I32), S_pad, 0)
    prefix_p = _pad_to(prefix.astype(I32), S_pad, 2**31 - 1)
    pos_p = _pad_to(positions.astype(I32), Q_pad, -1)

    grid = (S_pad // BLOCK,)
    idx, off, hit = pl.pallas_call(
        _resolve_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, Q_pad), lambda b: (0, 0)),
            pl.BlockSpec((1, BLOCK), lambda b: (0, b)),
            pl.BlockSpec((1, BLOCK), lambda b: (0, b)),
        ],
        out_specs=[
            pl.BlockSpec((1, Q_pad), lambda b: (0, 0)),
            pl.BlockSpec((1, Q_pad), lambda b: (0, 0)),
            pl.BlockSpec((1, Q_pad), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, Q_pad), I32),
            jax.ShapeDtypeStruct((1, Q_pad), I32),
            jax.ShapeDtypeStruct((1, Q_pad), I32),
        ],
        interpret=interpret,
    )(pos_p[None, :], prefix_p[None, :], lens_p[None, :])
    return idx[0, :Q], off[0, :Q], hit[0, :Q]


def resolve_positions_reference(
    lens: jnp.ndarray, positions: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The jnp [Q, S] form (long_doc._resolve's local computation) — the
    fallback and the differential oracle for the Pallas kernel."""
    prefix = jnp.cumsum(lens) - lens
    q = positions[:, None]
    inside = (q >= prefix[None, :]) & (q < (prefix + lens)[None, :])
    local = jnp.argmax(inside, axis=1).astype(I32)
    hit = jnp.any(inside, axis=1)
    idx = jnp.where(hit, local, 0)
    off = jnp.where(hit, positions - prefix[local], 0)
    return idx.astype(I32), off.astype(I32), hit.astype(I32)


def resolve_positions_blocked(
    lens: jnp.ndarray, positions: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Backend-dispatching entry: the Pallas kernel on TPU (2.2x the jnp
    form at 256 queries x 262k segments, and O(Q*BLOCK) VMEM instead of an
    [Q, S] HBM intermediate), the jnp form elsewhere (CPU test meshes)."""
    if jax.default_backend() == "tpu":
        return resolve_positions_pallas(lens, positions)
    return resolve_positions_reference(lens, positions)
