"""Columnar SharedMatrix kernel: permutation vectors + batched cell writes.

Reference parity: packages/dds/matrix/src/matrix.ts processMessagesCore
(position->handle resolution through the permutation merge-trees under the
op's perspective, then LWW or FWW cell conflict — shouldSetCellBasedOnFWW,
matrix.ts:987).

Re-uses the merge-tree kernel for the row/col permutation vectors: the
"text pool" stores handle ids instead of codepoints, and handle allocation
is deterministic-by-sequencing (a row-insert op applied at seq S allocates
the next ``count`` handles from the replica's counter — identical on every
replica because ops apply in total order).

Cell state is dense [HR, HC] int32 (values host-interned), with last-write
(seq, client) for the FWW rule.  This is the sequenced-replica path (the
DocBatchEngine analog for matrices); client-side pending overlay lives in
``dds/shared_matrix.py``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import mergetree_kernel as mk

I32 = jnp.int32

ERR_HANDLE_RANGE = 16


class MatrixOpKind:
    NOOP = 0
    INSERT_ROWS = 1
    INSERT_COLS = 2
    REMOVE_ROWS = 3
    REMOVE_COLS = 4
    SET_CELL = 5


# Op row layout (int32[8]):
#   0 kind | 1 seq | 2 client | 3 ref_seq | 4 pos1 | 5 pos2/count | 6 a | 7 b
# SET_CELL: pos1=row pos2=col a=value b=fww_flag
# INSERT_*: pos1=pos  pos2=count
# REMOVE_*: pos1=pos  pos2=count
MATRIX_OP_FIELDS = 8


class MatrixState(NamedTuple):
    rows: mk.DocState
    cols: mk.DocState
    next_row_handle: jnp.ndarray  # int32 scalar
    next_col_handle: jnp.ndarray  # int32 scalar
    cell_val: jnp.ndarray         # int32[HR, HC]
    cell_present: jnp.ndarray     # int32[HR, HC]
    cell_seq: jnp.ndarray         # int32[HR, HC] last write seq (0 = none)
    cell_client: jnp.ndarray      # int32[HR, HC] last write short client
    fww: jnp.ndarray              # int32 scalar 0/1
    error: jnp.ndarray            # int32 scalar


def init_state(
    max_rows: int = 256,
    max_cols: int = 256,
    max_segments: int = 128,
    remove_slots: int = 4,
) -> MatrixState:
    return MatrixState(
        rows=mk.init_state(max_segments, remove_slots, 1, max_rows),
        cols=mk.init_state(max_segments, remove_slots, 1, max_cols),
        next_row_handle=jnp.zeros((), I32),
        next_col_handle=jnp.zeros((), I32),
        cell_val=jnp.zeros((max_rows, max_cols), I32),
        cell_present=jnp.zeros((max_rows, max_cols), I32),
        cell_seq=jnp.zeros((max_rows, max_cols), I32),
        cell_client=jnp.full((max_rows, max_cols), -1, I32),
        fww=jnp.zeros((), I32),
        error=jnp.zeros((), I32),
    )


def _resolve_handle(perm: mk.DocState, pos, ref_seq, client):
    """Position -> handle under the op's perspective (ref adjustPosition)."""
    vis = mk._visible(perm, ref_seq, client)
    vlen, excl = mk._vis_lengths(perm, vis)
    inside = vis & (excl <= pos) & (pos < excl + vlen)
    k = mk._first_true(inside, jnp.asarray(0, I32))
    found = jnp.any(inside)
    off = pos - excl[k]
    handle = perm.text[perm.seg_start[k] + off]
    return jnp.where(found, handle, -1), found


def _perm_insert(perm: mk.DocState, next_handle, op):
    """Insert ``count`` handles at pos: a merge-tree insert whose payload is
    the next handle ids (capacity = the text pool, entries = handles)."""
    count = op[5]
    T = perm.text.shape[0]
    payload = next_handle + jnp.arange(T, dtype=I32)  # first `count` used
    ins_op = jnp.stack(
        [jnp.asarray(mk.OpKind.INSERT, I32), op[1], op[2], op[3], op[4],
         jnp.zeros((), I32), count, jnp.zeros((), I32)]
    )
    # Permutation vectors never carry obliterates: ob machinery stays off.
    new_perm = mk._do_insert(perm, ins_op, payload, jnp.zeros((), bool))
    return new_perm, next_handle + count


def _perm_remove(perm: mk.DocState, op):
    rem_op = jnp.stack(
        [jnp.asarray(mk.OpKind.REMOVE, I32), op[1], op[2], op[3], op[4],
         op[4] + op[5], jnp.zeros((), I32), jnp.zeros((), I32)]
    )
    return mk._do_remove(perm, rem_op, jnp.zeros((1,), I32))


def apply_op(s: MatrixState, op: jnp.ndarray) -> MatrixState:
    kind = op[0]

    def do_insert_rows(s, op):
        rows, nh = _perm_insert(s.rows, s.next_row_handle, op)
        over = nh > s.cell_val.shape[0]
        return s._replace(
            rows=rows, next_row_handle=nh,
            error=s.error | jnp.where(over, ERR_HANDLE_RANGE, 0),
        )

    def do_insert_cols(s, op):
        cols, nh = _perm_insert(s.cols, s.next_col_handle, op)
        over = nh > s.cell_val.shape[1]
        return s._replace(
            cols=cols, next_col_handle=nh,
            error=s.error | jnp.where(over, ERR_HANDLE_RANGE, 0),
        )

    def do_remove_rows(s, op):
        return s._replace(rows=_perm_remove(s.rows, op))

    def do_remove_cols(s, op):
        return s._replace(cols=_perm_remove(s.cols, op))

    def do_set_cell(s, op):
        seq, client, ref_seq = op[1], op[2], op[3]
        value, fww_flag = op[6], op[7]
        fww = jnp.maximum(s.fww, fww_flag)
        rh, rfound = _resolve_handle(s.rows, op[4], ref_seq, client)
        ch, cfound = _resolve_handle(s.cols, op[5], ref_seq, client)
        ok = rfound & cfound
        # FWW: first write, same client, or ref_seq >= last write's seq.
        last_seq = s.cell_seq[rh, ch]
        last_client = s.cell_client[rh, ch]
        should = jnp.where(
            fww > 0,
            (last_seq == 0) | (last_client == client) | (ref_seq >= last_seq),
            True,
        )
        write = ok & should
        rh_c = jnp.maximum(rh, 0)
        ch_c = jnp.maximum(ch, 0)
        upd = lambda arr, v: arr.at[rh_c, ch_c].set(jnp.where(write, v, arr[rh_c, ch_c]))
        return s._replace(
            cell_val=upd(s.cell_val, value),
            cell_present=upd(s.cell_present, 1),
            cell_seq=upd(s.cell_seq, seq),
            cell_client=upd(s.cell_client, client),
            fww=fww,
            error=s.error | jnp.where(~ok, ERR_HANDLE_RANGE, 0),
        )

    branches = [
        lambda s, op: s,
        do_insert_rows,
        do_insert_cols,
        do_remove_rows,
        do_remove_cols,
        do_set_cell,
    ]
    return jax.lax.switch(kind, branches, s, op)


def apply_ops(s: MatrixState, ops: jnp.ndarray) -> MatrixState:
    """Apply a [B, 8] batch of sequenced matrix ops in order."""

    def step(carry, op):
        return apply_op(carry, op), None

    out, _ = jax.lax.scan(step, s, ops)
    return out


apply_ops_fleet = jax.vmap(apply_ops)


# --------------------------------------------------------------------------
# Host views
# --------------------------------------------------------------------------

def visible_handles(perm: mk.DocState, ref_seq: int = None, view_client: int = -3):
    from ..protocol.stamps import ALL_ACKED

    ref = ALL_ACKED if ref_seq is None else ref_seq
    nseg, vis = mk._host_vis(perm, ref, view_client)
    text = np.asarray(perm.text)
    start = np.asarray(perm.seg_start)[:nseg]
    length = np.asarray(perm.seg_len)[:nseg]
    out = []
    for i in range(nseg):
        if vis[i]:
            out.extend(int(h) for h in text[start[i] : start[i] + length[i]])
    return out


def to_grid(s: MatrixState):
    """Materialized consensus grid (None for unset cells)."""
    rows = visible_handles(s.rows)
    cols = visible_handles(s.cols)
    val = np.asarray(s.cell_val)
    present = np.asarray(s.cell_present)
    return [
        [int(val[rh, ch]) if present[rh, ch] else None for ch in cols]
        for rh in rows
    ]


# --------------------------------------------------------------------------
# Summary-record codecs (the DDS-level checkpoint format matrix fleets were
# missing — same record shape as the string/tree engines: a JSON summary a
# cold consumer can boot from, replaying only the post-summary tail)
# --------------------------------------------------------------------------

def _perm_to_json(perm: mk.DocState) -> dict:
    """Exact dump of a permutation merge-tree (full arrays: seg layout,
    stamps, uids, remove slots — a restored perm must resolve every future
    position identically, including tiebreak/perspective state the
    canonical summary walk would normalize away)."""
    out = {}
    for name, arr in perm._asdict().items():
        if isinstance(arr, tuple):
            out[name] = [np.asarray(a).tolist() for a in arr]
        else:
            out[name] = np.asarray(arr).tolist()
    return out


def _perm_from_json(d: dict) -> mk.DocState:
    kw = {}
    for name, val in d.items():
        if name in ("rem_keys", "rem_clients", "prop_keys", "prop_vals"):
            kw[name] = tuple(jnp.asarray(v, I32) for v in val)
        else:
            kw[name] = jnp.asarray(val, I32)
    return mk.DocState(**kw)


def state_to_summary(s: MatrixState) -> dict:
    """MatrixState -> summary JSON: exact perm dumps + the sparse touched
    cell set + handle counters.  ``summary_to_state`` reproduces the state
    arrays bit-for-bit (given the same geometry)."""
    val = np.asarray(s.cell_val)
    present = np.asarray(s.cell_present)
    seq = np.asarray(s.cell_seq)
    client = np.asarray(s.cell_client)
    touched = np.nonzero((present != 0) | (seq != 0) | (client != -1) | (val != 0))
    return {
        "shape": [int(val.shape[0]), int(val.shape[1])],
        "rows": _perm_to_json(s.rows),
        "cols": _perm_to_json(s.cols),
        "next_row_handle": int(s.next_row_handle),
        "next_col_handle": int(s.next_col_handle),
        "cells": [
            [int(r), int(c), int(val[r, c]), int(present[r, c]),
             int(seq[r, c]), int(client[r, c])]
            for r, c in zip(*touched)
        ],
        "fww": int(s.fww),
    }


def summary_to_state(summary: dict) -> MatrixState:
    """Summary JSON -> a MatrixState identical to the one summarized."""
    HR, HC = summary["shape"]
    cell_val = np.zeros((HR, HC), np.int32)
    cell_present = np.zeros((HR, HC), np.int32)
    cell_seq = np.zeros((HR, HC), np.int32)
    cell_client = np.full((HR, HC), -1, np.int32)
    for r, c, v, pres, sq, cl in summary["cells"]:
        if not (0 <= r < HR and 0 <= c < HC):
            raise ValueError(f"summary cell ({r},{c}) outside shape {HR}x{HC}")
        cell_val[r, c], cell_present[r, c] = v, pres
        cell_seq[r, c], cell_client[r, c] = sq, cl
    return MatrixState(
        rows=_perm_from_json(summary["rows"]),
        cols=_perm_from_json(summary["cols"]),
        next_row_handle=jnp.asarray(summary["next_row_handle"], I32),
        next_col_handle=jnp.asarray(summary["next_col_handle"], I32),
        cell_val=jnp.asarray(cell_val),
        cell_present=jnp.asarray(cell_present),
        cell_seq=jnp.asarray(cell_seq),
        cell_client=jnp.asarray(cell_client),
        fww=jnp.asarray(summary["fww"], I32),
        error=jnp.zeros((), I32),
    )
