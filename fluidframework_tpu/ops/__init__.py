"""TPU kernels: columnar CRDT op application as integer-tensor programs.

These kernels replace the reference's hot TypeScript paths (merge-tree
Client.applyMsg, map/matrix kernels, EditManager rebase) with pure JAX
functions over SoA int32 arrays, designed so that `vmap` over a document
axis + `shard_map` over a TPU mesh applies whole batches of sequenced ops
for thousands of documents per step.
"""

from .mergetree_kernel import (
    DocState,
    OpKind,
    apply_op,
    apply_ops,
    compact,
    init_state,
    make_noop,
    visible_text,
)

__all__ = [
    "DocState",
    "OpKind",
    "apply_op",
    "apply_ops",
    "compact",
    "init_state",
    "make_noop",
    "visible_text",
]
