"""Segment-axis sharding: one huge document spread across the mesh.

The reference's long-sequence machinery — the merge-tree B-tree with
``PartialSequenceLengths`` giving O(log n) position resolution
(merge-tree/src/partialLengths.ts:230, SURVEY §5 "long-context") — exists
only to make prefix-length queries cheap on one CPU. The TPU-native form
(SURVEY §7): the flat segment SoA is block-sharded over a ``segs`` mesh
axis (order-preserving), per-shard partial lengths are combined with ICI
collectives, and every position query becomes

    global prefix  =  all_gather of shard totals (one tiny collective)
    local resolve  =  masked prefix-sum inside the shard (vector ops)
    combine        =  psum of per-shard one-hot results

— the distributed analog of the B-tree walk: two collective hops regardless
of document size. Range ops (remove/annotate) then apply as purely-local
mask updates. This composes with the ``docs`` axis as a 2-D mesh
(docs × segs): fleets of huge documents — documents across chips, segments
across chips — sequence parallelism for collaborative text.

Inserts migrate between shards only at rebalance points (the zamboni
compaction pass already gathers live segments; a sharded rebalance
re-blocks them), so the hot query path stays at the two hops above.

PROMOTED (PR 11): the serving engines now run this design end to end —
``ops.mergetree_kernel.apply_megastep_seg`` is the segment-parallel apply
(full op semantics, byte-identical to the single-lane kernel),
``parallel.mesh.seg_state_specs``/``docs_segs_mesh`` carry the layout and
the 2-D mesh, and ``DocBatchEngine`` segment lanes serve hot docs with it.
This module remains the read-side query plane (visible_length / resolve /
mark over an equal-block layout with replicated nseg) and the design
reference for the two-hop scheme.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability.flight_recorder import span
from ..ops.mergetree_kernel import DocState
from ..protocol.stamps import NO_REMOVE

I32 = jnp.int32


def shard_doc_state(state: DocState, mesh: Mesh, axis: str = "segs") -> DocState:
    """Place a single-doc state with segment arrays block-sharded over
    ``axis`` and scalars/text replicated. Block sharding preserves segment
    order: shard k owns the k-th contiguous run."""
    seg = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    specs = _specs_for(state, axis)
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, seg if sp == P(axis) else rep),
        state,
        specs,
    )


def _specs_for(state: DocState, axis: str) -> DocState:
    s, r = P(axis), P()
    return DocState(
        text=r, text_end=r, nseg=r,
        seg_start=s, seg_len=s, ins_key=s, ins_client=s,
        seg_uid=s, seg_obpre=s,
        rem_keys=(s,) * len(state.rem_keys),
        rem_clients=(s,) * len(state.rem_clients),
        prop_keys=(s,) * len(state.prop_keys),
        prop_vals=(s,) * len(state.prop_vals),
        # The obliterate window table is tiny: replicate it like scalars.
        uid_next=r, ob_key=r, ob_client=r, ob_start_uid=r, ob_end_uid=r,
        ob_start_side=r, ob_end_side=r, ob_ref_seq=r,
        min_seq=r, error=r,
    )


def _local_vis_lens(s: DocState, ref_seq, client, axis: str) -> jnp.ndarray:
    """Per-shard perspective-visible lengths, with GLOBAL aliveness (local
    row k is global row my_shard * S_local + k against the replicated
    nseg)."""
    my = jax.lax.axis_index(axis)
    n_local = s.seg_len.shape[0]
    gidx = my * n_local + jnp.arange(n_local, dtype=I32)
    alive = gidx < s.nseg
    ins_occ = (s.ins_key <= ref_seq) | (s.ins_client == client)
    rem_occ = jnp.zeros_like(alive)
    for k, c in zip(s.rem_keys, s.rem_clients):
        rem_occ = rem_occ | (k <= ref_seq) | (c == client)
    vis = alive & ins_occ & ~rem_occ
    return jnp.where(vis, s.seg_len, 0)


def _shard_offset(lens: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Sum of EARLIER shards' visible totals (one all_gather): the offset
    translating this shard's local coordinates to global ones."""
    totals = jax.lax.all_gather(jnp.sum(lens), axis)  # [n_shards]
    my = jax.lax.axis_index(axis)
    return jnp.sum(jnp.where(jnp.arange(totals.shape[0]) < my, totals, 0))


def _global_prefix(lens: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Per-segment exclusive prefix in GLOBAL visible coordinates: local
    cumsum shifted by the earlier shards' totals."""
    return jnp.cumsum(lens) - lens + _shard_offset(lens, axis)


def make_sharded_ops(mesh: Mesh, state: DocState, axis: str = "segs"):
    """Build (visible_length, resolve_positions, mark_range) for one
    document layout, each shard_map-jitted over the segment axis."""
    specs = _specs_for(state, axis)

    @partial(shard_map, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P())
    def _visible_length(s: DocState, ref_seq, client):
        return jax.lax.psum(jnp.sum(_local_vis_lens(s, ref_seq, client, axis)), axis)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(specs, P(), P(), P()), out_specs=(P(), P()),
    )
    def _resolve(s: DocState, positions, ref_seq, client):
        """positions[Q] (replicated, in perspective-visible coordinates) ->
        (global segment index, offset within segment) per query.

        The shard-local membership search runs as the blocked Pallas
        kernel on TPU (ops/pallas_kernels.py — streams the segment axis
        through VMEM instead of materializing [Q, S_local] in HBM); shard
        coordinates reduce to local ones by subtracting the earlier
        shards' visible total, then one psum merges the per-shard
        one-hots."""
        from ..ops.pallas_kernels import resolve_positions_blocked

        lens = _local_vis_lens(s, ref_seq, client, axis)
        my = jax.lax.axis_index(axis)
        local_q = positions - _shard_offset(lens, axis)
        local_idx, offset, hit = resolve_positions_blocked(lens, local_q)
        n_local = lens.shape[0]
        global_idx = jnp.where(hit == 1, my * n_local + local_idx, 0)
        # Exactly one shard hits each in-range query; psum merges one-hots.
        return (
            jax.lax.psum(global_idx.astype(I32), axis),
            jax.lax.psum(jnp.where(hit == 1, offset, 0).astype(I32), axis),
        )

    @partial(
        shard_map, mesh=mesh,
        in_specs=(specs, P(), P(), P(), P(), P(), P()),
        out_specs=specs,
    )
    def _mark_range(s: DocState, p1, p2, op_key, op_client, ref_seq, client):
        """Remove [p1, p2) under the op's perspective as a purely-local mask
        update (whole segments in range; boundary splits are the single-
        owner engine's job before a doc graduates to sharded layout — large
        deletes over long documents mark thousands of whole segments)."""
        lens = _local_vis_lens(s, ref_seq, client, axis)
        prefix = _global_prefix(lens, axis)
        vis = lens > 0
        in_range = vis & (prefix >= p1) & ((prefix + lens) <= p2)
        new_rem_keys = []
        new_rem_clients = []
        taken = jnp.zeros_like(in_range)
        for rk, rc in zip(s.rem_keys, s.rem_clients):
            free = (rk == NO_REMOVE) & in_range & ~taken
            new_rem_keys.append(jnp.where(free, op_key, rk).astype(I32))
            new_rem_clients.append(jnp.where(free, op_client, rc).astype(I32))
            taken = taken | free
        return s._replace(
            rem_keys=tuple(new_rem_keys), rem_clients=tuple(new_rem_clients)
        )

    n_shards = int(mesh.shape[axis])
    # jit the shard_map programs and span AROUND the jitted call: a span
    # inside the traced body fires once at trace time and never again
    # (the compiled executable dispatches without re-entering Python), so
    # it would record compile cost, not per-dispatch collective hops.
    jit_visible = jax.jit(_visible_length)
    jit_resolve = jax.jit(_resolve)
    jit_mark = jax.jit(_mark_range)

    def visible_length(s, ref_seq, client):
        # One trace span per collective program dispatch: the hop-1
        # all-gather + hop-2 psum pair lives inside the jitted program,
        # so the span is the host-visible record of the two-hop cost.
        with span("seg_collective", op="visible_length", shards=n_shards):
            return jit_visible(
                s, jnp.asarray(ref_seq, I32), jnp.asarray(client, I32)
            )

    def resolve_positions(s, positions, ref_seq, client):
        with span("seg_collective", op="resolve", shards=n_shards):
            return jit_resolve(
                s, jnp.asarray(positions, I32),
                jnp.asarray(ref_seq, I32), jnp.asarray(client, I32),
            )

    def mark_range(s, p1, p2, op_key, op_client, ref_seq, client):
        with span("seg_collective", op="mark_range", shards=n_shards):
            return jit_mark(
                s, jnp.asarray(p1, I32), jnp.asarray(p2, I32),
                jnp.asarray(op_key, I32), jnp.asarray(op_client, I32),
                jnp.asarray(ref_seq, I32), jnp.asarray(client, I32),
            )

    return (visible_length, resolve_positions, mark_range)
