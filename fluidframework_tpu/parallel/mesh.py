"""Device mesh + sharding for the document axis.

The reference's scale-out axis is per-document sharding (Kafka partitions by
documentId; each deli/lambda instance owns a disjoint doc set —
SURVEY.md §2.6).  The TPU-native equivalent is a 1-D ``Mesh`` over a ``docs``
axis: replica state arrays are sharded on their leading document dimension,
op batches likewise, and the per-step computation is purely doc-parallel so
XLA partitions it with zero collectives on the hot path (collectives appear
only in aggregate metrics/reductions).

Multi-host pods extend the same mesh across hosts: the doc axis rides
ICI within a slice and DCN across slices — no code change, just a larger
``jax.devices()`` list.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def doc_mesh(devices=None, axis: str = "docs") -> Mesh:
    """A 1-D mesh over all (or the given) devices for document parallelism."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs.reshape(-1), (axis,))


def shard_docs(mesh: Mesh, axis: str = "docs") -> NamedSharding:
    """Sharding for arrays with a leading document dimension."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
