"""Device mesh + sharding for the document axis.

The reference's scale-out axis is per-document sharding (Kafka partitions by
documentId; each deli/lambda instance owns a disjoint doc set —
SURVEY.md §2.6).  The TPU-native equivalent is a 1-D ``Mesh`` over a ``docs``
axis: replica state arrays are sharded on their leading document dimension,
op batches likewise, and the per-step computation is purely doc-parallel so
the ``shard_map``-wrapped fleet programs below run with ZERO collectives on
the hot path (collectives appear only in aggregate metrics/reductions, e.g.
the per-shard error-latch reduce).

Layers:

- ``match_partition_rules``: regex partition-rule matching over a state
  pytree's named leaves -> a pytree of ``PartitionSpec`` (scalars and
  singleton leaves replicate; everything matching a doc rule shards on its
  leading document dimension).
- ``mesh_fleet_program``: wrap a per-doc fleet step (``apply_megastep`` /
  ``apply_nested_megastep`` / compaction) in ``shard_map`` under the mesh
  and ``jax.jit`` with the state donated — one dispatch steps every shard,
  each shard's obliterate gate evaluated from ITS OWN docs (a hot
  obliterate shard no longer de-specializes the whole fleet's trace).
- ``error_count``: the per-shard reduce replacing the full [D] error-vector
  gather on the recover() path — each shard contributes a partial sum, the
  host reads one scalar.

Multi-host pods extend the same mesh across hosts: the doc axis rides
ICI within a slice and DCN across slices — no code change, just a larger
``jax.devices()`` list.

The doc-axis shard index (device position along ``docs``) is also the
shard domain of the shared placement plane (``models/placement.py``):
``shard_of``/``free_slots``/``migrate_doc`` address THESE shards, so a
live migration is a slot handoff between two positions of the same
sharded state arrays — the mesh program never recompiles for a move,
and 2-D seg-lane docs keep their reserved doc-axis slot while promoted.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# The seg-axis NAME is owned by the kernel whose collectives bind to it
# (ops.mergetree_kernel's all_gather/psum/pmin inside apply_megastep_seg);
# re-exported here so mesh construction and the kernel can never disagree.
from ..ops.mergetree_kernel import SEG_AXIS


def doc_mesh(devices=None, axis: str = "docs") -> Mesh:
    """A 1-D mesh over all (or the given) devices for document parallelism."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs.reshape(-1), (axis,))


def docs_segs_mesh(
    devices=None, seg_shards: int = 1, doc_axis: str = "docs",
    seg_axis: str = SEG_AXIS,
) -> Mesh:
    """The 2-D docs x segs mesh: documents place over rows, a hot
    document's merge-tree segments block-shard over the ``segs`` columns.
    ``seg_shards`` clamps to the largest divisor of the device count at or
    below the request (the mesh must factor).  Cold docs still use every
    device — their fleet state shards over BOTH axes flattened
    (``fleet_doc_axes``); only hot docs carve the segs axis."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    n = devs.size
    seg = max(1, min(int(seg_shards), n))
    while n % seg:
        seg -= 1
    return Mesh(devs.reshape(n // seg, seg), (doc_axis, seg_axis))


def fleet_doc_axes(mesh: Mesh):
    """The PartitionSpec ENTRY for a fleet state's leading doc dimension on
    this mesh: the plain docs axis on a 1-D mesh, both axes flattened on a
    docs x segs mesh (cold docs keep using every device)."""
    if SEG_AXIS in mesh.axis_names:
        return ("docs", SEG_AXIS)
    return "docs"


def shard_docs(mesh: Mesh, axis=None) -> NamedSharding:
    """Sharding for arrays with a leading document dimension."""
    return NamedSharding(
        mesh, P(axis if axis is not None else fleet_doc_axes(mesh))
    )


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Partition-rule matching over named pytree leaves
# ---------------------------------------------------------------------------

def _key_str(k) -> str:
    """One path entry -> its name (GetAttrKey/SequenceKey/DictKey/...)."""
    for attr in ("name", "idx", "key"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def named_leaves(tree) -> tuple[list[str], list, object]:
    """``(names, leaves, treedef)`` with "a/b/0"-style leaf path names."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = ["/".join(_key_str(k) for k in path) for path, _ in flat]
    return names, [leaf for _, leaf in flat], treedef


def match_partition_rules(rules, tree, default: P = P()):
    """A pytree of ``PartitionSpec`` matching ``tree``: first rule whose
    regex matches the leaf's path name wins; 0-d and singleton leaves
    always replicate (never partition scalars); unmatched leaves take
    ``default`` (replicated)."""
    names, leaves, treedef = named_leaves(tree)
    specs = []
    for name, leaf in zip(names, leaves):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                specs.append(spec)
                break
        else:
            specs.append(default)
    return jax.tree_util.tree_unflatten(treedef, specs)


# The batched engines broadcast every replica leaf to [D, ...], so every
# named leaf of a fleet state carries the leading document axis — per-doc
# scalars included (they are [D] vectors in the batch).  Anything that ever
# loses the doc axis (a future shared pool / global table) falls through to
# the replicated default via the scalar/singleton guard or a non-match.
FLEET_STATE_RULES: tuple = ((r".*", P("docs")),)


def fleet_state_specs(state, doc_axes="docs"):
    """Partition specs for a batched engine state pytree (leading doc dim
    sharded over ``doc_axes`` — the plain docs axis, or both axes of a
    docs x segs mesh via ``fleet_doc_axes`` — scalars/singletons
    replicated)."""
    rules = FLEET_STATE_RULES if doc_axes == "docs" else ((r".*", P(doc_axes)),)
    return match_partition_rules(rules, state)


def shard_fleet_state(state, mesh: Mesh):
    """Place a batched fleet state on the mesh per its matched specs."""
    specs = fleet_state_specs(state, fleet_doc_axes(mesh))
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


# ---------------------------------------------------------------------------
# Segment-axis partition rules (hot docs on the docs x segs mesh)
# ---------------------------------------------------------------------------

def seg_state_specs(state, axis: str = SEG_AXIS):
    """Partition specs for a SEG-SHARDED single-doc ``DocState``
    (ops.mergetree_kernel.seg_shard_state layout): per-segment arrays and
    the per-shard live-count vector block-shard over ``axis``; the text
    pool, scalars, and the obliterate window table replicate — the
    ``_specs_for`` layout of parallel/long_doc.py promoted to the serving
    path (where ``nseg`` must be per-shard because inserts land
    shard-local)."""
    from ..ops.mergetree_kernel import DocState

    s, r = P(axis), P()
    return DocState(
        text=r, text_end=r, nseg=s,
        seg_start=s, seg_len=s, ins_key=s, ins_client=s,
        seg_uid=s, seg_obpre=s,
        rem_keys=(s,) * len(state.rem_keys),
        rem_clients=(s,) * len(state.rem_clients),
        prop_keys=(s,) * len(state.prop_keys),
        prop_vals=(s,) * len(state.prop_vals),
        uid_next=r, ob_key=r, ob_client=r, ob_start_uid=r, ob_end_uid=r,
        ob_start_side=r, ob_end_side=r, ob_ref_seq=r,
        min_seq=r, error=r,
    )


def shard_seg_state(state, mesh: Mesh, axis: str = SEG_AXIS):
    """Place a seg-sharded single-doc state on the mesh per its specs."""
    specs = seg_state_specs(state, axis)
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)), state, specs
    )


@functools.lru_cache(maxsize=None)
def mesh_seg_program(step_fn, mesh: Mesh, state_specs,
                     arg_specs: tuple = (P(), P()), donate: bool = False):
    """``jit(shard_map(step_fn))`` over the SEGMENT axis: one dispatch
    applies a [K, B] op ring to one seg-sharded hot document, the
    per-segment work split across the segs shards with the two collective
    hops inside (ops.mergetree_kernel.apply_megastep_seg).  Cached per
    (fn, mesh, specs) like ``mesh_fleet_program`` so every segment lane
    serving the same mesh shares one compile.

    ``donate`` defaults OFF, deliberately: with donation, an executable
    for this program RELOADED from the persistent XLA compile cache
    returns permuted/garbage output buffers whenever the obliterate
    branch executes (jax 0.4.37, CPU; freshly-compiled executables are
    always correct, and tests/test_segment_parallel.py guards the
    byte-identity contract that caught it).  Re-enable only with the
    persistent cache off or after the upstream aliasing bug is fixed."""
    mapped = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(state_specs,) + tuple(arg_specs),
        out_specs=state_specs,
        check_rep=False,  # replicated leaves are replicated by construction
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# shard_map-wrapped fleet programs
# ---------------------------------------------------------------------------

def op_spec(ndim: int, axis: str = "docs") -> P:
    """Spec for an op/payload tensor whose doc axis sits at ``ndim - 3``
    ([..., D, B, F|L]): megastep rings [K, D, B, *] -> P(None, docs),
    single slices [D, B, *] -> P(docs)."""
    return P(*([None] * (ndim - 3)), axis)


@functools.lru_cache(maxsize=None)
def mesh_fleet_program(step_fn, mesh: Mesh, state_specs,
                       arg_specs: tuple = (P(None, "docs"), P(None, "docs")),
                       donate: bool = True):
    """``jit(shard_map(step_fn))``: ONE donated dispatch steps the whole
    fleet, each shard applying its own doc rows with no cross-shard
    communication.  ``state_specs`` must be the hashable pytree
    ``fleet_state_specs`` produces for the engine's state type (NamedTuple
    of PartitionSpec) and ``arg_specs`` the specs of the non-state args
    (default: a [K, D, B, *] megastep op ring pair), so the program cache
    is shared by every engine instance serving the same mesh."""
    mapped = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(state_specs,) + tuple(arg_specs),
        out_specs=state_specs,
        check_rep=False,  # per-doc program: nothing is replicated to check
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


@jax.jit
def error_count(error: jnp.ndarray) -> jnp.ndarray:
    """Fleet error-latch probe as a per-shard reduce: each shard partial-
    sums its own error rows and the host reads ONE scalar — the recover()
    gate no longer gathers the full [D] error vector across the mesh every
    step (the gather happens only when this count is nonzero)."""
    return jnp.sum((error != 0).astype(jnp.int32))


# ---------------------------------------------------------------------------
# Dispatch-seam registration: this module IS the default dispatch plane
# (models/dispatch.py).  The engines resolve it through the registry
# instead of importing parallel.mesh upward — the models -> parallel
# inversion the fftpu-check baseline used to carry.
# ---------------------------------------------------------------------------

import sys as _sys

from ..models.dispatch import register_dispatch_plane as _register

_register(_sys.modules[__name__])
