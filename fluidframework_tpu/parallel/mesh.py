"""Device mesh + sharding for the document axis.

The reference's scale-out axis is per-document sharding (Kafka partitions by
documentId; each deli/lambda instance owns a disjoint doc set —
SURVEY.md §2.6).  The TPU-native equivalent is a 1-D ``Mesh`` over a ``docs``
axis: replica state arrays are sharded on their leading document dimension,
op batches likewise, and the per-step computation is purely doc-parallel so
the ``shard_map``-wrapped fleet programs below run with ZERO collectives on
the hot path (collectives appear only in aggregate metrics/reductions, e.g.
the per-shard error-latch reduce).

Layers:

- ``match_partition_rules``: regex partition-rule matching over a state
  pytree's named leaves -> a pytree of ``PartitionSpec`` (scalars and
  singleton leaves replicate; everything matching a doc rule shards on its
  leading document dimension).
- ``mesh_fleet_program``: wrap a per-doc fleet step (``apply_megastep`` /
  ``apply_nested_megastep`` / compaction) in ``shard_map`` under the mesh
  and ``jax.jit`` with the state donated — one dispatch steps every shard,
  each shard's obliterate gate evaluated from ITS OWN docs (a hot
  obliterate shard no longer de-specializes the whole fleet's trace).
- ``error_count``: the per-shard reduce replacing the full [D] error-vector
  gather on the recover() path — each shard contributes a partial sum, the
  host reads one scalar.

Multi-host pods extend the same mesh across hosts: the doc axis rides
ICI within a slice and DCN across slices — no code change, just a larger
``jax.devices()`` list.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def doc_mesh(devices=None, axis: str = "docs") -> Mesh:
    """A 1-D mesh over all (or the given) devices for document parallelism."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs.reshape(-1), (axis,))


def shard_docs(mesh: Mesh, axis: str = "docs") -> NamedSharding:
    """Sharding for arrays with a leading document dimension."""
    return NamedSharding(mesh, P(axis))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Partition-rule matching over named pytree leaves
# ---------------------------------------------------------------------------

def _key_str(k) -> str:
    """One path entry -> its name (GetAttrKey/SequenceKey/DictKey/...)."""
    for attr in ("name", "idx", "key"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def named_leaves(tree) -> tuple[list[str], list, object]:
    """``(names, leaves, treedef)`` with "a/b/0"-style leaf path names."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = ["/".join(_key_str(k) for k in path) for path, _ in flat]
    return names, [leaf for _, leaf in flat], treedef


def match_partition_rules(rules, tree, default: P = P()):
    """A pytree of ``PartitionSpec`` matching ``tree``: first rule whose
    regex matches the leaf's path name wins; 0-d and singleton leaves
    always replicate (never partition scalars); unmatched leaves take
    ``default`` (replicated)."""
    names, leaves, treedef = named_leaves(tree)
    specs = []
    for name, leaf in zip(names, leaves):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                specs.append(spec)
                break
        else:
            specs.append(default)
    return jax.tree_util.tree_unflatten(treedef, specs)


# The batched engines broadcast every replica leaf to [D, ...], so every
# named leaf of a fleet state carries the leading document axis — per-doc
# scalars included (they are [D] vectors in the batch).  Anything that ever
# loses the doc axis (a future shared pool / global table) falls through to
# the replicated default via the scalar/singleton guard or a non-match.
FLEET_STATE_RULES: tuple = ((r".*", P("docs")),)


def fleet_state_specs(state):
    """Partition specs for a batched engine state pytree (leading doc dim
    sharded over ``docs``, scalars/singletons replicated)."""
    return match_partition_rules(FLEET_STATE_RULES, state)


def shard_fleet_state(state, mesh: Mesh):
    """Place a batched fleet state on the mesh per its matched specs."""
    specs = fleet_state_specs(state)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs
    )


# ---------------------------------------------------------------------------
# shard_map-wrapped fleet programs
# ---------------------------------------------------------------------------

def op_spec(ndim: int, axis: str = "docs") -> P:
    """Spec for an op/payload tensor whose doc axis sits at ``ndim - 3``
    ([..., D, B, F|L]): megastep rings [K, D, B, *] -> P(None, docs),
    single slices [D, B, *] -> P(docs)."""
    return P(*([None] * (ndim - 3)), axis)


@functools.lru_cache(maxsize=None)
def mesh_fleet_program(step_fn, mesh: Mesh, state_specs,
                       arg_specs: tuple = (P(None, "docs"), P(None, "docs")),
                       donate: bool = True):
    """``jit(shard_map(step_fn))``: ONE donated dispatch steps the whole
    fleet, each shard applying its own doc rows with no cross-shard
    communication.  ``state_specs`` must be the hashable pytree
    ``fleet_state_specs`` produces for the engine's state type (NamedTuple
    of PartitionSpec) and ``arg_specs`` the specs of the non-state args
    (default: a [K, D, B, *] megastep op ring pair), so the program cache
    is shared by every engine instance serving the same mesh."""
    mapped = shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(state_specs,) + tuple(arg_specs),
        out_specs=state_specs,
        check_rep=False,  # per-doc program: nothing is replicated to check
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


@jax.jit
def error_count(error: jnp.ndarray) -> jnp.ndarray:
    """Fleet error-latch probe as a per-shard reduce: each shard partial-
    sums its own error rows and the host reads ONE scalar — the recover()
    gate no longer gathers the full [D] error vector across the mesh every
    step (the gather happens only when this count is nonzero)."""
    return jnp.sum((error != 0).astype(jnp.int32))
