"""Native CPU dispatch plane: the second backend behind models/dispatch.

Satisfies the duck-typed plane surface of ``parallel.mesh`` (the default
jax/XLA plane) but routes the two fleet hot-path programs — the
merge-tree megastep and the zamboni compact — through the C++ row loops
of ``native/megastep.cpp`` instead of jit(shard_map) dispatches.  On the
CPU-degraded tail (no accelerator; XLA CPU dispatch is ~99% of the
pipeline per OBS_r07) this is the difference between ~10^2 and ~10^5
replay ops/s on the same box.

Design points:

* **Mesh machinery is delegated**, not faked: ``doc_mesh`` /
  ``shard_docs`` / ``shard_fleet_state`` come straight from
  ``parallel.mesh``, so ``StagingRing.upload``'s NamedSharding
  device_puts and the engines' state broadcast work unchanged.  A
  1-process CPU mesh is a perfectly good Mesh.
* **State stays jax-typed at the seam**: each native dispatch copies the
  int32 columns to writable numpy (the same arrays
  ``summary_to_state_host`` builds), mutates them in place in C++, and
  returns ``jnp.asarray``-wrapped leaves — so engine code that does
  ``.at[slot].set`` on leaves keeps working and checkpoints/scribe folds
  are backend-invariant by construction.
* **Byte identity is the contract**, enforced against the lax oracle by
  tests/test_dispatch_backends.py (full arrays incl. padding remnants,
  plus the per-doc error latch).
* **Seg lanes raise loudly**: the native plane has no segment-parallel
  programs; ``mesh_seg_program``/``seg_state_specs``/``shard_seg_state``
  raise NotImplementedError and ``DocBatchEngine`` maps that to its
  counted fallback (``seg_plane_unsupported``) — no silent degradation.
* **The .so never builds under a lock**: ``megastep_native.warm()`` runs
  only from ``mesh_fleet_program`` (engine construction); serving
  dispatches use the non-building accessors.

Importing this module registers it as THE dispatch plane (last-wins, see
``models.dispatch.register_dispatch_plane``); select it per process with
``FFTPU_DISPATCH_PLANE=fluidframework_tpu.parallel.native_plane``.
Callers flipping planes inside one process (tests, bench) must
re-register the plane they want afterwards.
"""

from __future__ import annotations

import sys as _sys

import jax
import jax.numpy as jnp
import numpy as np

from ..models.dispatch import register_dispatch_plane as _register
from ..native import megastep_native
from ..ops import mergetree_kernel as mk
from . import mesh as _mesh

# ----------------------------------------------------- delegated surface
P = _mesh.P
SEG_AXIS = _mesh.SEG_AXIS
doc_mesh = _mesh.doc_mesh
docs_segs_mesh = _mesh.docs_segs_mesh
fleet_doc_axes = _mesh.fleet_doc_axes
shard_docs = _mesh.shard_docs
replicate = _mesh.replicate
fleet_state_specs = _mesh.fleet_state_specs
shard_fleet_state = _mesh.shard_fleet_state


def available() -> bool:
    """True iff the native megastep library is built (building it if g++
    is present — call at startup, not under a serving lock)."""
    return megastep_native.warm()


# ------------------------------------------------------- fleet programs

def _wrap(state):
    """numpy-backed DocState -> jax-typed leaves (zero/one copy on CPU):
    the engines' ``.at[slot].set`` sites and digests need jnp arrays."""
    return jax.tree.map(jnp.asarray, state)


def _native_megastep(state, ops, payloads):
    return _wrap(megastep_native.megastep(state, ops, payloads))


def _native_compact(state, min_seqs):
    return _wrap(megastep_native.fleet_compact(state, min_seqs))


def mesh_fleet_program(step_fn, mesh, state_specs, arg_specs=None,
                       donate=True):
    """The plane's program factory.  The two fleet hot-path bodies map to
    their native twins; anything else (tree-fleet programs, digests)
    delegates to the jax plane — full correctness, just not native-fast.

    ``warm()`` runs HERE, at program-build time (engine construction,
    outside any serving lock): per the PR 15 split the returned callables
    only ever touch the prebuilt library."""
    if step_fn is mk.apply_megastep:
        if not megastep_native.warm():
            raise RuntimeError(
                "native dispatch plane: libtpumegastep.so unavailable "
                "(g++ build failed?) — use the default jax plane"
            )
        return _native_megastep
    if getattr(step_fn, "__name__", "") == "_fleet_compact_body":
        if not megastep_native.warm():
            raise RuntimeError(
                "native dispatch plane: libtpumegastep.so unavailable "
                "(g++ build failed?) — use the default jax plane"
            )
        return _native_compact
    if arg_specs is None:
        return _mesh.mesh_fleet_program(
            step_fn, mesh, state_specs, donate=donate
        )
    return _mesh.mesh_fleet_program(
        step_fn, mesh, state_specs, arg_specs=arg_specs, donate=donate
    )


def error_count(error) -> int:
    """Host-side error latch count (the jax plane jits a device sum; one
    numpy reduction is the native equivalent)."""
    return int(np.count_nonzero(np.asarray(error)))


# ------------------------------------------------- seg lanes: loud N/A

_SEG_MSG = (
    "native dispatch plane: segment-parallel lanes are not implemented "
    "(docs-sharded serving only); DocBatchEngine falls back to the "
    "doc-sharded path and counts seg_plane_unsupported"
)


def seg_state_specs(*args, **kwargs):
    raise NotImplementedError(_SEG_MSG)


def shard_seg_state(*args, **kwargs):
    raise NotImplementedError(_SEG_MSG)


def mesh_seg_program(*args, **kwargs):
    raise NotImplementedError(_SEG_MSG)


# Self-register (last-wins): importing this module selects the native
# plane for engines constructed afterwards.
_register(_sys.modules[__name__])
