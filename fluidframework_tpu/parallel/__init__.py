"""Mesh construction and document-axis sharding helpers."""

from .mesh import doc_mesh, shard_docs, replicate

__all__ = ["doc_mesh", "shard_docs", "replicate"]
