"""ctypes binding for the native merge-tree megastep (native/megastep.cpp).

The C++ loops apply a [K, D, B] op ring — and the zamboni compact — in
place over the SAME int32 state columns the lax kernel carries, byte
identical to ``ops.mergetree_kernel.apply_megastep`` /
``_fleet_compact_body`` (the conformance contract is enforced by
tests/test_dispatch_backends.py against the lax oracle).  The dispatch
plane built on top lives in ``parallel/native_plane.py``.

Build: ``native/libtpumegastep.so`` compiles with g++ if missing or stale
— but ONLY through ``warm()``/``available()``, which the plane calls at
program-build time (engine construction).  The serving-path entry points
(``loaded``, ``megastep``, ``fleet_compact``) never spawn the compiler:
they can run under the engines' ``ckpt_lock``, where a g++ run would
stall every ingest contender for seconds (fftpu-check
``blocking-under-lock``)."""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "megastep.cpp"
_LIB = _REPO_ROOT / "native" / "libtpumegastep.so"

OP_FIELDS = 8
ABI_VERSION = 1

_lib_cache: list = []
_warmed: list = []

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)

# Column table order — must match megastep.cpp's header comment.
_SCALAR_COLS = ("text_end", "nseg", "uid_next", "min_seq", "error")
_COL_ORDER = (
    "text", "text_end", "nseg", "seg_start", "seg_len", "ins_key",
    "ins_client", "seg_uid", "seg_obpre", "rem_keys", "rem_clients",
    "prop_keys", "prop_vals", "uid_next", "ob_key", "ob_client",
    "ob_start_uid", "ob_end_uid", "ob_start_side", "ob_end_side",
    "ob_ref_seq", "min_seq", "error",
)


def warm() -> bool:
    """Build (when missing or stale vs the source) and load the library,
    eagerly and idempotently.  This is the ONLY entry that runs g++: the
    native plane calls it while building its fleet programs (engine
    ``__init__``, outside any serving lock) — the hot-path accessors
    below only ever LOAD a prebuilt library (same warm/loaded split as
    ``ingest_native``, the PR 15 blocking-under-lock fix)."""
    if _warmed:
        return bool(_lib_cache) and _lib_cache[0] is not None
    _warmed.append(True)
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", str(_LIB), str(_SRC)],
                check=True, capture_output=True,
            )
    except (OSError, subprocess.CalledProcessError):
        pass  # a previously-built library may still load below
    _lib_cache[:] = [_try_load()]
    return _lib_cache[0] is not None


def _ensure_built() -> ctypes.CDLL | None:
    """Serving-path accessor: the cached library, loading a PREBUILT .so
    on first touch — never compiling."""
    if _lib_cache:
        return _lib_cache[0]
    _lib_cache[:] = [_try_load() if _LIB.exists() else None]
    return _lib_cache[0]


def _try_load() -> ctypes.CDLL | None:
    try:
        lib = ctypes.CDLL(str(_LIB))
    except OSError:
        return None
    if not hasattr(lib, "ms_megastep"):
        return None
    lib.ms_abi_version.restype = ctypes.c_int32
    lib.ms_abi_version.argtypes = []
    lib.ms_megastep.restype = ctypes.c_int32
    lib.ms_megastep.argtypes = [_I64P, _I32P, _I32P, _I32P]
    lib.ms_compact.restype = ctypes.c_int32
    lib.ms_compact.argtypes = [_I64P, _I32P, _I32P]
    if lib.ms_abi_version() != ABI_VERSION:
        return None
    return lib


def available() -> bool:
    """Build-on-demand probe for host tools/tests (outside any serving
    lock).  Serving paths use ``loaded()`` instead."""
    return warm()


def loaded() -> bool:
    """Non-building availability probe (safe under the engines' locks)."""
    return _ensure_built() is not None


def state_columns(state) -> tuple[dict, list]:
    """Copy a [D, ...] DocState's leaves into writable, C-contiguous
    numpy columns (tuple fields stacked on a leading axis) plus the
    megastep's column pointer table.  Returns ``(cols, addrs)`` where
    ``cols`` maps field name -> array and ``addrs`` is the int64 pointer
    list in ``_COL_ORDER``."""
    cols: dict[str, np.ndarray] = {}
    for name in _COL_ORDER:
        v = getattr(state, name)
        if isinstance(v, tuple):
            arr = np.ascontiguousarray(
                np.stack([np.asarray(a) for a in v]).astype(
                    np.int32, copy=False
                )
            )
        else:
            # Always a fresh buffer: the caller's leaves (jax arrays or
            # an oracle's numpy state) must never be mutated in place.
            arr = np.array(np.asarray(v), dtype=np.int32, order="C")
        cols[name] = arr
    addrs = [cols[name].ctypes.data for name in _COL_ORDER]
    return cols, addrs


def _dims(state, extra: tuple = ()) -> np.ndarray:
    D = int(np.asarray(state.text_end).shape[0])
    T = int(np.asarray(state.text).shape[-1])
    S = int(np.asarray(state.seg_len).shape[-1])
    R = len(state.rem_keys)
    P = len(state.prop_keys)
    OB = int(np.asarray(state.ob_key).shape[-1])
    return np.array((D, T, S, R, P, OB) + extra, np.int32)


def unpack_columns(state, cols: dict):
    """Rebuild a DocState from mutated columns (stacked tuple fields are
    re-split into per-slot views — zero copy)."""
    kw = {}
    for name in _COL_ORDER:
        arr = cols[name]
        if isinstance(getattr(state, name), tuple):
            kw[name] = tuple(arr[i] for i in range(arr.shape[0]))
        else:
            kw[name] = arr
    return state._replace(**kw)


def megastep(state, ops: np.ndarray, payloads: np.ndarray):
    """Apply a [K, D, B, 8] op ring (+ [K, D, B, L] payloads) to a
    [D, ...] DocState via the native loops; returns the stepped state as
    plain numpy-backed columns.  Raises RuntimeError when the prebuilt
    library is unavailable (callers guard with ``loaded()``/``warm()``)."""
    lib = _ensure_built()
    if lib is None:
        raise RuntimeError("native megastep library unavailable")
    ops = np.ascontiguousarray(np.asarray(ops, dtype=np.int32))
    payloads = np.ascontiguousarray(np.asarray(payloads, dtype=np.int32))
    K, D, B, L = (
        ops.shape[0], ops.shape[1], ops.shape[2], payloads.shape[-1]
    )
    cols, addrs = state_columns(state)
    addr_arr = np.array(addrs, np.int64)
    dims = _dims(state, (K, B, L))
    rc = lib.ms_megastep(
        addr_arr.ctypes.data_as(_I64P),
        dims.ctypes.data_as(_I32P),
        ops.ctypes.data_as(_I32P),
        payloads.ctypes.data_as(_I32P),
    )
    if rc != 0:
        raise RuntimeError(f"native megastep failed (rc={rc}): dims {dims}")
    return unpack_columns(state, cols)


def fleet_compact(state, min_seqs: np.ndarray):
    """set_min_seq + zamboni compact for every doc (the native twin of
    models.doc_batch_engine._fleet_compact_body)."""
    lib = _ensure_built()
    if lib is None:
        raise RuntimeError("native megastep library unavailable")
    min_seqs = np.ascontiguousarray(np.asarray(min_seqs, dtype=np.int32))
    cols, addrs = state_columns(state)
    addr_arr = np.array(addrs, np.int64)
    dims = _dims(state)
    rc = lib.ms_compact(
        addr_arr.ctypes.data_as(_I64P),
        dims.ctypes.data_as(_I32P),
        min_seqs.ctypes.data_as(_I32P),
    )
    if rc != 0:
        raise RuntimeError(f"native compact failed (rc={rc}): dims {dims}")
    return unpack_columns(state, cols)
