"""Native (C++) runtime components.

Where the reference leans on native code for its service hot paths (the
librdkafka ordering client, SURVEY §2 notes the deli ticket loop as the
ordering kernel), this package provides C++ equivalents with ctypes
bindings, built on demand from ``native/`` at the repo root. Everything has
a pure-Python twin used as the differential oracle; the native form is the
production path for host-side sequencing around the TPU compute.
"""

from .sequencer_native import NativeSequencer, native_available

__all__ = ["NativeSequencer", "native_available"]
