"""ctypes binding for the native wire-ingest encoder (native/ingest.cpp).

One ``NativeIngestEncoder`` per document: JSON-lines sequenced messages in,
kernel op-row tensors out — the whole decode+encode path (JSON parse,
quorum lookup, insert chunking, property interning) runs in C++, replacing
the per-op Python that bounds the fleet's ingest rate.  Differentially
tested against the Python path in tests/test_native_ingest.py.

Build: ``native/libtpuingest.so`` compiles with g++ if missing or stale
(same scheme as the native sequencer; no pip/pybind11 dependencies) — but
ONLY through ``warm()``/``available()``, which the engines call at
construction time.  The serving-path accessors (``loaded``,
``tree_decode``, ``NativeIngestEncoder``) never spawn the compiler: they
run under the engines' ``ckpt_lock``, where a g++ run would stall every
ingest contender for seconds (fftpu-check ``blocking-under-lock``).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "ingest.cpp"
_LIB = _REPO_ROOT / "native" / "libtpuingest.so"

OP_FIELDS = 8

_lib_cache: list = []
_warmed: list = []


def warm() -> bool:
    """Build (when missing or stale vs the source) and load the library,
    eagerly and idempotently.  This is the ONLY entry that runs g++: call
    it at process/engine startup, never from a serving path — the lazy
    rebuild used to be reachable under the engines' ``ckpt_lock``, and a
    multi-second compiler run under the serving lock convoys every ingest
    (fftpu-check blocking-under-lock: subprocess under ckpt_lock).  The
    engines warm in ``__init__``; the hot-path accessors below only ever
    LOAD a prebuilt library.

    The idempotence latch is the WARM flag, not the lib cache: a
    non-building accessor touched first may have cached a loadable but
    STALE .so, and the first warm() must still run the staleness rebuild
    (already-constructed encoders keep their old handle; everything after
    the warm sees the fresh library)."""
    if _warmed:
        return bool(_lib_cache) and _lib_cache[0] is not None
    _warmed.append(True)
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", str(_LIB), str(_SRC)],
                check=True, capture_output=True,
            )
    except (OSError, subprocess.CalledProcessError):
        pass  # a previously-built library may still load below
    _lib_cache[:] = [_try_load()]
    return _lib_cache[0] is not None


def _ensure_built() -> ctypes.CDLL | None:
    """Serving-path accessor: the cached library, loading a PREBUILT .so
    on first touch — never compiling.  Returns None when no usable
    prebuilt library exists (the callers fall back to the Python decode
    paths); ``warm()`` upgrades a None verdict after building."""
    if _lib_cache:
        return _lib_cache[0]
    _lib_cache[:] = [_try_load() if _LIB.exists() else None]
    return _lib_cache[0]


def _try_load() -> ctypes.CDLL | None:
    try:
        lib = ctypes.CDLL(str(_LIB))
    except OSError:
        return None
    lib.ing_create.restype = ctypes.c_void_p
    lib.ing_create.argtypes = [ctypes.c_int32, ctypes.c_int32]
    lib.ing_destroy.argtypes = [ctypes.c_void_p]
    lib.ing_min_seq.restype = ctypes.c_int64
    lib.ing_min_seq.argtypes = [ctypes.c_void_p]
    lib.ing_last_error.restype = ctypes.c_char_p
    lib.ing_last_error.argtypes = [ctypes.c_void_p]
    lib.ing_encode.restype = ctypes.c_int32
    lib.ing_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    # Prop-table export (checkpoint fidelity): absent from prebuilt .so
    # files older than the symbol — gate, don't crash (prop_table()
    # returns {} and checkpoints keep the legacy slot-number ids).
    if hasattr(lib, "ing_prop_table"):
        lib.ing_prop_table.restype = ctypes.c_int32
        lib.ing_prop_table.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
    # Tree wire decode: same symbol-presence gate (a stale prebuilt .so
    # simply keeps the Python tree decode).
    if hasattr(lib, "ing_tree_decode"):
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.ing_tree_decode.restype = ctypes.c_int32
        lib.ing_tree_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            i64p, ctypes.c_int32, i32p, ctypes.c_int32,
            i32p, ctypes.c_int32, i32p, ctypes.c_int32,
            i64p, ctypes.c_int32, i32p, i32p,
        ]
    return lib


def available() -> bool:
    """Build-on-demand probe for host tools/tests (outside any serving
    lock).  Serving paths use the non-building accessors instead."""
    return warm()


def loaded() -> bool:
    """Non-building availability probe for serving paths (safe under the
    engines' locks): True iff a prebuilt library is loaded/loadable."""
    return _ensure_built() is not None


class NativeIngestEncoder:
    """Per-document native wire decoder (quorum + prop tables live in C++)."""

    def __init__(self, max_insert_len: int = 64, prop_slots: int = 4) -> None:
        lib = _ensure_built()
        if lib is None:
            raise RuntimeError("native ingest encoder unavailable (g++ build failed)")
        self._lib = lib
        self.max_insert_len = max_insert_len
        self._h = lib.ing_create(max_insert_len, prop_slots)

    def __del__(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ing_destroy(self._h)
            self._h = None

    @property
    def min_seq(self) -> int:
        return int(self._lib.ing_min_seq(self._h))

    def prop_table(self) -> dict[int, int]:
        """The C++ property interning table as ``{prop_id: kernel slot}``.

        Checkpoint fidelity (ROADMAP): the engine folds this into its host
        table before summarizing a native-mode doc, so checkpoints carry
        the documents' REAL annotation property ids — a restored doc's
        annotations round-trip instead of surfacing private slot numbers.
        Empty when the loaded library predates the export."""
        if not hasattr(self._lib, "ing_prop_table"):
            return {}
        cap = 16
        while True:
            props = np.empty((cap,), np.int64)
            slots = np.empty((cap,), np.int32)
            n = self._lib.ing_prop_table(
                self._h,
                props.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                cap,
            )
            if n < cap:
                return {int(props[i]): int(slots[i]) for i in range(n)}
            cap *= 2

    def encode(self, data: bytes, max_rows: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Newline-separated JSON messages -> (ops[M, 8], payloads[M, L])."""
        if max_rows <= 0:
            # Every line yields at most a handful of rows; newline count is a
            # safe starting capacity, doubled on overflow.
            max_rows = max(16, 2 * (data.count(b"\n") + 1))
        while True:
            # np.empty is safe: the encoder writes every field of each row
            # it returns (payload rows are memset before use).
            ops = np.empty((max_rows, OP_FIELDS), np.int32)
            payloads = np.empty((max_rows, self.max_insert_len), np.int32)
            n = self._lib.ing_encode(
                self._h, data, len(data),
                ops.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                payloads.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                max_rows,
            )
            if n == -1:
                raise ValueError(
                    f"native ingest: {self._lib.ing_last_error(self._h).decode()}"
                )
            if n < -1:  # capacity exhausted mid-stream: grow and re-run
                max_rows *= 2
                continue
            return ops[:n], payloads[:n]


# ---------------------------------------------------------------------------
# Tree wire decode
# ---------------------------------------------------------------------------

# Row widths (mirror native/ingest.cpp ing_tree_decode).
_TREE_MSG_FIELDS = 14
_TREE_CHG_FIELDS = 3
_TREE_FLD_FIELDS = 4
_TREE_MARK_FIELDS = 5

TREE_ST_EDITS, TREE_ST_SKIP, TREE_ST_OPAQUE = 0, 1, 2


def tree_decode_available() -> bool:
    lib = _ensure_built()
    return lib is not None and hasattr(lib, "ing_tree_decode")


def tree_decode(data: bytes):
    """Decode newline-separated sequenced tree messages into mark-pool
    columns (stateless; the whole-feed grow-and-retry contract of
    ``NativeIngestEncoder.encode``).

    Returns ``(msgs, chgs, flds, marks, spans)`` numpy tables — see the
    C header comment for layouts — or ``None`` when the library (or the
    ``ing_tree_decode`` symbol on a stale prebuilt .so) is unavailable.
    Raises ``ValueError`` on a malformed line (message index included),
    matching the Python path's ownership of error semantics."""
    lib = _ensure_built()
    if lib is None or not hasattr(lib, "ing_tree_decode"):
        return None
    n_lines = data.count(b"\n") + 1
    m_msgs = max(16, n_lines)
    m_chgs = m_flds = max(32, 2 * n_lines)
    m_marks = m_spans = max(64, 8 * n_lines)
    while True:
        msgs = np.empty((m_msgs, _TREE_MSG_FIELDS), np.int64)
        chgs = np.empty((m_chgs, _TREE_CHG_FIELDS), np.int32)
        flds = np.empty((m_flds, _TREE_FLD_FIELDS), np.int32)
        marks = np.empty((m_marks, _TREE_MARK_FIELDS), np.int32)
        spans = np.empty((m_spans, 2), np.int64)
        counts = np.zeros((5,), np.int32)
        err_line = np.zeros((1,), np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        n = lib.ing_tree_decode(
            data, len(data),
            msgs.ctypes.data_as(i64p), m_msgs,
            chgs.ctypes.data_as(i32p), m_chgs,
            flds.ctypes.data_as(i32p), m_flds,
            marks.ctypes.data_as(i32p), m_marks,
            spans.ctypes.data_as(i64p), m_spans,
            counts.ctypes.data_as(i32p),
            err_line.ctypes.data_as(i32p),
        )
        if n == -1:
            raise ValueError(
                f"native tree decode: malformed message at line "
                f"{int(err_line[0])}"
            )
        if n == -2:  # some table filled: double everything, re-run
            m_msgs *= 2
            m_chgs *= 2
            m_flds *= 2
            m_marks *= 2
            m_spans *= 2
            continue
        return (
            msgs[: counts[0]], chgs[: counts[1]], flds[: counts[2]],
            marks[: counts[3]], spans[: counts[4]],
        )
