"""ctypes binding for the native deli sequencer (native/sequencer.cpp).

Drop-in replacement for ``server.sequencer.Sequencer``: same public
surface (join/leave/ticket/mint_service/clients membership, checkpoint/
restore, seq/min_seq/log — ``clients()`` maps client id to short id rather
than full ClientEntry objects) and bit-identical sequencing decisions —
enforced by the differential suite in tests/test_native_sequencer.py. The integer state machine runs in C++;
message-object construction stays in Python (it is not the hot part).

Build: ``native/libtpusequencer.so`` is compiled on demand with g++ if the
checked-in binary is missing or stale (no pip/pybind11 dependencies).
"""

from __future__ import annotations

import ctypes
import subprocess
import time
from pathlib import Path

from ..protocol.messages import MessageType, Nack, SequencedMessage, UnsequencedMessage

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "sequencer.cpp"
_LIB = _REPO_ROOT / "native" / "libtpusequencer.so"

_NACK_REASONS = {
    1: "client not joined",
    2: "refSeq below MSN",
    3: "refSeq from the future",
    4: "clientSeq out of order",
}


def _ensure_built() -> ctypes.CDLL | None:
    try:
        if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", str(_LIB), str(_SRC)],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(str(_LIB))
    except (OSError, subprocess.CalledProcessError):
        return None
    lib.seq_create.restype = ctypes.c_void_p
    lib.seq_create.argtypes = [ctypes.c_int64]
    lib.seq_destroy.argtypes = [ctypes.c_void_p]
    lib.seq_current.restype = ctypes.c_int64
    lib.seq_current.argtypes = [ctypes.c_void_p]
    lib.seq_min.restype = ctypes.c_int64
    lib.seq_min.argtypes = [ctypes.c_void_p]
    lib.seq_client_count.restype = ctypes.c_int32
    lib.seq_client_count.argtypes = [ctypes.c_void_p]
    lib.seq_join.restype = ctypes.c_int32
    lib.seq_join.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.seq_leave.restype = ctypes.c_int32
    lib.seq_leave.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.seq_ticket.restype = ctypes.c_int32
    lib.seq_ticket.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.seq_mint_service.restype = ctypes.c_int64
    lib.seq_mint_service.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.seq_checkpoint.restype = ctypes.c_int64
    lib.seq_checkpoint.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64
    ]
    lib.seq_restore.restype = ctypes.c_void_p
    lib.seq_restore.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
    return lib


_lib = _ensure_built()


def native_available() -> bool:
    return _lib is not None


class NativeSequencer:
    """C++-backed sequencer with the Python Sequencer's surface."""

    def __init__(self, starting_seq: int = 0, _handle=None) -> None:
        if _lib is None:
            raise RuntimeError("native sequencer library unavailable")
        self._h = _handle if _handle is not None else _lib.seq_create(starting_seq)
        self.log: list[SequencedMessage] = []
        self._members: dict[str, int] = {}  # client id -> short id

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h and _lib is not None:
            _lib.seq_destroy(h)
            self._h = None

    # ------------------------------------------------------------------ admin
    @property
    def seq(self) -> int:
        return _lib.seq_current(self._h)

    @property
    def min_seq(self) -> int:
        return _lib.seq_min(self._h)

    def clients(self) -> dict[str, int]:
        """client id -> short id for currently joined clients."""
        assert len(self._members) == _lib.seq_client_count(self._h)
        return dict(self._members)

    def __contains__(self, client_id: str) -> bool:
        return client_id in self._members

    # ------------------------------------------------------------------ joins
    def join(self, client_id: str) -> SequencedMessage:
        out_seq = ctypes.c_int64()
        out_min = ctypes.c_int64()
        short = _lib.seq_join(self._h, client_id.encode(), ctypes.byref(out_seq), ctypes.byref(out_min))
        if short < 0:
            raise ValueError(f"duplicate join: {client_id}")
        self._members[client_id] = short
        msg = SequencedMessage(
            client_id=client_id,
            client_seq=0,
            ref_seq=out_seq.value - 1,
            seq=out_seq.value,
            min_seq=out_min.value,
            type=MessageType.JOIN,
            contents={"clientId": client_id, "short": short},
            metadata=None,
            timestamp=time.time(),
            short_client=short,
        )
        self.log.append(msg)
        return msg

    def leave(self, client_id: str) -> SequencedMessage:
        out_seq = ctypes.c_int64()
        out_min = ctypes.c_int64()
        out_cseq = ctypes.c_int64()
        out_rseq = ctypes.c_int64()
        short = _lib.seq_leave(
            self._h, client_id.encode(), ctypes.byref(out_seq), ctypes.byref(out_min),
            ctypes.byref(out_cseq), ctypes.byref(out_rseq),
        )
        if short < 0:
            raise ValueError(f"leave of unjoined client: {client_id}")
        self._members.pop(client_id, None)
        msg = SequencedMessage(
            client_id=client_id,
            client_seq=out_cseq.value,
            ref_seq=out_rseq.value,
            seq=out_seq.value,
            min_seq=out_min.value,
            type=MessageType.LEAVE,
            contents={"clientId": client_id},
            metadata=None,
            timestamp=time.time(),
            short_client=short,
        )
        self.log.append(msg)
        return msg

    # ----------------------------------------------------------------- ticket
    def ticket(self, msg: UnsequencedMessage) -> SequencedMessage | Nack:
        out_seq = ctypes.c_int64()
        out_min = ctypes.c_int64()
        out_short = ctypes.c_int32()
        rc = _lib.seq_ticket(
            self._h, msg.client_id.encode(), msg.client_seq, msg.ref_seq,
            ctypes.byref(out_seq), ctypes.byref(out_min), ctypes.byref(out_short),
        )
        if rc != 0:
            return Nack(msg.client_id, msg.client_seq, _NACK_REASONS[rc])
        out = SequencedMessage(
            client_id=msg.client_id,
            client_seq=msg.client_seq,
            ref_seq=msg.ref_seq,
            seq=out_seq.value,
            min_seq=out_min.value,
            type=msg.type,
            contents=msg.contents,
            metadata=msg.metadata,
            timestamp=time.time(),
            short_client=out_short.value,
        )
        self.log.append(out)
        return out

    def mint_service(self, mtype: str, contents) -> SequencedMessage:
        out_min = ctypes.c_int64()
        seq = _lib.seq_mint_service(self._h, ctypes.byref(out_min))
        # Scribe-driven MSN plumbing (mirror Sequencer.mint_service): a
        # summary ack carries the ack-derived compaction floor.  The floor
        # itself is Python-side state — the C++ core predates acks and its
        # checkpoint format must stay stable — so a restore conservatively
        # restarts the floor at 0 (compaction lags, never overruns).
        if mtype == MessageType.SUMMARY_ACK and isinstance(contents, dict):
            ref = contents.get("refSeq")
            if isinstance(ref, int):
                self._ack_floor = max(getattr(self, "_ack_floor", 0), ref)
            contents.setdefault(
                "msn", min(getattr(self, "_ack_floor", 0), out_min.value)
            )
        out = SequencedMessage(
            client_id="__service__",
            client_seq=0,
            ref_seq=seq - 1,
            seq=seq,
            min_seq=out_min.value,
            type=mtype,
            contents=contents,
            metadata=None,
            timestamp=time.time(),
            short_client=-1,
        )
        self.log.append(out)
        return out

    # ------------------------------------------------------------- checkpoint
    def checkpoint_bytes(self) -> bytes:
        n = _lib.seq_checkpoint(self._h, None, 0)
        buf = (ctypes.c_uint8 * n)()
        _lib.seq_checkpoint(self._h, buf, n)
        return bytes(buf)

    @staticmethod
    def restore_bytes(data: bytes) -> "NativeSequencer":
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        h = _lib.seq_restore(buf, len(data))
        if not h:
            raise ValueError("truncated or corrupt sequencer checkpoint")
        out = NativeSequencer(_handle=h)
        out._members = _parse_checkpoint_members(data)
        return out


def _parse_checkpoint_members(data: bytes) -> dict[str, int]:
    """Read the client table from the flat checkpoint layout (see
    seq_checkpoint in native/sequencer.cpp)."""
    import struct

    off = 8 + 8 + 4  # seq, min_seq, next_short
    (n,) = struct.unpack_from("<i", data, off)
    off += 4
    members: dict[str, int] = {}
    for _ in range(n):
        short, _cseq, _rseq, slen = struct.unpack_from("<iqqi", data, off)
        off += 4 + 8 + 8 + 4
        name = data[off : off + slen].decode()
        off += slen
        members[name] = short
    return members
