"""Engine-owned dispatch seam: the models -> parallel.mesh inversion.

PR 6 made the mesh the production serving path, which left the batched
engines (state layer) importing ``parallel.mesh`` (orchestration layer) —
an upward edge the fftpu-check baseline carried with a rationale ever
since.  This module inverts it: the engines depend on an abstract
**dispatch plane** — the object that owns mesh construction, state
sharding, and the jitted ``shard_map`` program factories — and the
concrete plane registers itself here when its module loads.

Resolution order:

1. whatever called :func:`register_dispatch_plane` first (in-process
   composition: importing ``fluidframework_tpu.parallel.mesh`` anywhere —
   to build a mesh, which every mesh-passing caller already does —
   registers it);
2. otherwise the provider named by ``FFTPU_DISPATCH_PLANE`` (a dotted
   module path) is loaded and must self-register — the multi-backend
   seam: an alternative serving plane (single-host, virtual, a future
   non-JAX backend) binds here without the engines changing;
3. the default provider is ``fluidframework_tpu.parallel.mesh``.

The plane's surface is duck-typed (the default provider is the
``parallel.mesh`` module itself); engines use:

- ``doc_mesh()`` / ``docs_segs_mesh(seg_shards=)`` — mesh construction
- ``shard_fleet_state`` / ``fleet_doc_axes`` / ``fleet_state_specs`` /
  ``shard_docs`` — fleet placement
- ``mesh_fleet_program`` / ``mesh_seg_program`` — jitted dispatch
- ``seg_state_specs`` / ``shard_seg_state`` / ``SEG_AXIS`` — segment lanes
- ``error_count`` — the per-shard error-latch reduce
- ``P`` — PartitionSpec re-export
"""

from __future__ import annotations

import importlib
import os

_PLANE = None

DEFAULT_PROVIDER = "fluidframework_tpu.parallel.mesh"


def register_dispatch_plane(plane):
    """Install the concrete dispatch plane (called by the provider module
    at import time).  Last registration wins — tests swap in fakes."""
    global _PLANE
    _PLANE = plane
    return plane


def dispatch_plane():
    """The active dispatch plane, loading the configured provider on
    first use (the composition-root binding; see module docstring)."""
    if _PLANE is None:
        provider = os.environ.get("FFTPU_DISPATCH_PLANE", DEFAULT_PROVIDER)
        importlib.import_module(provider)
        if _PLANE is None:
            raise RuntimeError(
                f"dispatch provider {provider!r} did not register a plane "
                "(it must call models.dispatch.register_dispatch_plane)"
            )
    return _PLANE
