"""Recovery plane shared by the batched engines (ISSUE 12).

SOAK_r10 measured the stack's availability gap precisely: p99 op latency
under fault is ~16.8 s against a 93 ms p50, and the whole tail is the
``fleet_kill`` -> restore -> replay window.  This module holds the pieces
that crush it, shared by ``DocBatchEngine`` and ``TreeBatchEngine``:

- ``load_checkpoint_records`` — the batched-restore load phase: every
  doc's durable record fetched concurrently (thread pool over the
  checkpoint store) instead of one JSON read at a time.
- ``RecoveryTracker`` — the per-incident recovery clock: a supervisor
  stamps the kill time (``engine.note_incident``), restore keeps the
  clock running, and the first post-restore op applied on device closes
  the incident into a mergeable histogram (``recovery_p50_ms`` /
  ``recovery_p99_ms`` in health, fleet status, /metrics, and the soak
  artifact).
- ``BackgroundCheckpointWriter`` — bounded-staleness delta checkpoints: a
  daemon thread sweeping the engine's DIRTY docs on a cadence, writing a
  record for any doc whose durable floor fell more than ``max_ops_behind``
  applied ops or ``max_seconds_behind`` seconds behind the live stream.
  The replay tail a restore must cover is then bounded by these knobs
  even for docs too cold to ever hit ``checkpoint_every`` — exactly the
  docs whose recovery replay used to stretch back to their last busy
  period.

Thread-safety contract: the writer thread only ever enters the engine
through ``engine.checkpoint_stale``, which serializes against the serving
thread on the engine's own checkpoint lock (``ckpt_lock`` — taken by
``step``/``ingest*``/``maybe_checkpoint``/``restore_from_checkpoints``).
The writer's own counters are guarded by its private lock because
``stats()`` reads them from the supervising thread (fftpu-check
thread-shared-state: locks, not silent races).
"""

from __future__ import annotations

import threading
import time

from ..observability.flight_recorder import instant, span
from ..utils.telemetry import Histogram


def load_checkpoint_records(
    store, doc_keys: list[str], parallel: bool = True,
    max_workers: int | None = None,
) -> dict[int, dict]:
    """Load every listed doc's checkpoint record; returns {index in
    ``doc_keys`` -> record} for the docs that have one.

    The parallel path uses the store's ``load_many`` when it provides one
    (``CheckpointStore`` does: a thread pool over per-doc JSON reads —
    restore wall time becomes max(read), not sum(read)).  Stores without
    ``load_many`` (e.g. the scribe's read-only ``SummaryRecordStore``,
    whose object-store thread safety is not guaranteed) and the
    ``parallel=False`` oracle path load sequentially.  Either way the
    result is keyed by position, so the caller's doc-order build loop is
    identical — load concurrency can never reorder restores.
    """
    load_many = getattr(store, "load_many", None) if parallel else None
    with span(
        "restore_load", docs=len(doc_keys),
        parallel=int(load_many is not None),
    ):
        if load_many is not None:
            by_key = load_many(doc_keys, max_workers=max_workers)
        else:
            by_key = {k: store.load(k) for k in doc_keys}
    return {
        i: rec
        for i, k in enumerate(doc_keys)
        if (rec := by_key.get(k)) is not None
    }


def stale_due_docs(
    hosts, n_docs: int, max_ops_behind: int, max_seconds_behind: float,
    now: float,
) -> list[int]:
    """The bounded-staleness due list shared by both engines: dirty docs
    whose durable record trails by more than the configured op/second
    bounds (0 disables a bound)."""
    return [
        d for d in range(n_docs)
        if hosts[d].ops_since_ckpt > 0 and (
            (max_ops_behind and hosts[d].ops_since_ckpt >= max_ops_behind)
            or (
                max_seconds_behind
                and hosts[d].dirty_since
                and now - hosts[d].dirty_since >= max_seconds_behind
            )
        )
    ]


def write_checkpoint_records(
    engine, pending: list[tuple[int, int, dict]], default_lane: str
) -> None:
    """Durable half of a checkpoint sweep, shared by both engines and run
    AFTER ``ckpt_lock`` releases (crash-safe: the in-memory floor
    advancing first only means a crash before the write replays a little
    more from the upstream log).  ``_ckpt_io_lock`` + per-doc seq fencing
    keep concurrent sweeps (background writer vs the serving thread's
    cadence) from racing an older record over a newer one.  A FAILED save
    re-marks its doc dirty for retry — taken outside ``_ckpt_io_lock``,
    in the same ckpt-before-io order as the serving thread, so there is
    no deadlock — because the floor already advanced in memory and
    without the re-mark a quiet doc's stale record would hide behind
    healthy-looking gauges."""
    if not pending:
        return
    failed: list[int] = []
    for d, seq, record in pending:
        # io_lock held PER RECORD, not across the batch: a cadence
        # checkpoint from step() (which holds the re-entrant ckpt_lock)
        # that lands here mid-background-sweep waits behind at most one
        # fsync, not the writer's whole batch — a batch-wide hold would
        # convoy every ingest/step on ckpt_lock for the full sweep.
        with engine._ckpt_io_lock:
            if seq < engine._ckpt_saved_seq.get(d, -1):
                continue  # a concurrent sweep already wrote newer
            try:
                with span("checkpoint", doc=engine.doc_keys[d],
                          lane=record.get("lane", default_lane)):
                    engine.checkpoint_store.save(
                        engine.doc_keys[d], seq, record
                    )
            except OSError:
                failed.append(d)
                continue
            engine._ckpt_saved_seq[d] = seq
    if failed:
        with engine.ckpt_lock:
            for d in failed:
                h = engine.hosts[d]
                h.ops_since_ckpt = max(1, h.ops_since_ckpt)
                if not h.dirty_since:
                    h.dirty_since = time.monotonic()
        engine.counters.bump("checkpoint_write_failures", len(failed))


class RecoveryTracker:
    """Per-incident recovery clock: kill (or restore start) -> first
    post-restore op applied on device.

    ``begin`` is idempotent-earliest: a supervisor that knows the actual
    kill time stamps it first (``engine.note_incident``) and a later
    restore-start begin cannot shrink the measured window.  ``complete``
    (called from the engine's step sync boundary once real ops applied)
    closes the incident into the histogram and emits a flight-recorder
    instant, so every incident is visible in a trace next to its
    restore-phase spans."""

    def __init__(self) -> None:
        self.histogram = Histogram()
        self.incidents = 0
        self.last_ms: float | None = None
        self._t0: float | None = None

    def begin(self, started_at: float | None = None) -> None:
        """Open (or back-date) the current incident.  ``started_at`` is in
        ``time.monotonic`` domain; None = now."""
        t0 = time.monotonic() if started_at is None else float(started_at)
        if self._t0 is None or t0 < self._t0:
            self._t0 = t0

    @property
    def active(self) -> bool:
        return self._t0 is not None

    @property
    def started_at(self) -> float | None:
        """The open incident's start (``time.monotonic`` domain), or None.
        A supervisor replacing the engine mid-incident carries this onto
        the successor (``note_incident``) so the unresolved window is
        measured, not dropped."""
        return self._t0

    def cancel(self) -> None:
        """Abandon the open incident without recording it (a standby's
        boot-time restore is preparation, not recovery — only a real
        promotion/restart should measure)."""
        self._t0 = None

    def complete(self) -> float | None:
        """Close the open incident; returns the recovery seconds (None if
        no incident was open)."""
        if self._t0 is None:
            return None
        dt = max(0.0, time.monotonic() - self._t0)
        self._t0 = None
        self.incidents += 1
        self.last_ms = round(dt * 1e3, 3)
        self.histogram.record(dt)
        instant("recovery_complete", ms=self.last_ms)
        return dt

    def emit_gauges(self, counters) -> None:
        """The engines' shared health() surface for recovery time."""
        counters.gauge("recovery_incidents", self.incidents)
        counters.gauge("recovery_pending", int(self.active))
        if self.histogram.count:
            counters.gauge(
                "recovery_p50_ms",
                round(self.histogram.percentile(0.5) * 1e3, 3),
            )
            counters.gauge(
                "recovery_p99_ms",
                round(self.histogram.percentile(0.99) * 1e3, 3),
            )
            counters.gauge("last_recovery_ms", self.last_ms)


class BackgroundCheckpointWriter:
    """Bounded-staleness delta-checkpoint writer (daemon thread).

    Every ``interval_s`` the thread asks the engine to checkpoint any
    dirty doc whose durable record has fallen ``max_ops_behind`` applied
    ops or ``max_seconds_behind`` seconds behind (``engine.
    checkpoint_stale`` — which takes the engine's checkpoint lock, so the
    sweep serializes against the serving thread's step/ingest).  The
    engine's own ``checkpoint_every`` cadence keeps hot docs bounded by
    op count; this writer bounds the COLD tail — a doc that went quiet
    one op after its last checkpoint stays one op (not one busy-period)
    of replay away from restored.
    """

    def __init__(
        self,
        engine,
        max_ops_behind: int = 0,
        max_seconds_behind: float = 1.0,
        interval_s: float = 0.25,
    ) -> None:
        self._engine = engine
        self.max_ops_behind = int(max_ops_behind)
        self.max_seconds_behind = float(max_seconds_behind)
        self.interval_s = max(0.01, float(interval_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Guards the sweep counters: the thread body writes them, stats()
        # reads them from the supervising thread.
        self._lock = threading.Lock()
        self._sweeps = 0
        self._written = 0
        self._errors = 0

    def start(self) -> "BackgroundCheckpointWriter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            # A sweep failure must not kill the writer: the engine already
            # re-marks docs whose durable write failed, so the next tick
            # retries; the error count is the health signal.
            try:
                wrote = self._engine.checkpoint_stale(
                    max_ops_behind=self.max_ops_behind,
                    max_seconds_behind=self.max_seconds_behind,
                )
            except Exception:  # noqa: BLE001 — surfaced via stats()
                with self._lock:
                    self._sweeps += 1
                    self._errors += 1
                continue
            with self._lock:
                self._sweeps += 1
                self._written += len(wrote)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "ckpt_writer_sweeps": self._sweeps,
                "ckpt_writer_records": self._written,
                "ckpt_writer_errors": self._errors,
                "max_ops_behind": self.max_ops_behind,
                "max_seconds_behind": self.max_seconds_behind,
            }
