"""Assembled end-to-end engines (the benchmark targets).

``DocBatchEngine`` is the flagship: a server-side replica of thousands of
documents whose sequenced-op streams are applied in batched device steps —
the TPU-native expression of the reference's inbound-op hot path
(ContainerRuntime.process -> DDS apply) across a whole fleet of containers.
"""

from .doc_batch_engine import DocBatchEngine
from .placement import AdoptResult, PlacementError, PlacementPlane

__all__ = [
    "AdoptResult",
    "DocBatchEngine",
    "PlacementError",
    "PlacementPlane",
]
