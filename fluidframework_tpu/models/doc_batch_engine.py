"""DocBatchEngine: batched sequenced-op application across many documents.

The north-star configuration (BASELINE.json): thousands of SharedString
documents, each with its own totally-ordered op stream, applied in lockstep
device steps — ``vmap`` of the per-doc merge-tree kernel over a leading
document axis, sharded over a TPU mesh along ``docs``.

Host/device split (mirrors the reference's seam at
ContainerRuntime.processInboundMessages, containerRuntime.ts:3428 — where
contiguous ops are bunched before DDS apply; here the bunch becomes a
[D, B] tensor step):

- host: per-doc staging queues of sequenced messages, op encoding (stamp
  keys, positions, payload codepoints), quorum (clientId -> short id)
- device: ``step`` = vmap(scan(apply_op)) — applies up to B ops for each of
  D documents in one XLA program

This engine is the pure-replica path (no local pending ops): every op is a
remote sequenced apply, exactly the scenario of a server-side/materialized
replica fleet.  Client-side engines with pending/ack live in dds/.

Capacity overflow recovery (the kernel latches ERR_* bits instead of
trapping — mergetree_kernel.py): after every ``step`` the engine inspects
the fleet's error vector and recovers any flagged document, so no error bit
ever survives a run.  Recovery policy:

- ``"grow"`` (default): re-provision the document in an *overflow lane* — a
  single-doc DocState with the implicated capacity axes doubled — and
  replay its retained wire log from scratch (deterministic: the log is the
  total order).  Repeated overflows double again up to ``max_growths``,
  then fall through to the oracle.  Lanes keep applying on device (jit per
  geometry, cached), they just leave the lockstep batch.
- ``"oracle"``: replay the log through the host RefMergeTree and route all
  future ops there (the reference analog of a document leaving the fast
  path; SURVEY §7 capacity-management risk).

ERR_POS_RANGE is not recoverable by capacity: a malformed sequenced op
would corrupt every conforming replica, so the engine raises.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.mergetree_ref import RefMergeTree
from ..dds.shared_string import decode_obliterate_places
from ..ops import mergetree_kernel as mk
from ..parallel.mesh import doc_mesh, shard_docs
from ..protocol.messages import DeltaType, MessageType, SequencedMessage


@dataclass
class _DocHost:
    """Host-side per-document bookkeeping."""

    quorum: dict[str, int] = field(default_factory=dict)
    queue: list[np.ndarray] = field(default_factory=list)
    payloads: list[np.ndarray] = field(default_factory=list)
    min_seq: int = 0
    # Property id -> kernel prop slot (interned per document).
    prop_slot: dict[int, int] = field(default_factory=dict)
    # Retained wire log (every OP message, in sequence order): the replay
    # source for overflow recovery.  Docs fed through the native byte path
    # retain raw lines instead (mode is fixed per doc at first ingest).
    log: list[SequencedMessage] = field(default_factory=list)
    raw_log: list[bytes] = field(default_factory=list)
    native: object = None  # NativeIngestEncoder once the byte path is used
    mode: str | None = None  # "obj" | "native", fixed at first ingest


@dataclass
class _OverflowLane:
    """A document that outgrew the lockstep batch: own DocState, own queue."""

    state: mk.DocState
    geometry: dict[str, int]
    growths: int
    queue: list[np.ndarray] = field(default_factory=list)
    payloads: list[np.ndarray] = field(default_factory=list)


class DocBatchEngine:
    """A fleet of merge-tree replicas stepped as one batched device program."""

    def __init__(
        self,
        n_docs: int,
        max_segments: int = 512,
        remove_slots: int = 4,
        prop_slots: int = 4,
        text_capacity: int = 16384,
        max_insert_len: int = 64,
        ops_per_step: int = 16,
        ob_slots: int = 8,
        mesh=None,
        use_mesh: bool = True,
        recovery: str = "grow",
        max_growths: int = 4,
    ) -> None:
        assert recovery in ("grow", "oracle", "off")
        self.n_docs = n_docs
        self.max_insert_len = max_insert_len
        self.ops_per_step = ops_per_step
        self.recovery = recovery
        self.max_growths = max_growths
        self.hosts = [_DocHost() for _ in range(n_docs)]
        self.geometry = {
            "max_segments": max_segments,
            "remove_slots": remove_slots,
            "prop_slots": prop_slots,
            "text_capacity": text_capacity,
            "ob_slots": ob_slots,
        }
        # Recovery lanes (doc_idx -> lane / oracle replica).
        self.overflow: dict[int, _OverflowLane] = {}
        self.oracles: dict[int, RefMergeTree] = {}

        if use_mesh:
            self.mesh = mesh if mesh is not None else doc_mesh()
            n_shards = self.mesh.devices.size
        else:
            self.mesh = None
            n_shards = 1
        # Device capacity rounds up to a mesh multiple (padding docs are
        # inert: their queues stay empty so they only ever apply noops).
        self.capacity = -(-n_docs // n_shards) * n_shards

        proto = mk.init_state(
            max_segments, remove_slots, prop_slots, text_capacity, ob_slots
        )
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.capacity,) + x.shape), proto
        )
        if self.mesh is not None:
            docs_sharding = shard_docs(self.mesh)
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, docs_sharding), self.state
            )

        batched = jax.vmap(mk.apply_ops, in_axes=(0, 0, 0, None))

        def _step(state, ops, payloads):
            # Scalar (unbatched) obliterate gate: keeps the ob machinery a
            # real lax.cond branch under vmap (see mk.apply_op docstring).
            flag = jnp.any(state.ob_key >= 0) | jnp.any(
                ops[..., 0] == mk.OpKind.OBLITERATE
            )
            return batched(state, ops, payloads, flag)

        def _compact(state, min_seqs):
            state = jax.vmap(mk.set_min_seq)(state, min_seqs)
            flag = jnp.any(state.ob_key >= 0)
            return jax.vmap(mk.compact, in_axes=(0, None))(state, flag)

        self._step = jax.jit(_step, donate_argnums=(0,))
        self._compact = jax.jit(_compact, donate_argnums=(0,))
        # Lane programs: jit caches one executable per lane geometry.
        self._lane_apply = jax.jit(mk.apply_ops)
        self._lane_compact = jax.jit(
            lambda s, m: mk.compact(mk.set_min_seq(s, m))
        )
        # ---- Zipf straggler bucketing (SURVEY §7: doc-packing by op count)
        # Under skewed per-doc op counts one hot doc would force extra
        # FULL-fleet steps (every step scans B ops across all D lanes).
        # When few docs remain busy, gather just those docs' state rows
        # into a power-of-two cohort, step the small sub-fleet, and
        # masked-scatter the rows back — pad lanes route out of bounds
        # (mode="drop"), so duplicate writes never occur.  The jit caches
        # one executable per cohort size (log2(D) variants).
        # Single-chip optimization: under a mesh the doc axis is sharded
        # evenly and arbitrary-index gathers would cross shards.
        self.bucketing = self.mesh is None
        self.full_steps = 0     # fleet-wide steps taken
        self.cohort_steps = 0   # bucketed steps taken
        self.cohort_lanes = 0   # sum of cohort sizes (work proxy)
        self._gather_cohort = jax.jit(
            lambda st, idx: jax.tree.map(lambda x: x[idx], st)
        )

        def _scatter(st, sub, idx, valid):
            def put(x, s):
                safe = jnp.where(valid, idx, x.shape[0])
                return x.at[safe].set(s, mode="drop")

            return jax.tree.map(put, st, sub)

        self._scatter_cohort = jax.jit(_scatter, donate_argnums=(0,))

    # ------------------------------------------------------------------ ingest
    def ingest(self, doc_idx: int, msg: SequencedMessage) -> None:
        """Stage one sequenced message for a document (host-side decode).

        This is the engine's inbound seam: the equivalent of
        DeltaManager -> ContainerRuntime.process for one container, except
        application is deferred to the next batched device step.
        """
        h = self.hosts[doc_idx]
        assert h.mode != "native" or doc_idx in self.oracles or doc_idx in self.overflow, (
            f"doc {doc_idx} already fed through the native byte path; "
            "pick one ingest path per document"
        )
        if h.mode is None:
            h.mode = "obj"
        if msg.type == MessageType.JOIN:
            h.quorum[msg.contents["clientId"]] = msg.contents["short"]
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        if msg.type != MessageType.OP:
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        h.min_seq = max(h.min_seq, msg.min_seq)
        if doc_idx in self.oracles:
            # Oracle-routed docs apply immediately and can never need
            # another replay — no point retaining their log further.
            self._oracle_apply(self.oracles[doc_idx], h, msg)
            return

        if self.recovery != "off":
            # Replay source for overflow recovery.  Unbounded by design for
            # now: bounding it needs DDS-level checkpoints to replay from
            # (summary + suffix), which this pure-replica engine does not
            # carry yet.
            h.log.append(msg)
        if doc_idx in self.overflow:
            lane = self.overflow[doc_idx]
            for op, payload in self._encode(h, msg):
                lane.queue.append(op)
                lane.payloads.append(payload)
            return
        for op, payload in self._encode(h, msg):
            h.queue.append(op)
            h.payloads.append(payload)

    def ingest_lines(self, doc_idx: int, data: bytes) -> int:
        """Stage newline-separated wire JSON through the NATIVE encoder
        (native/ingest.cpp): the whole decode+encode runs in C++, so this is
        the production feed path for a server-side fleet consuming the
        broadcast stream.  Returns the number of op rows staged (op count
        applied, for oracle-routed docs).  Falls back to the Python path
        message by message when the native library is unavailable.  A
        healthy document stays on whichever path fed it first (the two
        paths intern property slots independently); recovery-lane routing
        normalizes a native doc onto the object path."""
        from ..native.ingest_native import NativeIngestEncoder, available

        h = self.hosts[doc_idx]
        in_lane = doc_idx in self.oracles or doc_idx in self.overflow
        if in_lane or not available():
            # Lanes (and the no-native fallback) consume parsed messages.
            self._normalize_native(h)
            lane = self.overflow.get(doc_idx)
            before = len(lane.queue) if lane else len(h.queue)
            n_msgs = 0
            for line in data.split(b"\n"):
                if line.strip():
                    msg = SequencedMessage.from_json(line.decode())
                    n_msgs += msg.type == MessageType.OP
                    self.ingest(doc_idx, msg)
            if doc_idx in self.oracles:
                return n_msgs
            lane = self.overflow.get(doc_idx)
            return (len(lane.queue) if lane else len(h.queue)) - before
        assert h.mode != "obj", (
            f"doc {doc_idx} already fed through the object path; "
            "pick one ingest path per document"
        )
        if h.native is None:
            h.native = NativeIngestEncoder(
                self.max_insert_len, self.geometry["prop_slots"]
            )
            h.mode = "native"
        ops, payloads = h.native.encode(data)
        if self.recovery != "off":
            h.raw_log.append(data)
        h.queue.extend(ops)
        h.payloads.extend(payloads)
        h.min_seq = max(h.min_seq, h.native.min_seq)
        return len(ops)

    def _normalize_native(self, h: _DocHost) -> None:
        """Move a native-path doc onto the object path: parse the retained
        raw lines into quorum + message log (PREPENDED — they precede
        anything the object path appended later) so recovery replay, oracle
        takeover, and further ingest share one consistent stream and one
        prop-slot interning order."""
        if not h.raw_log:
            if h.mode == "native":
                h.mode = "obj"
                h.native = None
            return
        prefix: list[SequencedMessage] = []
        for chunk in h.raw_log:
            for line in chunk.split(b"\n"):
                if line.strip():
                    m = SequencedMessage.from_json(line.decode())
                    if m.type == MessageType.JOIN:
                        h.quorum[m.contents["clientId"]] = m.contents["short"]
                    elif m.type == MessageType.OP:
                        prefix.append(m)
        h.raw_log.clear()
        h.log[:0] = prefix
        h.mode = "obj"
        h.native = None

    def _encode(
        self, h: _DocHost, msg: SequencedMessage
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Wire message -> kernel op rows (+payloads)."""
        c = msg.contents
        kind = c["type"]
        client = h.quorum[msg.client_id]
        empty = np.zeros((self.max_insert_len,), np.int32)
        if kind == DeltaType.INSERT:
            return mk.encode_insert(
                c["pos1"], c["seg"], msg.seq, client, msg.ref_seq,
                self.max_insert_len,
            )
        if kind == DeltaType.REMOVE:
            op = np.array(
                [mk.OpKind.REMOVE, msg.seq, client, msg.ref_seq,
                 c["pos1"], c["pos2"], 0, 0],
                np.int32,
            )
            return [(op, empty)]
        if kind == DeltaType.ANNOTATE:
            out = []
            for prop, value in c["props"].items():
                slot = self._prop_slot_for(h, int(prop))
                out.append(
                    (
                        np.array(
                            [mk.OpKind.ANNOTATE, msg.seq, client, msg.ref_seq,
                             c["pos1"], c["pos2"], slot, value],
                            np.int32,
                        ),
                        empty,
                    )
                )
            return out
        if kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            return [
                (mk.encode_obliterate(p1, s1, p2, s2, msg.seq, client, msg.ref_seq),
                 empty)
            ]
        raise ValueError(f"unsupported op type {kind}")

    @staticmethod
    def _oracle_apply(tree: RefMergeTree, h: _DocHost, msg: SequencedMessage) -> None:
        """Apply one wire OP message to a host oracle replica (the pure
        remote path of SharedString._apply_remote)."""
        c = msg.contents
        kind = c["type"]
        client = h.quorum[msg.client_id]
        if kind == DeltaType.INSERT:
            tree.apply_insert(c["pos1"], c["seg"], msg.seq, client, msg.ref_seq)
        elif kind == DeltaType.REMOVE:
            tree.apply_remove(c["pos1"], c["pos2"], msg.seq, client, msg.ref_seq)
        elif kind == DeltaType.ANNOTATE:
            for prop, value in c["props"].items():
                tree.apply_annotate(
                    c["pos1"], c["pos2"], int(prop), value,
                    msg.seq, client, msg.ref_seq,
                )
        elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            tree.apply_obliterate(p1, s1, p2, s2, msg.seq, client, msg.ref_seq)
        else:
            raise ValueError(f"unsupported op type {kind}")

    def _prop_slot_for(self, h: _DocHost, prop: int) -> int:
        """Intern a property id to a kernel prop slot (range-checked)."""
        if prop not in h.prop_slot:
            slot = len(h.prop_slot)
            if slot >= self.geometry["prop_slots"]:
                raise ValueError(
                    f"document exhausted its {self.geometry['prop_slots']} prop "
                    f"slots; raise prop_slots to accommodate prop id {prop}"
                )
            h.prop_slot[prop] = slot
        return h.prop_slot[prop]

    # ------------------------------------------------------------------- step
    def pending_ops(self) -> int:
        return sum(len(h.queue) for h in self.hosts) + sum(
            len(l.queue) for l in self.overflow.values()
        )

    def _drain_into(
        self, docs: list[int], ops: np.ndarray, payloads: np.ndarray
    ) -> None:
        """Dequeue up to ops_per_step ops per listed doc into row j of the
        padded arrays — the ONE drain used by full-fleet and cohort steps
        (their semantics must never diverge)."""
        B = self.ops_per_step
        for j, d in enumerate(docs):
            h = self.hosts[d]
            take = min(B, len(h.queue))
            for k in range(take):
                ops[j, k] = h.queue[k]
                payloads[j, k] = h.payloads[k]
            del h.queue[:take]
            del h.payloads[:take]

    def build_step_batch(self) -> tuple[np.ndarray, np.ndarray] | None:
        """Dequeue up to ops_per_step ops per doc into padded [D,B] arrays."""
        B = self.ops_per_step
        if not any(h.queue for h in self.hosts):
            return None
        ops = np.zeros((self.capacity, B, mk.OP_FIELDS), np.int32)
        payloads = np.zeros((self.capacity, B, self.max_insert_len), np.int32)
        self._drain_into(list(range(self.n_docs)), ops, payloads)
        return ops, payloads

    def step(self) -> int:
        """Run device steps until all staged ops are applied; returns the
        number of batched steps.  Busy-doc cohorts far below fleet size
        run bucketed (see __init__), so a Zipf-skewed tail stops costing
        full-fleet steps.  Afterwards, any latched overflow bits are
        recovered (grow-and-replay or oracle routing), so ``errors()`` is
        all-zero on return unless recovery is off."""
        steps = 0
        while True:
            busy = [d for d, h in enumerate(self.hosts) if h.queue]
            if not busy:
                break
            if self.bucketing and len(busy) <= self.capacity // 4:
                self._cohort_step(busy)
            else:
                batch = self.build_step_batch()
                self.state = self._step(
                    self.state, jnp.asarray(batch[0]), jnp.asarray(batch[1])
                )
                self.full_steps += 1
            steps += 1
        self._step_lanes()
        if self.recovery != "off":
            self.recover()
        return steps

    def _cohort_step(self, busy: list[int]) -> None:
        """One bucketed step over just the busy docs."""
        B = self.ops_per_step
        K = max(1, 1 << (len(busy) - 1).bit_length())  # pow2 ladder
        idx = np.full((K,), busy[-1], np.int32)  # gather pad: harmless dup
        idx[: len(busy)] = busy
        valid = np.zeros((K,), bool)
        valid[: len(busy)] = True
        ops = np.zeros((K, B, mk.OP_FIELDS), np.int32)
        payloads = np.zeros((K, B, self.max_insert_len), np.int32)
        self._drain_into(busy, ops, payloads)
        sub = self._gather_cohort(self.state, jnp.asarray(idx))
        sub = self._step(sub, jnp.asarray(ops), jnp.asarray(payloads))
        self.state = self._scatter_cohort(
            self.state, sub, jnp.asarray(idx), jnp.asarray(valid)
        )
        self.cohort_steps += 1
        self.cohort_lanes += K

    def _step_lanes(self) -> None:
        B = self.ops_per_step
        for lane in self.overflow.values():
            while lane.queue:
                take = min(B, len(lane.queue))
                ops = np.zeros((B, mk.OP_FIELDS), np.int32)
                payloads = np.zeros((B, self.max_insert_len), np.int32)
                for j in range(take):
                    ops[j] = lane.queue[j]
                    payloads[j] = lane.payloads[j]
                del lane.queue[:take]
                del lane.payloads[:take]
                lane.state = self._lane_apply(
                    lane.state, jnp.asarray(ops), jnp.asarray(payloads)
                )

    def compact(self) -> None:
        """Advance MSNs and run zamboni eviction across the fleet."""
        mins = [h.min_seq for h in self.hosts]
        mins += [0] * (self.capacity - self.n_docs)
        self.state = self._compact(self.state, jnp.asarray(mins, jnp.int32))
        for d, lane in self.overflow.items():
            lane.state = self._lane_compact(
                lane.state, jnp.asarray(self.hosts[d].min_seq, jnp.int32)
            )
        for d, tree in self.oracles.items():
            tree.update_min_seq(self.hosts[d].min_seq)

    # --------------------------------------------------------------- recovery
    def recover(self) -> list[int]:
        """Inspect every error vector and recover flagged docs; returns the
        doc indices recovered this call."""
        recovered: list[int] = []
        err = np.asarray(self.state.error)
        for d in range(self.n_docs):
            if d not in self.overflow and d not in self.oracles and err[d]:
                self._recover_doc(d, int(err[d]), growths=0)
                # Retire the batch slot: clear the latched bits so the slot
                # never re-triggers (its queue is empty and future ops route
                # to the lane).
                self.state = self.state._replace(
                    error=self.state.error.at[d].set(0)
                )
                recovered.append(d)
        for d, lane in list(self.overflow.items()):
            bits = int(lane.state.error)
            if bits:
                self._recover_doc(d, bits, growths=lane.growths)
                recovered.append(d)
        return recovered

    def _recover_doc(self, d: int, bits: int, growths: int) -> None:
        # Recovery works on the parsed-message log: fold a native doc's raw
        # lines in first (ordering: they precede any object-path appends).
        self._normalize_native(self.hosts[d])
        if bits == mk.ERR_POS_RANGE:
            # POS_RANGE alone (no capacity bit) means the op stream itself is
            # malformed.  Alongside a capacity bit it is usually a CASCADE —
            # an op referencing content a capacity overflow dropped — which
            # the replay at grown capacity resolves, so fall through.
            raise RuntimeError(
                f"doc {d}: sequenced op out of range (error bits {bits:#x}) — "
                "not a capacity problem; the op stream is malformed"
            )
        h = self.hosts[d]
        geom = dict(
            self.overflow[d].geometry if d in self.overflow else self.geometry
        )
        while self.recovery == "grow" and growths < self.max_growths:
            growths += 1
            geom = self._grown_geometry(geom, bits)
            state = self._replay(h, geom)
            new_bits = int(state.error)
            if new_bits == 0:
                self.overflow[d] = _OverflowLane(
                    state=state, geometry=geom, growths=growths
                )
                return
            bits = new_bits
            if bits == mk.ERR_POS_RANGE:
                raise RuntimeError(
                    f"doc {d}: sequenced op out of range during replay at "
                    f"capacity {geom} — the op stream is malformed"
                )
        # Growth exhausted (or policy is oracle): host replica takes over.
        self.overflow.pop(d, None)
        tree = RefMergeTree()
        for msg in h.log:
            self._oracle_apply(tree, h, msg)
        tree.update_min_seq(h.min_seq)
        self.oracles[d] = tree

    @staticmethod
    def _grown_geometry(base: dict[str, int], bits: int) -> dict[str, int]:
        geom = dict(base)
        if bits & mk.ERR_SEG_OVERFLOW:
            geom["max_segments"] *= 2
        if bits & mk.ERR_TEXT_OVERFLOW:
            geom["text_capacity"] *= 2
        if bits & mk.ERR_REM_OVERFLOW:
            geom["remove_slots"] *= 2
        if bits & mk.ERR_OB_OVERFLOW:
            geom["ob_slots"] *= 2
        return geom

    def _replay(self, h: _DocHost, geom: dict[str, int]) -> mk.DocState:
        """Re-apply the retained wire log on a fresh state with ``geom``."""
        state = mk.init_state(
            geom["max_segments"], geom["remove_slots"], geom["prop_slots"],
            geom["text_capacity"], geom["ob_slots"],
        )
        B = self.ops_per_step
        rows: list[tuple[np.ndarray, np.ndarray]] = []
        for msg in h.log:
            rows.extend(self._encode(h, msg))
        for i in range(0, len(rows), B):
            chunk = rows[i : i + B]
            ops = np.zeros((B, mk.OP_FIELDS), np.int32)
            payloads = np.zeros((B, self.max_insert_len), np.int32)
            for j, (op, payload) in enumerate(chunk):
                ops[j] = op
                payloads[j] = payload
            state = self._lane_apply(
                state, jnp.asarray(ops), jnp.asarray(payloads)
            )
        return state

    # ------------------------------------------------------------------ views
    def doc_state(self, doc_idx: int) -> mk.DocState:
        if doc_idx in self.overflow:
            return self.overflow[doc_idx].state
        return jax.tree.map(lambda x: x[doc_idx], self.state)

    def text(self, doc_idx: int) -> str:
        if doc_idx in self.oracles:
            return self.oracles[doc_idx].visible_text()
        return mk.visible_text(self.doc_state(doc_idx))

    def annotations(self, doc_idx: int) -> list[dict[int, int]]:
        if doc_idx in self.oracles:
            return self.oracles[doc_idx].annotations()
        raw = mk.annotations(self.doc_state(doc_idx))
        inv = {v: k for k, v in self.hosts[doc_idx].prop_slot.items()}
        return [{inv[p]: v for p, v in d.items()} for d in raw]

    def errors(self) -> np.ndarray:
        """Combined per-doc error vector across batch, lanes, and oracles."""
        err = np.asarray(self.state.error).copy()
        for d in range(self.n_docs, self.capacity):
            err[d] = 0  # padding slots
        for d, lane in self.overflow.items():
            err[d] = int(lane.state.error)
        for d in self.oracles:
            err[d] = 0
        return err
