"""DocBatchEngine: batched sequenced-op application across many documents.

The north-star configuration (BASELINE.json): thousands of SharedString
documents, each with its own totally-ordered op stream, applied in lockstep
device steps — ``vmap`` of the per-doc merge-tree kernel over a leading
document axis, sharded over a TPU mesh along ``docs``.

Host/device split (mirrors the reference's seam at
ContainerRuntime.processInboundMessages, containerRuntime.ts:3428 — where
contiguous ops are bunched before DDS apply; here the bunch becomes a
[D, B] tensor step):

- host: per-doc staging queues of sequenced messages, op encoding (stamp
  keys, positions, payload codepoints), quorum (clientId -> short id)
- device: ``step`` = vmap(scan(apply_op)) — applies up to B ops for each of
  D documents in one XLA program

This engine is the pure-replica path (no local pending ops): every op is a
remote sequenced apply, exactly the scenario of a server-side/materialized
replica fleet.  Client-side engines with pending/ack live in dds/.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.shared_string import SharedString, decode_obliterate_places
from ..ops import mergetree_kernel as mk
from ..parallel.mesh import doc_mesh, shard_docs
from ..protocol.messages import DeltaType, MessageType, SequencedMessage


@dataclass
class _DocHost:
    """Host-side per-document bookkeeping."""

    quorum: dict[str, int] = field(default_factory=dict)
    queue: list[np.ndarray] = field(default_factory=list)
    payloads: list[np.ndarray] = field(default_factory=list)
    min_seq: int = 0
    # Property id -> kernel prop slot (interned per document).
    prop_slot: dict[int, int] = field(default_factory=dict)


class DocBatchEngine:
    """A fleet of merge-tree replicas stepped as one batched device program."""

    def __init__(
        self,
        n_docs: int,
        max_segments: int = 512,
        remove_slots: int = 4,
        prop_slots: int = 4,
        text_capacity: int = 16384,
        max_insert_len: int = 64,
        ops_per_step: int = 16,
        mesh=None,
        use_mesh: bool = True,
    ) -> None:
        self.n_docs = n_docs
        self.max_insert_len = max_insert_len
        self.ops_per_step = ops_per_step
        self.hosts = [_DocHost() for _ in range(n_docs)]

        if use_mesh:
            self.mesh = mesh if mesh is not None else doc_mesh()
            n_shards = self.mesh.devices.size
        else:
            self.mesh = None
            n_shards = 1
        # Device capacity rounds up to a mesh multiple (padding docs are
        # inert: their queues stay empty so they only ever apply noops).
        self.capacity = -(-n_docs // n_shards) * n_shards

        proto = mk.init_state(max_segments, remove_slots, prop_slots, text_capacity)
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.capacity,) + x.shape), proto
        )
        if self.mesh is not None:
            docs_sharding = shard_docs(self.mesh)
            self.state = jax.tree.map(
                lambda x: jax.device_put(x, docs_sharding), self.state
            )

        batched = jax.vmap(mk.apply_ops, in_axes=(0, 0, 0, None))

        def _step(state, ops, payloads):
            # Scalar (unbatched) obliterate gate: keeps the ob machinery a
            # real lax.cond branch under vmap (see mk.apply_op docstring).
            flag = jnp.any(state.ob_key >= 0) | jnp.any(
                ops[..., 0] == mk.OpKind.OBLITERATE
            )
            return batched(state, ops, payloads, flag)

        def _compact(state, min_seqs):
            state = jax.vmap(mk.set_min_seq)(state, min_seqs)
            flag = jnp.any(state.ob_key >= 0)
            return jax.vmap(mk.compact, in_axes=(0, None))(state, flag)

        self._step = jax.jit(_step, donate_argnums=(0,))
        self._compact = jax.jit(_compact, donate_argnums=(0,))

    # ------------------------------------------------------------------ ingest
    def ingest(self, doc_idx: int, msg: SequencedMessage) -> None:
        """Stage one sequenced message for a document (host-side decode).

        This is the engine's inbound seam: the equivalent of
        DeltaManager -> ContainerRuntime.process for one container, except
        application is deferred to the next batched device step.
        """
        h = self.hosts[doc_idx]
        if msg.type == MessageType.JOIN:
            h.quorum[msg.contents["clientId"]] = msg.contents["short"]
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        if msg.type != MessageType.OP:
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        c = msg.contents
        kind = c["type"]
        client = h.quorum[msg.client_id]
        if kind == DeltaType.INSERT:
            for op, payload in mk.encode_insert(
                c["pos1"], c["seg"], msg.seq, client, msg.ref_seq,
                self.max_insert_len,
            ):
                h.queue.append(op)
                h.payloads.append(payload)
        elif kind == DeltaType.REMOVE:
            h.queue.append(
                np.array(
                    [mk.OpKind.REMOVE, msg.seq, client, msg.ref_seq,
                     c["pos1"], c["pos2"], 0, 0],
                    np.int32,
                )
            )
            h.payloads.append(np.zeros((self.max_insert_len,), np.int32))
        elif kind == DeltaType.ANNOTATE:
            for prop, value in c["props"].items():
                slot = self._prop_slot_for(h, int(prop))
                h.queue.append(
                    np.array(
                        [mk.OpKind.ANNOTATE, msg.seq, client, msg.ref_seq,
                         c["pos1"], c["pos2"], slot, value],
                        np.int32,
                    )
                )
                h.payloads.append(np.zeros((self.max_insert_len,), np.int32))
        elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            h.queue.append(
                mk.encode_obliterate(p1, s1, p2, s2, msg.seq, client, msg.ref_seq)
            )
            h.payloads.append(np.zeros((self.max_insert_len,), np.int32))
        else:
            raise ValueError(f"unsupported op type {kind}")
        h.min_seq = max(h.min_seq, msg.min_seq)

    def _prop_slot_for(self, h: _DocHost, prop: int) -> int:
        """Intern a property id to a kernel prop slot (range-checked)."""
        if prop not in h.prop_slot:
            slot = len(h.prop_slot)
            if slot >= len(self.state.prop_keys):
                raise ValueError(
                    f"document exhausted its {len(self.state.prop_keys)} prop "
                    f"slots; raise prop_slots to accommodate prop id {prop}"
                )
            h.prop_slot[prop] = slot
        return h.prop_slot[prop]

    # ------------------------------------------------------------------- step
    def pending_ops(self) -> int:
        return sum(len(h.queue) for h in self.hosts)

    def build_step_batch(self) -> tuple[np.ndarray, np.ndarray] | None:
        """Dequeue up to ops_per_step ops per doc into padded [D,B] arrays."""
        B = self.ops_per_step
        if self.pending_ops() == 0:
            return None
        ops = np.zeros((self.capacity, B, mk.OP_FIELDS), np.int32)
        payloads = np.zeros((self.capacity, B, self.max_insert_len), np.int32)
        for d, h in enumerate(self.hosts):
            take = min(B, len(h.queue))
            for j in range(take):
                ops[d, j] = h.queue[j]
                payloads[d, j] = h.payloads[j]
            del h.queue[:take]
            del h.payloads[:take]
        return ops, payloads

    def step(self) -> int:
        """Run device steps until all staged ops are applied; returns steps."""
        steps = 0
        while True:
            batch = self.build_step_batch()
            if batch is None:
                return steps
            ops, payloads = batch
            self.state = self._step(self.state, jnp.asarray(ops), jnp.asarray(payloads))
            steps += 1

    def compact(self) -> None:
        """Advance MSNs and run zamboni eviction across the fleet."""
        mins = [h.min_seq for h in self.hosts]
        mins += [0] * (self.capacity - self.n_docs)
        self.state = self._compact(self.state, jnp.asarray(mins, jnp.int32))

    # ------------------------------------------------------------------ views
    def doc_state(self, doc_idx: int) -> mk.DocState:
        return jax.tree.map(lambda x: x[doc_idx], self.state)

    def text(self, doc_idx: int) -> str:
        return mk.visible_text(self.doc_state(doc_idx))

    def annotations(self, doc_idx: int) -> list[dict[int, int]]:
        raw = mk.annotations(self.doc_state(doc_idx))
        inv = {v: k for k, v in self.hosts[doc_idx].prop_slot.items()}
        return [{inv[p]: v for p, v in d.items()} for d in raw]

    def errors(self) -> np.ndarray:
        return np.asarray(self.state.error)
