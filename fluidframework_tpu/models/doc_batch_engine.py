"""DocBatchEngine: batched sequenced-op application across many documents.

The north-star configuration (BASELINE.json): thousands of SharedString
documents, each with its own totally-ordered op stream, applied in lockstep
device steps — ``vmap`` of the per-doc merge-tree kernel over a leading
document axis, sharded over a TPU mesh along ``docs``.

Host/device split (mirrors the reference's seam at
ContainerRuntime.processInboundMessages, containerRuntime.ts:3428 — where
contiguous ops are bunched before DDS apply; here the bunch becomes a
[D, B] tensor step):

- host: per-doc staging queues of sequenced messages, op encoding (stamp
  keys, positions, payload codepoints), quorum (clientId -> short id)
- device: ``step`` = vmap(scan(apply_op)) — applies up to B ops for each of
  D documents in one XLA program

This engine is the pure-replica path (no local pending ops): every op is a
remote sequenced apply, exactly the scenario of a server-side/materialized
replica fleet.  Client-side engines with pending/ack live in dds/.

Capacity overflow recovery (the kernel latches ERR_* bits instead of
trapping — mergetree_kernel.py): after every ``step`` the engine inspects
the fleet's error vector and recovers any flagged document, so no error bit
ever survives a run.  Recovery policy:

- ``"grow"`` (default): re-provision the document in an *overflow lane* — a
  single-doc DocState with the implicated capacity axes doubled — and
  replay its retained wire log from scratch (deterministic: the log is the
  total order).  Repeated overflows double again up to ``max_growths``,
  then fall through to the oracle.  Lanes keep applying on device (jit per
  geometry, cached), they just leave the lockstep batch.
- ``"oracle"``: replay the log through the host RefMergeTree and route all
  future ops there (the reference analog of a document leaving the fast
  path; SURVEY §7 capacity-management risk).

Fault isolation (this module's robustness contract):

- **Capacity errors** (ERR_SEG/TEXT/REM/OB_OVERFLOW) are recoverable:
  grow-and-replay into an overflow lane, or oracle routing (above).
- **Poison errors** — ERR_POS_RANGE with no capacity bit, a decode failure
  at ingest, or a divergence caught by the watchdog — mean the op stream
  (or the device state) is bad for THAT document only.  The doc is
  **quarantined**: evicted from the device batch into a host oracle lane
  rebuilt from its last checkpoint + retained tail, where every further op
  is validated before apply (malformed ops are dropped and counted, never
  applied).  The other documents in the batch never see a stall or a
  corrupt row.  A quarantined doc stays fully serviceable (reads + op
  application through the oracle) and can be re-admitted to the lockstep
  batch with ``readmit()`` once its replay is clean.
- **Checkpoints** bound recovery: with a ``checkpoint_store``
  (server/ordered_log.CheckpointStore) the engine periodically snapshots
  each doc's packed ``DocState`` as a summary record, truncates the
  retained wire log to ops after the checkpoint seq, and every recovery
  replay (grow lanes, quarantine, engine restart via
  ``restore_from_checkpoints``) starts from the checkpoint instead of op
  zero — replay work is bounded by ``checkpoint_every``, not history.
- A sampling **divergence watchdog** cross-checks device text against a
  host-oracle replay of checkpoint + tail every ``watchdog_every`` steps
  and quarantines on mismatch.  Health counters (quarantined_docs,
  checkpoint_age_seqs, recovery_replay_len, watchdog_mismatches, ...)
  surface through ``health()`` / utils.telemetry.HealthCounters.
"""

from __future__ import annotations

import functools
import json
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..dds import kernel_backend as kb
from ..dds.mergetree_ref import RefMergeTree
from ..dds.shared_string import decode_obliterate_places
from ..observability.flight_recorder import RecompileWatchdog, instant, span
from ..ops import mergetree_kernel as mk
from .dispatch import dispatch_plane
from . import placement
from ..protocol.messages import DeltaType, MessageType, SequencedMessage
from ..utils.telemetry import HealthCounters, Histogram, SampledTelemetryHelper
from .recovery import (
    RecoveryTracker,
    load_checkpoint_records,
    stale_due_docs,
    write_checkpoint_records,
)
from .staging import OverloadGate, RowQueue, StagingRing, upload_replicated


@dataclass
class _DocHost:
    """Host-side per-document bookkeeping."""

    # Columnar pending op rows (ops + payloads in one RowQueue): batch
    # ingest lands whole blocks, the drain consumes slice copies — no
    # per-op Python list traffic on either side.
    queue: RowQueue = None
    quorum: dict[str, int] = field(default_factory=dict)
    min_seq: int = 0
    # Property id -> kernel prop slot (interned per document).
    prop_slot: dict[int, int] = field(default_factory=dict)
    # Retained wire log (every OP message with seq > base_seq, in sequence
    # order): the replay source for recovery.  Bounded by checkpoints —
    # ops at or below ``base_seq`` live in ``base_summary`` instead.  Docs
    # fed through the native byte path retain raw lines instead (mode is
    # fixed per doc at first ingest).
    log: list[SequencedMessage] = field(default_factory=list)
    raw_log: list[bytes] = field(default_factory=list)
    native: object = None  # NativeIngestEncoder once the byte path is used
    mode: str | None = None  # "obj" | "native", fixed at first ingest
    # Checkpoint floor: the durable record covers ops up to ``base_seq``;
    # ``base_summary`` is its state (None = empty doc), the replay base.
    base_seq: int = 0
    base_summary: dict | None = None
    last_seq: int = 0  # highest OP seq ingested
    ops_since_ckpt: int = 0
    # Monotonic time the doc FIRST went dirty after its last durable
    # checkpoint (0.0 = clean): the bounded-staleness writer's seconds-
    # behind signal (recovery.BackgroundCheckpointWriter).
    dirty_since: float = 0.0
    # Set by restore_from_checkpoints: the doc consumes parsed messages
    # (seq dedupe needs per-message seqs the native encoder can't skip).
    restored: bool = False
    # Count applied ops as boot_replay_len only during the boot catch-up
    # phase — the first post-boot checkpoint ends it (live traffic after
    # that must not keep inflating a counter named "boot").
    boot_counting: bool = False


@dataclass
class _OverflowLane:
    """A document that outgrew the lockstep batch: own DocState, own queue."""

    state: mk.DocState
    geometry: dict[str, int]
    growths: int
    queue: RowQueue = None


@dataclass
class _SegmentLane:
    """A HOT document promoted to the segment-parallel serving path: its
    merge-tree segment arrays block-shard over the mesh's ``segs`` axis
    (per-segment work splits across shards; text/scalars/ob table
    replicate), served by the seg-parallel megastep
    (ops.mergetree_kernel.apply_megastep_seg) with the single-lane kernel
    as the byte-identity oracle.  Inserts land shard-local; the layout
    re-blocks at rebalance points (``rebalance_segments``)."""

    state: mk.DocState   # seg-sharded layout, device-resident
    n_shards: int
    s_local: int         # per-shard segment capacity
    queue: RowQueue = None
    rebalances: int = 0
    ops_since_rebalance: int = 0
    # Bumped at every state reassignment (dispatch/rebalance/compact): the
    # watchdog's host-side change mark — the slot-digest pre-filter cannot
    # vouch for a lane doc, and hot docs are the most expensive to replay.
    version: int = 0


def _i32(v) -> int:
    """Coerce one wire scalar for the batch walk with the per-message
    path's exact failure shape: ``np.array([...], np.int32)`` raises
    OverflowError on out-of-range ints, while the batch path's int64
    staging columns would silently WRAP on the int32 cast — so the range
    check must happen at collection time, loudly."""
    v = int(v)
    if not (-0x80000000 <= v <= 0x7FFFFFFF):
        raise OverflowError(f"op scalar {v} out of int32 range")
    return v


# Module-level jitted programs: every engine instance shares ONE compile
# cache keyed by input shapes (geometry x batch), instead of each instance
# recompiling identical programs through its own jit closures — engines are
# created per test / per restart, and the programs close over nothing
# instance-specific.

@functools.partial(jax.jit, donate_argnums=(0,))
def _fleet_step(state, ops, payloads):
    # Scalar (unbatched) obliterate gate: keeps the ob machinery a real
    # lax.cond branch under vmap (see mk.apply_op docstring).
    flag = jnp.any(state.ob_key >= 0) | jnp.any(
        ops[..., 0] == mk.OpKind.OBLITERATE
    )
    return jax.vmap(mk.apply_ops, in_axes=(0, 0, 0, None))(
        state, ops, payloads, flag
    )


# Megastep dispatch: a [K, D, B] op ring applied as ONE donated program
# (lax.scan over slices, vmap over docs, per-slice obliterate gate carried
# on device — see mk.apply_megastep).  Amortizes the per-slice jit dispatch
# and host->device upload that starved the device at high fleet rates.
_fleet_megastep = functools.partial(jax.jit, donate_argnums=(0,))(
    mk.apply_megastep
)


def _fleet_compact_body(state, min_seqs):
    # Module-level body: shared by the single-device jit below and the
    # shard_map-wrapped mesh program (parallel.mesh.mesh_fleet_program
    # caches by function identity, so the body must be stable).
    state = jax.vmap(mk.set_min_seq)(state, min_seqs)
    flag = jnp.any(state.ob_key >= 0)
    return jax.vmap(mk.compact, in_axes=(0, None))(state, flag)


_fleet_compact = functools.partial(jax.jit, donate_argnums=(0,))(
    _fleet_compact_body
)


_lane_apply_jit = jax.jit(mk.apply_ops)
_lane_compact_jit = jax.jit(lambda s, m: mk.compact(mk.set_min_seq(s, m)))
_gather_cohort_jit = jax.jit(lambda st, idx: jax.tree.map(lambda x: x[idx], st))


@jax.jit
def _fleet_digest(state):
    """Cheap per-doc state digest computed ON DEVICE from the batched
    state: a position-weighted checksum of the text pool plus the segment
    layout scalars.  The divergence watchdog uses it as a pre-filter — a
    doc whose digest has not moved since its last verified check cannot
    have diverged SINCE then, so the expensive host-oracle replay is spent
    only on docs whose digest drifted."""
    U = jnp.uint32
    T = state.text.shape[-1]
    S = state.seg_len.shape[-1]
    wt = (jnp.arange(T, dtype=U) * U(2654435761) + U(0x9E3779B9))
    ws = (jnp.arange(S, dtype=U) * U(0x85EBCA6B) + U(0xC2B2AE35))
    dig = (state.text.astype(U) * wt).sum(axis=-1)
    dig += (state.seg_len.astype(U) * ws).sum(axis=-1)
    dig += (state.seg_start.astype(U) * (ws ^ U(0xA5A5A5A5))).sum(axis=-1)
    for rk in state.rem_keys:
        dig = dig * U(31) + (rk.astype(U) * ws).sum(axis=-1)
    dig = dig * U(31) + state.text_end.astype(U)
    dig = dig * U(31) + state.nseg.astype(U)
    return dig


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_cohort_jit(st, sub, idx, valid):
    def put(x, s):
        safe = jnp.where(valid, idx, x.shape[0])
        return x.at[safe].set(s, mode="drop")

    return jax.tree.map(put, st, sub)


class DocBatchEngine:
    """A fleet of merge-tree replicas stepped as one batched device program."""

    def __init__(
        self,
        n_docs: int,
        max_segments: int = 512,
        remove_slots: int = 4,
        prop_slots: int = 4,
        text_capacity: int = 16384,
        max_insert_len: int = 64,
        ops_per_step: int = 16,
        ob_slots: int = 8,
        mesh=None,
        use_mesh: bool = True,
        recovery: str = "grow",
        max_growths: int = 4,
        checkpoint_store=None,
        checkpoint_every: int = 0,
        doc_keys: list[str] | None = None,
        watchdog_every: int = 0,
        watchdog_sample: int = 4,
        readmit_after_steps: int = 0,
        poison_budget: int = 0,
        megastep_k: int = 1,
        spare_slots: int = 0,
        telemetry=None,
        latency_sample_every: int = 16,
        overload_high_watermark: int = 0,
        overload_low_watermark: int = 0,
        seg_shards: int = 0,
        seg_lane_segments: int = 0,
        seg_lane_text_capacity: int = 0,
        seg_rebalance_every: int = 0,
        max_seg_lanes: int = 4,
    ) -> None:
        assert recovery in ("grow", "oracle", "off")
        self.n_docs = n_docs
        self.max_insert_len = max_insert_len
        self.ops_per_step = ops_per_step
        # Megastep depth cap: up to K [D, B] op slices fuse into one
        # donated dispatch (adaptive per dispatch — see _select_k).  K=1
        # preserves the per-slice dispatch behavior exactly.
        self.megastep_k = max(1, megastep_k)
        # Ingest watermarks (credit-based flow control): the megastep
        # budget is what one fused dispatch retires per doc; a queue deeper
        # than ``overload_high`` watermarks the doc as overloaded (the
        # consumer pauses its partition) until it drains to
        # ``overload_low``.  Defaults: 8x / 1x the budget.
        budget = self.megastep_k * ops_per_step
        self.overload_gate = OverloadGate(
            high=overload_high_watermark or 8 * budget,
            low=overload_low_watermark or budget,
        )
        self.recovery = recovery
        self.max_growths = max_growths
        self.hosts = [
            _DocHost(queue=RowQueue(mk.OP_FIELDS, max_insert_len))
            for _ in range(n_docs)
        ]
        self.geometry = {
            "max_segments": max_segments,
            "remove_slots": remove_slots,
            "prop_slots": prop_slots,
            "text_capacity": text_capacity,
            "ob_slots": ob_slots,
        }
        # Recovery lanes (doc_idx -> lane / oracle replica).
        self.overflow: dict[int, _OverflowLane] = {}
        self.oracles: dict[int, RefMergeTree] = {}
        # Quarantine lane: docs whose op stream (or device state) proved
        # bad — served by a validated host oracle until readmission.
        self.quarantine: dict[int, RefMergeTree] = {}
        self.quarantine_reason: dict[int, str] = {}
        # Checkpoint / watchdog knobs (see module docstring).
        self.checkpoint_store = checkpoint_store
        self.checkpoint_every = checkpoint_every
        # Checkpoint-plane lock: the bounded-staleness background writer
        # (models/recovery.BackgroundCheckpointWriter) enters through
        # checkpoint_stale() on its own thread; step()/ingest*/
        # maybe_checkpoint/restore all take this, so a sweep only ever
        # sees the engine at an op boundary.  Re-entrant because step()
        # calls maybe_checkpoint under it.  Uncontended acquisition is
        # nanoseconds against ms-scale dispatches.
        self.ckpt_lock = threading.RLock()
        # Durable-write plane for checkpoint sweeps: saves happen outside
        # ckpt_lock (fsyncs must not stall serving), serialized here with
        # per-doc seq fencing so concurrent sweeps never write an older
        # record over a newer one.
        self._ckpt_io_lock = threading.Lock()
        self._ckpt_saved_seq: dict[int, int] = {}
        # Per-incident recovery clock (kill/restore -> first post-restore
        # op applied); gauges ride health(), the histogram rides
        # latency_histograms() into /metrics.
        self.recovery_tracker = RecoveryTracker()
        # Record-file mtimes last seen by a refresh trail: the standby's
        # poll skips unchanged records instead of re-reading and
        # re-parsing every checkpoint every poll_s.
        self._trail_mtime: dict[int, float] = {}
        self.doc_keys = list(doc_keys) if doc_keys is not None else [
            str(d) for d in range(n_docs)
        ]
        assert len(self.doc_keys) == n_docs
        # Warm the native ingest plane HERE, with no lock held: the byte
        # path's g++ rebuild (missing/stale .so) must never run lazily
        # under ckpt_lock — ingest_lines only probes the non-building
        # loaded() accessor (fftpu-check blocking-under-lock).
        from ..native import ingest_native as _ingest_native

        _ingest_native.warm()
        self.watchdog_every = watchdog_every
        self.watchdog_sample = watchdog_sample
        self._watchdog_cursor = 0
        self._steps_since_watchdog = 0
        # Watchdog pre-filter state: device digest at the last sweep, and
        # per doc the (digest, last_seq) pair recorded when it last PASSED
        # a check.  Skipping requires BOTH unchanged: the digest alone
        # cannot distinguish "no ops applied" from "ops silently dropped
        # by the kernel" — the exact divergence class the watchdog hunts.
        self._digests: np.ndarray | None = None
        self._verified_digest: dict[int, tuple[int, int]] = {}
        # Quarantine auto-readmission policy: with ``readmit_after_steps``
        # a quarantined doc is automatically re-tried after that many
        # engine steps, doubling per flap (exponential backoff).  A doc
        # that gets quarantined more than ``poison_budget`` times (0 = no
        # budget) is flapping — permanently oracle-routed instead of
        # bouncing in and out of the batch forever.
        self.readmit_after_steps = readmit_after_steps
        self.poison_budget = poison_budget
        self._step_count = 0
        self._flaps: dict[int, int] = {}
        self._readmit_due: dict[int, int] = {}
        # Current backoff interval per quarantined doc: doubles on every
        # flap AND on every failed readmission attempt (a doc whose state
        # outgrew the batch geometry must not re-pay the export/pack cost
        # at a fixed cadence forever).
        self._readmit_interval: dict[int, int] = {}
        self.counters = HealthCounters(telemetry)
        # Sampled hot-path timing through the reference's sampled-telemetry
        # shape (one event per N steps; flush_all drains the tail at
        # shutdown / status-snapshot time via ``flush_telemetry``).
        self.sampled = (
            SampledTelemetryHelper(telemetry, "engine_step", sample_every=64)
            if telemetry is not None
            else None
        )
        # Op end-to-end latency: sequencer stamp time -> applied-on-device
        # readback, sampled every ``latency_sample_every`` staged ops (the
        # per-message cost of full tracking would show on the feed path).
        # Pending samples resolve at the step() sync boundary (recover()'s
        # error readback proves the dispatches that drained them retired).
        self.latency_sample_every = max(1, latency_sample_every)
        self.op_latency = Histogram()
        self._doc_latency: dict[int, Histogram] = {}
        self._lat_tick = 0
        self._lat_pending: list[tuple[float, int]] = []

        if use_mesh:
            # Engine-owned dispatch seam (models/dispatch.py): the plane
            # owns mesh construction + shard_map program factories; the
            # concrete provider (parallel.mesh by default) registers
            # itself, inverting the old models -> parallel import.
            pm = self._pm = dispatch_plane()
            if mesh is not None:
                self.mesh = mesh
            elif seg_shards > 1:
                # The 2-D docs x segs serving mesh: cold docs shard over
                # BOTH axes flattened (every device), hot docs carve the
                # segs axis via segment lanes.
                self.mesh = pm.docs_segs_mesh(seg_shards=seg_shards)
            else:
                self.mesh = pm.doc_mesh()
            n_shards = self.mesh.devices.size
            self.seg_shards = int(dict(self.mesh.shape).get(pm.SEG_AXIS, 1))
        else:
            self._pm = None
            self.mesh = None
            n_shards = 1
            self.seg_shards = 1
        # Segment-lane knobs (hot-doc opt-in; see _SegmentLane).
        self.seg_lanes: dict[int, _SegmentLane] = {}
        self.seg_lane_segments = seg_lane_segments
        self.seg_lane_text_capacity = seg_lane_text_capacity
        self.seg_rebalance_every = seg_rebalance_every
        self.max_seg_lanes = max_seg_lanes
        self.n_shards = n_shards
        self._shard_latency = [Histogram() for _ in range(n_shards)]
        # Device-row placement rides the shared plane (models/placement.py):
        # doc -> slot indirection with per-shard spare-slot free pools, the
        # same plane the tree fleet rides.  ``_slot`` aliases the plane's
        # live array for hot-path staging packs.
        self.placement_plane = placement.PlacementPlane(
            n_docs, n_shards, spare_slots
        )
        self.capacity = self.placement_plane.capacity
        self.docs_per_shard = self.placement_plane.docs_per_shard
        self._slot = self.placement_plane.slots
        # Per-shard applied-op counters (host-side, no device readback):
        # accumulated at drain time, the hot-shard detection signal.
        self._shard_ops = np.zeros((n_shards,), np.int64)

        proto = mk.init_state(
            max_segments, remove_slots, prop_slots, text_capacity, ob_slots
        )
        self._proto = proto  # pristine row: retires vacated migration slots
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.capacity,) + x.shape), proto
        )
        if self.mesh is not None:
            self.state = pm.shard_fleet_state(self.state, self.mesh)

        # Module-level jitted programs (shared compile cache across engine
        # instances; one executable per geometry/batch shape).
        self._step = _fleet_step
        self._megastep = _fleet_megastep
        self._compact = _fleet_compact
        self._seg_megastep = None
        self._seg_compact = None
        if self.mesh is not None:
            # shard_map-wrapped fleet programs: one donated dispatch steps
            # every shard with zero hot-path collectives; each shard's
            # obliterate gate is evaluated from its OWN docs, so one hot
            # obliterate shard no longer de-specializes the whole fleet.
            # Cached per (mesh, specs) — instances serving the same mesh
            # share compiles (parallel.mesh.mesh_fleet_program).  On a
            # docs x segs mesh the doc dim shards over BOTH axes flattened.
            da = pm.fleet_doc_axes(self.mesh)
            specs = pm.fleet_state_specs(self.state, da)
            self._state_specs = specs
            self._megastep = pm.mesh_fleet_program(
                mk.apply_megastep, self.mesh, specs,
                arg_specs=(pm.P(None, da), pm.P(None, da)),
            )
            self._compact = pm.mesh_fleet_program(
                _fleet_compact_body, self.mesh, specs,
                arg_specs=(pm.P(da),),
            )
            if self.seg_shards > 1:
                # Segment-lane programs: one donated dispatch applies a
                # [K, B] op ring to one seg-sharded hot doc, per-segment
                # work split over the segs axis (two collective hops
                # inside — mk.apply_megastep_seg).  A plane without
                # seg-lane programs (the native CPU plane) raises a loud
                # NotImplementedError here; the engine maps it to the
                # doc-sharded path and counts the downgrade — never a
                # silent degradation.
                try:
                    seg_specs = pm.seg_state_specs(self._proto)
                    self._seg_megastep = pm.mesh_seg_program(
                        mk.apply_megastep_seg, self.mesh, seg_specs
                    )
                    self._seg_compact = pm.mesh_seg_program(
                        mk.compact_seg, self.mesh, seg_specs,
                        arg_specs=(pm.P(),),
                    )
                except NotImplementedError:
                    self._seg_megastep = None
                    self._seg_compact = None
                    self.seg_shards = 1
                    self.counters.bump("seg_plane_unsupported")
        self._lane_apply = _lane_apply_jit
        self._lane_compact = _lane_compact_jit
        # Recompile watchdog: executable-cache growth on any fleet program
        # after warmup = a megastep trace de-specialized mid-serve (counted
        # in health() as ``recompiles``; each emits an instant trace
        # event).  Polled once per step() — one int read per program.
        self.recompile_watchdog = RecompileWatchdog()
        for prog_name, prog in (
            ("fleet_step", self._step),
            ("fleet_megastep", self._megastep),
            ("fleet_compact", self._compact),
            ("lane_apply", self._lane_apply),
        ):
            self.recompile_watchdog.register(prog_name, prog)
        if self._seg_megastep is not None:
            self.recompile_watchdog.register("seg_megastep", self._seg_megastep)
            self.recompile_watchdog.register("seg_compact", self._seg_compact)
        # Incremental busy set: doc indices whose host queue is nonempty,
        # maintained by ingest/drain/quarantine — step() never rescans the
        # whole host array (O(busy) per loop iteration, not O(capacity)).
        self._busy: set[int] = set()
        # Preallocated, double-buffered [K, D, B] staging (lazy: sized from
        # the megastep depth and fleet capacity on first use).
        self._stage: StagingRing | None = None
        # ---- Zipf straggler bucketing (SURVEY §7: doc-packing by op count)
        # Under skewed per-doc op counts one hot doc would force extra
        # FULL-fleet steps (every step scans B ops across all D lanes).
        # When few docs remain busy, gather just those docs' state rows
        # into a power-of-two cohort, step the small sub-fleet, and
        # masked-scatter the rows back — pad lanes route out of bounds
        # (mode="drop"), so duplicate writes never occur.  The jit caches
        # one executable per cohort size (log2(D) variants).
        # Single-chip optimization: under a mesh the doc axis is sharded
        # evenly and arbitrary-index gathers would cross shards.
        self.bucketing = self.mesh is None
        self.full_steps = 0     # fleet-wide steps taken
        self.cohort_steps = 0   # bucketed steps taken
        self.cohort_lanes = 0   # sum of cohort sizes (work proxy)
        self._gather_cohort = _gather_cohort_jit
        self._scatter_cohort = _scatter_cohort_jit

    # ------------------------------------------------------------------ ingest
    def ingest(self, doc_idx: int, msg: SequencedMessage) -> None:
        """Stage one sequenced message for a document (host-side decode).

        This is the engine's inbound seam: the equivalent of
        DeltaManager -> ContainerRuntime.process for one container, except
        application is deferred to the next batched device step.
        Serialized on ``ckpt_lock`` against the background checkpoint
        writer (a sweep never sees a half-staged message).
        """
        with self.ckpt_lock:
            return self._ingest_one(doc_idx, msg)

    def _ingest_one(self, doc_idx: int, msg: SequencedMessage) -> None:
        h = self.hosts[doc_idx]
        assert h.mode != "native" or self._in_lane(doc_idx), (
            f"doc {doc_idx} already fed through the native byte path; "
            "pick one ingest path per document"
        )
        if h.mode is None:
            h.mode = "obj"
        if msg.type == MessageType.JOIN:
            h.quorum[msg.contents["clientId"]] = msg.contents["short"]
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        if msg.type != MessageType.OP:
            h.min_seq = max(h.min_seq, msg.min_seq)
            return
        h.min_seq = max(h.min_seq, msg.min_seq)
        if h.base_seq and msg.seq <= h.base_seq:
            # Already folded into the durable checkpoint (a restarted
            # consumer replaying its topic from an older offset): skip —
            # restart must be idempotent, not double-apply.
            self.counters.bump("checkpointed_ops_skipped")
            return
        h.last_seq = max(h.last_seq, msg.seq)
        h.ops_since_ckpt += 1
        if not h.dirty_since:
            h.dirty_since = time.monotonic()
        self._lat_sample(doc_idx, msg.timestamp)
        if h.boot_counting:
            # Post-summary tail actually replayed on a boot-from-checkpoint/
            # summary consumer (the skipped prefix counts separately above;
            # the first post-boot checkpoint ends the boot phase).
            self.counters.bump("boot_replay_len")
        if doc_idx in self.quarantine:
            # Quarantined docs stay serviceable: validated host-oracle
            # apply; malformed ops are dropped and counted, never applied.
            self._oracle_apply_validated(self.quarantine[doc_idx], h, msg)
            # Keep the tail log so checkpoints and readmission replay stay
            # bounded and auditable.
            if self.recovery != "off":
                h.log.append(msg)
            return
        if doc_idx in self.oracles:
            # Oracle-routed docs apply immediately and can never need
            # another replay — no point retaining their log further.  Same
            # validation gate as quarantine: a malformed op for this doc
            # drops (counted) instead of crashing the whole consumer.
            self._oracle_apply_validated(self.oracles[doc_idx], h, msg)
            return

        if self.recovery != "off":
            # Replay source for recovery, bounded by checkpoints: ops at or
            # below base_seq live in base_summary, this list is the tail.
            h.log.append(msg)
        try:
            rows = self._encode(h, msg)
        except NotImplementedError:
            # Legal-but-unsupported wire form: loud feature gap.  The op
            # was never applied — keep it out of the replay log so a
            # caller that survives the raise doesn't poison recovery.
            if h.log and h.log[-1] is msg:
                h.log.pop()
            h.ops_since_ckpt -= 1
            raise
        except (ValueError, KeyError, TypeError) as e:
            if self.recovery == "off":
                raise  # no retained log to rebuild from: surface it
            # Decode failure: the wire op is malformed for THIS doc only.
            # Quarantine it (checkpoint + validated tail replay, which
            # drops this op and counts it) so the rest of the batch keeps
            # stepping.
            self._quarantine_doc(doc_idx, f"decode: {e}")
            return
        if doc_idx in self.overflow:
            self.overflow[doc_idx].queue.extend_rows(rows)
            return
        if doc_idx in self.seg_lanes:
            self.seg_lanes[doc_idx].queue.extend_rows(rows)
            return
        h.queue.extend_rows(rows)
        if h.queue:
            self._busy.add(doc_idx)

    # -------------------------------------------------------- batched ingest
    def ingest_batch(self, doc_idxs, msgs) -> int:
        """Flight-recorded entry over ``_ingest_batch`` (the ``ingest``
        phase of a trace; a free no-op while no recorder is installed).
        Holds ``ckpt_lock`` so the background checkpoint writer only ever
        sweeps at a whole-batch boundary."""
        with self.ckpt_lock, span("ingest", msgs=len(doc_idxs)):
            return self._ingest_batch(doc_idxs, msgs)

    def _ingest_batch(self, doc_idxs, msgs) -> int:
        """Columnar ingest fast path: decode a whole wire batch into
        [N, OP_FIELDS] op rows + payload rows with vectorized numpy and
        land them in the per-doc RowQueues as block copies — Python is
        touched per *message* for routing/bookkeeping only; all op-row
        materialization is batched (mk.encode_insert_batch /
        encode_obliterate_batch / column stacks).

        Semantics are byte-identical to calling ``ingest`` per message:

        - JOINs, non-OP messages, quarantined/oracle/overflow docs, and
          native-mode docs take the per-message path row by row
          (``ingest_fallback_msgs`` counts them).
        - A decode error quarantines ONLY the offending doc, exactly as
          the per-message path does: its earlier batch rows are dropped
          from the scatter (they already rode the retained log into the
          quarantine replay) and its later messages route through the
          validated oracle.
        - Recovery logging, checkpoint-floor dedupe, and boot counting
          run per message in the routing walk, unchanged.

        Returns the op-row count landed through the batch path.
        """
        L = self.max_insert_len
        counters = self.counters
        total = 0
        doc_of: list[int] = []  # row id -> doc
        # Per-kind columnar collectors (row ids reserved in walk order so
        # the per-doc ordering of mixed-kind streams is preserved).
        i_start: list[int] = []
        i_nch: list[int] = []
        i_pos: list[int] = []
        i_txt: list[str] = []
        i_key: list[int] = []
        i_cli: list[int] = []
        i_ref: list[int] = []
        s_id: list[int] = []  # single-row ops: global row ids
        s_row: list[tuple[int, int, int, int, int, int, int, int]] = []
        o_id: list[int] = []  # obliterates (vectorized encoder columns)
        o_col: tuple[list[int], ...] = ([], [], [], [], [], [], [])
        pending_raise: BaseException | None = None
        for d, msg in zip(doc_idxs, msgs):
            h = self.hosts[d]
            if (
                msg.type != MessageType.OP
                or d in self.quarantine
                or d in self.oracles
                or d in self.overflow
                or d in self.seg_lanes
                or h.mode == "native"
            ):
                counters.bump("ingest_fallback_msgs")
                self.ingest(d, msg)
                continue
            if h.mode is None:
                h.mode = "obj"
            h.min_seq = max(h.min_seq, msg.min_seq)
            if h.base_seq and msg.seq <= h.base_seq:
                counters.bump("checkpointed_ops_skipped")
                continue
            h.last_seq = max(h.last_seq, msg.seq)
            h.ops_since_ckpt += 1
            if not h.dirty_since:
                h.dirty_since = time.monotonic()
            self._lat_sample(d, msg.timestamp)
            if h.boot_counting:
                counters.bump("boot_replay_len")
            if self.recovery != "off":
                h.log.append(msg)
            try:
                c = msg.contents
                kind = c["type"]
                client = h.quorum[msg.client_id]
                if kind == DeltaType.INSERT:
                    seg = c["seg"]
                    if not isinstance(seg, str):
                        # Legal-but-unsupported wire form: loud feature
                        # gap, never applied — same unwinding as _encode.
                        if h.log and h.log[-1] is msg:
                            h.log.pop()
                        h.ops_since_ckpt -= 1
                        pending_raise = NotImplementedError(
                            "engine supports plain-text insert segs only; "
                            f"got {type(seg).__name__}"
                        )
                        break
                    # _i32 coercions throughout this walk are load-bearing
                    # AND must complete before ANY collector append: a
                    # malformed scalar (string value, dict pos) raises
                    # INSIDE this try — per-doc quarantine — an
                    # out-of-int32 scalar raises OverflowError (per-message
                    # parity: loud, never a silent int64->int32 wrap), and
                    # a partial append would misalign the columnar
                    # collectors and crash the whole-batch numpy scatter.
                    pos = _i32(c["pos1"])
                    nch = -(-len(seg) // L)
                    i_start.append(total)
                    i_nch.append(nch)
                    i_pos.append(pos)
                    i_txt.append(seg)
                    i_key.append(_i32(msg.seq))
                    i_cli.append(client)
                    i_ref.append(_i32(msg.ref_seq))
                    doc_of.extend([d] * nch)
                    total += nch
                elif kind == DeltaType.REMOVE:
                    row = (
                        mk.OpKind.REMOVE, _i32(msg.seq), client,
                        _i32(msg.ref_seq), _i32(c["pos1"]), _i32(c["pos2"]),
                        0, 0,
                    )
                    s_id.append(total)
                    s_row.append(row)
                    doc_of.append(d)
                    total += 1
                elif kind == DeltaType.ANNOTATE:
                    seq32, ref32 = _i32(msg.seq), _i32(msg.ref_seq)
                    p1, p2 = _i32(c["pos1"]), _i32(c["pos2"])
                    # All props coerce before any append, mirroring the
                    # per-message path where a mid-props failure lands
                    # NOTHING for the message.
                    prop_rows = [
                        (self._prop_slot_for(h, int(prop)), _i32(value))
                        for prop, value in c["props"].items()
                    ]
                    for slot, value in prop_rows:
                        s_id.append(total)
                        s_row.append((
                            mk.OpKind.ANNOTATE, seq32, client,
                            ref32, p1, p2, slot, value,
                        ))
                        doc_of.append(d)
                        total += 1
                elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
                    places = decode_obliterate_places(c)
                    vals = tuple(
                        _i32(v)
                        for v in (*places, msg.seq, client, msg.ref_seq)
                    )
                    o_id.append(total)
                    for col, v in zip(o_col, vals):
                        col.append(v)
                    doc_of.append(d)
                    total += 1
                else:
                    raise ValueError(f"unsupported op type {kind}")
            except OverflowError as e:
                # Per-message parity: OverflowError is NOT a quarantine
                # class there (np.array raises it out of ingest with the
                # message's bookkeeping committed) — land the earlier
                # messages' rows, then surface it.
                pending_raise = e
                break
            except (ValueError, KeyError, TypeError) as e:
                if self.recovery == "off":
                    pending_raise = e
                    break
                # Decode failure: poison for THIS doc only — quarantine it
                # (its staged + batch rows ride the retained log into the
                # validated replay) and keep batching the rest.
                self._quarantine_doc(d, f"decode: {e}")
        staged = self._scatter_batch_rows(
            total, doc_of, i_start, i_nch, i_pos, i_txt, i_key, i_cli,
            i_ref, s_id, s_row, o_id, o_col,
        )
        if pending_raise is not None:
            raise pending_raise
        return staged

    def _scatter_batch_rows(
        self, total, doc_of, i_start, i_nch, i_pos, i_txt, i_key, i_cli,
        i_ref, s_id, s_row, o_id, o_col,
    ) -> int:
        """Materialize the collected batch rows (vectorized) and land them
        per doc as block copies; rows for docs that left the device path
        mid-batch are dropped (their ops already rode the log into the
        lane replay)."""
        if not total:
            return 0
        ops_all = np.zeros((total, mk.OP_FIELDS), np.int32)
        pay_all = np.zeros((total, self.max_insert_len), np.int32)
        if i_txt:
            ops_i, pay_i, _owner = mk.encode_insert_batch(
                np.asarray(i_pos, np.int64), i_txt,
                np.asarray(i_key, np.int64), np.asarray(i_cli, np.int64),
                np.asarray(i_ref, np.int64), self.max_insert_len,
            )
            nch = np.asarray(i_nch, np.int64)
            m = int(nch.sum())
            row0 = np.concatenate(([0], np.cumsum(nch)[:-1]))
            ids = np.repeat(np.asarray(i_start, np.int64), nch) + (
                np.arange(m) - np.repeat(row0, nch)
            )
            ops_all[ids] = ops_i
            pay_all[ids] = pay_i
        if s_row:
            ops_all[np.asarray(s_id, np.int64)] = np.asarray(s_row, np.int32)
        if o_id:
            ops_all[np.asarray(o_id, np.int64)] = mk.encode_obliterate_batch(
                *(np.asarray(col, np.int64) for col in o_col)
            )
        doc_arr = np.asarray(doc_of, np.int64)
        live = np.ones((total,), bool)
        for d in set(doc_of):
            if (
                d in self.quarantine or d in self.oracles
                or d in self.overflow or d in self.seg_lanes
            ):
                live[doc_arr == d] = False
        # Stable doc-sort: one extend_block per doc, original order kept.
        order = np.argsort(doc_arr, kind="stable")
        order = order[live[order]]
        staged = int(order.size)
        if not staged:
            return 0
        sorted_docs = doc_arr[order]
        cuts = np.flatnonzero(np.diff(sorted_docs)) + 1
        for seg in np.split(order, cuts):
            d = int(doc_arr[seg[0]])
            self.hosts[d].queue.extend_block(ops_all[seg], pay_all[seg])
            self._busy.add(d)
        self.counters.bump("ingest_batch_rows", staged)
        return staged

    def _make_lane(
        self, state: mk.DocState, geometry: dict[str, int], growths: int
    ) -> _OverflowLane:
        return _OverflowLane(
            state=state, geometry=geometry, growths=growths,
            queue=RowQueue(mk.OP_FIELDS, self.max_insert_len),
        )

    def _in_lane(self, doc_idx: int) -> bool:
        """True when the doc has left the lockstep batch (or was restored
        from a checkpoint): its ingest consumes parsed messages.  A live
        native-path doc that merely CHECKPOINTED is not in a lane — it
        stays on the C++ fast path."""
        return (
            doc_idx in self.oracles
            or doc_idx in self.overflow
            or doc_idx in self.quarantine
            or doc_idx in self.seg_lanes
            or self.hosts[doc_idx].restored
        )

    def ingest_lines(self, doc_idx: int, data: bytes) -> int:
        """Stage newline-separated wire JSON through the NATIVE encoder
        (native/ingest.cpp): the whole decode+encode runs in C++, so this is
        the production feed path for a server-side fleet consuming the
        broadcast stream.  Returns the number of op rows staged (op count
        applied, for oracle-routed docs).  Falls back to the Python path
        message by message when the native library is unavailable.  A
        healthy document stays on whichever path fed it first (the two
        paths intern property slots independently); recovery-lane routing
        normalizes a native doc onto the object path."""
        with self.ckpt_lock:
            return self._ingest_lines(doc_idx, data)

    def _ingest_lines(self, doc_idx: int, data: bytes) -> int:
        # loaded(), not available(): this runs under ckpt_lock, and the
        # building probe spawns g++ for a stale .so — warm() at __init__
        # already did any building with the lock free.
        from ..native.ingest_native import NativeIngestEncoder, loaded

        h = self.hosts[doc_idx]
        if self._in_lane(doc_idx) or not loaded():
            # Lanes, checkpoint-restored docs, and the no-native fallback
            # consume parsed messages — decoded as one batch and fed
            # through the columnar fast path (ingest_batch routes lane
            # docs message by message itself, so semantics match).
            self._normalize_native(h)
            lane = self.overflow.get(doc_idx) or self.seg_lanes.get(doc_idx)
            before = len(lane.queue) if lane else len(h.queue)
            msgs = [
                SequencedMessage.from_json(line.decode())
                for line in data.split(b"\n")
                if line.strip()
            ]
            n_msgs = sum(m.type == MessageType.OP for m in msgs)
            self.ingest_batch([doc_idx] * len(msgs), msgs)
            if doc_idx in self.oracles or doc_idx in self.quarantine:
                return n_msgs
            lane = self.overflow.get(doc_idx) or self.seg_lanes.get(doc_idx)
            return (len(lane.queue) if lane else len(h.queue)) - before
        assert h.mode != "obj", (
            f"doc {doc_idx} already fed through the object path; "
            "pick one ingest path per document"
        )
        if h.native is None:
            h.native = NativeIngestEncoder(
                self.max_insert_len, self.geometry["prop_slots"]
            )
            h.mode = "native"
        with span("ingest", doc=doc_idx, bytes=len(data)):
            ops, payloads = h.native.encode(data)
            if self.recovery != "off":
                h.raw_log.append(data)
            # Native row output lands as one block copy per chunk — the doc
            # lane "gather" is a slice assignment, never a per-row Python
            # loop.
            h.queue.extend_block(ops, payloads)
        if len(ops):
            # One latency sample per chunk (the C++ decode exposes no wire
            # timestamps): stamp 0.0 = receipt time, so the sample covers
            # staging -> device apply, not the sequencer hop.
            self._lat_sample(doc_idx, 0.0, force=True)
        if h.queue:
            self._busy.add(doc_idx)
        h.min_seq = max(h.min_seq, h.native.min_seq)
        h.ops_since_ckpt += len(ops)
        if len(ops) and not h.dirty_since:
            h.dirty_since = time.monotonic()
        if self.checkpoint_store is not None:
            # Checkpoints need the seq floor; one JSON parse of the chunk's
            # last line covers the whole chunk (lines are seq-ordered).
            tail_line = data.rstrip(b"\n").rsplit(b"\n", 1)[-1]
            if tail_line.strip():
                try:
                    h.last_seq = max(
                        h.last_seq,
                        int(json.loads(tail_line)["sequenceNumber"]),
                    )
                except (ValueError, KeyError):
                    pass
        return len(ops)

    def _normalize_native(self, h: _DocHost) -> None:
        """Move a native-path doc onto the object path: parse the retained
        raw lines into quorum + message log (PREPENDED — they precede
        anything the object path appended later) so recovery replay, oracle
        takeover, and further ingest share one consistent stream and one
        prop-slot interning order."""
        if not h.raw_log:
            if h.mode == "native":
                h.mode = "obj"
                h.native = None
            return
        prefix: list[SequencedMessage] = []
        for chunk in h.raw_log:
            for line in chunk.split(b"\n"):
                if line.strip():
                    m = SequencedMessage.from_json(line.decode())
                    if m.type == MessageType.JOIN:
                        h.quorum[m.contents["clientId"]] = m.contents["short"]
                    elif m.type == MessageType.OP and m.seq > h.base_seq:
                        prefix.append(m)
                        h.last_seq = max(h.last_seq, m.seq)
        h.raw_log.clear()
        h.log[:0] = prefix
        h.mode = "obj"
        h.native = None

    def _encode(
        self, h: _DocHost, msg: SequencedMessage
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Wire message -> kernel op rows (+payloads)."""
        c = msg.contents
        kind = c["type"]
        client = h.quorum[msg.client_id]
        empty = np.zeros((self.max_insert_len,), np.int32)
        if kind == DeltaType.INSERT:
            if not isinstance(c["seg"], str):
                # Marker/annotated dict specs and per-props-run spec LISTS
                # are legal channel-layer wire forms this engine cannot
                # encode yet.  They must fail LOUD (a feature gap), never
                # quarantine-drop as poison — silently dropping a legal op
                # would split-brain the fleet tier against every channel
                # replica that applied it.
                raise NotImplementedError(
                    "engine supports plain-text insert segs only; got "
                    f"{type(c['seg']).__name__}"
                )
            return mk.encode_insert(
                c["pos1"], c["seg"], msg.seq, client, msg.ref_seq,
                self.max_insert_len,
            )
        if kind == DeltaType.REMOVE:
            op = np.array(
                [mk.OpKind.REMOVE, msg.seq, client, msg.ref_seq,
                 c["pos1"], c["pos2"], 0, 0],
                np.int32,
            )
            return [(op, empty)]
        if kind == DeltaType.ANNOTATE:
            out = []
            for prop, value in c["props"].items():
                slot = self._prop_slot_for(h, int(prop))
                out.append(
                    (
                        np.array(
                            [mk.OpKind.ANNOTATE, msg.seq, client, msg.ref_seq,
                             c["pos1"], c["pos2"], slot, value],
                            np.int32,
                        ),
                        empty,
                    )
                )
            return out
        if kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            return [
                (mk.encode_obliterate(p1, s1, p2, s2, msg.seq, client, msg.ref_seq),
                 empty)
            ]
        raise ValueError(f"unsupported op type {kind}")

    @staticmethod
    def _oracle_apply(tree: RefMergeTree, h: _DocHost, msg: SequencedMessage) -> None:
        """Apply one wire OP message to a host oracle replica (the pure
        remote path of SharedString._apply_remote)."""
        c = msg.contents
        kind = c["type"]
        client = h.quorum[msg.client_id]
        if kind == DeltaType.INSERT:
            tree.apply_insert(c["pos1"], c["seg"], msg.seq, client, msg.ref_seq)
        elif kind == DeltaType.REMOVE:
            tree.apply_remove(c["pos1"], c["pos2"], msg.seq, client, msg.ref_seq)
        elif kind == DeltaType.ANNOTATE:
            for prop, value in c["props"].items():
                tree.apply_annotate(
                    c["pos1"], c["pos2"], int(prop), value,
                    msg.seq, client, msg.ref_seq,
                )
        elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            tree.apply_obliterate(p1, s1, p2, s2, msg.seq, client, msg.ref_seq)
        else:
            raise ValueError(f"unsupported op type {kind}")

    def _prop_slot_for(self, h: _DocHost, prop: int) -> int:
        """Intern a property id to a kernel prop slot (range-checked)."""
        if prop not in h.prop_slot:
            slot = len(h.prop_slot)
            if slot >= self.geometry["prop_slots"]:
                raise ValueError(
                    f"document exhausted its {self.geometry['prop_slots']} prop "
                    f"slots; raise prop_slots to accommodate prop id {prop}"
                )
            h.prop_slot[prop] = slot
        return h.prop_slot[prop]

    # ------------------------------------------------------------- op latency
    def _lat_sample(self, doc_idx: int, stamp: float, force: bool = False) -> None:
        """Maybe sample one staged op's e2e latency: record its sequencer
        stamp time (wall clock; 0.0 = unstamped synthetic streams, which
        fall back to receipt time) to resolve at the next step() sync
        boundary.  Gated to every ``latency_sample_every``-th staged op so
        the per-message feed cost stays one int increment."""
        self._lat_tick += 1
        if not force and self._lat_tick % self.latency_sample_every:
            return
        if len(self._lat_pending) < 4096:  # bound a step-starved feed
            self._lat_pending.append(
                (stamp if stamp > 0 else time.time(), doc_idx)
            )

    def _lat_flush(self) -> None:
        """Resolve pending latency samples at the applied-on-device
        boundary (end of step(), after the error-latch readback proved the
        dispatches retired) into the per-doc and per-shard histograms."""
        if not self._lat_pending:
            return
        now = time.time()
        for stamp, d in self._lat_pending:
            lat = max(0.0, now - stamp)
            self.op_latency.record(lat)
            if 0 <= d < self.n_docs:
                self._shard_latency[self.shard_of(d)].record(lat)
                h = self._doc_latency.get(d)
                if h is None:
                    h = self._doc_latency[d] = Histogram()
                h.record(lat)
        self._lat_pending.clear()

    def latency_histograms(self) -> dict[str, Histogram]:
        """Mergeable op-latency histograms for the metrics plane: the
        fleet aggregate, one per mesh shard, and the per-incident
        recovery-time histogram (kill/restore -> first post-restore op
        applied)."""
        out = {
            "op_latency": self.op_latency,
            "recovery_time": self.recovery_tracker.histogram,
        }
        if self.n_shards > 1:
            for s, h in enumerate(self._shard_latency):
                out[f"op_latency_shard{s}"] = h
        return out

    def doc_latency(self, doc_idx: int) -> Histogram | None:
        return self._doc_latency.get(doc_idx)

    def flush_telemetry(self) -> None:
        """Drain residual sampled-telemetry buckets (status snapshot /
        shutdown hook): tail samples below ``sample_every`` must reach the
        sink before the process goes away."""
        if self.sampled is not None:
            self.sampled.flush_all()

    # ------------------------------------------------------------------- step
    def pending_ops(self) -> int:
        return (
            sum(len(h.queue) for h in self.hosts)
            + sum(len(l.queue) for l in self.overflow.values())
            + sum(len(l.queue) for l in self.seg_lanes.values())
        )

    # --------------------------------------------------------- flow control
    def update_overload(self) -> tuple[list[int], list[int]]:
        """Advance the ingest watermark hysteresis; -> (docs newly over the
        high watermark, docs drained back under the low watermark).  The
        consumer calls this once per pump and pauses/resumes per-partition
        reads on the deltas; the gate's paused set IS the engine's overload
        state (``health()['overload']``).  Lane docs (segment-sharded or
        overflow) queue on their lane, not the batch host, so the gate
        reads the combined depth — otherwise promotion (which empties
        ``h.queue`` into the lane) would instantly resume a paused hot doc
        and its lane queue would grow unboundedly."""
        return self.overload_gate.update(
            self._busy | set(self.seg_lanes) | set(self.overflow),
            self._queue_depth,
        )

    def _queue_depth(self, d: int) -> int:
        """Total staged-but-unapplied rows for doc ``d``: its batch host
        queue plus any seg/overflow lane queue (the flow-control signal)."""
        lane = self.seg_lanes.get(d) or self.overflow.get(d)
        return len(self.hosts[d].queue) + (
            len(lane.queue) if lane is not None else 0
        )

    def ingest_watermarks(self) -> dict:
        """The flow-control contract numbers: one megastep dispatch retires
        ``megastep_budget`` rows per doc; pause at ``high``, resume at
        ``low``."""
        return self.overload_gate.watermarks(
            self.megastep_k * self.ops_per_step
        )

    @property
    def overloaded(self) -> bool:
        return bool(self.overload_gate.paused)

    def _drain_into(
        self,
        docs: list[int],
        ops: np.ndarray,
        payloads: np.ndarray,
        rows: list[int] | None = None,
        slots: bool = False,
    ) -> list[int]:
        """Dequeue up to ops_per_step ops per listed doc into the padded
        arrays (``docs[j]`` fills row ``rows[j]``, default ``j``) — the
        ONE drain used by full-fleet, cohort, and megastep packing (their
        semantics must never diverge).  Vectorized: each doc moves as two
        slice copies (op rows + payload rows), never a per-op Python loop.
        The caller guarantees the target rows are zeroed
        (StagingRing.acquire); returns the rows written so a reused buffer
        re-zeroes exactly those."""
        B = self.ops_per_step
        written: list[int] = []
        for j, d in enumerate(docs):
            h = self.hosts[d]
            take = min(B, len(h.queue))
            if not take:
                continue
            r = j if rows is None else rows[j]
            src_ops, src_payloads = h.queue.take(take)
            ops[r, :take] = src_ops
            payloads[r, :take] = src_payloads
            if slots:
                # Row IS the device slot here (full-fleet packing): charge
                # the op count to its shard for hot-shard detection.
                self._shard_ops[r // self.docs_per_shard] += take
            if not h.queue:
                self._busy.discard(d)
            written.append(r)
        return written

    def _staging(self) -> StagingRing:
        if self._stage is None:
            self._stage = StagingRing(
                self.megastep_k, self.capacity, self.ops_per_step,
                mk.OP_FIELDS, self.max_insert_len, mesh=self.mesh,
                doc_axis=(
                    self._pm.fleet_doc_axes(self.mesh)
                    if self.mesh is not None else "docs"
                ),
            )
        return self._stage

    @staticmethod
    def _pow2_floor(n: int) -> int:
        return 1 << (max(n, 1).bit_length() - 1)

    def _select_k(self, busy: list[int], cohort: bool) -> int:
        """Adaptive megastep depth from queue depths: how many B-op slices
        to fuse into the next dispatch.  Cohort-bucketing aware: a
        full-fleet megastep fuses only as many slices as the busy set
        stays ABOVE the cohort threshold (bounded by the (thresh+1)-th
        deepest queue), so a Zipf tail still collapses into small gathered
        cohorts exactly when it would have.  Quantized to powers of two
        (compile cache stays log2(K) deep, and an undershoot just means
        one more dispatch — never wasted all-NOOP slices)."""
        if self.megastep_k <= 1:
            return 1
        B = self.ops_per_step
        depths = np.array(
            [-(-len(self.hosts[d].queue) // B) for d in busy], np.int64
        )
        if cohort or not self.bucketing:
            need = int(depths.max())
        else:
            thresh = self.capacity // 4
            if len(depths) > thresh:
                # Slices until the busy set shrinks to cohort size: the
                # (thresh+1)-th deepest queue still has ops at slice k iff
                # its depth > k.
                need = int(np.partition(depths, -thresh - 1)[-thresh - 1])
            else:
                need = int(depths.max())
        return min(self.megastep_k, self._pow2_floor(need))

    def _full_step(self, busy: list[int]) -> int:
        """One fleet-wide megastep: pack up to K [capacity, B] slices into
        the staging ring (slice k+1 packs while the upload/dispatch of the
        previous megastep is still in flight) and apply them as one
        donated program; returns the slices applied."""
        K = self._select_k(busy, cohort=False)
        stage = self._staging()
        ops, payloads = stage.acquire(K, self.capacity)
        # Pack by doc PLACEMENT: doc d's ops land in row slot(d), so each
        # shard's slice of the staging buffer holds exactly its own docs
        # and the shard-layout upload splits per chip with no reshuffle.
        rows = [int(s) for s in self._slot[busy]]
        for k in range(K):
            stage.mark(
                k,
                self._drain_into(
                    busy, ops[k], payloads[k], rows=rows, slots=True
                ),
            )
            if k + 1 < K:
                pairs = [
                    (d, r) for d, r in zip(busy, rows) if d in self._busy
                ]
                busy = [d for d, _ in pairs]
                rows = [r for _, r in pairs]
        if self.mesh is None and K == 1:
            dev_ops, dev_payloads = stage.upload(ops[0], payloads[0])
            with span("dispatch", kind="full", k=K):
                self.state = self._step(self.state, dev_ops, dev_payloads)
        else:
            # The mesh path always dispatches the [K, D, B] megastep
            # program (K=1 included — apply_megastep at K=1 is bit-
            # identical to one apply_ops dispatch): one donated shard_map
            # call steps every chip, zero hot-path collectives.
            dev_ops, dev_payloads = stage.upload(ops, payloads)
            with span("dispatch", kind="full", k=K, shards=self.n_shards):
                self.state = self._megastep(self.state, dev_ops, dev_payloads)
        self.full_steps += K
        self.counters.bump("megastep_dispatches")
        self.counters.bump("megastep_slices", K)
        return K

    def step(self) -> int:
        """Run device dispatches until all staged ops are applied; returns
        the number of batched SLICES applied (a K-slice megastep counts K,
        so the return value is K-invariant).  Busy-doc cohorts far below
        fleet size run bucketed (see __init__), so a Zipf-skewed tail
        stops costing full-fleet steps.  No host/device sync happens
        between megasteps — uploads and dispatches queue asynchronously;
        the pipeline synchronizes only at the recover()/watchdog/
        checkpoint boundaries below.  Afterwards, any latched overflow
        bits are recovered (grow-and-replay or oracle routing), so
        ``errors()`` is all-zero on return unless recovery is off.

        Holds ``ckpt_lock`` end to end (the background checkpoint writer
        can only sweep between steps), and is the recovery clock's
        completion point: the first step that applies staged work after a
        restore closes the open incident (kill -> first post-restore op
        applied)."""
        with self.ckpt_lock:
            had_work = bool(
                self._busy
                or any(ln.queue for ln in self.overflow.values())
                or any(ln.queue for ln in self.seg_lanes.values())
            )
            steps = self._step_fleet()
            if had_work and self.recovery_tracker.active:
                self.recovery_tracker.complete()
        # Cadence checkpoints run AFTER the serving lock releases: the
        # record build retakes ckpt_lock briefly, but the durable fsyncs
        # land with it free — the serving thread no longer pays platter
        # time under the lock every ingest/step contender waits on
        # (fftpu-check blocking-under-lock: fsync under ckpt_lock).
        # Work staged by a racing ingest meanwhile is skipped by the
        # sweep's staged-but-unapplied guard, exactly as a background
        # sweep would skip it.
        self.maybe_checkpoint()
        return steps

    def _step_fleet(self) -> int:
        t0 = time.perf_counter() if self.sampled is not None else 0.0
        steps = 0
        while self._busy:
            busy = sorted(self._busy)
            if self.bucketing and len(busy) <= self.capacity // 4:
                steps += self._cohort_step(busy)
            else:
                steps += self._full_step(busy)
        self._step_lanes()
        self._step_seg_lanes()
        self._step_count += 1
        if self.recovery != "off":
            self.recover()
            self._steps_since_watchdog += 1
            if (
                self.watchdog_every
                and self._steps_since_watchdog >= self.watchdog_every
            ):
                self._steps_since_watchdog = 0
                self.watchdog()
            if self.readmit_after_steps:
                self._maybe_readmit()
        # Sync boundary housekeeping (host-side, O(programs + samples)):
        # resolve e2e latency samples, poll for mid-serve recompiles, and
        # feed the sampled step timing when a telemetry sink is attached.
        self._lat_flush()
        self.recompile_watchdog.poll()
        if self.sampled is not None:
            self.sampled.record(time.perf_counter() - t0, "step")
        return steps

    def _maybe_readmit(self) -> None:
        """Backoff-scheduled quarantine readmission (see __init__)."""
        for d, due_step in list(self._readmit_due.items()):
            if self._step_count < due_step or d not in self.quarantine:
                if d not in self.quarantine:
                    self._readmit_due.pop(d, None)
                continue
            if self.readmit(d):
                self.counters.bump("auto_readmissions")
            else:
                # State no longer fits the batch geometry: double the
                # backoff and retry later (the doc stays serviceable in
                # its quarantine lane).
                interval = min(
                    2 * self._readmit_interval.get(d, self.readmit_after_steps),
                    self.readmit_after_steps << 16,
                )
                self._readmit_interval[d] = interval
                self._readmit_due[d] = self._step_count + interval

    def _cohort_step(self, busy: list[int]) -> int:
        """One bucketed megastep over just the busy docs: gather the
        cohort's state rows once, apply up to K fused [Kc, B] slices, and
        masked-scatter the rows back — K > 1 amortizes the gather/scatter
        pair as well as the dispatch.  Returns the slices applied."""
        K = self._select_k(busy, cohort=True)
        Kc = max(1, 1 << (len(busy) - 1).bit_length())  # pow2 ladder
        idx = np.full((Kc,), busy[-1], np.int32)  # gather pad: harmless dup
        idx[: len(busy)] = busy
        valid = np.zeros((Kc,), bool)
        valid[: len(busy)] = True
        stage = self._staging()
        ops, payloads = stage.acquire(K, Kc)
        row_of = {d: j for j, d in enumerate(busy)}
        cur = busy
        for k in range(K):
            stage.mark(
                k,
                self._drain_into(
                    cur, ops[k], payloads[k], rows=[row_of[d] for d in cur]
                ),
            )
            if k + 1 < K:
                cur = [d for d in cur if d in self._busy]
        sub = self._gather_cohort(self.state, jnp.asarray(idx))
        if K == 1:
            dev_ops, dev_payloads = stage.upload(ops[0], payloads[0])
            with span("dispatch", kind="cohort", k=K, lanes=Kc):
                sub = self._step(sub, dev_ops, dev_payloads)
        else:
            dev_ops, dev_payloads = stage.upload(ops, payloads)
            with span("dispatch", kind="cohort", k=K, lanes=Kc):
                sub = self._megastep(sub, dev_ops, dev_payloads)
        self.state = self._scatter_cohort(
            self.state, sub, jnp.asarray(idx), jnp.asarray(valid)
        )
        self.cohort_steps += K
        self.cohort_lanes += K * Kc
        self.counters.bump("megastep_dispatches")
        self.counters.bump("megastep_slices", K)
        return K

    def _step_lanes(self) -> None:
        B = self.ops_per_step
        if not self.overflow:
            return
        stage = self._staging()
        for lane in self.overflow.values():
            while lane.queue:
                take = min(B, len(lane.queue))
                # One staged [B] chunk per dispatch through the shared
                # ring (row 0 of a 1-slice view): slice copies, no fresh
                # allocation, and the double buffer keeps the host from
                # mutating an upload still in flight.
                ops, payloads = stage.acquire(1, 1)
                src_ops, src_payloads = lane.queue.take(take)
                ops[0, 0, :take] = src_ops
                payloads[0, 0, :take] = src_payloads
                stage.mark(0, [0])
                dev_ops, dev_payloads = stage.upload(
                    ops[0, 0], payloads[0, 0]
                )
                with span("dispatch", kind="lane"):
                    lane.state = self._lane_apply(
                        lane.state, dev_ops, dev_payloads
                    )

    # -------------------------------------------------------- segment lanes
    def _step_seg_lanes(self) -> None:
        """Drain every segment lane with [K, B] seg-parallel megastep
        dispatches, re-blocking any lane past its rebalance budget."""
        for d, lane in list(self.seg_lanes.items()):
            self._drain_seg_lane(d, lane)
            if (
                self.seg_rebalance_every
                and lane.ops_since_rebalance >= self.seg_rebalance_every
            ):
                self.rebalance_segments(d)

    def _drain_seg_lane(self, d: int, lane: _SegmentLane) -> None:
        """Apply ONE lane's staged ops as [K, B] seg megasteps: ops/
        payloads upload REPLICATED over the segs axis (each shard applies
        every op to its own segment block) and the dispatch spans carry
        the 2-D layout for the flight recorder.  The [K, B] buffers are
        fresh per dispatch — at K*B*(OP_FIELDS+L) int32 they are tiny next
        to the dispatch itself (phase_shares pins dispatch at ~99%), so
        the fleet ring's reuse machinery is not worth threading in here."""
        B = self.ops_per_step
        while lane.queue:
            need = -(-len(lane.queue) // B)
            K = min(self.megastep_k, self._pow2_floor(max(need, 1)))
            ops = np.zeros((K, B, mk.OP_FIELDS), np.int32)
            payloads = np.zeros((K, B, self.max_insert_len), np.int32)
            taken = 0
            for k in range(K):
                take = min(B, len(lane.queue))
                if not take:
                    break
                src_ops, src_payloads = lane.queue.take(take)
                ops[k, :take] = src_ops
                payloads[k, :take] = src_payloads
                taken += take
            dev_ops, dev_payloads = upload_replicated(ops, payloads, self.mesh)
            with span(
                "dispatch", kind="seg", k=K, doc=self.doc_keys[d],
                seg_shards=lane.n_shards,
            ):
                lane.state = self._seg_megastep(
                    lane.state, dev_ops, dev_payloads
                )
            lane.version += 1
            lane.ops_since_rebalance += taken
            self.counters.bump("megastep_dispatches")
            self.counters.bump("megastep_slices", K)

    def segment_sharded(self) -> dict[str, int]:
        """doc key -> segment shard count for every promoted hot doc: the
        2-D placement surface (fleet status / supervisors)."""
        return {
            self.doc_keys[d]: lane.n_shards
            for d, lane in self.seg_lanes.items()
        }

    def enable_segment_sharding(
        self, d: int, s_local: int = 0, text_capacity: int = 0
    ) -> bool:
        # ckpt_lock: promotion moves the doc's row into a seg lane the
        # background checkpoint sweep also reads — see migrate_doc.
        with self.ckpt_lock:
            return self._enable_segment_sharding_locked(
                d, s_local, text_capacity
            )

    def _enable_segment_sharding_locked(
        self, d: int, s_local: int = 0, text_capacity: int = 0
    ) -> bool:
        """Promote a hot doc onto the segment-parallel path: its device row
        re-blocks into the seg-sharded layout (``mk.seg_shard_state`` — live
        segments split into contiguous runs over the segs axis, text/
        scalars/ob table replicated) and future ops apply segment-parallel.
        The batch slot stays RESERVED (pristine) so placement/scribe
        alignment are untouched and demotion lands back in place.  Staged
        ops move to the lane queue — promotion is legal MID-STREAM.
        Returns False when seg serving is off, the doc is off the batch
        path, the lane budget is spent, or the state does not block."""
        if self.seg_shards <= 1 or self._seg_megastep is None:
            return False
        if not (0 <= d < self.n_docs):
            raise ValueError(f"no doc {d}")
        if (
            d in self.seg_lanes or d in self.overflow
            or d in self.oracles or d in self.quarantine
        ):
            return False
        if len(self.seg_lanes) >= self.max_seg_lanes:
            self.counters.bump("seg_promotions_skipped")
            return False
        slot = int(self._slot[d])
        row = jax.tree.map(lambda x: np.asarray(x[slot]), self.state)
        if int(row.error):
            return False  # recover first; never promote a latched row
        s_local = (
            s_local or self.seg_lane_segments or self.geometry["max_segments"]
        )
        tc = (
            text_capacity or self.seg_lane_text_capacity
            or self.geometry["text_capacity"]
        )
        try:
            blocked = mk.seg_shard_state(row, self.seg_shards, s_local, tc)
        except (ValueError, NotImplementedError):
            return False
        lane = _SegmentLane(
            state=self._pm.shard_seg_state(blocked, self.mesh),
            n_shards=self.seg_shards, s_local=s_local,
            queue=RowQueue(mk.OP_FIELDS, self.max_insert_len),
        )
        h = self.hosts[d]
        if h.queue:
            ops_p, payloads_p = h.queue.pending()
            lane.queue.extend_block(ops_p.copy(), payloads_p.copy())
            h.queue.clear()
        self._busy.discard(d)
        self.seg_lanes[d] = lane
        # Retire the batch row to the pristine proto (slot reserved).
        self.state = jax.tree.map(
            lambda x, s: x.at[slot].set(s), self.state, self._proto
        )
        self._verified_digest.pop(d, None)
        self.counters.bump("seg_promotions")
        instant(
            "seg_promote", doc=self.doc_keys[d], shards=self.seg_shards,
            s_local=s_local,
        )
        return True

    def disable_segment_sharding(self, d: int) -> bool:
        """Demote a segment-sharded doc back into its reserved batch row
        (the migrate_doc handoff: gather -> summary export -> re-pack at
        batch geometry).  Staged lane ops apply first so nothing is lost.
        Returns False when the gathered state no longer fits the batch
        geometry (the doc stays segment-sharded and serviceable)."""
        with self.ckpt_lock:  # mutates state/seg_lanes the sweep reads
            return self._disable_segment_sharding_locked(d)

    def _disable_segment_sharding_locked(self, d: int) -> bool:
        lane = self.seg_lanes.get(d)
        if lane is None:
            return False
        if lane.queue:
            self._drain_seg_lane(d, lane)
        host = jax.tree.map(np.asarray, lane.state)
        if int(host.error):
            return False  # recover() handles latched lanes
        gathered = mk.seg_gather_state(host)
        h = self.hosts[d]
        self._sync_native_props(h)
        summary = kb.state_to_summary(
            gathered, {v: k for k, v in h.prop_slot.items()}
        )
        try:
            row = kb.summary_to_state(
                summary, self.geometry,
                lambda p: self._prop_slot_for_geom(h, p, self.geometry),
            )
        except (ValueError, IndexError):
            return False
        slot = int(self._slot[d])
        self.state = jax.tree.map(
            lambda x, s: x.at[slot].set(s), self.state, row
        )
        del self.seg_lanes[d]
        self._verified_digest.pop(d, None)
        self.counters.bump("seg_demotions")
        instant("seg_demote", doc=self.doc_keys[d])
        return True

    def rebalance_segments(self, d: int) -> bool:
        """Re-block a segment lane so every shard holds an even share of
        the live segments again (inserts land shard-local between rebalance
        points, so runs skew toward the hot shard over time).  Gather +
        re-shard, byte- and order-preserving (``mk.seg_rebalance_state``,
        the compaction gather's fill conventions)."""
        with self.ckpt_lock:  # mutates lane state the sweep reads
            return self._rebalance_segments_locked(d)

    def _rebalance_segments_locked(self, d: int) -> bool:
        lane = self.seg_lanes.get(d)
        if lane is None:
            return False
        if int(np.asarray(lane.state.error)):
            # One scalar readback, not the tree-wide gather below: a
            # latched lane is re-tried every step while it waits for
            # recover() (or forever under recovery='off').
            return False
        with span(
            "seg_rebalance", doc=self.doc_keys[d], shards=lane.n_shards
        ):
            host = jax.tree.map(np.asarray, lane.state)
            blocked = mk.seg_rebalance_state(host, s_local=lane.s_local)
            lane.state = self._pm.shard_seg_state(blocked, self.mesh)
        lane.version += 1
        lane.rebalances += 1
        lane.ops_since_rebalance = 0
        self.counters.bump("seg_rebalances")
        instant("seg_rebalance", doc=self.doc_keys[d])
        return True

    def compact(self) -> None:
        """Advance MSNs and run zamboni eviction across the fleet."""
        mins = np.zeros((self.capacity,), np.int32)
        for d, h in enumerate(self.hosts):
            mins[self._slot[d]] = h.min_seq
        if self.mesh is not None:
            mins_dev = jax.device_put(mins, self._pm.shard_docs(self.mesh))
        else:
            mins_dev = jnp.asarray(mins)
        self.state = self._compact(self.state, mins_dev)
        for d, lane in self.seg_lanes.items():
            lane.state = self._seg_compact(
                lane.state, jnp.asarray(self.hosts[d].min_seq, jnp.int32)
            )
            lane.version += 1
        for d, lane in self.overflow.items():
            lane.state = self._lane_compact(
                lane.state, jnp.asarray(self.hosts[d].min_seq, jnp.int32)
            )
        for d, tree in self.oracles.items():
            tree.update_min_seq(self.hosts[d].min_seq)
        for d, tree in self.quarantine.items():
            tree.update_min_seq(self.hosts[d].min_seq)

    # --------------------------------------------------------------- recovery
    def recover(self) -> list[int]:
        """Inspect every error vector and recover flagged docs; returns the
        doc indices recovered this call.  Capacity bits grow-and-replay (or
        oracle-route); poison bits (ERR_POS_RANGE alone) quarantine."""
        recovered: list[int] = []
        batch_clean = False
        if self.mesh is not None:
            # Per-shard reduce instead of a cross-mesh [D] gather: each
            # shard partial-sums its own latch rows and the host reads ONE
            # scalar — the full error vector transfers only when it is
            # actually nonzero (recovery itself, off the hot path).  Lane
            # errors are per-lane scalars checked below, so an active seg
            # or overflow lane must not force the batch-state gather.
            with span("readback", kind="error_count"):
                batch_clean = int(self._pm.error_count(self.state.error)) == 0
        if not batch_clean:
            with span("readback", kind="error_vector"):
                err = np.asarray(self.state.error)
            for d in range(self.n_docs):
                slot = int(self._slot[d])
                if (
                    d not in self.overflow
                    and d not in self.oracles
                    and d not in self.quarantine
                    and err[slot]
                ):
                    bits = int(err[slot])
                    if mk.is_capacity_error(bits):
                        self._recover_doc(d, bits, growths=0)
                    else:  # poison: ERR_POS_RANGE with no capacity bit
                        self._quarantine_doc(d, f"error bits {bits:#x}")
                    # Retire the batch slot: clear the latched bits so the
                    # slot never re-triggers (its queue is empty and future
                    # ops route to the lane).
                    self.state = self.state._replace(
                        error=self.state.error.at[slot].set(0)
                    )
                    recovered.append(d)
        for d, lane in list(self.overflow.items()):
            bits = int(lane.state.error)
            if bits:
                if mk.is_capacity_error(bits):
                    self._recover_doc(d, bits, growths=lane.growths)
                else:
                    self._quarantine_doc(d, f"error bits {bits:#x}")
                recovered.append(d)
        for d, lane in list(self.seg_lanes.items()):
            bits = int(np.asarray(lane.state.error))
            if bits:
                # A latched segment lane leaves the seg path entirely: the
                # retained log replays into a standard overflow lane (grow)
                # or quarantine — staged lane rows ride the log, so nothing
                # is lost.  Re-promotion is the supervisor's call.
                self.seg_lanes.pop(d)
                if mk.is_capacity_error(bits):
                    self._recover_doc(d, bits, growths=0)
                else:
                    self._quarantine_doc(d, f"error bits {bits:#x} (seg lane)")
                recovered.append(d)
        if recovered:
            # One structured health event per recovery action (no-op
            # without a telemetry logger).
            self.counters.emit(recovered_docs=len(recovered))
        return recovered

    def _recover_doc(self, d: int, bits: int, growths: int) -> None:
        # Recovery works on the parsed-message log: fold a native doc's raw
        # lines in first (ordering: they precede any object-path appends).
        self._normalize_native(self.hosts[d])
        h = self.hosts[d]
        geom = dict(
            self.overflow[d].geometry if d in self.overflow else self.geometry
        )
        while self.recovery == "grow" and growths < self.max_growths:
            growths += 1
            geom = self._grown_geometry(geom, bits)
            if h.base_summary is not None:
                # The replay base must fit before a single op applies.
                geom = self._fit_geometry(
                    geom, h.base_summary, len(h.prop_slot)
                )
            state = self._replay(h, geom)
            new_bits = int(state.error)
            if new_bits == 0:
                self.overflow[d] = self._make_lane(state, geom, growths)
                self.counters.bump("capacity_recoveries")
                return
            bits = new_bits
            if mk.is_poison_error(bits):
                # POS_RANGE that survives replay at grown capacity is not a
                # cascade: the op stream itself is malformed.  Isolate the
                # document instead of killing the fleet.
                self._quarantine_doc(
                    d, f"error bits {bits:#x} during replay at {geom}"
                )
                return
        # Growth exhausted (or policy is oracle): host replica takes over.
        self.overflow.pop(d, None)
        tree = self._oracle_from_base(h)
        for msg in h.log:
            self._oracle_apply(tree, h, msg)
        tree.update_min_seq(h.min_seq)
        self.oracles[d] = tree
        self.counters.bump("oracle_routes")

    @staticmethod
    def _grown_geometry(base: dict[str, int], bits: int) -> dict[str, int]:
        geom = dict(base)
        if bits & mk.ERR_SEG_OVERFLOW:
            geom["max_segments"] *= 2
        if bits & mk.ERR_TEXT_OVERFLOW:
            geom["text_capacity"] *= 2
        if bits & mk.ERR_REM_OVERFLOW:
            geom["remove_slots"] *= 2
        if bits & mk.ERR_OB_OVERFLOW:
            geom["ob_slots"] *= 2
        return geom

    @staticmethod
    def _fit_geometry(
        geom: dict[str, int], summary: dict, min_prop_slots: int = 0
    ) -> dict[str, int]:
        """Grow ``geom`` (doubling, preserving the pow2 ladder) until the
        checkpoint summary fits — a replay base must never itself overflow.
        ``min_prop_slots`` covers slots the doc's restored prop table
        already interned (slot indices, not just distinct summary props)."""
        geom = dict(geom)
        n_seg = len(summary["segments"])
        n_text = sum(len(e["text"]) for e in summary["segments"])
        n_rem = max(
            (len(e["removes"]) for e in summary["segments"]), default=0
        )
        n_ob = len(summary.get("obliterates", []))
        while geom["max_segments"] < n_seg:
            geom["max_segments"] *= 2
        while geom["text_capacity"] < n_text:
            geom["text_capacity"] *= 2
        while geom["remove_slots"] < n_rem:
            geom["remove_slots"] *= 2
        while geom["ob_slots"] < n_ob:
            geom["ob_slots"] *= 2
        while geom["prop_slots"] < min_prop_slots:
            geom["prop_slots"] *= 2
        return geom

    def _replay(self, h: _DocHost, geom: dict[str, int]) -> mk.DocState:
        """Re-apply the retained wire log on a state with ``geom`` — from
        the checkpoint base when one exists (bounded replay), from scratch
        otherwise."""
        if h.base_summary is not None:
            state = kb.summary_to_state(
                h.base_summary, geom, lambda p: self._prop_slot_for_geom(h, p, geom)
            )
        else:
            state = mk.init_state(
                geom["max_segments"], geom["remove_slots"], geom["prop_slots"],
                geom["text_capacity"], geom["ob_slots"],
            )
        B = self.ops_per_step
        rows: list[tuple[np.ndarray, np.ndarray]] = []
        for msg in h.log:
            rows.extend(self._encode(h, msg))
        self.counters.gauge("recovery_replay_len", len(h.log))
        for i in range(0, len(rows), B):
            chunk = rows[i : i + B]
            ops = np.zeros((B, mk.OP_FIELDS), np.int32)
            payloads = np.zeros((B, self.max_insert_len), np.int32)
            ops[: len(chunk)] = [op for op, _ in chunk]
            payloads[: len(chunk)] = [payload for _, payload in chunk]
            state = self._lane_apply(
                state, jnp.asarray(ops), jnp.asarray(payloads)
            )
        return state

    def _prop_slot_for_geom(self, h: _DocHost, prop: int, geom: dict) -> int:
        """Intern a checkpointed property id during a replay-base restore
        (same table as live encoding; range-checked against ``geom``)."""
        if prop not in h.prop_slot:
            slot = len(h.prop_slot)
            if slot >= geom["prop_slots"]:
                raise ValueError(
                    f"checkpoint needs more than {geom['prop_slots']} prop slots"
                )
            h.prop_slot[prop] = slot
        return h.prop_slot[prop]

    # ------------------------------------------------------------- quarantine
    def _oracle_from_base(self, h: _DocHost) -> RefMergeTree:
        """A host oracle seeded with the doc's checkpoint base (or empty)."""
        tree = RefMergeTree()
        if h.base_summary is not None:
            tree.import_summary(h.base_summary)
        return tree

    def _oracle_apply_validated(
        self, tree: RefMergeTree, h: _DocHost, msg: SequencedMessage
    ) -> bool:
        """Apply one wire op to a quarantine oracle with a validation gate:
        positions must resolve inside the op's own perspective and the
        sender must be in the quorum.  A malformed op is dropped and
        counted — it can corrupt neither this replica nor the batch."""
        try:
            c = msg.contents
            client = h.quorum[msg.client_id]  # KeyError: unknown sender
            n = tree.visible_length(msg.ref_seq, client)
            kind = c["type"]
            if kind == DeltaType.INSERT:
                if not isinstance(c["seg"], str):
                    # Legal-but-unsupported spec shapes fail LOUD (see
                    # _encode) — they are a feature gap, not poison.
                    raise NotImplementedError(
                        f"unsupported seg spec {type(c['seg']).__name__}"
                    )
                if not (0 <= c["pos1"] <= n):
                    raise ValueError(f"insert pos {c['pos1']} > length {n}")
            elif kind in (DeltaType.REMOVE, DeltaType.ANNOTATE):
                if not (0 <= c["pos1"] < c["pos2"] <= n):
                    raise ValueError(
                        f"range [{c['pos1']},{c['pos2']}) outside length {n}"
                    )
            elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
                p1, s1, p2, s2 = decode_obliterate_places(c)
                from ..dds.shared_string import validate_obliterate_places

                validate_obliterate_places(p1, s1, p2, s2, n)
            self._oracle_apply(tree, h, msg)
            return True
        except NotImplementedError:
            raise  # feature gap, not poison: stay loud
        except Exception as e:  # noqa: BLE001 — the gate IS the handler
            self.counters.bump("poison_ops_dropped")
            if self.counters.logger is not None:
                self.counters.logger.error(
                    "poison_op_dropped", e, seq=msg.seq
                )
            return False

    def _quarantine_doc(self, d: int, reason: str) -> None:
        """Evict one doc from the device batch into the validated host
        oracle lane: checkpoint base + validated replay of the retained
        tail (malformed ops drop).  The rest of the batch is untouched."""
        h = self.hosts[d]
        self._normalize_native(h)
        tree = self._oracle_from_base(h)
        self.counters.gauge("quarantine_replay_len", len(h.log))
        for msg in h.log:
            self._oracle_apply_validated(tree, h, msg)
        tree.update_min_seq(h.min_seq)
        self.overflow.pop(d, None)
        self.seg_lanes.pop(d, None)
        flaps = self._flaps[d] = self._flaps.get(d, 0) + 1
        if self.poison_budget and flaps > self.poison_budget:
            # Flapping: the doc keeps getting re-poisoned after clean
            # readmissions.  Spend no more recovery work on it — route it
            # to the oracle lane permanently (still serviceable, never
            # auto-readmitted).
            self.quarantine.pop(d, None)
            self.quarantine_reason.pop(d, None)
            self._readmit_due.pop(d, None)
            self._readmit_interval.pop(d, None)
            self.oracles[d] = tree
            self.counters.bump("poison_routed_docs")
            if self.counters.logger is not None:
                self.counters.logger.error(
                    "doc_poison_routed", reason, doc=self.doc_keys[d],
                    flaps=flaps,
                )
        else:
            self.quarantine[d] = tree
            self.quarantine_reason[d] = reason
            if self.readmit_after_steps:
                # Exponential backoff: 1 flap -> base, 2 -> 2x, 3 -> 4x...
                interval = self.readmit_after_steps << min(flaps - 1, 16)
                self._readmit_interval[d] = interval
                self._readmit_due[d] = self._step_count + interval
        h.queue.clear()
        self._busy.discard(d)
        if d < self.n_docs:
            slot = int(self._slot[d])
            self.state = self.state._replace(
                error=self.state.error.at[slot].set(0)
            )
        self.counters.bump("quarantines")
        if self.counters.logger is not None:
            self.counters.logger.error(
                "doc_quarantined", reason, doc=self.doc_keys[d]
            )

    def readmit(self, d: int) -> bool:
        """Re-admit a quarantined doc to the lockstep batch: pack the
        oracle's (clean, validated) state back into the batch geometry and
        scatter it into the doc's row.  Returns False — doc stays
        quarantined — when the state no longer fits the batch geometry."""
        tree = self.quarantine.get(d)
        if tree is None:
            return False
        h = self.hosts[d]
        summary = tree.export_summary()
        try:
            row = kb.summary_to_state(
                summary, self.geometry,
                lambda p: self._prop_slot_for_geom(h, p, self.geometry),
            )
        except (ValueError, IndexError):
            return False
        slot = int(self._slot[d])
        self.state = jax.tree.map(
            lambda x, s: x.at[slot].set(s), self.state, row
        )
        del self.quarantine[d]
        self.quarantine_reason.pop(d, None)
        self._readmit_due.pop(d, None)
        self._readmit_interval.pop(d, None)
        # The scattered row is fresh device truth: invalidate the verified
        # digest so the watchdog re-verifies it on the next sweep.
        self._verified_digest.pop(d, None)
        # The oracle state becomes the doc's new replay base: the dropped
        # poison ops are gone from both the state and the log.
        h.base_summary = summary
        h.base_seq = max(h.base_seq, h.last_seq)
        h.log = [m for m in h.log if m.seq > h.base_seq]
        self.counters.bump("readmissions")
        return True

    # ---------------------------------------------------- placement/migration
    def shard_of(self, doc_idx: int) -> int:
        """The mesh shard currently hosting this doc's device row."""
        return self.placement_plane.shard_of(doc_idx)

    def placement(self) -> dict[str, int]:
        """doc key -> mesh shard: the summary-ownership alignment surface
        (server.partition_manager.ScribePool.align_to_placement)."""
        return self.placement_plane.placement(self.doc_keys)

    def shard_load(self) -> tuple[np.ndarray, np.ndarray]:
        """Per-shard (applied ops since the last ``hot_shards`` reset,
        currently queued ops) — see placement.shard_load."""
        return placement.shard_load(self)

    def hot_shards(
        self, factor: float = 2.0, reset: bool = False, load=None
    ) -> list[int]:
        """Shards whose load (applied + queued ops) exceeds ``factor`` x
        the fleet mean — see placement.hot_shards."""
        return placement.hot_shards(self, factor, reset, load)

    def free_slots(self, shard: int) -> int:
        return self.placement_plane.free_slots(shard)

    def migrate_doc(self, d: int, dst_shard: int) -> bool:
        # ckpt_lock: migration mutates self.state/self._slot, which the
        # background checkpoint sweep reads (bulk host transfer + per-doc
        # slot slicing) — an unlocked scatter mid-sweep could checkpoint
        # a torn or vacated row as the doc's durable record.
        with self.ckpt_lock:
            return self._migrate_doc_locked(d, dst_shard)

    def _migrate_doc_locked(self, d: int, dst_shard: int) -> bool:
        """Live doc migration between mesh shards (hot-shard rebalancing).

        The handoff is checkpoint + summary adoption — the same primitives
        the recovery and scribe paths trust: the doc's device row exports
        through ``kb.state_to_summary`` (the checkpoint codec), re-packs at
        the batch geometry with ``kb.summary_to_state``, and scatters into
        a free slot on the destination shard; the vacated slot retires to
        the pristine proto row.  Observable state (text, annotations,
        obliterate table, exported summary) is byte-identical before and
        after.  Host-side queues, retained logs, and checkpoint floors
        travel with the doc untouched — a doc may migrate MID-STREAM with
        staged ops pending; they simply apply at the new slot on the next
        step.  Raises ``placement.PlacementError`` for a doc pinned to a
        parallel lane (segment-sharded or overflow: its serving state
        lives outside the fleet slot, so a silent slot handoff would
        strand it — drain or demote first).  Returns False (doc stays
        put) when the doc is oracle/quarantine-routed, already on
        ``dst_shard``, poisoned, or the destination has no free slot.
        """
        plane = self.placement_plane
        plane.validate(d, dst_shard)
        plane.require_migratable(
            d,
            "segment" if d in self.seg_lanes
            else "overflow" if d in self.overflow else None,
        )
        if d in self.oracles or d in self.quarantine:
            return False
        reservation = plane.reserve(d, dst_shard)
        if reservation is None:
            return False
        src_slot, dst_slot = reservation
        src_shard = src_slot // self.docs_per_shard
        h = self.hosts[d]
        row = jax.tree.map(lambda x: np.asarray(x[src_slot]), self.state)
        if int(row.error):
            plane.release(dst_slot)
            return False  # recover first; never migrate a latched row
        self._sync_native_props(h)
        summary = kb.state_to_summary(
            row, {v: k for k, v in h.prop_slot.items()}
        )
        try:
            new_row = kb.summary_to_state(
                summary, self.geometry,
                lambda p: self._prop_slot_for_geom(h, p, self.geometry),
            )
        except (ValueError, IndexError):
            plane.release(dst_slot)
            return False  # does not re-pack at batch geometry: stay put
        self.state = jax.tree.map(
            lambda x, s: x.at[dst_slot].set(s), self.state, new_row
        )
        self.state = jax.tree.map(
            lambda x, s: x.at[src_slot].set(s), self.state, self._proto
        )
        plane.commit(d, src_slot, dst_slot)
        # Fresh row content (text pool re-packed): the watchdog must
        # re-verify before the pre-filter may skip this doc again.
        self._verified_digest.pop(d, None)
        self.counters.bump("doc_migrations")
        instant(
            "migrate_doc", doc=self.doc_keys[d], src=src_shard, dst=dst_shard
        )
        return True

    def rebalance_hot_shards(
        self, factor: float = 2.0, max_moves: int = 1
    ) -> list[tuple[int, int, int]]:
        """Detect hot shards and live-migrate their deepest-queued docs to
        the coldest shards with free slots (one checkpoint + summary-
        adoption handoff per move — ``migrate_doc``).  Returns the
        ``(doc, src_shard, dst_shard)`` moves made; callers re-align the
        scribe pool afterwards (``ScribePool.align_to_placement``) so
        summary ownership follows the docs.  A shard hot because of ONE doc
        whose own queue exceeds the fleet mean cannot be rebalanced by
        placement; with a segs axis available that doc is promoted to the
        segment-parallel path instead and appears in the result with
        ``dst_shard == -1`` (its placement slot stays reserved).  The
        detection + move-selection skeleton is the shared plane's
        (placement.rebalance_hot_shards — the tree fleet rides the same
        one); the segment-parallel promotion of hot DOCUMENTS is this
        engine's hook into it."""
        return placement.rebalance_hot_shards(
            self, self.placement_plane, factor, max_moves,
            in_lane=self._in_lane,
            promote_hot_doc=(
                self.enable_segment_sharding if self.seg_shards > 1
                else None
            ),
        )

    def _sync_native_props(self, h: _DocHost) -> None:
        """Fold the native encoder's C++ prop-interning table into the host
        table, so checkpoints and migrations of native-mode docs carry REAL
        property ids instead of private kernel slot numbers (ROADMAP:
        native-path checkpoint fidelity).  No-op for object-path docs and
        for native builds without the export; safe to call repeatedly —
        both tables intern in first-seen stream order, so entries agree."""
        if h.native is None:
            return
        for prop, slot in h.native.prop_table().items():
            cur = h.prop_slot.setdefault(prop, slot)
            if cur != slot:
                raise RuntimeError(
                    f"native/host prop table skew: id {prop} -> {slot} vs {cur}"
                )

    # --------------------------------------------------------------- watchdog
    def watchdog(self, sample: int | None = None) -> list[int]:
        """Cross-check a rotating sample of batch docs against a host-oracle
        replay of checkpoint + tail; quarantine (oracle wins) on mismatch.
        Returns the doc indices that failed the check."""
        if self.recovery == "off":
            return []
        eligible = [
            d for d in range(self.n_docs)
            if not (
                d in self.overflow or d in self.oracles or d in self.quarantine
            )
            and not (d in self.seg_lanes and self.seg_lanes[d].queue)
            and self.hosts[d].mode == "obj"
            and not self.hosts[d].queue
        ]
        if eligible:
            # Device-digest pre-filter: one [D] device reduction per sweep
            # (NOT per step — it blocks on a device->host transfer).  A doc
            # whose digest AND ingested seq both match its last PASSED
            # check cannot have diverged since — skip its host-oracle
            # replay entirely (counted).
            self._digests = np.asarray(_fleet_digest(self.state))
            drifted = []
            for d in eligible:
                if d in self.seg_lanes:
                    # A segment lane's state lives off the batch rows, so
                    # the slot digest is pristine-stale; the lane's host-
                    # side version stamp (bumped at every dispatch/
                    # rebalance/compact) vouches instead — without it every
                    # sweep would oracle-replay exactly the fleet's
                    # longest-log docs.
                    mark = (
                        "seg", self.seg_lanes[d].version,
                        self.hosts[d].last_seq,
                    )
                else:
                    mark = (
                        int(self._digests[int(self._slot[d])]),
                        self.hosts[d].last_seq,
                    )
                if self._verified_digest.get(d) == mark:
                    self.counters.bump("watchdog_prefiltered")
                else:
                    drifted.append(d)
            eligible = drifted
        if not eligible:
            return []
        k = sample if sample is not None else self.watchdog_sample
        start = self._watchdog_cursor
        picks = [eligible[(start + i) % len(eligible)] for i in range(min(k, len(eligible)))]
        self._watchdog_cursor = (start + len(picks)) % max(len(eligible), 1)
        failed: list[int] = []
        for d in picks:
            h = self.hosts[d]
            try:
                tree = self._oracle_from_base(h)
                for msg in h.log:
                    self._oracle_apply(tree, h, msg)
                expected = tree.visible_text()
            except Exception:
                # The oracle replay itself failing means the log carries an
                # op the strict host path rejects — that is the quarantine
                # lane's job, not the watchdog's verdict to fake.
                self._quarantine_doc(d, "watchdog: oracle replay failed")
                failed.append(d)
                continue
            self.counters.bump("watchdog_checks")
            if mk.visible_text(self.doc_state(d)) != expected:
                self.counters.bump("watchdog_mismatches")
                self._quarantine_doc(d, "watchdog: device/oracle divergence")
                failed.append(d)
            elif d in self.seg_lanes:
                # Passed: pin the lane's host-side change mark so the next
                # sweep skips this doc until a dispatch/rebalance/compact
                # moves its state or the stream advances.
                self._verified_digest[d] = (
                    "seg", self.seg_lanes[d].version,
                    self.hosts[d].last_seq,
                )
            elif self._digests is not None:
                # Passed: pin (digest, seq) so the pre-filter can skip this
                # doc until its device state or ingested stream moves.
                self._verified_digest[d] = (
                    int(self._digests[int(self._slot[d])]),
                    self.hosts[d].last_seq,
                )
        return failed

    # ------------------------------------------------------------- checkpoint
    def maybe_checkpoint(self, force: bool = False, docs=None) -> list[int]:
        """Write durable checkpoint records for docs whose op count since
        the last checkpoint reached ``checkpoint_every`` (all dirty docs
        when ``force``), then truncate their replay logs to the tail.
        ``docs`` restricts the sweep to an explicit due list (the
        bounded-staleness writer's candidates) — those checkpoint whenever
        dirty, regardless of cadence.  Takes ``ckpt_lock`` for the record
        build only; callers must NOT hold it across this call (step()
        invokes it after its serving hold releases).  Returns the doc
        indices checkpointed."""
        if self.checkpoint_store is None:
            return []
        if docs is None and not force and self.checkpoint_every <= 0:
            return []
        with self.ckpt_lock:
            out, pending = self._checkpoint_sweep(force, docs)
        # Durable writes (one fsync per record) land OUTSIDE ckpt_lock —
        # for every caller: the background writer's sweeps and, since the
        # step() call site moved below its lock hold, the serving
        # thread's own cadence checkpoints too (fftpu-check
        # blocking-under-lock enforces this: ckpt_lock denies fsync).
        write_checkpoint_records(self, pending, "batch")
        return out

    def checkpoint_stale(
        self, max_ops_behind: int = 0, max_seconds_behind: float = 0.0
    ) -> list[int]:
        """Bounded-staleness delta sweep: checkpoint every dirty doc whose
        durable record is ``max_ops_behind`` applied ops or
        ``max_seconds_behind`` seconds behind the live stream (0 disables
        that bound).  Safe from a background thread — the record BUILD
        runs under ``ckpt_lock`` so it only ever observes op boundaries;
        the durable writes land after release so the sweep's fsyncs never
        stall the serving thread.  Returns the doc indices checkpointed."""
        if self.checkpoint_store is None or not (
            max_ops_behind or max_seconds_behind
        ):
            return []
        now = time.monotonic()
        with self.ckpt_lock:
            due = stale_due_docs(
                self.hosts, self.n_docs, max_ops_behind,
                max_seconds_behind, now,
            )
            if not due:
                return []
            with span("checkpoint_sweep", docs=len(due)):
                out, pending = self._checkpoint_sweep(force=False, docs=due)
            if out:
                self.counters.bump("stale_checkpoints_written", len(out))
        write_checkpoint_records(self, pending, "batch")
        return out

    def _checkpoint_sweep(
        self, force: bool, docs
    ) -> tuple[list[int], list[tuple[int, int, dict]]]:
        """Build-and-account half of a checkpoint sweep (under
        ``ckpt_lock``); the returned ``pending`` records go to
        ``_write_checkpoint_records`` after release."""
        candidates = range(self.n_docs) if docs is None else docs
        due = [
            d for d in candidates
            if self.hosts[d].ops_since_ckpt > 0
            and (
                force or docs is not None
                or self.hosts[d].ops_since_ckpt >= self.checkpoint_every
            )
        ]
        if not due:
            return [], []  # host-side check only: no device readback paid
        out: list[int] = []
        pending: list[tuple[int, int, dict]] = []
        # ONE bulk device->host transfer covers every due batch doc (the
        # per-doc summary walk below then slices host arrays; per-doc
        # device_get would serialize ~25 tiny transfers per doc against
        # the step pipeline).
        host_state = (
            jax.tree.map(np.asarray, self.state)
            if any(
                d not in self.quarantine
                and d not in self.oracles
                and d not in self.overflow
                and d not in self.seg_lanes
                for d in due
            )
            else None
        )
        err = np.asarray(host_state.error) if host_state is not None else None
        for d in due:
            h = self.hosts[d]
            if (
                h.queue
                or (d in self.overflow and self.overflow[d].queue)
                or (d in self.seg_lanes and self.seg_lanes[d].queue)
            ):
                continue  # staged-but-unapplied ops: state is mid-step
            lane = "batch"
            geometry = None
            if d in self.seg_lanes:
                # A segment lane checkpoints through the same summary codec
                # as everything else (gather the live prefixes first).  The
                # record restores as a batch row — or the fitted-overflow
                # path when it outgrew the batch geometry — and the
                # supervisor re-promotes if the doc is still hot.
                ln = self.seg_lanes[d]
                seg_host = jax.tree.map(np.asarray, ln.state)
                if int(seg_host.error):
                    continue  # never checkpoint a latched lane
                self._sync_native_props(h)
                summary = kb.state_to_summary(
                    mk.seg_gather_state(seg_host),
                    {v: k for k, v in h.prop_slot.items()},
                )
            elif d in self.quarantine:
                lane = "quarantine"
                summary = self.quarantine[d].export_summary()
            elif d in self.oracles:
                lane = "oracle"
                summary = self.oracles[d].export_summary()
            elif d in self.overflow:
                lane = "overflow"
                ln = self.overflow[d]
                if int(ln.state.error):
                    continue
                geometry = ln.geometry
                growths = ln.growths
                summary = kb.state_to_summary(
                    ln.state, {v: k for k, v in h.prop_slot.items()}
                )
            else:
                slot = int(self._slot[d])
                if err[slot]:
                    continue  # never checkpoint a poisoned row
                self._sync_native_props(h)
                summary = kb.state_to_summary(
                    jax.tree.map(lambda x, _s=slot: x[_s], host_state),
                    {v: k for k, v in h.prop_slot.items()},
                )
            record = {
                "engine": "doc_batch",
                "lane": lane,
                "summary": summary,
                "quorum": h.quorum,
                "prop_slot": {str(k): v for k, v in h.prop_slot.items()},
                "min_seq": h.min_seq,
                "mode": h.mode,
            }
            if geometry is not None:
                record["geometry"] = geometry
                record["growths"] = growths
            pending.append((d, h.last_seq, record))
            h.base_seq = h.last_seq
            h.base_summary = summary
            h.log = [m for m in h.log if m.seq > h.base_seq]
            if h.raw_log:
                h.raw_log = self._truncate_raw_log(h.raw_log, h.base_seq)
            h.ops_since_ckpt = 0
            h.dirty_since = 0.0
            h.boot_counting = False  # a new durable floor ends the boot phase
            self.counters.bump("checkpoints_written")
            out.append(d)
        return out, pending

    @staticmethod
    def _truncate_raw_log(raw_log: list[bytes], base_seq: int) -> list[bytes]:
        """Drop raw wire OP lines already covered by the checkpoint.  JOIN
        lines are retained regardless of seq: a later recovery replay
        rebuilds the quorum from them (_normalize_native), and a native
        doc's checkpoint record carries no parsed quorum to fall back on."""
        kept: list[bytes] = []
        for chunk in raw_log:
            lines = []
            for line in chunk.split(b"\n"):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                    if (
                        rec.get("type") == MessageType.JOIN
                        or int(rec.get("sequenceNumber", 0)) > base_seq
                    ):
                        lines.append(line)
                except ValueError:
                    lines.append(line)
            if lines:
                kept.append(b"\n".join(lines) + b"\n")
        return kept

    def note_incident(self, started_at: float) -> None:
        """Back-date the current recovery incident to the supervisor's
        kill timestamp (``time.monotonic`` domain): the recovery histogram
        then measures kill -> first post-restore op applied, not merely
        restore -> applied."""
        self.recovery_tracker.begin(started_at)

    def restore_from_checkpoints(
        self,
        store=None,
        parallel: bool = True,
        max_workers: int | None = None,
        refresh: bool = False,
    ) -> list[int]:
        """Engine restart path: load each doc's durable checkpoint record,
        rebuild its state (batch row, overflow lane, or oracle/quarantine
        replica), and set the seq floor so the upstream replay of ops the
        checkpoint already covers is skipped.  Returns restored doc
        indices.

        ``parallel`` (default) is the batched fast path: all records load
        concurrently (thread pool over the store's ``load_many``) and
        every batch-lane doc seeds through ONE stacked host build + ONE
        scatter dispatch instead of a per-doc device round-trip.
        ``parallel=False`` is the sequential oracle — per-doc load,
        per-doc scatter, the original restore loop — kept byte-identical
        by contract (fuzzed in tests/test_recovery_plane.py).

        ``refresh`` is the warm-standby trailing mode: docs already
        restored RE-adopt a record strictly newer than their current seq
        floor (first-source-wins still holds for live serving — refresh
        refuses any doc with staged work).  A trailing standby calls this
        on a cadence so promotion starts from the freshest durable state.
        """
        store = store if store is not None else self.checkpoint_store
        if store is None:
            return []
        with self.ckpt_lock:
            return self._restore(store, parallel, max_workers, refresh)

    def _restore(self, store, parallel, max_workers, refresh) -> list[int]:
        t_start = time.monotonic()
        with span("restore_scan", docs=self.n_docs):
            # First-boot vs trailing/re-seed candidate selection is the
            # shared plane's (placement.restore_candidates): first source
            # wins for live serving, trailing never races staged work,
            # unchanged record files skip on one mtime stat per doc.
            candidates, cand_mtime = placement.restore_candidates(
                self, store, refresh, self._queue_depth
            )
        if not candidates:
            return []
        records = load_checkpoint_records(
            store, [self.doc_keys[d] for d in candidates],
            parallel=parallel, max_workers=max_workers,
        )
        restored: list[int] = []
        # Batch-lane rows collected host-side for the single scatter
        # (parallel path); the sequential oracle scatters per doc instead.
        batch_rows: list[tuple[int, object]] = []
        with span("restore_build", records=len(records)):
            for i, d in enumerate(candidates):
                rec = records.get(i)
                if rec is not None and d in cand_mtime:
                    # Load succeeded: this record content is now seen —
                    # future trails skip it until the file changes.
                    self._trail_mtime[d] = cand_mtime[d]
                if rec is None or rec.get("engine") != "doc_batch":
                    continue
                h = self.hosts[d]
                if refresh and h.restored:
                    if int(rec["seq"]) <= h.last_seq:
                        continue  # nothing newer to adopt
                    self.counters.bump("checkpoint_refreshes")
                if refresh:
                    self._drop_restored_identity(d)
                h.quorum = dict(rec.get("quorum", {}))
                h.prop_slot = {
                    int(k): v for k, v in rec.get("prop_slot", {}).items()
                }
                h.min_seq = rec.get("min_seq", 0)
                h.base_seq = h.last_seq = int(rec["seq"])
                h.base_summary = rec["summary"]
                # Restored docs consume parsed messages (the object path):
                # the native encoder cannot skip already-checkpointed seqs.
                h.mode = "obj"
                h.restored = True
                h.boot_counting = True
                lane = rec.get("lane", "batch")
                if lane in ("oracle", "quarantine"):
                    tree = RefMergeTree()
                    tree.import_summary(rec["summary"])
                    tree.update_min_seq(h.min_seq)
                    if lane == "oracle":
                        self.oracles[d] = tree
                    else:
                        self.quarantine[d] = tree
                        self.quarantine_reason[d] = "restored"
                        if self.readmit_after_steps:
                            # A restart must not strand the doc in
                            # quarantine when auto-readmission is the
                            # configured policy: schedule it like a first
                            # flap.
                            self._flaps.setdefault(d, 1)
                            self._readmit_interval[d] = (
                                self.readmit_after_steps
                            )
                            self._readmit_due[d] = (
                                self._step_count + self.readmit_after_steps
                            )
                elif lane == "overflow":
                    geom = {k: int(v) for k, v in rec["geometry"].items()}
                    state = kb.summary_to_state(
                        rec["summary"], geom,
                        lambda p, _h=h, _g=geom: self._prop_slot_for_geom(
                            _h, p, _g
                        ),
                    )
                    self.overflow[d] = self._make_lane(
                        state, geom, int(rec.get("growths", 1))
                    )
                else:
                    try:
                        row = kb.summary_to_state_host(
                            rec["summary"], self.geometry,
                            lambda p, _h=h: self._prop_slot_for_geom(
                                _h, p, self.geometry
                            ),
                        )
                    except (ValueError, IndexError):
                        # The checkpoint outgrew the batch geometry (a
                        # restart with smaller capacity — including fewer
                        # prop slots than the restored prop table):
                        # restore into an overflow lane at a fitted
                        # geometry.
                        geom = self._fit_geometry(
                            self.geometry, rec["summary"], len(h.prop_slot)
                        )
                        state = kb.summary_to_state(
                            rec["summary"], geom,
                            lambda p, _h=h, _g=geom: self._prop_slot_for_geom(
                                _h, p, _g
                            ),
                        )
                        self.overflow[d] = self._make_lane(state, geom, 1)
                    else:
                        slot = int(self._slot[d])
                        if parallel:
                            batch_rows.append((slot, row))
                        else:
                            self.state = jax.tree.map(
                                lambda x, s, _s=slot: x.at[_s].set(
                                    jnp.asarray(s)
                                ),
                                self.state, row,
                            )
                restored.append(d)
                self.counters.bump("docs_restored")
        if batch_rows:
            # ONE stacked transfer + ONE donated scatter dispatch seeds
            # every batch-lane doc (pow2-padded like the cohort scatter,
            # so the executable ladder stays log2(fleet) deep; pad lanes
            # route out of bounds via mode="drop").
            with span("restore_scatter", rows=len(batch_rows)):
                n = len(batch_rows)
                nc = 1 << (n - 1).bit_length()
                idx = np.full((nc,), batch_rows[-1][0], np.int32)
                idx[:n] = [s for s, _ in batch_rows]
                valid = np.zeros((nc,), bool)
                valid[:n] = True
                rows = [r for _, r in batch_rows]
                rows += [batch_rows[-1][1]] * (nc - n)
                stacked = jax.tree.map(
                    lambda *xs: jnp.asarray(np.stack(xs)), *rows
                )
                self.state = self._scatter_cohort(
                    self.state, stacked, jnp.asarray(idx), jnp.asarray(valid)
                )
        if restored and not refresh:
            # A real restore (not standby trailing) opens a recovery
            # incident: the clock runs until the first post-restore op
            # applies on device.  note_incident() back-dates it to the
            # supervisor's kill time when one is known.
            self.recovery_tracker.begin(t_start)
        return restored

    def adopt_boot_snapshot(
        self, doc_idx: int, record: dict
    ) -> placement.AdoptResult:
        """Client half of the fan-out plane's ``{"t":"resync","boot":true}``
        contract (the shared orchestration — placement.adopt_boot_snapshot —
        riding this engine's refresh re-seed path): a consumer that fell
        off the retained log re-seeds the document from a historian
        snapshot record (the scribe summary schema, ``engine: doc_batch``)
        and re-consumes from the returned floor; lanes, quorum, prop
        tables and the replay floor all reset consistently."""
        return placement.adopt_boot_snapshot(
            self, doc_idx, record, self._clear_staged
        )

    def _clear_staged(self, doc_idx: int) -> None:
        """Drop a doc's staged pre-gap work ahead of a boot-snapshot
        adoption: the refresh guard refuses docs with pending ops
        (trailing must not race serving), but a boot resync REPLACES the
        doc — pre-gap rows are covered by the snapshot."""
        self.hosts[doc_idx].queue.clear()
        for lane in (self.overflow.get(doc_idx),
                     self.seg_lanes.get(doc_idx)):
            if lane is not None:
                lane.queue.clear()
        self._busy.discard(doc_idx)

    def _drop_restored_identity(self, d: int) -> None:
        """Forget a doc's prior adoption before a refresh re-seed (warm-
        standby trailing only: the doc has no staged work by contract)."""
        self.overflow.pop(d, None)
        self.oracles.pop(d, None)
        self.quarantine.pop(d, None)
        self.quarantine_reason.pop(d, None)
        self.seg_lanes.pop(d, None)
        self._readmit_due.pop(d, None)
        self._readmit_interval.pop(d, None)
        self._verified_digest.pop(d, None)
        h = self.hosts[d]
        h.log.clear()
        h.raw_log.clear()
        h.queue.clear()
        self._busy.discard(d)

    def warmup(self) -> int:
        """Pre-compile the fleet's serving programs (warm-standby boot):
        dispatch all-NOOP megasteps at every pow2 depth up to
        ``megastep_k`` plus one compact through the exact serving entry
        points, so a promoted standby pays ZERO XLA compiles on its first
        real dispatch.  NOOP slices are identity by kernel contract, so
        state bytes are untouched.  Cohort-bucketed executables (mesh-less
        Zipf tails) still compile on first use — they are per-cohort-size
        and cheap relative to the fleet programs.  Returns the number of
        warmup dispatches run."""
        warmed = 0
        with self.ckpt_lock, span("warmup", k_max=self.megastep_k):
            stage = self._staging()
            if self.mesh is None:
                # The K=1 mesh-less fast path dispatches _step directly.
                ops, payloads = stage.acquire(1, self.capacity)
                dev_ops, dev_payloads = stage.upload(ops[0], payloads[0])
                self.state = self._step(self.state, dev_ops, dev_payloads)
                warmed += 1
            depths = []
            k = 1
            while k <= self.megastep_k:
                depths.append(k)
                k *= 2
            if self.megastep_k > 1 and self.megastep_k not in depths:
                # _select_k clamps to min(megastep_k, pow2(need)), so a
                # non-pow2 configured K is itself a reachable dispatch
                # shape — skip it here and the first deep-queue dispatch
                # after promotion pays the compile warmup exists to kill.
                depths.append(self.megastep_k)
            for k in depths:
                if self.mesh is not None or k > 1:
                    ops, payloads = stage.acquire(k, self.capacity)
                    dev_ops, dev_payloads = stage.upload(ops, payloads)
                    self.state = self._megastep(
                        self.state, dev_ops, dev_payloads
                    )
                    warmed += 1
            mins = np.zeros((self.capacity,), np.int32)
            for d, h in enumerate(self.hosts):
                mins[self._slot[d]] = h.min_seq
            if self.mesh is not None:
                mins_dev = jax.device_put(mins, self._pm.shard_docs(self.mesh))
            else:
                mins_dev = jnp.asarray(mins)
            self.state = self._compact(self.state, mins_dev)
            warmed += 1
            jax.block_until_ready(self.state)
            # Absorb the warmup compiles into the watchdog count NOW, so
            # they show up as boot-time cache growth rather than landing
            # on the first serving step's poll.
            self.recompile_watchdog.poll()
        self.counters.gauge("warmup_dispatches", warmed)
        return warmed

    # ----------------------------------------------------------------- health
    def health(self) -> dict:
        """Per-engine degraded-mode health counters (bench + fleet status)."""
        ages = [
            h.last_seq - h.base_seq for h in self.hosts if h.last_seq
        ]
        # Megastep pipeline surface: configured depth, realized dispatch
        # amortization, and how often the double buffer actually overlapped
        # a pack with in-flight device work.
        self.counters.gauge("megastep_k", self.megastep_k)
        self.counters.gauge(
            "staging_overlap_packs",
            self._stage.overlapped_packs if self._stage is not None else 0,
        )
        self.counters.gauge(
            "staging_aliased_swaps",
            self._stage.aliased_swaps if self._stage is not None else 0,
        )
        self.counters.ratio(
            "steps_per_dispatch", "megastep_slices", "megastep_dispatches"
        )
        # Flow-control surface (graceful degradation; shared shape with
        # the tree engine via OverloadGate.emit_gauges).
        self.overload_gate.emit_gauges(
            self.counters, self.megastep_k * self.ops_per_step,
            max(
                (
                    self._queue_depth(d)
                    for d in self._busy | set(self.seg_lanes)
                    | set(self.overflow)
                ),
                default=0,
            ),
        )
        # Mesh/placement surface: per-shard load for hot-shard detection
        # (applied since the last hot_shards reset + queued right now).
        self.counters.gauge("n_shards", self.n_shards)
        # 2-D docs x segs surface: the segs-axis width, how many hot docs
        # are segment-sharded right now, and the per-shard live-segment
        # occupancy across all lanes (the rebalance trigger signal).
        # seg_promotions / seg_demotions / seg_rebalances counters ride the
        # snapshot; everything here reaches fleet status and /metrics.
        self.counters.gauge("segment_shards", self.seg_shards)
        self.counters.gauge("segment_sharded_docs", len(self.seg_lanes))
        if self.seg_lanes:
            occ = np.zeros((self.seg_shards,), np.int64)
            for lane in self.seg_lanes.values():
                occ += mk.seg_occupancy(lane.state)
            self.counters.gauge("seg_occupancy", [int(v) for v in occ])
            self.counters.gauge(
                "seg_lane_rebalances",
                sum(lane.rebalances for lane in self.seg_lanes.values()),
            )
        elif self.seg_shards > 1:
            # Gauges persist in the snapshot: zero them once the last lane
            # demotes, or a supervisor alarming on occupancy skew keeps
            # seeing the final promoted-state values forever.
            self.counters.gauge(
                "seg_occupancy", [0] * self.seg_shards
            )
            self.counters.gauge("seg_lane_rebalances", 0)
        if self.n_shards > 1:
            ops, depth = self.shard_load()
            self.counters.gauge("shard_ops", [int(v) for v in ops])
            self.counters.gauge(
                "shard_queue_depth", [int(v) for v in depth]
            )
            self.counters.gauge(
                "hot_shards", self.hot_shards(load=ops + depth)
            )
        # Observability surface: program cache misses (recompiles, warmup
        # included), growth after first specialization (despecializations,
        # the mid-serve alarm), and sampled op e2e latency (sequencer
        # stamp -> applied-on-device), ms percentiles.
        self.counters.gauge("recompiles", self.recompile_watchdog.recompiles)
        self.counters.gauge(
            "despecializations", self.recompile_watchdog.despecializations
        )
        self.counters.gauge("latency_samples", self.op_latency.count)
        if self.op_latency.count:
            self.counters.gauge(
                "latency_p50_ms",
                round(self.op_latency.percentile(0.5) * 1e3, 3),
            )
            self.counters.gauge(
                "latency_p99_ms",
                round(self.op_latency.percentile(0.99) * 1e3, 3),
            )
        if self.n_shards > 1:
            self.counters.gauge(
                "shard_latency_p99_ms",
                [
                    round(h.percentile(0.99) * 1e3, 3) if h.count else 0.0
                    for h in self._shard_latency
                ],
            )
        # Recovery surface: per-incident recovery percentiles plus how far
        # the durable checkpoints trail the live stream right now (the
        # bounded-staleness writer's target signal).
        self.recovery_tracker.emit_gauges(self.counters)
        now = time.monotonic()
        self.counters.gauge(
            "dirty_docs",
            sum(1 for h in self.hosts if h.ops_since_ckpt > 0),
        )
        self.counters.gauge(
            "checkpoint_age_s",
            round(
                max(
                    (now - h.dirty_since for h in self.hosts
                     if h.dirty_since),
                    default=0.0,
                ),
                3,
            ),
        )
        snap = self.counters.snapshot()
        snap.update(
            quarantined_docs=len(self.quarantine),
            overflow_docs=len(self.overflow),
            oracle_docs=len(self.oracles),
            checkpoint_age_seqs=max(ages, default=0),
            retained_log_msgs=sum(len(h.log) for h in self.hosts),
            quarantine_flaps=sum(self._flaps.values()),
            readmits_scheduled=len(self._readmit_due),
        )
        return snap

    # ------------------------------------------------------------------ views
    def doc_state(self, doc_idx: int) -> mk.DocState:
        if doc_idx in self.seg_lanes:
            # Gather the per-shard live prefixes back into the canonical
            # single-doc layout (byte-identical to what the single-lane
            # kernel would hold — the seg path's oracle contract).
            return mk.seg_gather_state(
                jax.tree.map(np.asarray, self.seg_lanes[doc_idx].state)
            )
        if doc_idx in self.overflow:
            return self.overflow[doc_idx].state
        slot = int(self._slot[doc_idx])
        return jax.tree.map(lambda x: x[slot], self.state)

    def text(self, doc_idx: int) -> str:
        if doc_idx in self.quarantine:
            return self.quarantine[doc_idx].visible_text()
        if doc_idx in self.oracles:
            return self.oracles[doc_idx].visible_text()
        return mk.visible_text(self.doc_state(doc_idx))

    def annotations(self, doc_idx: int) -> list[dict[int, int]]:
        if doc_idx in self.quarantine:
            return self.quarantine[doc_idx].annotations()
        if doc_idx in self.oracles:
            return self.oracles[doc_idx].annotations()
        raw = mk.annotations(self.doc_state(doc_idx))
        # Live native-path docs intern props in C++: fold the table in so
        # the view names real prop ids (same sync the checkpoint takes).
        self._sync_native_props(self.hosts[doc_idx])
        inv = {v: k for k, v in self.hosts[doc_idx].prop_slot.items()}
        return [{inv[p]: v for p, v in d.items()} for d in raw]

    def errors(self) -> np.ndarray:
        """Combined per-doc error vector across batch, lanes, and oracles.
        Quarantined docs read 0: they are isolated and serviceable — their
        degraded state surfaces through ``health()``, not as a latched
        error that would fail a convergence sweep."""
        by_slot = np.asarray(self.state.error)
        err = np.zeros((self.capacity,), by_slot.dtype)
        err[: self.n_docs] = by_slot[self._slot]  # doc-indexed view
        for d, lane in self.overflow.items():
            err[d] = int(lane.state.error)
        for d, lane in self.seg_lanes.items():
            err[d] = int(np.asarray(lane.state.error))
        for d in self.oracles:
            err[d] = 0
        for d in self.quarantine:
            err[d] = 0
        return err
