"""Host-side op staging for the megastep pipeline (doc/tree batch engines).

The per-slice dispatch path used to allocate a fresh ``np.zeros`` [D, B]
batch every device step and upload it synchronously with the dispatch.  The
megastep pipeline replaces that with a ring of PREALLOCATED [K, D, B]
staging buffers:

- **Reuse, not reallocation**: buffers are zeroed lazily — only the rows a
  previous megastep actually wrote are cleared before refill (tracked per
  slice), so idle lanes cost nothing and the allocator is out of the hot
  loop entirely.
- **Double buffering**: with ``depth=2`` the engine packs megastep N+1 into
  one buffer while the ``jax.device_put`` + dispatch of megastep N is still
  reading the other.  Before a buffer is reused, the ring blocks on the
  device arrays produced FROM IT (transfer completion only, two megasteps
  stale by then — in the steady state a no-op wait), so the host never
  mutates memory an in-flight upload may still be reading.  This is the
  only sync the pipeline takes between megasteps; full synchronization
  happens solely at the engine's recover()/watchdog/checkpoint boundaries.
- **Zero-copy backends**: on backends where host->device "transfer" is
  zero-copy (CPU jax: ``jnp.asarray(np_arr)`` ALIASES the numpy memory),
  the uploaded device array reads the staging buffer for as long as it
  lives — reuse would mutate the input of an asynchronously executing (or
  even future) dispatch.  ``acquire`` detects this by pointer probe and
  hands the memory over to the device arrays, swapping a fresh buffer
  into the ring slot (``aliased_swaps`` counts these).  That degrades the
  reuse win to exactly the seed's allocate-per-step behavior on CPU while
  keeping the DMA-backed reuse path on real accelerators.
"""

from __future__ import annotations

import numpy as np


class RowQueue:
    """Columnar per-document pending-op queue: one [N, F] op-row array and
    one [N, L] payload array with head/tail cursors, replacing the
    list-of-tiny-arrays queues that made the host feeder touch Python per
    op.  The batched ingest path lands whole wire batches with ONE slice
    copy per document (``extend_block``), and ``_drain_into`` consumes
    with one slice copy per document per slice (``take``) — the host cost
    of a message is amortized over its batch, not paid per op row.

    Growth doubles; a drained prefix is reclaimed by shifting the live
    window down whenever it would save a grow (amortized O(1) per row).
    ``take`` returns views valid until the next append/extend — callers
    copy out immediately (the staging buffers do).
    """

    __slots__ = ("ops", "payloads", "head", "tail")

    def __init__(self, op_fields: int, payload_len: int, capacity: int = 0) -> None:
        self.ops = np.empty((capacity, op_fields), np.int32)
        self.payloads = np.empty((capacity, payload_len), np.int32)
        self.head = 0
        self.tail = 0

    def __len__(self) -> int:
        return self.tail - self.head

    def __bool__(self) -> bool:
        return self.tail > self.head

    def __iter__(self):
        """Iterate pending op rows (diagnostics/tests; not a hot path)."""
        return iter(self.ops[self.head : self.tail])

    def _room(self, n: int) -> None:
        cap = self.ops.shape[0]
        if self.tail + n <= cap:
            return
        live = self.tail - self.head
        if live + n <= cap and self.head >= live + n:
            # Shifting beats growing: reclaim the drained prefix in place.
            self.ops[:live] = self.ops[self.head : self.tail]
            self.payloads[:live] = self.payloads[self.head : self.tail]
        else:
            new_cap = max(16, cap)
            while new_cap < live + n:
                new_cap *= 2
            ops = np.empty((new_cap, self.ops.shape[1]), np.int32)
            pay = np.empty((new_cap, self.payloads.shape[1]), np.int32)
            ops[:live] = self.ops[self.head : self.tail]
            pay[:live] = self.payloads[self.head : self.tail]
            self.ops, self.payloads = ops, pay
        self.head, self.tail = 0, live

    def append(self, op: np.ndarray, payload: np.ndarray) -> None:
        self._room(1)
        self.ops[self.tail] = op
        self.payloads[self.tail] = payload
        self.tail += 1

    def extend_rows(self, rows) -> None:
        """Per-message path: a small list of (op_row, payload_row) pairs."""
        n = len(rows)
        if not n:
            return
        self._room(n)
        t = self.tail
        for op, payload in rows:
            self.ops[t] = op
            self.payloads[t] = payload
            t += 1
        self.tail = t

    def extend_block(self, ops: np.ndarray, payloads: np.ndarray) -> None:
        """Batch path: land [M, F] / [M, L] row blocks as two slice copies."""
        m = ops.shape[0]
        if not m:
            return
        self._room(m)
        self.ops[self.tail : self.tail + m] = ops
        self.payloads[self.tail : self.tail + m] = payloads
        self.tail += m

    def take(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        """Dequeue ``n`` rows as views (copy out before the next append)."""
        h = self.head
        self.head = h + n
        return self.ops[h : h + n], self.payloads[h : h + n]

    def pending(self) -> tuple[np.ndarray, np.ndarray]:
        """Views of everything queued (watermark accounting, tests)."""
        return self.ops[self.head : self.tail], self.payloads[self.head : self.tail]

    def clear(self) -> None:
        self.head = self.tail = 0


class OverloadGate:
    """Per-doc ingest watermark hysteresis (credit-based flow control).

    Both batched engines expose their staged-op pressure through one of
    these: a doc whose RowQueue depth reaches ``high`` (sized in multiples
    of the megastep budget — what one K-slice dispatch can retire) enters
    the paused set, and leaves only when its depth drains to ``low`` — so
    a consumer pausing/resuming per-partition reads at the gate never
    flaps at the boundary.  ``update`` is O(busy + paused) per call and is
    meant to run once per pump, not per message.
    """

    __slots__ = ("high", "low", "paused", "events")

    def __init__(self, high: int, low: int) -> None:
        assert 0 < low < high, (low, high)
        self.high = high
        self.low = low
        self.paused: set[int] = set()
        self.events = 0  # pause transitions (the overload_events counter)

    def update(self, busy, depth_of) -> tuple[list[int], list[int]]:
        """-> (newly paused docs, newly resumed docs).  ``busy`` is the
        candidate set for NEW pauses (a doc over the high watermark is
        necessarily busy); ``depth_of(doc) -> int`` reads queue depth."""
        to_pause = [
            d for d in busy
            if d not in self.paused and depth_of(d) >= self.high
        ]
        for d in to_pause:
            self.paused.add(d)
        self.events += len(to_pause)
        to_resume = [d for d in self.paused if depth_of(d) <= self.low]
        for d in to_resume:
            self.paused.discard(d)
        return to_pause, to_resume

    def watermarks(self, megastep_budget: int) -> dict:
        """The flow-control contract numbers (ingest_watermarks surface
        shared by both engines)."""
        return {
            "megastep_budget": megastep_budget,
            "high": self.high,
            "low": self.low,
        }

    def emit_gauges(self, counters, megastep_budget: int,
                    queue_depth_max: int) -> None:
        """The engines' shared health() surface for graceful degradation:
        is any doc over its watermark, how many, how deep, and how many
        pause transitions the gate has taken over the run."""
        counters.gauge("megastep_budget", megastep_budget)
        counters.gauge("overload", int(bool(self.paused)))
        counters.gauge("overloaded_docs", len(self.paused))
        counters.gauge("overload_events", self.events)
        counters.gauge("queue_depth_max", queue_depth_max)


class _StageBuf:
    __slots__ = ("ops", "payloads", "dirty", "inflight")

    def __init__(self, shape_ops: tuple, shape_payloads: tuple) -> None:
        self.ops = np.zeros(shape_ops, np.int32)
        self.payloads = np.zeros(shape_payloads, np.int32)
        # [(slice k, row-index array)] written since the last reset.
        self.dirty: list[tuple[int, object]] = []
        # Device arrays last uploaded from this buffer (held so the memory
        # they were copied from is provably drained before reuse).
        self.inflight: tuple | None = None


class StagingRing:
    """A depth-N ring of reusable [K, D, B] op/payload staging buffers.

    Usage per megastep::

        ops, payloads = ring.acquire(k, rows)   # zeroed [k, rows, B, ...]
        ...fill slices, ring.mark(k, written_rows) per slice...
        dev = jnp.asarray(ops), jnp.asarray(payloads)
        ring.launched(*dev)                     # arms the reuse barrier

    ``acquire`` hands out views of the preallocated buffers; leading-axis
    views ([:k]) are contiguous, so the full-fleet upload path is
    zero-extra-copy.  Sub-row views ([:k, :rows]) are strided and copied by
    ``jnp.asarray`` (cohort steps — small by construction).
    """

    def __init__(
        self,
        k_max: int,
        rows: int,
        batch: int,
        op_fields: int,
        payload_len: int,
        depth: int = 2,
        mesh=None,
        doc_axis: str = "docs",
    ) -> None:
        # Mesh-aware upload: with a mesh, ``upload`` device_puts the
        # staging views with the SHARD layout (doc axis at dim -3), so each
        # chip receives exactly its placement-packed slice of the buffer
        # and the per-chip transfers overlap the previous dispatch
        # independently.  The engines pack doc rows by device slot, so the
        # buffer is contiguous per shard by construction.
        self._mesh = mesh
        self._doc_axis = doc_axis
        self.k_max = max(1, int(k_max))
        self._shape_ops = (self.k_max, rows, batch, op_fields)
        self._shape_payloads = (self.k_max, rows, batch, payload_len)
        self._bufs = [
            _StageBuf(self._shape_ops, self._shape_payloads)
            for _ in range(depth)
        ]
        self._i = 0
        self._cur: _StageBuf | None = None
        # Packs that overlapped an in-flight upload/dispatch (no blocking
        # wait was needed before reuse) — the double-buffer win counter.
        self.overlapped_packs = 0
        # Buffers surrendered to zero-copy device arrays (see module
        # docstring): each swap is one fresh allocation, the seed-parity
        # cost on backends without a real host->device transfer.
        self.aliased_swaps = 0

    def acquire(self, k: int, rows: int) -> tuple[np.ndarray, np.ndarray]:
        """A zeroed [k, rows, B, ...] staging view, safe to fill now."""
        slot = self._i
        buf = self._bufs[slot]
        self._i = (self._i + 1) % len(self._bufs)
        if buf.inflight is not None:
            import jax

            arrs = buf.inflight
            buf.inflight = None
            if self._aliased(buf, arrs):
                # The device arrays ALIAS this buffer's memory (zero-copy
                # backend): reuse would corrupt an in-flight dispatch's
                # input.  The arrays keep the old memory alive; the ring
                # slot gets fresh zeroed buffers.
                buf = self._bufs[slot] = _StageBuf(
                    self._shape_ops, self._shape_payloads
                )
                self.aliased_swaps += 1
            elif all(_transfer_done(a) for a in arrs):
                # The upload that read this buffer already drained: this
                # pack overlaps the previous megastep's device work.
                self.overlapped_packs += 1
            else:
                jax.block_until_ready(arrs)
        for kk, rr in buf.dirty:
            buf.ops[kk, rr] = 0
            buf.payloads[kk, rr] = 0
        buf.dirty.clear()
        self._cur = buf
        return buf.ops[:k, :rows], buf.payloads[:k, :rows]

    def mark(self, k: int, written_rows) -> None:
        """Record the rows slice ``k`` wrote (cleared on the next reuse)."""
        if len(written_rows):
            self._cur.dirty.append((k, np.asarray(written_rows)))

    def launched(self, *device_arrays) -> None:
        """Arm the reuse barrier with the arrays uploaded from the current
        buffer: the next acquire of this buffer waits for their transfers
        (not the consuming computation) before handing the memory back."""
        self._cur.inflight = device_arrays

    def upload(self, ops_view, payloads_view) -> tuple:
        """Upload the filled staging views and arm the reuse barrier in one
        call.  Under a mesh, [.., D, B, *] views (ndim >= 3) device_put
        with the shard layout — per-chip slices upload independently;
        lane-sized views ([B, *]) and mesh-less rings take the plain
        ``jnp.asarray`` path (zero-copy on CPU; the aliasing probe in
        ``acquire`` keeps reuse safe either way)."""
        import jax
        import jax.numpy as jnp

        from ..observability.flight_recorder import span

        nbytes = ops_view.nbytes + payloads_view.nbytes
        if self._mesh is not None and ops_view.ndim >= 3:
            from jax.sharding import NamedSharding, PartitionSpec

            spec = PartitionSpec(
                *([None] * (ops_view.ndim - 3)), self._doc_axis
            )
            sharding = NamedSharding(self._mesh, spec)
            # One span per shard-layout transfer: the device_put splits the
            # staging view per chip, so the span carries the shard count
            # and per-shard byte share for the trace.
            with span(
                "upload",
                shards=int(self._mesh.devices.size),
                bytes=nbytes,
                bytes_per_shard=nbytes // int(self._mesh.devices.size),
            ):
                dev = (
                    jax.device_put(ops_view, sharding),
                    jax.device_put(payloads_view, sharding),
                )
        else:
            with span("upload", shards=1, bytes=nbytes):
                dev = (jnp.asarray(ops_view), jnp.asarray(payloads_view))
        self.launched(*dev)
        return dev

    @staticmethod
    def _aliased(buf: _StageBuf, arrs) -> bool:
        """True when any uploaded device array points into the staging
        buffer's own memory (zero-copy backend; probe is best-effort —
        backends with real transfers either copy or lack the pointer)."""
        spans = [
            (buf.ops.ctypes.data, buf.ops.nbytes),
            (buf.payloads.ctypes.data, buf.payloads.nbytes),
        ]
        for a in arrs:
            probe = getattr(a, "unsafe_buffer_pointer", None)
            if probe is None:
                continue
            try:
                p = int(probe())
            except Exception:  # noqa: BLE001 — probe failure = assume no alias
                continue
            if any(base <= p < base + n for base, n in spans):
                return True
        return False


def upload_replicated(ops: np.ndarray, payloads: np.ndarray, mesh=None) -> tuple:
    """Replicated upload for SEGMENT-LANE op rings: a seg-sharded hot doc's
    [K, B] slices must reach every shard of the segment axis whole (each
    shard applies every op to its own segment block), so the device layout
    is replication — the other half of the 2-D docs x segs shard layout
    (``StagingRing.upload`` ships the doc-axis half).  Plain ``jnp.asarray``
    off-mesh."""
    import jax
    import jax.numpy as jnp

    from ..observability.flight_recorder import span

    nbytes = ops.nbytes + payloads.nbytes
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        from ..ops.mergetree_kernel import SEG_AXIS

        rep = NamedSharding(mesh, PartitionSpec())
        # Label with the SEG width (not the full 2-D device count) so
        # upload(kind=seg) spans correlate with the dispatch spans'
        # seg_shards tag in the flight trace.
        seg_width = int(dict(mesh.shape).get(SEG_AXIS, mesh.devices.size))
        with span("upload", kind="seg", shards=seg_width, bytes=nbytes):
            return jax.device_put(ops, rep), jax.device_put(payloads, rep)
    with span("upload", kind="seg", shards=1, bytes=nbytes):
        return jnp.asarray(ops), jnp.asarray(payloads)


def _transfer_done(arr) -> bool:
    """Non-blocking transfer-completion probe (best effort: absent on some
    jax versions/backends, where the caller just blocks)."""
    probe = getattr(arr, "is_ready", None)
    if probe is None:
        return False
    try:
        return bool(probe())
    except Exception:  # noqa: BLE001 — a probe failure must never break staging
        return False
