"""TreeBatchEngine: batched sequenced tree-edit application across documents.

The SharedTree analog of ``doc_batch_engine``: D tree documents, each with
its own totally-ordered edit stream, stepped in lockstep device batches.

Host/device split (the seam SURVEY §7 step 7 names):

- host: per-doc EditManager runs the deterministic trunk translation
  (dds/tree/editmanager.py) — rebase is control-plane work over tiny mark
  lists; the result is a TRUNK-COORDINATE commit every replica agrees on.
- device: the forest state as NESTED columnar rows — (parent, field,
  index) SoA beside the value column (ops/tree_kernel.py
  NestedForestState; ref chunked-forest/uniformChunk.ts:42 generalized) —
  applying trunk commits as masked column arithmetic with bounded-depth
  path resolution.

The device path covers nested shapes end to end (VERDICT r3 next #3) and
mixed-type leaves (VERDICT r4 next #2): int/bool values inline in the
value column, str/float values in a per-doc append-only word pool
addressed by (offset, vlen) — the merge-tree kernel's text-pool pattern.
Only genuinely irregular commits fall back to a host Forest replica:
paths deeper than the kernel's MAX_PATH, split/cross-field moves or
moves mixed with other structural marks in one field, out-of-range ints,
and leaf values wider than one payload row — the same route-to-oracle
policy as the string engine.
"""

from __future__ import annotations

import functools
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.tree.changeset import (
    Insert,
    Modify,
    MoveIn,
    MoveOut,
    NodeChange,
    Remove,
    Skip,
    apply_commit,
    commit_from_json,
)
from ..dds.tree.editmanager import EditManager
from ..dds.tree.mark_pool import MarkPool
from ..dds.tree.mark_pool import pool_commit_from_json as _pool_commit_from_json
from ..dds.tree.field_kinds import OptionalChange
from ..dds.tree.forest import ROOT_FIELD, Forest, Node
from ..observability.flight_recorder import RecompileWatchdog, instant, span
from ..ops import tree_kernel as tk
from .dispatch import dispatch_plane
from . import placement
from ..protocol.messages import MessageType, SequencedMessage
from ..utils.telemetry import HealthCounters
from .recovery import (
    RecoveryTracker,
    load_checkpoint_records,
    stale_due_docs,
    write_checkpoint_records,
)
from .staging import OverloadGate, RowQueue, StagingRing


@dataclass
class _TreeHost:
    em: EditManager = field(default_factory=EditManager)
    # Columnar pending op rows (see staging.RowQueue): flattened edits land
    # as row blocks, the drain consumes slice copies.
    queue: RowQueue = None
    # Trunk-coordinate commit suffix since ``checkpoint`` (replay source for
    # fallback routing); folded into the checkpoint forest every
    # CHECKPOINT_EVERY commits so host memory stays bounded.
    trunk_log: list[list] = field(default_factory=list)
    checkpoint: Forest = field(default_factory=Forest)
    device_commits: int = 0
    total_commits: int = 0
    # Durable-checkpoint floor (ops at or below base_seq are covered by the
    # stored record; a restarted consumer's replay of them is skipped).
    base_seq: int = 0
    last_seq: int = 0
    ops_since_ckpt: int = 0
    # Monotonic time the doc first went dirty after its last durable
    # checkpoint (0.0 = clean): the bounded-staleness writer's signal.
    dirty_since: float = 0.0
    # Set by restore_from_checkpoints: tail ops this doc applies are a
    # boot replay (counted as boot_replay_len in health until the first
    # post-boot checkpoint ends the boot phase).
    restored: bool = False
    boot_counting: bool = False


class UnsupportedShape(Exception):
    """A commit the columnar path cannot express."""


class _FlattenCollector:
    """One walk per trunk commit collects the structural KEY (field path /
    kind / payload arity — everything that determines row layout) and the
    DYNAMIC scalars (path indices, positions, counts, destinations,
    values, payload words).  A commit whose key was seen before skips all
    per-row numpy work: its cached _TranslationPlan turns the dynamics
    into row blocks with two vectorized fills (steady-state translation
    is a fill, not a walk)."""

    __slots__ = ("key", "dyn", "pay")

    _PTAG = {"v": 1, "w": 2, "r": 3}

    def __init__(self) -> None:
        self.key: list[tuple] = []
        self.dyn: list[int] = []
        # Per-row payload spec: None | ('v', val) | ('w', words) | ('r', vals)
        self.pay: list[tuple | None] = []

    def reset(self) -> None:
        self.key.clear()
        self.dyn.clear()
        self.pay.clear()

    def emit(self, kind, steps, fld, pos=0, count=0, dst=0, value=0,
             vkind=0, ntype=0, payload=None):
        if len(steps) > tk.MAX_PATH:
            raise UnsupportedShape("path deeper than kernel MAX_PATH")
        ptag = 0 if payload is None else self._PTAG[payload[0]]
        plen = len(payload[1]) if ptag >= 2 else ptag
        self.key.append(
            (kind, fld, ptag, plen, vkind, ntype, len(steps))
            + tuple(f for f, _ in steps)
        )
        dyn = self.dyn
        for _f, i in steps:
            dyn.append(i)
        dyn.append(pos)
        dyn.append(count)
        dyn.append(dst)
        dyn.append(value)
        self.pay.append(payload)


class _TranslationPlan:
    """Cached row layout for one commit shape: a static template block
    plus the (row, col) scatter of every dynamic cell.  ``fill`` reuses
    the plan's own scratch blocks — callers copy them out immediately
    (RowQueue.extend_block does), so steady state allocates nothing."""

    __slots__ = (
        "template", "dyn_rows", "dyn_cols", "scratch_ops", "scratch_pay",
    )

    def __init__(self, key: tuple, payload_len: int) -> None:
        t = tk._TGT
        m = len(key)
        self.template = np.zeros((m, tk.NESTED_OP_FIELDS), np.int32)
        dyn_rows: list[int] = []
        dyn_cols: list[int] = []
        for r, (kind, fld, _ptag, _plen, vkind, ntype, depth, *fids) in enumerate(key):
            row = self.template[r]
            row[0] = kind
            row[2] = depth
            for k, f in enumerate(fids):
                row[3 + 2 * k] = f
            row[t] = fld
            row[t + 5] = vkind
            row[t + 6] = ntype
            # Dynamic cells, in collector emission order: path indices,
            # then pos / count / dst / value.
            for k in range(depth):
                dyn_rows.append(r)
                dyn_cols.append(4 + 2 * k)
            for col in (t + 1, t + 2, t + 3, t + 4):
                dyn_rows.append(r)
                dyn_cols.append(col)
        self.dyn_rows = np.asarray(dyn_rows, np.int64)
        self.dyn_cols = np.asarray(dyn_cols, np.int64)
        self.scratch_ops = np.empty_like(self.template)
        # Payload cells beyond each row's fixed arity stay zero forever
        # (arity is part of the key), so one zeroing at build time
        # suffices — every fill rewrites exactly the same cells.
        self.scratch_pay = np.zeros((m, payload_len), np.int32)

    def fill(self, dyn: list[int], pays: list, seq: int):
        ops = self.scratch_ops
        np.copyto(ops, self.template)
        ops[:, 1] = seq
        if self.dyn_rows.size:
            ops[self.dyn_rows, self.dyn_cols] = dyn
        pay = self.scratch_pay
        for r, spec in enumerate(pays):
            if spec is None:
                continue
            tag, data = spec
            if tag == "v":
                pay[r, 0] = data
            else:  # 'w' words / 'r' run values
                pay[r, : len(data)] = data
        return ops, pay


# Watermark-accounting kind sets (the scalar _block_upper fast path; the
# vectorized branch derives the same sets from tk directly).
_GROW_KINDS = (int(tk.NestedOpKind.INSERT), int(tk.NestedOpKind.REPLACE_FIELD))
_POOLED_KINDS = _GROW_KINDS + (int(tk.NestedOpKind.SET),)
_POOLED_VKINDS = tuple(int(p) for p in tk._POOLED)

# Module-level jitted programs: shared compile cache across engine
# instances (keyed by input shapes), instead of per-instance jit closures.

_tree_step_jit = functools.partial(jax.jit, donate_argnums=(0,))(
    jax.vmap(tk.apply_nested_ops)
)
_tree_megastep_jit = functools.partial(jax.jit, donate_argnums=(0,))(
    tk.apply_nested_megastep
)
# Module-level body (stable identity: parallel.mesh caches its
# shard_map-wrapped mesh programs by function).
_tree_compact_body = jax.vmap(tk.compact_nested)
_tree_compact_jit = functools.partial(jax.jit, donate_argnums=(0,))(
    _tree_compact_body
)


class TreeBatchEngine:
    """A fleet of tree replicas: host EditManagers + nested device columns."""

    CHECKPOINT_EVERY = 64  # trunk-log fold threshold (bounds host memory)
    COMPACT_FRACTION = 0.75  # row watermark that triggers a device compact

    def __init__(
        self,
        n_docs: int,
        capacity: int = 1024,
        ops_per_step: int = 16,
        max_insert_len: int = 16,
        pool_capacity: int = 4096,
        mesh=None,
        checkpoint_store=None,
        checkpoint_every: int = 0,
        doc_keys: list[str] | None = None,
        megastep_k: int = 1,
        spare_slots: int = 0,
        plan_cache: bool = True,
        mark_pool: bool = True,
        device_rebase: bool = False,
        native_wire: bool = True,
        telemetry=None,
        overload_high_watermark: int = 0,
        overload_low_watermark: int = 0,
    ) -> None:
        self.n_docs = n_docs
        self.capacity = capacity
        self.pool_capacity = pool_capacity
        self.ops_per_step = ops_per_step
        self.max_insert_len = max_insert_len
        # Megastep depth cap (see doc_batch_engine): up to K [D, B] op
        # slices fuse into one donated dispatch; K=1 is the exact
        # per-slice path.
        self.megastep_k = max(1, megastep_k)
        # Ingest watermarks (same flow-control contract as the string
        # engine): pause a doc's feed at 8x the megastep budget, resume
        # once a dispatch's worth remains.
        budget = self.megastep_k * ops_per_step
        self.overload_gate = OverloadGate(
            high=overload_high_watermark or 8 * budget,
            low=overload_low_watermark or budget,
        )
        # Pooled columnar mark store (dds/tree/mark_pool.py): one pool is
        # shared by every doc's EditManager so occupancy/reuse gauges are
        # fleet-wide.  ``mark_pool=False`` keeps the object-mark fold —
        # the byte-identity fuzz oracle, same pattern as plan_cache.
        self.markpool = MarkPool() if mark_pool else None
        # Device rebase window (PR 19): one shared DeviceRebaser so the
        # fleet shares the field-interning table and the health gauges
        # (device_rebase_fraction / rebase_fallbacks), same pattern as
        # the shared MarkPool.  Requires the pooled fold.
        self.rebaser = None
        if device_rebase and self.markpool is not None:
            from ..dds.tree.device_rebase import DeviceRebaser

            self.rebaser = DeviceRebaser(self.markpool)
        # ingest_lines rides the native tree decoder when its symbol is
        # present (stale prebuilt .so -> Python decode, never a crash).
        self.native_wire = native_wire
        self.hosts = [
            _TreeHost(
                em=EditManager(
                    mark_pool=self.markpool, device_rebase=self.rebaser,
                ),
                queue=RowQueue(tk.NESTED_OP_FIELDS, max_insert_len),
            )
            for _ in range(n_docs)
        ]
        self.fallbacks: dict[int, Forest] = {}
        self.mesh = mesh
        self.checkpoint_store = checkpoint_store
        self.checkpoint_every = checkpoint_every
        # Checkpoint-plane lock + per-incident recovery clock (same
        # contract as doc_batch_engine: the bounded-staleness background
        # writer enters via checkpoint_stale under this lock; step/ingest
        # hold it so sweeps only see op boundaries).
        self.ckpt_lock = threading.RLock()
        # Durable-write plane: saves outside ckpt_lock, seq-fenced per doc
        # (same contract as DocBatchEngine).
        self._ckpt_io_lock = threading.Lock()
        self._ckpt_saved_seq: dict[int, int] = {}
        self.recovery_tracker = RecoveryTracker()
        # Record-file mtimes last seen by a refresh trail (standby
        # trailing: one stat per doc per poll, not a full record re-read).
        self._trail_mtime: dict[int, float] = {}
        self.doc_keys = list(doc_keys) if doc_keys is not None else [
            str(d) for d in range(n_docs)
        ]
        assert len(self.doc_keys) == n_docs
        # Warm the native decode plane with no lock held: ingest_lines
        # probes only the non-building tree_decode accessor under
        # ckpt_lock (fftpu-check blocking-under-lock — a lazy g++ run
        # under the serving lock convoys every ingest).
        from ..native import ingest_native as _ingest_native

        _ingest_native.warm()
        self.counters = HealthCounters(telemetry)
        # Interning tables shared by the fleet; ROOT_FIELD must be id 0
        # (the virtual root's field in the kernel's materializer).
        self._fields: dict[str, int] = {ROOT_FIELD: 0}
        self._types: dict[str, int] = {}
        # Translation plan cache: commit shape -> row-layout plan (see
        # _FlattenCollector).  ``plan_cache=False`` keeps the original
        # per-row emit path — the independent oracle the batch-vs-legacy
        # identity fuzz compares against.
        self.plan_cache = plan_cache
        self._plans: dict[tuple, _TranslationPlan] = {}
        self._collector = _FlattenCollector()
        self._PLAN_CACHE_MAX = 4096
        # Placement rides the shared plane (models/placement.py): doc ->
        # slot indirection with per-shard spare-slot free pools, the same
        # contract as the string engine (fleet capacity rounds up to a
        # mesh multiple; padding/free rows are inert pristine protos).
        # ``_slot`` aliases the plane's live array for hot-path packing.
        self.n_shards = mesh.devices.size if mesh is not None else 1
        self.placement_plane = placement.PlacementPlane(
            n_docs, self.n_shards, spare_slots
        )
        self.fleet_capacity = self.placement_plane.capacity
        self.docs_per_shard = self.placement_plane.docs_per_shard
        self._slot = self.placement_plane.slots
        # Per-shard applied-op counters (host-side): accumulated at drain
        # time, the hot-shard detection signal.
        self._shard_ops = np.zeros((self.n_shards,), np.int64)
        proto = tk.init_nested_forest(capacity, pool_capacity)
        self._proto = proto  # pristine row: retires vacated/re-seeded slots
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(
                x, (self.fleet_capacity,) + x.shape
            ),
            proto,
        )
        self._step = _tree_step_jit
        self._megastep = _tree_megastep_jit
        self._compact = _tree_compact_jit
        self._pm = None
        if mesh is not None:
            # Partition-rule-matched placement + shard_map-wrapped fleet
            # programs resolved through the engine-owned dispatch seam
            # (models/dispatch.py): one donated dispatch steps every
            # shard, zero hot-path collectives (same machinery as the
            # string engine).
            pm = self._pm = dispatch_plane()
            self.state = pm.shard_fleet_state(self.state, mesh)
            # On a docs x segs mesh the doc dim shards over BOTH axes
            # flattened — the program specs must match the placement
            # shard_fleet_state derives from the mesh, or the first
            # donated dispatch reshards the fleet.
            da = pm.fleet_doc_axes(mesh)
            specs = pm.fleet_state_specs(self.state, da)
            self._megastep = pm.mesh_fleet_program(
                tk.apply_nested_megastep, mesh, specs,
                arg_specs=(pm.P(None, da), pm.P(None, da)),
            )
            self._compact = pm.mesh_fleet_program(
                _tree_compact_body, mesh, specs, arg_specs=()
            )
        # Recompile watchdog (same contract as the string engine): cache
        # growth after warmup = a trace de-specialized mid-serve.
        self.recompile_watchdog = RecompileWatchdog()
        for prog_name, prog in (
            ("tree_step", self._step),
            ("tree_megastep", self._megastep),
            ("tree_compact", self._compact),
        ):
            self.recompile_watchdog.register(prog_name, prog)
        # Incremental busy set + preallocated double-buffered staging
        # (lazy), mirroring doc_batch_engine's megastep pipeline.
        self._busy: set[int] = set()
        self._stage: StagingRing | None = None
        # Host-side upper bound on each doc's row watermark (rows only grow
        # on INSERT ops, whose counts the host knows at staging time) — the
        # compaction trigger without a per-batch device readback.  The word
        # pool gets the same treatment: INSERT/SET of pooled values append
        # wordcount words (overwrites leak until compaction).
        self._rows_upper = np.zeros((n_docs,), np.int64)
        self._pool_upper = np.zeros((n_docs,), np.int64)

    # -------------------------------------------------------------- interning
    def _field_id(self, key: str) -> int:
        return self._fields.setdefault(key, len(self._fields))

    def _type_id(self, t: str) -> int:
        return self._types.setdefault(t, len(self._types))

    def _encode_value(self, v) -> tuple[int, int, list[int] | None]:
        """value -> (vkind, inline-value-or-wordcount, pool words).

        int/bool/None stay inline; str and float encode as pool words
        (codepoints / f64 halves — tk.encode_pooled_words).  Raises
        UnsupportedShape for values the columnar path cannot carry:
        out-of-range ints, strings wider than one payload row, exotic
        types — those documents route to the host Forest."""
        try:
            vk, val, words = tk.encode_pooled_words(v)
        except ValueError as e:
            raise UnsupportedShape(str(e)) from None
        if words is not None and len(words) > self.max_insert_len:
            raise UnsupportedShape(f"leaf value wider than payload row: {v!r}")
        return vk, val, words

    # ------------------------------------------------------------------ ingest
    @staticmethod
    def _unwrap(contents: dict):
        """Yield the tree edit ops inside a wire message: handles grouped
        batches and the runtime's address envelopes (containerRuntime ->
        datastore -> channel), so the engine ingests the same streams a
        container fleet produces."""
        if not isinstance(contents, dict):
            return
        if contents.get("type") == "groupedBatch":
            for inner in contents.get("contents", []):
                yield from TreeBatchEngine._unwrap(inner)
            return
        if contents.get("type") == "edit":
            yield contents
            return
        if "address" in contents and "contents" in contents:
            yield from TreeBatchEngine._unwrap(contents["contents"])

    def ingest(self, doc_idx: int, msg: SequencedMessage) -> None:
        """Integrate one sequenced message: EditManager translation on the
        host, op-row staging for the device (or fallback apply).
        Serialized on ``ckpt_lock`` against the background checkpoint
        writer."""
        if msg.type != MessageType.OP:
            return
        with self.ckpt_lock:
            for edit in self._unwrap(msg.contents):
                self._ingest_edit(doc_idx, msg, edit)

    def ingest_batch(self, doc_idxs, msgs) -> None:
        """Batch-delivery seam (BroadcasterLambda.subscribe_batch / the
        fleet feeder): tree translation is inherently per-edit — each
        commit rebases through the EditManager before it can flatten — so
        the batch win here is the translation plan cache + columnar
        RowQueue landing, which ``ingest`` already rides.  This wrapper
        keeps the two engine families API-compatible for batch callers."""
        for d, m in zip(doc_idxs, msgs):
            self.ingest(d, m)

    def ingest_lines(self, doc_idx: int, data: bytes) -> int:
        """Stage newline-separated wire JSON for one tree document — the
        firehose consumer seam (API parity with ``DocBatchEngine``).
        With the native tree decoder present (native/ingest.cpp
        ``ing_tree_decode``, symbol-gated like ``_sync_native_props``) and
        the mark pool enabled, the envelope + mark numeric plane decodes
        in C++ straight into pool columns; otherwise every line takes the
        Python parse.  A malformed line lands all EARLIER lines, then
        raises through the Python decode (which owns error semantics) —
        per-document isolation, other docs' feeds are untouched.  Returns
        op rows staged (applied edits for fallback-routed docs)."""
        with self.ckpt_lock:
            return self._ingest_lines(doc_idx, data)

    def _ingest_lines(self, doc_idx: int, data: bytes) -> int:
        h = self.hosts[doc_idx]
        commits_before = h.total_commits
        rows_before = len(h.queue)
        tables = None
        if self.markpool is not None and self.native_wire:
            from ..native import ingest_native as inat

            try:
                tables = inat.tree_decode(data)  # None: lib/symbol absent
            except ValueError:
                # Malformed line: re-decode in Python so the error carries
                # the Python path's exact semantics (earlier lines land).
                self.counters.bump("tree_native_decode_errors")
                tables = None
        if tables is not None:
            self.counters.bump("tree_native_batches")
            self._ingest_native_tables(doc_idx, data, tables)
        else:
            for raw in data.split(b"\n"):
                line = raw.strip()
                if line:
                    self.ingest(
                        doc_idx, SequencedMessage.from_json(line.decode())
                    )
        if doc_idx in self.fallbacks:
            return h.total_commits - commits_before
        return len(h.queue) - rows_before

    def _ingest_native_tables(self, doc_idx: int, data: bytes, tables) -> None:
        import json as _json

        from ..dds.tree.mark_pool import pool_commit_from_native
        from ..native.ingest_native import TREE_ST_EDITS, TREE_ST_OPAQUE

        msgs, chgs, flds, marks, spans = (t.tolist() for t in tables)
        for m in msgs:
            status = m[10]
            if status != TREE_ST_EDITS and status != TREE_ST_OPAQUE:
                continue  # non-op line: the op path ignores it too
            msg = SequencedMessage(
                client_id=data[m[4] : m[4] + m[5]].decode(),
                client_seq=m[13], ref_seq=m[1], seq=m[0], min_seq=m[2],
                type=MessageType.OP, contents=None,
            )
            if status == TREE_ST_OPAQUE:
                # Grouped batches, address envelopes, dict-form commits,
                # escaped ids: the Python walk, exactly as without native.
                contents = _json.loads(data[m[11] : m[11] + m[12]])
                for edit in self._unwrap(contents):
                    self._ingest_edit(doc_idx, msg, edit)
                continue
            with span("host_fold_mark_alloc", doc=doc_idx):
                commit = pool_commit_from_native(
                    self.markpool, data, m, chgs, flds, marks, spans
                )
            self._ingest_edit(
                doc_idx, msg,
                {"sid": data[m[6] : m[6] + m[7]].decode(), "rev": m[3]},
                commit=commit,
            )

    def _ingest_edit(self, doc_idx: int, msg: SequencedMessage, c: dict,
                     commit=None) -> None:
        h = self.hosts[doc_idx]
        if h.base_seq and msg.seq <= h.base_seq:
            # Covered by the durable checkpoint (restart replay): skip.
            self.counters.bump("checkpointed_ops_skipped")
            return
        h.last_seq = max(h.last_seq, msg.seq)
        h.ops_since_ckpt += 1
        if not h.dirty_since:
            h.dirty_since = time.monotonic()
        if h.boot_counting:
            self.counters.bump("boot_replay_len")
        # Host-fold sub-phases (flight recorder): mark_alloc (wire ->
        # commit/mark construction), rebase (EditManager window fold),
        # compose (trunk-suffix fold into the checkpoint forest) and
        # translate (_flatten) — the phase_shares row that makes the
        # "Mark.__init__ is ~30% of host time" claim reproducible.
        if commit is None:
            with span("host_fold_mark_alloc", doc=doc_idx):
                if self.markpool is not None:
                    commit = _pool_commit_from_json(
                        self.markpool, c["changes"]
                    )
                else:
                    commit = commit_from_json(c["changes"])
        with span("host_fold_rebase", doc=doc_idx):
            trunk = h.em.add_sequenced(
                client_id=msg.client_id,
                revision=(c["sid"], c["rev"]),
                change=commit,
                ref_seq=msg.ref_seq,
                seq=msg.seq,
            )
            h.em.advance_min_seq(msg.min_seq)
        h.total_commits += 1
        if doc_idx in self.fallbacks:
            # Fallback docs apply directly; their trunk log is dead weight
            # (they can never be re-replayed onto the device path).
            apply_commit(self.fallbacks[doc_idx].root, trunk)
            return
        h.trunk_log.append(trunk)
        if len(h.trunk_log) >= self.CHECKPOINT_EVERY:
            # Fold the suffix into the checkpoint forest: bounded host
            # memory, and fallback routing replays only the tail.
            with span("host_fold_compose", doc=doc_idx):
                for t in h.trunk_log:
                    apply_commit(h.checkpoint.root, t)
                h.trunk_log.clear()
        try:
            with span("host_fold_translate", doc=doc_idx):
                ops_blk, pay_blk = self._flatten(trunk, msg.seq)
        except UnsupportedShape:
            self._route_to_fallback(doc_idx)
            return
        h.device_commits += 1
        rows_up, words_up = self._block_upper(ops_blk)
        self._rows_upper[doc_idx] += rows_up
        self._pool_upper[doc_idx] += words_up
        h.queue.extend_block(ops_blk, pay_blk)
        if h.queue:
            self._busy.add(doc_idx)

    @staticmethod
    def _block_upper(ops_blk: np.ndarray) -> tuple[int, int]:
        """(row, pool-word) upper bounds of an op-row block — vectorized
        watermark accounting (ingest and resync share it).  Tiny blocks
        (the per-edit ingest case) take a scalar walk: numpy reductions on
        2-row arrays cost more than the loop they replace."""
        if not len(ops_blk):
            return 0, 0
        if len(ops_blk) <= 8:
            t = tk._TGT
            rows = words = 0
            for r in ops_blk.tolist():
                if r[0] in _GROW_KINDS:
                    rows += r[t + 2]
                if r[0] in _POOLED_KINDS and r[t + 5] in _POOLED_VKINDS:
                    words += r[t + 4]
            return rows, words
        kinds = ops_blk[:, 0]
        ins = (kinds == tk.NestedOpKind.INSERT) | (
            kinds == tk.NestedOpKind.REPLACE_FIELD
        )
        vk = ops_blk[:, tk._TGT + 5]
        pooled_vk = vk == tk._POOLED[0]
        for p in tk._POOLED[1:]:
            pooled_vk |= vk == p
        pooled = (ins | (kinds == tk.NestedOpKind.SET)) & pooled_vk
        return (
            int(ops_blk[ins, tk._TGT + 2].sum()),
            int(ops_blk[pooled, tk._TGT + 4].sum()),
        )

    def _queued_upper(self, h: _TreeHost) -> tuple[int, int]:
        q_ops, _q_pay = h.queue.pending()
        return self._block_upper(q_ops)

    # --------------------------------------------------------------- flatten
    def _flatten(self, trunk_commit, seq: int) -> tuple[np.ndarray, np.ndarray]:
        """Trunk commit -> nested forest op-row BLOCKS ([M, F], [M, L]).

        Front-to-back walk in OUTPUT coordinates: every emitted op's
        positions (and every path step's sibling index) are valid in the
        state produced by the ops emitted before it, so sequential device
        application reproduces the simultaneous mark semantics exactly —
        including nested paths, which back-to-front emission could not
        keep stable.

        With the plan cache on (default), the walk only COLLECTS (key +
        dynamic scalars, plain list appends); the per-row numpy work runs
        once per commit SHAPE and replays as a vectorized fill (see
        _TranslationPlan).  ``plan_cache=False`` runs the original
        per-row emit — the identity-fuzz oracle."""
        if not self.plan_cache:
            return self._flatten_legacy(trunk_commit, seq)
        col = self._collector
        col.reset()
        for change in trunk_commit:
            if change.value is not None:
                raise UnsupportedShape("value change on the virtual root")
            for key, fc in change.fields.items():
                self._walk_field(fc, (), self._field_id(key), col.emit)
        key = tuple(col.key)
        plan = self._plans.get(key)
        if plan is None:
            plan = _TranslationPlan(key, self.max_insert_len)
            if len(self._plans) < self._PLAN_CACHE_MAX:
                self._plans[key] = plan
            self.counters.bump("translation_plan_misses")
        else:
            self.counters.bump("translation_plan_hits")
        return plan.fill(col.dyn, col.pay, seq)

    def _flatten_legacy(self, trunk_commit, seq: int) -> tuple[np.ndarray, np.ndarray]:
        """The pre-plan-cache path: one numpy row pair per emit."""
        ops_rows: list[np.ndarray] = []
        pay_rows: list[np.ndarray] = []
        L = self.max_insert_len
        empty = np.zeros((L,), np.int32)

        def emit(kind, steps, fld, pos=0, count=0, dst=0, value=0,
                 vkind=0, ntype=0, payload=None):
            if len(steps) > tk.MAX_PATH:
                raise UnsupportedShape("path deeper than kernel MAX_PATH")
            op = np.zeros((tk.NESTED_OP_FIELDS,), np.int32)
            op[0], op[1], op[2] = kind, seq, len(steps)
            for k, (f, i) in enumerate(steps):
                op[3 + 2 * k], op[4 + 2 * k] = f, i
            t = tk._TGT
            op[t], op[t + 1], op[t + 2], op[t + 3] = fld, pos, count, dst
            op[t + 4], op[t + 5], op[t + 6] = value, vkind, ntype
            ops_rows.append(op)
            if payload is None:
                pay_rows.append(empty)
            else:
                tag, data = payload
                pay = np.zeros((L,), np.int32)
                if tag == "v":
                    pay[0] = data
                else:
                    pay[: len(data)] = data
                pay_rows.append(pay)

        for change in trunk_commit:
            if change.value is not None:
                raise UnsupportedShape("value change on the virtual root")
            for key, fc in change.fields.items():
                self._walk_field(fc, (), self._field_id(key), emit)
        if not ops_rows:
            return (
                np.zeros((0, tk.NESTED_OP_FIELDS), np.int32),
                np.zeros((0, L), np.int32),
            )
        return np.stack(ops_rows), np.stack(pay_rows)

    def _walk_field(self, fc, steps: tuple, fid: int, emit) -> None:
        """Dispatch one field change by kind: sequence mark lists walk as
        before; optional/value whole-content sets become REPLACE_FIELD
        device ops; other kinds route to the host fallback."""
        if isinstance(fc, list):
            if fc:
                self._walk_marks(fc, steps, fid, emit)
            return
        if not isinstance(fc, OptionalChange):
            raise UnsupportedShape(f"field kind {getattr(fc, 'kind', fc)!r}")
        if fc.set is not None:
            content = fc.set[0]
            if content is None:
                emit(tk.NestedOpKind.REPLACE_FIELD, steps, fid, count=0)
                return
            vk, val, words = self._encode_value(content.value)
            nt = self._type_id(content.type)
            emit(tk.NestedOpKind.REPLACE_FIELD, steps, fid, count=1,
                 value=val if words is not None else 0, vkind=vk, ntype=nt,
                 payload=("w", words) if words is not None else ("v", val))
            child_steps = steps + ((fid, 0),)
            for key, kids in content.fields.items():
                if kids:
                    self._insert_content(
                        kids, child_steps, self._field_id(key), 0, emit
                    )
            return
        if fc.nested is not None and not fc.nested.is_empty():
            self._walk_node_change(fc.nested, steps, fid, 0, emit)

    def _walk_node_change(
        self, ch, steps: tuple, fid: int, pos: int, emit
    ) -> None:
        """A NodeChange against the node at (fid, pos) under ``steps``."""
        if ch.value is not None:
            vk, val, words = self._encode_value(ch.value[0])
            emit(tk.NestedOpKind.SET, steps, fid, pos=pos,
                 value=val, vkind=vk,
                 payload=("w", words) if words is not None else None)
        if any(ch.fields.values()):
            child_steps = steps + ((fid, pos),)
            for key, fc in ch.fields.items():
                self._walk_field(fc, child_steps, self._field_id(key), emit)

    def _walk_marks(self, marks, steps: tuple, fid: int, emit) -> None:
        if any(isinstance(m, (MoveOut, MoveIn)) for m in marks):
            self._emit_move_field(marks, steps, fid, emit)
            return
        out_pos = 0
        for m in marks:
            if isinstance(m, Skip):
                out_pos += m.count
            elif isinstance(m, Insert):
                out_pos += self._insert_content(
                    m.content, steps, fid, out_pos, emit
                )
            elif isinstance(m, Remove):
                emit(tk.NestedOpKind.REMOVE, steps, fid, pos=out_pos,
                     count=m.count)
            elif isinstance(m, Modify):
                self._walk_node_change(m.change, steps, fid, out_pos, emit)
                out_pos += 1
            else:
                raise UnsupportedShape(type(m).__name__)

    def _insert_content(
        self, nodes: list[Node], steps: tuple, fid: int, start: int, emit
    ) -> int:
        """Decompose a content forest into path-addressed inserts,
        parent-first; consecutive childless same-shape nodes batch into one
        op row.  Returns the number of nodes inserted at this level."""
        pos = start
        run_vals: list[int] = []
        run_shape: tuple[int, int] | None = None  # (vkind, ntype)

        def flush() -> None:
            nonlocal run_vals, run_shape
            if run_vals:
                emit(tk.NestedOpKind.INSERT, steps, fid,
                     pos=pos - len(run_vals), count=len(run_vals),
                     vkind=run_shape[0], ntype=run_shape[1],
                     payload=("r", list(run_vals)))
            run_vals, run_shape = [], None

        for node in nodes:
            vk, val, words = self._encode_value(node.value)
            nt = self._type_id(node.type)
            pooled = words is not None
            if pooled or (node.fields and any(node.fields.values())):
                # Pooled values carry their words in the payload row (one
                # node per op); interior nodes need their own op so child
                # inserts can address them parent-first.
                flush()
                emit(tk.NestedOpKind.INSERT, steps, fid, pos=pos, count=1,
                     value=val if pooled else 0, vkind=vk, ntype=nt,
                     payload=("w", words) if pooled else ("v", val))
                child_steps = steps + ((fid, pos),)
                for key, kids in node.fields.items():
                    if kids:
                        self._insert_content(
                            kids, child_steps, self._field_id(key), 0, emit
                        )
                pos += 1
            else:
                if run_shape not in (None, (vk, nt)) or len(run_vals) >= self.max_insert_len:
                    flush()
                run_shape = (vk, nt)
                run_vals.append(val)
                pos += 1
        flush()
        return pos - start

    def _emit_move_field(self, marks, steps: tuple, fid: int, emit) -> None:
        """A field containing a move: only the pure single-pair contiguous
        form maps to one device op (input coordinates); anything else —
        split moves, cross-field pairs, moves mixed with other structural
        marks — is host-fallback territory."""
        move_out: dict[int, tuple[int, int]] = {}
        move_in: dict[int, int] = {}
        in_pos = 0
        for m in marks:
            if isinstance(m, Skip):
                in_pos += m.count
            elif isinstance(m, MoveOut):
                if m.id in move_out:
                    raise UnsupportedShape("split move")
                move_out[m.id] = (in_pos, m.count)
                in_pos += m.count
            elif isinstance(m, MoveIn):
                if m.id in move_in:
                    raise UnsupportedShape("split move")
                move_in[m.id] = in_pos
            else:
                raise UnsupportedShape("mixed structural marks with move")
        if len(move_out) != 1 or set(move_out) != set(move_in):
            raise UnsupportedShape("non-single-pair move")
        (mid, (src, count)), = move_out.items()
        emit(tk.NestedOpKind.MOVE, steps, fid, pos=src, count=count,
             dst=move_in[mid])

    # ---------------------------------------------------------------- routing
    def _route_to_fallback(self, doc_idx: int) -> None:
        """Rebuild the document as a host Forest from its trunk log; all
        future commits apply there (route-to-oracle, like the string
        engine's recovery lanes)."""
        h = self.hosts[doc_idx]
        f = h.checkpoint  # trunk state up to the last checkpoint fold
        for trunk in h.trunk_log:
            apply_commit(f.root, trunk)
        self.fallbacks[doc_idx] = f
        h.checkpoint = Forest()
        h.trunk_log.clear()  # never replayed again
        h.queue.clear()
        self._busy.discard(doc_idx)
        # The doc's device columns are dead weight now; stop letting its
        # stale watermarks trigger fleet-wide compactions.
        self._rows_upper[doc_idx] = 0
        self._pool_upper[doc_idx] = 0

    # ------------------------------------------------------------------- step
    def pending_ops(self) -> int:
        return sum(len(h.queue) for h in self.hosts)

    # --------------------------------------------------------- flow control
    def update_overload(self) -> tuple[list[int], list[int]]:
        """Ingest watermark hysteresis (see doc_batch_engine): -> (newly
        paused docs, newly resumed docs)."""
        return self.overload_gate.update(
            self._busy, lambda d: len(self.hosts[d].queue)
        )

    def ingest_watermarks(self) -> dict:
        return self.overload_gate.watermarks(
            self.megastep_k * self.ops_per_step
        )

    @property
    def overloaded(self) -> bool:
        return bool(self.overload_gate.paused)

    def device_fraction(self) -> float:
        """Fraction of ingested commits applied on the device path."""
        total = sum(h.total_commits for h in self.hosts)
        dev = sum(h.device_commits for h in self.hosts)
        return dev / total if total else 1.0

    def _staging(self) -> StagingRing:
        if self._stage is None:
            self._stage = StagingRing(
                self.megastep_k, self.fleet_capacity, self.ops_per_step,
                tk.NESTED_OP_FIELDS, self.max_insert_len, mesh=self.mesh,
                doc_axis=(
                    self._pm.fleet_doc_axes(self.mesh)
                    if self.mesh is not None else "docs"
                ),
            )
        return self._stage

    def _select_k(self, busy: list[int]) -> int:
        """Megastep depth from the deepest busy queue (pow2-quantized,
        capped at megastep_k); K=1 degenerates to the per-slice path."""
        if self.megastep_k <= 1:
            return 1
        B = self.ops_per_step
        need = max(-(-len(self.hosts[d].queue) // B) for d in busy)
        return min(self.megastep_k, 1 << (max(need, 1).bit_length() - 1))

    def _drain_into(
        self, busy: list[int], ops: np.ndarray, payloads: np.ndarray
    ) -> list[int]:
        """Dequeue up to ops_per_step op rows per busy doc into its
        PLACEMENT slot's row of the zeroed staging arrays — slice copies,
        never a per-op Python loop.  Returns the rows written
        (buffer-reuse dirty tracking)."""
        B = self.ops_per_step
        written: list[int] = []
        for d in busy:
            h = self.hosts[d]
            take = min(B, len(h.queue))
            if not take:
                continue
            r = int(self._slot[d])
            src_ops, src_payloads = h.queue.take(take)
            ops[r, :take] = src_ops
            payloads[r, :take] = src_payloads
            # Charge the op count to the hosting shard (hot-shard signal).
            self._shard_ops[r // self.docs_per_shard] += take
            if not h.queue:
                self._busy.discard(d)
            written.append(r)
        return written

    def step(self) -> int:
        """Apply everything staged as batched device megasteps.  Holds
        ``ckpt_lock`` end to end (the background checkpoint writer only
        sweeps between steps) and closes any open recovery incident once
        staged work actually applied (kill -> first post-restore op)."""
        with self.ckpt_lock:
            had_work = bool(self._busy)
            steps = self._step_fleet()
            if had_work and self.recovery_tracker.active:
                self.recovery_tracker.complete()
        # Cadence checkpoints after the serving lock releases (same
        # contract as DocBatchEngine.step): the durable fsyncs must not
        # run while every ingest contender queues on ckpt_lock.
        self.maybe_checkpoint()
        return steps

    def _step_fleet(self) -> int:
        steps = 0
        while self._busy:
            # Proactive compact: dead rows accumulate monotonically (stable
            # rows never reuse slots) — reclaim before overflow.  The
            # trigger is the host-side row UPPER BOUND (no per-batch device
            # sync); the one readback after compacting re-syncs it to the
            # true live counts.
            if (
                self._rows_upper.max() > self.capacity * self.COMPACT_FRACTION
                or self._pool_upper.max()
                > self.pool_capacity * self.COMPACT_FRACTION
            ):
                self.state = self._compact(self.state)
                # Resync = live rows/words (applied) + the counts still in
                # each doc's queue (unapplied) — dropping the queued part
                # would let a long churn stream overflow mid-step without
                # ever re-triggering compaction.
                queued_pairs = [self._queued_upper(h) for h in self.hosts]
                queued = np.array([q for q, _w in queued_pairs], np.int64)
                queued_words = np.array(
                    [w for _q, w in queued_pairs], np.int64
                )
                # Fallback docs keep stale live rows on device (nothing
                # compacts them away); excluding them here keeps the reset
                # in _route_to_fallback effective — otherwise one resync
                # resurrects an above-threshold watermark that no
                # compaction can ever lower, and the fleet compacts on
                # every batch forever.
                active = np.array(
                    [d not in self.fallbacks for d in range(self.n_docs)]
                )
                self._rows_upper = np.where(
                    active,
                    np.asarray(self.state.nrow)[self._slot].astype(
                        np.int64
                    )
                    + queued,
                    0,
                )
                self._pool_upper = np.where(
                    active,
                    np.asarray(self.state.pool_end)[self._slot].astype(
                        np.int64
                    )
                    + queued_words,
                    0,
                )
            busy = sorted(self._busy)
            K = self._select_k(busy)
            stage = self._staging()
            ops, payloads = stage.acquire(K, self.fleet_capacity)
            for k in range(K):
                stage.mark(k, self._drain_into(busy, ops[k], payloads[k]))
                if k + 1 < K:
                    busy = [d for d in busy if d in self._busy]
            if self.mesh is None and K == 1:
                dev_ops, dev_payloads = stage.upload(ops[0], payloads[0])
                with span("dispatch", kind="tree", k=K):
                    self.state = self._step(
                        self.state, dev_ops, dev_payloads
                    )
            else:
                # Mesh path: always the [K, D, B] shard_map megastep (K=1
                # included — bit-identical to one batched dispatch), one
                # donated call stepping every chip.
                dev_ops, dev_payloads = stage.upload(ops, payloads)
                with span("dispatch", kind="tree", k=K,
                          shards=self.n_shards):
                    self.state = self._megastep(
                        self.state, dev_ops, dev_payloads
                    )
            steps += K
            self.counters.bump("megastep_dispatches")
            self.counters.bump("megastep_slices", K)
        self.recompile_watchdog.poll()
        if self.mesh is not None:
            # Per-shard latch reduce: one scalar readback instead of a
            # cross-mesh [D] error gather on every step.
            with span("readback", kind="error_count"):
                clean = int(self._pm.error_count(self.state.error)) == 0
            if clean:
                return steps
        with span("readback", kind="error_vector"):
            err = np.asarray(self.state.error)
        for d in range(self.n_docs):
            s = int(self._slot[d])
            if err[s] and d not in self.fallbacks:
                # Capacity/range overflow on device: replay on the host.
                self._route_to_fallback(d)
                self.counters.bump("fallback_routes")
                self.state = self.state._replace(
                    error=self.state.error.at[s].set(0)
                )
        return steps

    # ------------------------------------------------------------- checkpoint
    def maybe_checkpoint(self, force: bool = False, docs=None) -> list[int]:
        """Write durable checkpoint records (forest + EditManager window)
        for docs whose commit count since the last record reached
        ``checkpoint_every``; all dirty docs when ``force``.  The host
        trunk fold (``checkpoint`` forest) IS the snapshot state, so this
        needs no device readback.  ``docs`` restricts the sweep to an
        explicit due list (the bounded-staleness writer): those
        checkpoint whenever dirty, regardless of cadence."""
        if self.checkpoint_store is None:
            return []
        if docs is None and not force and self.checkpoint_every <= 0:
            return []
        with self.ckpt_lock:
            out, pending = self._checkpoint_sweep(force, docs)
        # Durable writes outside ckpt_lock (same contract as the string
        # engine): a background sweep's fsyncs must not stall serving.
        write_checkpoint_records(self, pending, "device")
        return out

    def checkpoint_stale(
        self, max_ops_behind: int = 0, max_seconds_behind: float = 0.0
    ) -> list[int]:
        """Bounded-staleness delta sweep (same contract as
        ``DocBatchEngine.checkpoint_stale``): checkpoint every dirty doc
        whose durable record trails by ``max_ops_behind`` applied ops or
        ``max_seconds_behind`` seconds.  Record build under ``ckpt_lock``;
        durable writes after release."""
        if self.checkpoint_store is None or not (
            max_ops_behind or max_seconds_behind
        ):
            return []
        now = time.monotonic()
        with self.ckpt_lock:
            due = stale_due_docs(
                self.hosts, self.n_docs, max_ops_behind,
                max_seconds_behind, now,
            )
            if not due:
                return []
            with span("checkpoint_sweep", docs=len(due)):
                out, pending = self._checkpoint_sweep(force=False, docs=due)
            if out:
                self.counters.bump("stale_checkpoints_written", len(out))
        write_checkpoint_records(self, pending, "device")
        return out

    def _checkpoint_sweep(
        self, force: bool, docs
    ) -> tuple[list[int], list[tuple[int, int, dict]]]:
        out: list[int] = []
        pending: list[tuple[int, int, dict]] = []
        for d in (range(self.n_docs) if docs is None else docs):
            h = self.hosts[d]
            if h.ops_since_ckpt <= 0:
                continue
            if (
                docs is None and not force
                and h.ops_since_ckpt < self.checkpoint_every
            ):
                continue
            if d in self.fallbacks:
                lane = "fallback"
                forest_json = self.fallbacks[d].to_json()
            else:
                lane = "device"
                # Fold the trunk suffix so the checkpoint forest is the
                # full trunk state (this is the same fold the host-memory
                # bound performs, just on the durable cadence too).
                for t in h.trunk_log:
                    apply_commit(h.checkpoint.root, t)
                h.trunk_log.clear()
                forest_json = h.checkpoint.to_json()
            record = {
                "engine": "tree_batch",
                "lane": lane,
                "forest": forest_json,
                "em": h.em.summarize(),
                "commits": h.total_commits,
            }
            pending.append((d, h.last_seq, record))
            h.base_seq = h.last_seq
            h.ops_since_ckpt = 0
            h.dirty_since = 0.0
            h.boot_counting = False  # a new durable floor ends the boot phase
            self.counters.bump("checkpoints_written")
            out.append(d)
        return out, pending

    def note_incident(self, started_at: float) -> None:
        """Back-date the current recovery incident to the supervisor's
        kill timestamp (``time.monotonic`` domain)."""
        self.recovery_tracker.begin(started_at)

    def restore_from_checkpoints(
        self, store=None, parallel: bool = True,
        max_workers: int | None = None, refresh: bool = False,
    ) -> list[int]:
        """Engine restart path: rebuild each doc's host forest and
        EditManager window from its durable record, re-materialize the
        device columns from the forest (a synthesized whole-content insert
        commit), and set the seq floor so replayed ops the checkpoint
        covers are skipped.

        ``parallel`` (default) loads every record concurrently (thread
        pool over the store's ``load_many`` — the JSON read+parse is the
        restore's I/O phase); the host builds stay in doc order either
        way, and the re-materialized device rows land through the normal
        batched step, so the device half is already one megastep per K·B
        rows.  ``parallel=False`` is the sequential oracle (per-doc
        loads), byte-identical by contract.

        ``refresh`` is the warm-standby trailing mode: adopt docs that
        GAINED a record since the last pass, without opening a recovery
        incident — including the IN-PLACE RE-SEED of an already-adopted
        doc from a strictly newer record (string-engine parity): the
        doc's materialized pooled columns reset to the pristine proto row
        and the fresh forest re-materializes on top, so a promoted tree
        standby replays from each doc's freshest durable floor."""
        store = store if store is not None else self.checkpoint_store
        if store is None:
            return []
        with self.ckpt_lock:
            return self._restore(store, parallel, max_workers, refresh)

    def _restore(self, store, parallel, max_workers, refresh) -> list[int]:
        t_start = time.monotonic()
        with span("restore_scan", docs=self.n_docs):
            # First-boot vs trailing/re-seed candidate selection is the
            # shared plane's (placement.restore_candidates): first source
            # wins for live serving, trailing never races staged work,
            # unchanged record files skip on one mtime stat per doc.
            candidates, cand_mtime = placement.restore_candidates(
                self, store, refresh, lambda d: len(self.hosts[d].queue)
            )
        if not candidates:
            return []
        records = load_checkpoint_records(
            store, [self.doc_keys[d] for d in candidates],
            parallel=parallel, max_workers=max_workers,
        )
        restored: list[int] = []
        for i, d in enumerate(candidates):
            rec = records.get(i)
            if rec is not None and d in cand_mtime:
                self._trail_mtime[d] = cand_mtime[d]
            if rec is None or rec.get("engine") != "tree_batch":
                continue
            h = self.hosts[d]
            if refresh and h.restored:
                if int(rec["seq"]) <= h.last_seq:
                    continue  # nothing newer to adopt
                self.counters.bump("checkpoint_refreshes")
            if refresh:
                # In-place re-seed: forget the prior adoption (host
                # windows, staged rows, fallback entry) and reset the
                # doc's materialized pooled columns to the pristine proto
                # row, so the fresh record's re-materialization lands on
                # clean state.
                self._drop_restored_identity(d)
            h.em = EditManager(mark_pool=self.markpool)
            h.em.load(rec["em"])
            h.base_seq = h.last_seq = int(rec["seq"])
            h.restored = True
            h.boot_counting = True
            h.total_commits = int(rec.get("commits", 0))
            forest = Forest()
            forest.load_json(rec["forest"])
            if rec.get("lane") == "fallback":
                self.fallbacks[d] = forest
                h.checkpoint = Forest()
                restored.append(d)
                self.counters.bump("docs_restored")
                continue
            h.checkpoint = forest
            if forest.root_field:
                # Re-materialize the device columns: the checkpoint forest
                # as one whole-content insert commit (same flatten path as
                # live commits, so interning and accounting match).
                ch = NodeChange()
                ch.fields[ROOT_FIELD] = [
                    Insert([n.clone() for n in forest.root_field])
                ]
                try:
                    ops_blk, pay_blk = self._flatten([ch], seq=h.base_seq)
                except UnsupportedShape:
                    self._route_to_fallback(d)
                    restored.append(d)
                    self.counters.bump("docs_restored")
                    continue
                rows_up, words_up = self._block_upper(ops_blk)
                self._rows_upper[d] += rows_up
                self._pool_upper[d] += words_up
                h.queue.extend_block(ops_blk, pay_blk)
                if h.queue:
                    self._busy.add(d)
            restored.append(d)
            self.counters.bump("docs_restored")
        if restored and not refresh:
            # A real restore (not standby trailing) opens a recovery
            # incident: the clock runs until the first post-restore step
            # applies staged work (the re-materialization rows count —
            # they ARE the restore's device half).  note_incident()
            # back-dates to the kill time.
            self.recovery_tracker.begin(t_start)
        if restored and refresh:
            # Trailing/re-seed hands back LIVE state: apply the staged
            # re-materializations now (unlike the string engine's direct
            # row scatter, the tree handoff rides the batched step), so a
            # promoted standby serves byte-identical reads immediately
            # and the next trailing pass's staged-work guard doesn't see
            # this pass's own rows.
            self._step_fleet()
        return restored

    # ----------------------------------------------------------------- warmup
    def warmup(self) -> int:
        """Pre-compile the fleet's serving programs (warm-standby boot):
        dispatch all-NOOP megasteps at every pow2 depth up to
        ``megastep_k`` plus one compact through the exact serving entry
        points, so a promoted standby pays ZERO XLA compiles on its first
        real dispatch.  Zeroed staging rows are NOOP by kernel contract
        (NestedOpKind.NOOP == 0), so state bytes are untouched.  Returns
        the number of warmup dispatches run."""
        warmed = 0
        with self.ckpt_lock, span("warmup", k_max=self.megastep_k):
            stage = self._staging()
            if self.mesh is None:
                # The K=1 mesh-less fast path dispatches _step directly.
                ops, payloads = stage.acquire(1, self.fleet_capacity)
                dev_ops, dev_payloads = stage.upload(ops[0], payloads[0])
                self.state = self._step(self.state, dev_ops, dev_payloads)
                warmed += 1
            depths = []
            k = 1
            while k <= self.megastep_k:
                depths.append(k)
                k *= 2
            if self.megastep_k > 1 and self.megastep_k not in depths:
                # _select_k clamps to min(megastep_k, pow2(need)), so a
                # non-pow2 configured K is itself a reachable dispatch
                # shape — skip it here and the first deep-queue dispatch
                # after promotion pays the compile warmup exists to kill.
                depths.append(self.megastep_k)
            for k in depths:
                if self.mesh is not None or k > 1:
                    ops, payloads = stage.acquire(k, self.fleet_capacity)
                    dev_ops, dev_payloads = stage.upload(ops, payloads)
                    self.state = self._megastep(
                        self.state, dev_ops, dev_payloads
                    )
                    warmed += 1
            self.state = self._compact(self.state)
            warmed += 1
            jax.block_until_ready(self.state)
            # Absorb the warmup compiles into the watchdog count NOW, so
            # they show up as boot-time cache growth rather than landing
            # on the first serving step's poll.
            self.recompile_watchdog.poll()
        self.counters.gauge("warmup_dispatches", warmed)
        return warmed

    # ----------------------------------------------------------------- health
    def health(self) -> dict:
        self.counters.gauge("megastep_k", self.megastep_k)
        self.counters.gauge(
            "staging_overlap_packs",
            self._stage.overlapped_packs if self._stage is not None else 0,
        )
        self.counters.gauge(
            "staging_aliased_swaps",
            self._stage.aliased_swaps if self._stage is not None else 0,
        )
        self.counters.ratio(
            "steps_per_dispatch", "megastep_slices", "megastep_dispatches"
        )
        hits = self.counters.get("translation_plan_hits")
        misses = self.counters.get("translation_plan_misses")
        self.counters.gauge(
            "translation_plan_hit_rate",
            round(hits / (hits + misses), 4) if hits + misses else 0.0,
        )
        self.counters.gauge("translation_plans", len(self._plans))
        # Mark-pool surface: hit rate = span demands answered by reusing
        # an existing immutable span (the incremental-rebase identity
        # reuse) over all demands; occupancy = live slots / pool storage.
        if self.markpool is not None:
            ps = self.markpool.stats()
            hits = ps["mark_pool_reuse_hits"]
            total = hits + ps["mark_pool_spans"]
            self.counters.gauge(
                "mark_pool_hit_rate",
                round(hits / total, 4) if total else 0.0,
            )
            for k, v in ps.items():
                self.counters.gauge(k, v)
        # Device-rebase surface: fraction of window steps resolved on
        # the kernel plane; fallbacks are the pooled-fold remainder
        # (ineligible commits + invalidated steps), counted never silent.
        if self.rebaser is not None:
            for k, v in self.rebaser.stats().items():
                self.counters.gauge(k, v)
        self.counters.gauge("recompiles", self.recompile_watchdog.recompiles)
        self.counters.gauge(
            "despecializations", self.recompile_watchdog.despecializations
        )
        # Flow-control surface (shared shape with the string engine via
        # OverloadGate.emit_gauges).
        self.overload_gate.emit_gauges(
            self.counters, self.megastep_k * self.ops_per_step,
            max((len(self.hosts[d].queue) for d in self._busy), default=0),
        )
        self.counters.gauge("n_shards", self.n_shards)
        if self.n_shards > 1:
            depth = [0] * self.n_shards
            for d in range(self.n_docs):
                q = len(self.hosts[d].queue)
                if q:
                    depth[self.shard_of(d)] += q
            self.counters.gauge("shard_queue_depth", depth)
        # Recovery surface (same shape as the string engine): incident
        # percentiles + current checkpoint staleness.
        self.recovery_tracker.emit_gauges(self.counters)
        now = time.monotonic()
        self.counters.gauge(
            "dirty_docs",
            sum(1 for h in self.hosts if h.ops_since_ckpt > 0),
        )
        self.counters.gauge(
            "checkpoint_age_s",
            round(
                max(
                    (now - h.dirty_since for h in self.hosts
                     if h.dirty_since),
                    default=0.0,
                ),
                3,
            ),
        )
        snap = self.counters.snapshot()
        snap.update(
            fallback_docs=len(self.fallbacks),
            checkpoint_age_seqs=max(
                (h.last_seq - h.base_seq for h in self.hosts if h.last_seq),
                default=0,
            ),
            device_fraction=round(self.device_fraction(), 4),
        )
        return snap

    # ------------------------------------------------------------------ views
    def _name_tables(self) -> tuple[dict[int, str], dict[int, str]]:
        return (
            {v: k for k, v in self._fields.items()},
            {v: k for k, v in self._types.items()},
        )

    def tree_json(self, doc_idx: int) -> list[dict]:
        """The document's root field as forest JSON (Node.to_json shape)."""
        if doc_idx in self.fallbacks:
            return [n.to_json() for n in self.fallbacks[doc_idx].root_field]
        slot = int(self._slot[doc_idx])
        st = jax.tree.map(lambda x: x[slot], self.state)
        field_names, type_names = self._name_tables()
        return tk.nested_to_json(st, field_names, type_names)

    def values(self, doc_idx: int) -> list:
        """The document's root-field node values (int/str/float/bool
        leaves, None for valueless nodes)."""
        return [n.get("v") for n in self.tree_json(doc_idx)]

    def shard_of(self, doc_idx: int) -> int:
        """The mesh shard currently hosting this doc's device row."""
        return self.placement_plane.shard_of(doc_idx)

    def placement(self) -> dict[str, int]:
        """doc key -> mesh shard (ScribePool.align_to_placement surface)."""
        return self.placement_plane.placement(self.doc_keys)

    def shard_load(self) -> tuple[np.ndarray, np.ndarray]:
        """Per-shard (applied ops since the last ``hot_shards`` reset,
        currently queued ops) — see placement.shard_load."""
        return placement.shard_load(self)

    def hot_shards(
        self, factor: float = 2.0, reset: bool = False, load=None
    ) -> list[int]:
        """Shards whose load (applied + queued ops) exceeds ``factor`` x
        the fleet mean — see placement.hot_shards (the same detection the
        string engine rides)."""
        return placement.hot_shards(self, factor, reset, load)

    def free_slots(self, shard: int) -> int:
        return self.placement_plane.free_slots(shard)

    def migrate_doc(self, d: int, dst_shard: int) -> bool:
        # ckpt_lock: migration mutates self.state and the slot map, which
        # the background checkpoint sweep and ingest both read.
        with self.ckpt_lock:
            return self._migrate_doc_locked(d, dst_shard)

    def _migrate_doc_locked(self, d: int, dst_shard: int) -> bool:
        """Live tree-doc migration between mesh shards (hot-shard
        rebalancing; string-engine parity).

        The handoff is the same trunk-fold + re-materialization the
        restore path trusts: the trunk suffix folds into the checkpoint
        forest (which then carries the doc's FULL ingested trunk state —
        including any rows still queued for the device, so the queue
        drops), the vacated slot retires to the pristine proto row, and
        the forest re-materializes at the destination slot as one
        whole-content insert staged through the normal batched step.
        Observable state (``tree_json``) is byte-identical once staged
        work applies; host EditManager windows and checkpoint floors
        travel with the doc untouched, so a doc may migrate MID-STREAM.
        Raises ``placement.PlacementError`` for a fallback-routed doc
        (its serving state lives in a host Forest, not the fleet slot).
        Returns False (doc stays put) when the doc is already on
        ``dst_shard``, its row latched an error, the forest cannot
        re-flatten, or the destination has no free slot."""
        plane = self.placement_plane
        plane.validate(d, dst_shard)
        plane.require_migratable(
            d, "fallback" if d in self.fallbacks else None
        )
        reservation = plane.reserve(d, dst_shard)
        if reservation is None:
            return False
        src_slot, dst_slot = reservation
        src_shard = src_slot // self.docs_per_shard
        h = self.hosts[d]
        if int(np.asarray(self.state.error)[src_slot]):
            plane.release(dst_slot)
            return False  # recover first; never migrate a latched row
        # Fold the trunk suffix: the checkpoint forest becomes the full
        # ingested trunk state (the same fold the checkpoint sweep and
        # fallback routing perform).
        for t in h.trunk_log:
            apply_commit(h.checkpoint.root, t)
        h.trunk_log.clear()
        ops_blk = pay_blk = None
        if h.checkpoint.root_field:
            ch = NodeChange()
            ch.fields[ROOT_FIELD] = [
                Insert([n.clone() for n in h.checkpoint.root_field])
            ]
            try:
                ops_blk, pay_blk = self._flatten([ch], seq=h.last_seq)
            except UnsupportedShape:
                plane.release(dst_slot)
                return False  # cannot re-pack: doc keeps serving in place
        # Queued rows are covered by the folded forest; re-staging them on
        # top of the re-materialization would double-apply.
        h.queue.clear()
        self._busy.discard(d)
        self.state = jax.tree.map(
            lambda x, s: x.at[src_slot].set(s), self.state, self._proto
        )
        plane.commit(d, src_slot, dst_slot)
        # The destination slot is pristine by pool invariant (spare slots
        # start as broadcast protos; retired slots reset above), so the
        # watermarks restart at the re-materialization bound.
        self._rows_upper[d] = 0
        self._pool_upper[d] = 0
        if ops_blk is not None and len(ops_blk):
            rows_up, words_up = self._block_upper(ops_blk)
            self._rows_upper[d] += rows_up
            self._pool_upper[d] += words_up
            h.queue.extend_block(ops_blk, pay_blk)
            self._busy.add(d)
        self.counters.bump("doc_migrations")
        instant(
            "migrate_doc", doc=self.doc_keys[d], src=src_shard,
            dst=dst_shard,
        )
        return True

    def rebalance_hot_shards(
        self, factor: float = 2.0, max_moves: int = 1
    ) -> list[tuple[int, int, int]]:
        """Detect hot shards and live-migrate their deepest-queued docs
        to the coldest shards with free slots — the shared plane's
        skeleton (placement.rebalance_hot_shards), one trunk-fold +
        re-materialization handoff per move.  Returns the ``(doc,
        src_shard, dst_shard)`` moves made; callers re-align the scribe
        pool afterwards so summary ownership follows the docs."""
        return placement.rebalance_hot_shards(
            self, self.placement_plane, factor, max_moves,
            in_lane=lambda d: d in self.fallbacks,
        )

    def adopt_boot_snapshot(
        self, doc_idx: int, record: dict
    ) -> placement.AdoptResult:
        """Client half of the fan-out plane's ``{"t":"resync","boot":true}``
        contract (the shared orchestration — placement.adopt_boot_snapshot —
        riding this engine's refresh re-seed path): a consumer that fell
        off the retained log re-seeds the document from a historian
        snapshot record (the scribe summary schema, ``engine:
        tree_batch``) and re-consumes from the returned floor; the host
        EditManager window, checkpoint forest, and materialized device
        columns all reset consistently."""
        return placement.adopt_boot_snapshot(
            self, doc_idx, record, self._clear_staged
        )

    def _clear_staged(self, doc_idx: int) -> None:
        """Drop a doc's staged pre-gap work ahead of a boot-snapshot
        adoption (the refresh guard refuses docs with pending ops; a boot
        resync REPLACES the doc, so pre-gap rows are covered)."""
        self.hosts[doc_idx].queue.clear()
        self._busy.discard(doc_idx)

    def _drop_restored_identity(self, d: int) -> None:
        """Forget a doc's prior adoption before a refresh re-seed (warm-
        standby trailing / boot-snapshot adoption: no staged work by
        contract).  The device half resets the doc's materialized pooled
        columns to the pristine proto row — re-materialization is
        incremental on top of whatever the row holds, so a re-seed must
        land on clean state (this reset is what closes the old
        'cannot be overwritten in place' parity gap)."""
        had_fallback = self.fallbacks.pop(d, None) is not None
        h = self.hosts[d]
        h.queue.clear()
        h.trunk_log.clear()
        h.checkpoint = Forest()
        self._busy.discard(d)
        self._rows_upper[d] = 0
        self._pool_upper[d] = 0
        if h.total_commits or h.restored or had_fallback:
            # Only docs that ever materialized (or whose slot may hold
            # stale pre-fallback content) pay the row reset; a fresh
            # standby's first adoption lands on already-pristine rows.
            slot = int(self._slot[d])
            self.state = jax.tree.map(
                lambda x, s: x.at[slot].set(s), self.state, self._proto
            )

    def errors(self) -> np.ndarray:
        return np.asarray(self.state.error)[self._slot]
