"""TreeBatchEngine: batched sequenced tree-edit application across documents.

The SharedTree analog of ``doc_batch_engine``: D tree documents, each with
its own totally-ordered edit stream, stepped in lockstep device batches.

Host/device split (the seam SURVEY §7 step 7 names):

- host: per-doc EditManager runs the deterministic trunk translation
  (dds/tree/editmanager.py) — rebase is control-plane work over tiny mark
  lists; the result is a TRUNK-COORDINATE commit every replica agrees on.
- device: the forest state — a uniform-chunk value column per document
  (ref chunked-forest/uniformChunk.ts:42) — applies the trunk commits as
  batched index-map gathers (ops/tree_kernel.py ForestState).

The device path covers the uniform-chunk shape: a flat root field of leaf
values with insert/remove/set-value/contiguous-move edits.  Documents whose
commits leave that shape (nested fields, non-leaf content, split moves)
fall back to a host Forest replica — the same route-to-oracle policy as the
string engine, keeping every document correct while the common case stays
on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.tree.changeset import (
    Insert,
    Modify,
    MoveIn,
    MoveOut,
    Remove,
    Skip,
    apply_commit,
    commit_from_json,
)
from ..dds.tree.editmanager import EditManager
from ..dds.tree.forest import Forest, Node
from ..ops import tree_kernel as tk
from ..protocol.messages import MessageType, SequencedMessage


def _int32(v) -> bool:
    return isinstance(v, int) and -(1 << 31) <= v < (1 << 31)


@dataclass
class _TreeHost:
    em: EditManager = field(default_factory=EditManager)
    queue: list[np.ndarray] = field(default_factory=list)
    payloads: list[np.ndarray] = field(default_factory=list)
    # Trunk-coordinate commit suffix since ``checkpoint`` (replay source for
    # fallback routing); folded into the checkpoint forest every
    # CHECKPOINT_EVERY commits so host memory stays bounded.
    trunk_log: list[list] = field(default_factory=list)
    checkpoint: Forest = field(default_factory=Forest)


class UnsupportedShape(Exception):
    """A commit the columnar path cannot express."""


class TreeBatchEngine:
    """A fleet of tree replicas: host EditManagers + device value columns."""

    CHECKPOINT_EVERY = 64  # trunk-log fold threshold (bounds host memory)

    def __init__(
        self,
        n_docs: int,
        capacity: int = 1024,
        ops_per_step: int = 16,
        max_insert_len: int = 16,
        mesh=None,
    ) -> None:
        self.n_docs = n_docs
        self.capacity = capacity
        self.ops_per_step = ops_per_step
        self.max_insert_len = max_insert_len
        self.hosts = [_TreeHost() for _ in range(n_docs)]
        self.fallbacks: dict[int, Forest] = {}
        self.mesh = mesh
        if mesh is not None:
            n_shards = mesh.devices.size
            assert n_docs % n_shards == 0, "pad n_docs to a mesh multiple"
        proto = tk.init_forest(capacity)
        self.state = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_docs,) + x.shape), proto
        )
        if mesh is not None:
            from ..parallel.mesh import shard_docs

            self.state = jax.tree.map(
                lambda x: jax.device_put(x, shard_docs(mesh)), self.state
            )
        self._step = jax.jit(
            jax.vmap(tk.apply_forest_ops), donate_argnums=(0,)
        )

    # ------------------------------------------------------------------ ingest
    @staticmethod
    def _unwrap(contents: dict):
        """Yield the tree edit ops inside a wire message: handles grouped
        batches and the runtime's address envelopes (containerRuntime ->
        datastore -> channel), so the engine ingests the same streams a
        container fleet produces."""
        if not isinstance(contents, dict):
            return
        if contents.get("type") == "groupedBatch":
            for inner in contents.get("contents", []):
                yield from TreeBatchEngine._unwrap(inner)
            return
        if contents.get("type") == "edit":
            yield contents
            return
        if "address" in contents and "contents" in contents:
            yield from TreeBatchEngine._unwrap(contents["contents"])

    def ingest(self, doc_idx: int, msg: SequencedMessage) -> None:
        """Integrate one sequenced message: EditManager translation on the
        host, op-row staging for the device (or fallback apply)."""
        if msg.type != MessageType.OP:
            return
        for edit in self._unwrap(msg.contents):
            self._ingest_edit(doc_idx, msg, edit)

    def _ingest_edit(self, doc_idx: int, msg: SequencedMessage, c: dict) -> None:
        h = self.hosts[doc_idx]
        commit = commit_from_json(c["changes"])
        trunk = h.em.add_sequenced(
            client_id=msg.client_id,
            revision=(c["sid"], c["rev"]),
            change=commit,
            ref_seq=msg.ref_seq,
            seq=msg.seq,
        )
        h.em.advance_min_seq(msg.min_seq)
        if doc_idx in self.fallbacks:
            # Fallback docs apply directly; their trunk log is dead weight
            # (they can never be re-replayed onto the device path).
            apply_commit(self.fallbacks[doc_idx].root, trunk)
            return
        h.trunk_log.append(trunk)
        if len(h.trunk_log) >= self.CHECKPOINT_EVERY:
            # Fold the suffix into the checkpoint forest: bounded host
            # memory, and fallback routing replays only the tail.
            for t in h.trunk_log:
                apply_commit(h.checkpoint.root, t)
            h.trunk_log.clear()
        try:
            rows = self._flatten(trunk, msg.seq)
        except UnsupportedShape:
            self._route_to_fallback(doc_idx)
            return
        h.queue.extend(r for r, _p in rows)
        h.payloads.extend(p for _r, p in rows)

    def _flatten(self, trunk_commit, seq: int) -> list[tuple[np.ndarray, np.ndarray]]:
        """Trunk commit -> forest op rows.  Raises UnsupportedShape for
        anything beyond the uniform-chunk edit grammar."""
        rows: list[tuple[np.ndarray, np.ndarray]] = []
        empty = np.zeros((self.max_insert_len,), np.int32)

        def row(kind, pos=0, count=0, dst=0, value=0, payload=None):
            op = np.array(
                [kind, seq, pos, count, dst, value, 0, 0], np.int32
            )
            rows.append((op, empty if payload is None else payload))

        for change in trunk_commit:
            if change.value is not None:
                raise UnsupportedShape("value change on the virtual root")
            for key, marks in change.fields.items():
                if key != "":
                    raise UnsupportedShape(f"non-root field {key!r}")
                self._flatten_marks(marks, row)
        return rows

    def _flatten_marks(self, marks, row) -> None:
        """Mark list (simultaneous, input coordinates) -> sequential op rows.

        All positions stay in INPUT coordinates and the ops are emitted
        back-to-front (descending position): an op never shifts the
        coordinates of ops below it, so sequential application reproduces
        the simultaneous mark semantics exactly.  Moves flatten to one
        contiguous (src, count, dst) op; split moves or moves mixed with
        other structural marks fall back to the host."""
        move_out: dict[int, tuple[int, int]] = {}
        move_in: dict[int, int] = {}
        in_pos = 0
        pending: list[tuple] = []
        for m in marks:
            if isinstance(m, Skip):
                in_pos += m.count
            elif isinstance(m, Insert):
                vals = []
                for node in m.content:
                    if node.fields or not _int32(node.value):
                        raise UnsupportedShape("non-int32-leaf insert content")
                    vals.append(node.value)
                if len(vals) > self.max_insert_len:
                    raise UnsupportedShape("insert wider than payload row")
                pending.append(("ins", in_pos, vals))
            elif isinstance(m, Remove):
                pending.append(("rm", in_pos, m.count))
                in_pos += m.count
            elif isinstance(m, Modify):
                ch = m.change
                if ch.fields or ch.value is None:
                    raise UnsupportedShape("nested modify")
                if not _int32(ch.value[0]):
                    raise UnsupportedShape("non-int32 value")
                pending.append(("set", in_pos, ch.value[0]))
                in_pos += 1
            elif isinstance(m, MoveOut):
                if m.id in move_out:
                    raise UnsupportedShape("split move")
                move_out[m.id] = (in_pos, m.count)
                in_pos += m.count
            elif isinstance(m, MoveIn):
                if m.id in move_in:
                    raise UnsupportedShape("split move")
                move_in[m.id] = in_pos
            else:
                raise UnsupportedShape(type(m).__name__)
        if move_out or move_in:
            if len(move_out) != 1 or set(move_out) != set(move_in) or pending:
                raise UnsupportedShape("mixed structural marks with move")
            (mid, (src, count)), = move_out.items()
            row(tk.ForestOpKind.MOVE, pos=src, count=count, dst=move_in[mid])
            return
        for kind, pos, arg in reversed(pending):
            if kind == "ins":
                payload = np.zeros((self.max_insert_len,), np.int32)
                payload[: len(arg)] = arg
                row(tk.ForestOpKind.INSERT, pos=pos, count=len(arg), payload=payload)
            elif kind == "rm":
                row(tk.ForestOpKind.REMOVE, pos=pos, count=arg)
            else:
                row(tk.ForestOpKind.SET, pos=pos, value=arg)

    # ---------------------------------------------------------------- routing
    def _route_to_fallback(self, doc_idx: int) -> None:
        """Rebuild the document as a host Forest from its trunk log; all
        future commits apply there (route-to-oracle, like the string
        engine's recovery lanes)."""
        h = self.hosts[doc_idx]
        f = h.checkpoint  # trunk state up to the last checkpoint fold
        for trunk in h.trunk_log:
            apply_commit(f.root, trunk)
        self.fallbacks[doc_idx] = f
        h.checkpoint = Forest()
        h.trunk_log.clear()  # never replayed again
        h.queue.clear()
        h.payloads.clear()

    # ------------------------------------------------------------------- step
    def pending_ops(self) -> int:
        return sum(len(h.queue) for h in self.hosts)

    def step(self) -> int:
        steps = 0
        B = self.ops_per_step
        while any(h.queue for h in self.hosts):
            ops = np.zeros((self.n_docs, B, tk.FOREST_OP_FIELDS), np.int32)
            payloads = np.zeros((self.n_docs, B, self.max_insert_len), np.int32)
            for d, h in enumerate(self.hosts):
                take = min(B, len(h.queue))
                for j in range(take):
                    ops[d, j] = h.queue[j]
                    payloads[d, j] = h.payloads[j]
                del h.queue[:take]
                del h.payloads[:take]
            self.state = self._step(
                self.state, jnp.asarray(ops), jnp.asarray(payloads)
            )
            steps += 1
        err = np.asarray(self.state.error)
        for d in range(self.n_docs):
            if err[d] and d not in self.fallbacks:
                # Capacity/range overflow on device: replay on the host.
                self._route_to_fallback(d)
                self.state = self.state._replace(
                    error=self.state.error.at[d].set(0)
                )
        return steps

    # ------------------------------------------------------------------ views
    def values(self, doc_idx: int) -> list[int]:
        """The document's root-field leaf values."""
        if doc_idx in self.fallbacks:
            return [n.value for n in self.fallbacks[doc_idx].root_field]
        st = jax.tree.map(lambda x: x[doc_idx], self.state)
        return [int(v) for v in tk.forest_values(st)]

    def errors(self) -> np.ndarray:
        return np.asarray(self.state.error)
